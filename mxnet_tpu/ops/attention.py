"""Attention ops: Pallas flash attention + ring attention (context parallel).

Reference parity note: the reference (Apache MXNet 2.0-dev) ships NO fused
attention and NO sequence/context parallelism (SURVEY.md §2.3, §5 "long-
context: none in the reference") — attention lived in gluon-nlp as unfused
batch_dot+softmax. This module is the TPU-idiomatic superset the build plan
(SURVEY.md §7 stage 10) calls for:

- ``flash_attention``: O(S) memory online-softmax attention. On TPU both
  the forward AND the backward are Pallas kernels (FlashAttention-2 style:
  the forward saves a per-row log-sum-exp residual; the backward's dq and
  dk/dv kernels reconstruct softmax blocks from it — no S×S residual is
  ever materialized). Elsewhere a blockwise ``lax.scan`` XLA implementation
  with identical math and a recompute-based backward.
- ``ring_attention``: context parallelism over a mesh axis. Each device
  holds a sequence shard of Q/K/V; K/V blocks rotate around the ring via
  ``lax.ppermute`` (ICI neighbor exchange) while online-softmax accumulators
  merge partial results — sequence length scales with the number of chips.

Math convention: inputs are (batch, heads, seq, head_dim); softmax scale
defaults to head_dim**-0.5; masking uses a large negative finite value so
fully-masked rows stay NaN-free through exp/renormalization.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["flash_attention", "paged_decode_attention", "ring_attention",
           "ring_attention_sharded", "attention_reference"]

_NEG_INF = -1e30  # finite mask value: keeps exp() NaN-free for masked rows


def _PLTPU_COMPILER_PARAMS(**kwargs):
    """pallas-tpu CompilerParams across jax versions (older releases spell
    it TPUCompilerParams)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def attention_reference(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None, mask=None):
    """Unfused softmax(QK^T)V — the numeric oracle for tests and the
    arbitrary-additive-mask path (XLA fuses the softmax). ``mask`` is an
    additive float mask broadcastable to (B, H, Sq, Sk). Convention shared
    by every attention path in this module: a query row with NO valid key
    outputs exactly zero (the flash-kernel convention)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(tri, s, _NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
    out = jnp.where(m > _NEG_INF / 2, out, 0.0)  # fully-masked rows → 0
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Blockwise XLA implementation (fallback forward + backward recompute target)
# ---------------------------------------------------------------------------

def _attention_xla(q, k, v, causal: bool, sm_scale: float,
                   block_k: int = 512, valid_length=None):
    """Online-softmax attention scanning over K/V blocks: O(Sq·block_k)
    live memory instead of O(Sq·Sk). Pure lax.scan — XLA pipelines the
    blocks and keeps the matmuls on the MXU. ``valid_length`` is an
    optional (B,) per-sample key length (padding mask)."""
    orig_dtype = q.dtype
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_k = min(block_k, sk)
    nk = -(-sk // block_k)
    pad = nk * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    qf = q.astype(jnp.float32) * sm_scale
    kb = jnp.moveaxis(k.reshape(b, h, nk, block_k, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, h, nk, block_k, d), 2, 0)
    q_pos = jnp.arange(sq) + (sk - sq)  # align causal diagonal to the end

    def body(carry, inp):
        acc, m, l = carry
        kblk, vblk, ki = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk.astype(jnp.float32))
        k_pos = ki * block_k + jnp.arange(block_k)
        valid = (k_pos < sk)[None, None, None, :]
        if causal:
            valid = valid & (k_pos[None, :] <= q_pos[:, None])
        if valid_length is not None:
            valid = valid & (k_pos[None, None, None, :]
                             < valid_length[:, None, None, None])
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32))
        return (acc, m_new, l), None

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (acc, m, l), _ = lax.scan(body, (acc0, m0, l0),
                              (kb, vb, jnp.arange(nk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((m > _NEG_INF / 2)[..., None], out, 0.0)  # no-key rows
    return out.astype(orig_dtype)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _head_group(bh: int, block_q: int, block_k: int,
                n_tiles: int = 1) -> int:
    """Heads per Pallas program. Per-program fixed overhead (~2-3 µs:
    launch + DMA setup) dominates short-seq attention when the grid has
    one program per (batch, head) — 384 programs for BERT-base bs=32.
    Batch G heads per program, bounded by the CONCURRENT (G, bq, bk) f32
    tiles' VMEM footprint (~16 MiB/core on v5e; the shared tile budget
    lives in ops/kernels — the rnn_scan timestep-block sizer accounts
    against the same number). ``n_tiles`` is how many such score-shaped
    tiles the kernel holds live at once: 1 for the forward (s; p
    overwrites it), 4 for the fused backward (s, p, dp, ds) — budgeting
    the backward as a single tile oversizes G and fails Mosaic lowering
    at large blocks."""
    from .kernels import vmem_tile_budget
    budget = vmem_tile_budget()
    g = 1
    while (g * 2 <= 8 and bh % (g * 2) == 0
           and g * 2 * block_q * block_k * 4 * n_tiles
           <= budget):
        g *= 2
    return g


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                  sm_scale, causal, block_q, block_k, nk, seq_q, seq_k,
                  need_mask):
    from jax.experimental import pallas as pl
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # Causal: skip blocks strictly above the diagonal (no valid entries).
    diag_off = seq_k - seq_q
    run = True
    if causal:
        run = _causal_block_skip(qi, ki, block_q, block_k, seq_q, seq_k)

    @pl.when(run)
    def _compute():
        # dots take the INPUT dtype (bf16 under AMP) with f32
        # accumulation — an astype(f32) here would push the MXU onto its
        # ~6x slower f32 passes
        q = q_ref[...]                            # (G, block_q, d)
        k = k_ref[...]                            # (G, block_k, d)
        s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale
        if need_mask or causal:
            # masking is real VPU work on a (bq, bk) tile — emitted only
            # when there is padding to hide or a causal wedge to cut
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = k_pos < seq_k
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + diag_off
                valid = valid & (k_pos <= q_pos)
            s = jnp.where(valid[None], s, _NEG_INF)

        m_prev = m_s[:, :, :1]                    # (G, block_q, 1)
        m_cur = s.max(axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_s[:, :, :1] * alpha + p.sum(axis=2, keepdims=True)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
        acc_s[...] = acc_s[...] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_s[:, :, :1], 1e-30)
        out = acc_s[...] / l
        # rows that never saw a valid key (m still at init) output zero —
        # the shared convention across every path in this module
        out = jnp.where(m_s[:, :, :1] > _NEG_INF / 2, out, 0.0)
        o_ref[...] = out.astype(o_ref.dtype)
        # log-sum-exp per row: the residual the backward kernels need
        # (p = exp(s - lse) reconstructs softmax without the S×S matrix)
        lse = jnp.where(m_s[:, :, :1] > _NEG_INF / 2,
                        m_s[:, :, :1] + jnp.log(l), _NEG_INF)
        # 8-lane replication: narrowest layout the TPU tiling rules allow
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _pad_for_blocks(q, k, v, block_q, block_k):
    """Shared fwd/bwd tiling preamble: clamp block sizes, pad seq dims to
    block multiples and head_dim to the 128-lane tile, fold (B, H) →
    batch-of-heads. The backward's exp(s - lse) recompute is only correct
    when it uses EXACTLY these conventions — keep this the single source."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    dp = max(128, -(-d // 128) * 128)
    sqp = -(-sq // block_q) * block_q
    skp = -(-sk // block_k) * block_k

    def pad3(x, s_to, d_to):
        return jnp.pad(x, ((0, 0), (0, 0), (0, s_to - x.shape[2]),
                           (0, d_to - x.shape[3])))

    qp = pad3(q, sqp, dp).reshape(b * h, sqp, dp)
    kp = pad3(k, skp, dp).reshape(b * h, skp, dp)
    vp = pad3(v, skp, dp).reshape(b * h, skp, dp)
    return (qp, kp, vp, pad3, block_q, block_k, dp, sqp, skp,
            sqp // block_q, skp // block_k)


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float,
                      block_q: int = 512, block_k: int = 512,
                      interpret: bool = False):
    # 512x512 blocks measured 2.2x faster than 128x128 on one TPU chip
    # (8x12x2048x64 causal: 4.5ms vs 13ms; XLA blockwise scan: 9.7ms)
    """Pallas flash attention forward → (out, lse). Padding/tiling via
    _pad_for_blocks; zero-padded head dims cancel in QK^T and are sliced
    off the output."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    (qp, kp, vp, _, block_q, block_k, dp, sqp, skp, nq, nk) = \
        _pad_for_blocks(q, k, v, block_q, block_k)
    g = _head_group(b * h, block_q, block_k)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk, seq_q=sq, seq_k=sk,
        need_mask=(skp != sk))
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h // g, nq, nk),
        in_specs=[
            pl.BlockSpec((g, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((g, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((g, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((g, block_q, 8), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sqp, dp), q.dtype),
            jax.ShapeDtypeStruct((b * h, sqp, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, dp), jnp.float32),
        ],
        compiler_params=_PLTPU_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return (out.reshape(b, h, sqp, dp)[:, :, :sq, :d],
            lse[:, :, 0].reshape(b, h, sqp)[:, :, :sq])


# ---------------------------------------------------------------------------
# Pallas TPU backward kernels (FlashAttention-2 style: recompute p from the
# saved per-row log-sum-exp; no S×S residual is ever materialized)
# ---------------------------------------------------------------------------

def _causal_block_skip(qi, ki, block_q, block_k, seq_q, seq_k):
    """True iff block (qi, ki) holds ANY valid causal entry — the shared
    skip predicate for the forward and both backward kernels (a divergence
    here would desynchronize forward and backward masking)."""
    return ki * block_k <= qi * block_q + block_q - 1 + (seq_k - seq_q)


def _bwd_mask(qi, ki, block_q, block_k, causal, seq_q, seq_k):
    k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 1)
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 0)
    valid = (k_pos < seq_k) & (q_pos < seq_q)
    if causal:
        valid = valid & (k_pos <= q_pos + (seq_k - seq_q))
    return valid


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_s, dv_s, *, sm_scale, causal,
                          block_q, block_k, nq, seq_q, seq_k, need_mask):
    from jax.experimental import pallas as pl
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    run = True
    if causal:  # this k block only touches q rows at/after the diagonal
        run = _causal_block_skip(qi, ki, block_q, block_k, seq_q, seq_k)

    @pl.when(run)
    def _compute():
        # operands keep the input dtype (bf16 under AMP), f32 accumulate
        # — see the forward kernel's MXU-pass note
        q = q_ref[...]                              # (G, bq, d)
        k = k_ref[...]                              # (G, bk, d)
        v = v_ref[...]
        do = do_ref[...]                            # (G, bq, d)
        lse = lse_ref[...][:, :, :1]                # (G, bq, 1)
        delta = delta_ref[...][:, :, :1]            # (G, bq, 1)
        s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)                        # (G, bq, bk)
        if need_mask or causal:
            valid = _bwd_mask(qi, ki, block_q, block_k, causal,
                              seq_q, seq_k)
            p = jnp.where(valid[None], p, 0.0)
        dv_s[...] += lax.dot_general(p.astype(do.dtype), do,
                                     (((1,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale)
        dk_s[...] += lax.dot_general(ds.astype(q.dtype), q,
                                     (((1,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[...] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_s[...].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dk_ref, dv_ref, *, sm_scale, causal,
                            block_q, block_k, seq_q, seq_k, need_mask):
    """Single-block backward (nq == nk == 1, the short-seq fast path):
    one program computes dq, dk AND dv, reconstructing the softmax block
    ONCE — the two-kernel general path pays the s = qk^T + exp recompute
    twice, and that VPU work dominates short-seq attention (r5)."""
    q = q_ref[...]                                  # (G, bq, d)
    k = k_ref[...]                                  # (G, bk, d)
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...][:, :, :1]
    delta = delta_ref[...][:, :, :1]
    s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32) * sm_scale
    p = jnp.exp(s - lse)                            # (G, bq, bk)
    if need_mask or causal:
        valid = _bwd_mask(0, 0, block_q, block_k, causal, seq_q, seq_k)
        p = jnp.where(valid[None], p, 0.0)
    pb = p.astype(do.dtype)
    dv_ref[...] = lax.dot_general(
        pb, do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)
    ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
    dq_ref[...] = lax.dot_general(
        ds, k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[...] = lax.dot_general(
        ds, q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_s, *, sm_scale, causal, block_q,
                         block_k, nk, seq_q, seq_k, need_mask):
    from jax.experimental import pallas as pl
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    run = True
    if causal:
        run = _causal_block_skip(qi, ki, block_q, block_k, seq_q, seq_k)

    @pl.when(run)
    def _compute():
        # operands keep the input dtype (bf16 under AMP), f32 accumulate
        q = q_ref[...]                              # (G, bq, d)
        k = k_ref[...]                              # (G, bk, d)
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][:, :, :1]
        delta = delta_ref[...][:, :, :1]
        s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if need_mask or causal:
            valid = _bwd_mask(qi, ki, block_q, block_k, causal,
                              seq_q, seq_k)
            p = jnp.where(valid[None], p, 0.0)
        dp = lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_s[...] += lax.dot_general(ds.astype(k.dtype), k,
                                     (((2,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[...] = dq_s[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal: bool, sm_scale: float,
                      block_q: int = 512, block_k: int = 512,
                      interpret: bool = False):
    """Pallas flash attention backward: dq via a (q-parallel, k-inner)
    kernel, dk/dv via a (k-parallel, q-inner) kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    (qp, kp, vp, pad3, block_q, block_k, dp, sqp, skp, nq, nk) = \
        _pad_for_blocks(q, k, v, block_q, block_k)
    dop = pad3(do.astype(q.dtype), sqp, dp).reshape(b * h, sqp, dp)
    # delta_i = rowsum(dO_i * O_i) (cheap; XLA fuses into the pad)
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    dl = jnp.pad(delta.reshape(b * h, sq), ((0, 0), (0, sqp - sq)))
    lsep = jnp.pad(lse.reshape(b * h, sq), ((0, 0), (0, sqp - sq)))
    # 8-lane replication (TPU block tiling minimum for a row vector)
    dl = jnp.broadcast_to(dl[..., None], dl.shape + (8,))
    lsep = jnp.broadcast_to(lsep[..., None], lsep.shape + (8,))
    g = _head_group(b * h, block_q, block_k, n_tiles=4)
    need_mask = (skp != sk) or (sqp != sq)

    if nq == 1 and nk == 1:
        bspec = lambda blk: pl.BlockSpec((g, blk, dp),
                                         lambda bh: (bh, 0, 0))
        rspec = pl.BlockSpec((g, block_q, 8), lambda bh: (bh, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k, seq_q=sq, seq_k=sk,
                need_mask=need_mask),
            grid=(b * h // g,),
            in_specs=[bspec(block_q), bspec(block_k), bspec(block_k),
                      bspec(block_q), rspec, rspec],
            out_specs=[bspec(block_q), bspec(block_k), bspec(block_k)],
            out_shape=[jax.ShapeDtypeStruct((b * h, sqp, dp), q.dtype),
                       jax.ShapeDtypeStruct((b * h, skp, dp), k.dtype),
                       jax.ShapeDtypeStruct((b * h, skp, dp), v.dtype)],
            compiler_params=_PLTPU_COMPILER_PARAMS(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(qp, kp, vp, dop, lsep, dl)
        return (dq.reshape(b, h, sqp, dp)[:, :, :sq, :d],
                dk.reshape(b, h, skp, dp)[:, :, :sk, :d],
                dv.reshape(b, h, skp, dp)[:, :, :sk, :d])

    q_spec = pl.BlockSpec((g, block_q, dp), lambda bh, a, c: (bh, a, 0))
    row_spec = pl.BlockSpec((g, block_q, 8), lambda bh, a, c: (bh, a, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nk=nk, seq_q=sq, seq_k=sk, need_mask=need_mask),
        grid=(b * h // g, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((g, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((g, block_k, dp), lambda bh, qi, ki: (bh, ki, 0)),
            q_spec, row_spec, row_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sqp, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((g, block_q, dp), jnp.float32)],
        compiler_params=_PLTPU_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dl)

    k_spec = pl.BlockSpec((g, block_k, dp), lambda bh, ki, qi: (bh, ki, 0))
    qrow = pl.BlockSpec((g, block_q, dp), lambda bh, ki, qi: (bh, qi, 0))
    rrow = pl.BlockSpec((g, block_q, 8), lambda bh, ki, qi: (bh, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nq=nq, seq_q=sq, seq_k=sk, need_mask=need_mask),
        grid=(b * h // g, nk, nq),
        in_specs=[qrow, k_spec, k_spec, qrow, rrow, rrow],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, skp, dp), k.dtype),
                   jax.ShapeDtypeStruct((b * h, skp, dp), v.dtype)],
        scratch_shapes=[pltpu.VMEM((g, block_k, dp), jnp.float32),
                        pltpu.VMEM((g, block_k, dp), jnp.float32)],
        compiler_params=_PLTPU_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dl)

    return (dq.reshape(b, h, sqp, dp)[:, :, :sq, :d],
            dk.reshape(b, h, skp, dp)[:, :, :sk, :d],
            dv.reshape(b, h, skp, dp)[:, :, :sk, :d])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_tpu(q, k, v, causal, sm_scale, interpret):
    return _flash_fwd_pallas(q, k, v, causal, sm_scale,
                             interpret=interpret)[0]


def _flash_tpu_fwd(q, k, v, causal, sm_scale, interpret):
    o, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale,
                               interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_tpu_bwd(causal, sm_scale, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale,
                             interpret=interpret)


_flash_tpu.defvjp(_flash_tpu_fwd, _flash_tpu_bwd)


# ---------------------------------------------------------------------------
# Public flash_attention with recompute backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, sm_scale):
    """XLA (non-Pallas) flash path: blockwise scan forward, recompute
    backward. The TPU default goes through _flash_tpu instead."""
    return _attention_xla(q, k, v, causal, sm_scale)


def _flash_fwd(q, k, v, causal, sm_scale):
    return _flash(q, k, v, causal, sm_scale), (q, k, v)


def _flash_bwd(causal, sm_scale, res, g):
    q, k, v = res
    # Flash-style backward: recompute attention blockwise (no S×S residual).
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_xla(q_, k_, v_, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_vl(q, k, v, vl, causal, sm_scale):
    return _attention_xla(q, k, v, causal, sm_scale, valid_length=vl)


def _flash_vl_fwd(q, k, v, vl, causal, sm_scale):
    return _flash_vl(q, k, v, vl, causal, sm_scale), (q, k, v, vl)


def _flash_vl_bwd(causal, sm_scale, res, g):
    q, k, v, vl = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_xla(q_, k_, v_, causal, sm_scale,
                                          valid_length=vl), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(vl)


_flash_vl.defvjp(_flash_vl_fwd, _flash_vl_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    valid_length=None):
    """Fused memory-efficient attention on (B, H, S, D) tensors.

    On TPU forward and backward run as Pallas kernels (_flash_tpu:
    FlashAttention-2 dq/dkv kernels off the saved log-sum-exp); elsewhere
    a blockwise lax.scan implementation with identical online-softmax math
    and a recompute-based backward. ``valid_length`` (B,) masks padded
    keys; that path uses the blockwise implementation (still O(S·block)
    memory, never an S×S score matrix).
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise MXNetError("flash_attention expects (batch, heads, seq, dim)")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if valid_length is not None:
        vl = jnp.asarray(valid_length, jnp.float32)
        return _flash_vl(q, k, v, vl, causal, float(sm_scale))
    if use_pallas is None:
        # the shared MXNET_PALLAS three-tier gate (ops/kernels):
        # compiled kernels on TPU, interpret-mode bodies when forced
        # on other backends, blockwise-XLA reference otherwise
        from .kernels import dispatch as _kdispatch
        path, _ = _kdispatch("flash_attention")
        if path != "xla":
            return _flash_tpu(q, k, v, causal, float(sm_scale),
                              path == "interpret")
        return _flash(q, k, v, causal, float(sm_scale))
    if use_pallas:
        # full-Pallas path: flash forward AND FlashAttention-2-style
        # backward kernels (dq + dkv) off the saved log-sum-exp
        return _flash_tpu(q, k, v, causal, float(sm_scale), False)
    return _flash(q, k, v, causal, float(sm_scale))


# ---------------------------------------------------------------------------
# Paged decode attention: the single-token serving read path
# ---------------------------------------------------------------------------

def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           sm_scale: Optional[float] = None):
    """One query token per batch slot attending over K/V held in a
    paged cache (serving/kvcache.py) — the decode path through the
    flash-attention kernel, reading keys through page-table
    indirection.

    - ``q``: (S, H, D) — the current token's query per slot;
    - ``k_pages``/``v_pages``: (P, page_size, Hkv, D) — the pooled page
      arrays of one layer. ``Hkv`` may DIVIDE the query head count H
      (grouped-query attention): each stored K/V head is broadcast
      across its group of ``H // Hkv`` query heads, so a GQA decoder
      pays the KV-cache bytes of ``Hkv`` heads while attending with H;
    - ``page_table``: (S, max_pages) int32 — slot → page ids, padded
      with the null page 0 past each slot's allocation;
    - ``lengths``: (S,) — valid key count per slot (the token just
      written included).

    The page gather is a shape-stable XLA gather (the compiled program
    never depends on which pages a slot holds), and the attention runs
    as ``flash_attention(..., valid_length=lengths)`` so padding pages
    and unwritten tail positions are masked exactly (never a NaN, never
    a contribution from another request's freed pages). Returns
    (S, H, D).
    """
    s, h, d = q.shape
    hkv = k_pages.shape[2]
    if h != hkv and (hkv < 1 or h % hkv):
        raise MXNetError(
            f"paged_decode_attention: query heads {h} not a multiple "
            f"of K/V heads {hkv} (GQA needs integer groups)")
    ps = k_pages.shape[1]
    t = page_table.shape[1] * ps
    # (S, max_pages, page_size, Hkv, D) -> (S, Hkv, T, D): slot s's key
    # at position p lives at flat index p because pages fill in order
    k = k_pages[page_table].reshape(s, t, hkv, d).transpose(0, 2, 1, 3)
    v = v_pages[page_table].reshape(s, t, hkv, d).transpose(0, 2, 1, 3)
    if h != hkv:
        # GQA broadcast: repeat each stored head over its query group
        # (head j serves query heads [j*g, (j+1)*g))
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)
    out = flash_attention(q[:, :, None, :], k, v, causal=False,
                          sm_scale=sm_scale, valid_length=lengths)
    return out[:, :, 0, :]


# ---------------------------------------------------------------------------
# Ring attention: context parallelism over a mesh axis
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Per-shard ring attention body — call under shard_map with the
    sequence dimension sharded over ``axis_name``.

    Each of the N devices holds S/N of the sequence. K/V shards rotate
    around the ring (lax.ppermute = ICI neighbor exchange, overlapping with
    the local attention block), and online-softmax stats merge the partial
    results — the TPU-native form of sequence/context parallelism.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s, d = q.shape
    orig_dtype = q.dtype
    qf = q.astype(jnp.float32) * sm_scale
    q_pos = idx * s + jnp.arange(s)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def _merge(acc, m, l, kc, vc, src):
        """Online-softmax merge of one K/V chunk (chunk id ``src``)."""
        s_ij = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
        if causal:
            k_pos = src * s + jnp.arange(s)
            mask = k_pos[None, :] <= q_pos[:, None]
            s_ij = jnp.where(mask, s_ij, _NEG_INF)
        m_new = jnp.maximum(m, s_ij.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_ij - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        return acc, m_new, l

    def body(carry, i):
        acc, m, l, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)   # rotate, then merge: the
        vc = lax.ppermute(vc, axis_name, perm)   # local chunk was step 0
        acc, m, l = _merge(acc, m, l, kc, vc, (idx - i) % axis_size)
        return (acc, m, l, kc, vc), None

    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    # Step 0 = local chunk; steps 1..N-1 rotate first, so exactly N-1
    # neighbor exchanges happen in total.
    acc0, m0, l0 = _merge(acc0, m0, l0, k, v, idx)
    (acc, m, l, _, _), _ = lax.scan(body, (acc0, m0, l0, k, v),
                                    jnp.arange(1, axis_size))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((m > _NEG_INF / 2)[..., None], out, 0.0)  # no-key rows
    return out.astype(orig_dtype)


def ring_attention_sharded(q, k, v, mesh, axis: str = "sp",
                           causal: bool = False,
                           sm_scale: Optional[float] = None):
    """shard_map wrapper: jax arrays in, sequence dim sharded over ``axis``
    of ``mesh`` (a jax.sharding.Mesh or mxnet_tpu DeviceMesh)."""
    from jax.sharding import PartitionSpec as P
    m = getattr(mesh, "mesh", mesh)
    spec = P(None, None, axis, None)
    fn = functools.partial(ring_attention, axis_name=axis, causal=causal,
                           sm_scale=sm_scale)
    from ..parallel.collectives import shard_map as _shard_map
    return _shard_map(lambda a, b_, c: fn(a, b_, c), m,
                      (spec, spec, spec), spec)(q, k, v)


# ---------------------------------------------------------------------------
# sharding spec packs (analysis/sharding.py expect_spec)
# ---------------------------------------------------------------------------
# The invariant packs for the two attention parallelism paths, declared
# NEXT TO the implementations they describe so a change to the
# collective pattern and its contract land in the same review:
#
# - tensor-parallel attention ("tp-attention"): per-head QKV projections
#   column-sharded over 'tp', the output projection row-sharded — the
#   Megatron signature is exactly ONE all-reduce (the output psum) per
#   application; any all-gather above the floor means an activation
#   silently left the head-sharded layout.
# - sequence-parallel ring attention ("sp-ring-attention"): K and V
#   shards rotate the ring with lax.ppermute — >= 2 collective-permutes
#   (K and V; the backward adds reverse hops) and NOTHING ELSE: a
#   gather here means the sequence dimension was materialized on one
#   device, the exact failure ring attention exists to avoid.
try:
    from ..analysis import sharding as _asharding

    TP_ATTENTION_SPEC_PACK = _asharding.register_spec_pack(
        _asharding.SpecPack(
            name="tp-attention",
            description="tensor-parallel attention (Megatron split: "
                        "column-sharded QKV, row-sharded output proj, "
                        "one output all-reduce)",
            axes=("tp",),
            rules=(_asharding.CollectiveRule(
                "all_reduce", axis="tp", min_count=1),),
            declared=(_asharding.CollectiveRule(
                "reduce_scatter", axis="tp"),),
            state_axis="tp"))

    RING_ATTENTION_SPEC_PACK = _asharding.register_spec_pack(
        _asharding.SpecPack(
            name="sp-ring-attention",
            description="sequence-parallel ring attention (K/V shards "
                        "rotate via ppermute, online-softmax merge)",
            axes=("sp",),
            rules=(_asharding.CollectiveRule(
                "collective_permute", axis="sp", min_count=2),),
            declared=()))
except Exception:                        # pragma: no cover - defensive
    pass
