"""Op registry + imperative invoke path.

Reference analog: the nnvm op registry plus ``Imperative::Invoke``
(src/imperative/imperative.cc:98) and ``PushFCompute``
(src/imperative/imperative_utils.h:448). The reference infers shape/type,
picks a DispatchMode, and pushes a closure to the threaded engine; here the
"kernel" is a pure JAX function dispatched through XLA's async runtime, and
the invoke layer's remaining jobs are (a) NDArray unwrap/wrap, (b) autograd
tape recording (see _tape.py), (c) NaiveEngine synchronous mode.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import _tape, engine
from ..base import MXNetError

__all__ = ["Op", "register", "get_op", "invoke", "invoke_raw", "list_ops",
           "set_np_ndarray_cls", "add_invoke_wrapper", "remove_invoke_wrapper"]

_OP_REGISTRY: Dict[str, "Op"] = {}

# Cross-cutting hooks on the imperative invoke funnel (profiler timing, AMP
# dtype casting). Each wrapper is fn(op_name, kernel) -> kernel'. The analog
# of the reference's engine-level profiler hooks (threaded_engine.h:85) and
# AMP op patching (contrib/amp/amp.py:282).
_INVOKE_WRAPPERS: List = []


def add_invoke_wrapper(wrapper):
    _INVOKE_WRAPPERS.append(wrapper)


def remove_invoke_wrapper(wrapper):
    if wrapper in _INVOKE_WRAPPERS:
        _INVOKE_WRAPPERS.remove(wrapper)

# The mx.np ndarray class, registered by mxnet_tpu.numpy at import. When any
# input to an op is an mx.np array, outputs are mx.np arrays — the analog of
# the reference's _set_np_ndarray_class hook (python/mxnet/ndarray/register.py).
_NP_CLS = None


def set_np_ndarray_cls(cls):
    global _NP_CLS
    _NP_CLS = cls


class Op:
    """A registered operator.

    ``fn(*jax_arrays, **attrs)`` is the pure functional kernel — everything
    XLA needs. Optional metadata mirrors the reference op attributes
    (include/mxnet/op_attr_types.h): num_outputs, differentiability.
    """

    def __init__(self, name: str, fn: Callable, num_outputs: int = 1,
                 differentiable: bool = True, ndarray_alias: Optional[str] = None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.ndarray_alias = ndarray_alias

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return f"Op({self.name})"


def register(name: str, num_outputs: int = 1, differentiable: bool = True,
             alias: Optional[str] = None):
    """Decorator: register a JAX function as an operator."""
    def deco(fn):
        op = Op(name, fn, num_outputs, differentiable, alias)
        _OP_REGISTRY[name] = op
        if alias:
            _OP_REGISTRY[alias] = op
        return fn
    return deco


def get_op(name: str) -> Op:
    try:
        return _OP_REGISTRY[name]
    except KeyError as e:
        raise MXNetError(f"operator {name!r} is not registered") from e


def list_ops() -> List[str]:
    return sorted(_OP_REGISTRY)


try:
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError:  # future jax relayout: annotate unconditionally
    def _trace_state_clean():
        return False


def _named_scope_kernel(name: str, fn: Callable) -> Callable:
    """Run the kernel under ``jax.named_scope(op_name)`` so the op name lands
    in the HLO metadata name stack: XProf device traces then attribute fused
    kernels back to framework op names even inside a single jitted CachedOp
    computation (reference __profiler_scope__ + ProfileOperator,
    src/profiler/profiler.h:251-299, c_api_ndarray.cc:104).

    Only applied while a trace is being built (hybridize/_build_cache, jit,
    vjp) — the metadata is meaningless on the eager hot path, so eager
    dispatch pays one thread-local check instead of a context manager."""
    if _trace_state_clean():
        return fn
    safe = name.replace(" ", "_")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        import jax
        with jax.named_scope(safe):
            return fn(*args, **kwargs)
    return wrapped


def invoke_raw(name: str, fn: Callable, inputs: Sequence[Any],
               n_outputs: int = 1, record: Optional[bool] = None,
               out_cls=None):
    """Invoke a pure function on NDArray inputs, returning NDArray outputs.

    This is the single funnel every imperative op goes through — the analog
    of MXImperativeInvokeEx → Imperative::Invoke (c_api_ndarray.cc:153).
    """
    from ..ndarray.ndarray import NDArray  # lazy to break import cycle

    cls = out_cls
    if cls is None:
        cls = NDArray
        if _NP_CLS is not None and any(isinstance(x, _NP_CLS) for x in inputs):
            cls = _NP_CLS
    fn = _named_scope_kernel(name, fn)
    for _w in _INVOKE_WRAPPERS:
        fn = _w(name, fn)
    in_datas = [x._data if isinstance(x, NDArray) else x for x in inputs]
    should_record = _tape.is_recording() if record is None else record

    if should_record:
        nd_inputs = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
        # Allocate output handles; record_op fills data + tape entries.
        outs = [cls.__new__(cls) for _ in range(n_outputs)]
        for o in outs:
            o._init_empty()
        node = _tape.record_op(name, fn, nd_inputs, outs)
        del node
        result = outs[0] if n_outputs == 1 else tuple(outs)
    else:
        raw = fn(*in_datas)
        if n_outputs == 1 and not isinstance(raw, (tuple, list)):
            result = cls(raw)
        else:
            raw = raw if isinstance(raw, (tuple, list)) else (raw,)
            result = tuple(cls(r) for r in raw)

    eng = engine.get()
    if eng.is_naive:
        rs = result if isinstance(result, tuple) else (result,)
        eng.maybe_sync([r._data for r in rs])
    return result


def invoke(name: str, *inputs, **attrs):
    """Invoke a registered op by name with NDArray inputs + python attrs."""
    op = get_op(name)
    fn = functools.partial(op.fn, **attrs) if attrs else op.fn
    return invoke_raw(op.name, fn, list(inputs), n_outputs=op.num_outputs)
