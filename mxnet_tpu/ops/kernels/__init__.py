"""Pallas TPU kernel layer: hand-written kernels for the fusion gaps
XLA's automatic fuser cannot close (arXiv:2301.13062 measured them; the
census in analysis/fusion.py ranks them per program).

Members (each joins the flash-attention kernels in ops/attention.py):

- :mod:`.rnn_scan` — time-fused LSTM/GRU/vanilla-RNN recurrence: the
  hidden-to-hidden matmul, gate nonlinearities and carry update of a
  whole timestep block live in ONE kernel with h/c pinned in VMEM,
  killing the per-step HBM round-trips that made LSTM the worst-MFU
  BENCH leg (0.17).
- :mod:`.opt_update` — fused elementwise optimizer update (SGD-mom,
  Adam) over the ZeRO flat padded 1/N shards of gluon/fused_step.py.
- :mod:`.norm` — LayerNorm and bias-GELU forward+backward kernels for
  the transformer/BERT leg.

Dispatch discipline (shared by every kernel in this package, and by
``ops.attention.flash_attention``): one ``MXNET_PALLAS`` gate with
three tiers —

- ``auto`` (default): compiled Pallas kernels on TPU backends, the XLA
  reference implementation everywhere else;
- ``on``: Pallas on TPU; on non-TPU backends the kernels run in
  ``pl.pallas_call(interpret=True)`` mode — the kernel BODY executes
  (as plain XLA ops), which is how tier-1 CPU tests exercise kernels
  and how the parity sweep pins kernel-vs-reference equivalence;
- ``off``: XLA reference everywhere (including TPU) — the A/B switch
  for attribution and the escape hatch for a miscompiling kernel.

Every decision is recorded (``decisions()``, ``tools/diagnose.py
--kernels``) and counted (``mx_kernel_dispatch_total{path}``), and the
per-leg BENCH json attaches ``dispatch_table()`` next to the fusion
posture so a throughput number always names the path that produced it.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

__all__ = ["pallas_mode", "dispatch", "decisions", "dispatch_table",
           "KERNELS", "VMEM_TILE_BUDGET_BYTES", "VMEM_BYTES_PER_CORE",
           "vmem_tile_budget"]

#: VMEM ceiling one kernel's CONCURRENT working-set tiles may claim —
#: the budget ops.attention._head_group sizes head groups against, and
#: the one rnn_scan sizes its timestep block against. ~16 MiB/core is
#: the physical VMEM (v5e); 4 MiB leaves room for Mosaic's own double
#: buffering of the streamed operands. The DEFAULT: every kernel reads
#: the live value through :func:`vmem_tile_budget` (env/autotune
#: overridable), never this constant directly.
VMEM_BYTES_PER_CORE = 16 * 1024 * 1024
VMEM_TILE_BUDGET_BYTES = 4 * 1024 * 1024


def vmem_tile_budget() -> int:
    """THE tile-budget accessor — rnn_scan's timestep-block sizer,
    attention's ``_head_group``, and the norm/opt_update row-block caps
    all size against this one number, resolved as

        autotune override > ``MXNET_VMEM_TILE_BUDGET`` > the default

    (``tuning/space.py`` precedence), clamped to the physical
    per-core VMEM. Hand-tuners and the autotuner turn the same knob."""
    from ...tuning import space as _tspace
    try:
        v = int(_tspace.value("kernels.vmem_tile_budget",
                              VMEM_TILE_BUDGET_BYTES))
    except (TypeError, ValueError):
        v = VMEM_TILE_BUDGET_BYTES
    return max(64 * 1024, min(v, VMEM_BYTES_PER_CORE))


def _register_tunables():
    """Kernel-layer tunables, declared next to the constants they make
    sweepable (docs/PERF_NOTES.md \"Autotuner\")."""
    from ...tuning.space import Tunable, register
    mib = 1024 * 1024
    register(Tunable(
        "kernels.vmem_tile_budget", default=VMEM_TILE_BUDGET_BYTES,
        grid=(1 * mib, 2 * mib, 4 * mib, 8 * mib),
        env="MXNET_VMEM_TILE_BUDGET", parse=lambda s: int(float(s)),
        valid=lambda v, _c: 64 * 1024 <= int(v) <= VMEM_BYTES_PER_CORE,
        seam="ops.kernels.vmem_tile_budget() -> rnn_scan block_t, "
             "attention _head_group, norm/opt_update row blocks",
        scope="train", affects_program=True,
        doc="VMEM bytes one kernel's concurrent working-set tiles may "
            "claim (<= physical VMEM/core)"))
    register(Tunable(
        "kernels.rnn_block_t", default=0,
        grid=(0, 1, 2, 4, 8, 16),
        valid=lambda v, _c: 0 <= int(v) <= 16,
        seam="ops.kernels.rnn_scan._block_t() timesteps per grid step "
             "(0 = auto-size against the VMEM budget)",
        scope="train", affects_program=True,
        doc="timesteps one Pallas rnn_scan grid step walks"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break ops
    import logging
    logging.getLogger("mxnet_tpu.tuning").debug(
        "kernel tunable registration failed", exc_info=True)

#: the kernel names the dispatch gate knows (bench/diagnose vocabulary)
KERNELS = ("rnn_scan", "rnn_decode_step", "opt_update", "layernorm",
           "bias_gelu", "flash_attention")

# last decision per kernel name: {kernel: (path, reason)}
_DECISIONS: Dict[str, Tuple[str, str]] = {}


def pallas_mode() -> str:
    """Normalized ``MXNET_PALLAS`` setting: 'auto' | 'on' | 'off'."""
    v = os.environ.get("MXNET_PALLAS", "auto").strip().lower()
    if v in ("", "auto", "default"):
        return "auto"
    if v in ("1", "on", "true", "yes", "force"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    return "auto"


def dispatch(kernel: str, supported: bool = True,
             reason: Optional[str] = None) -> Tuple[str, str]:
    """The three-tier dispatch decision for one kernel call site.

    Returns ``(path, reason)`` with path one of ``'pallas'`` (compiled
    TPU kernel), ``'interpret'`` (kernel body under
    ``pallas_call(interpret=True)``), ``'xla'`` (reference
    implementation). ``supported=False`` forces the XLA tier with the
    caller's ``reason`` (shape/mode the kernel does not cover) — the
    fallback is automatic, never an error."""
    import jax
    mode = pallas_mode()
    if not supported:
        out = ("xla", reason or "kernel does not cover this case")
    elif mode == "off":
        out = ("xla", "MXNET_PALLAS=off")
    else:
        backend = jax.default_backend()
        if backend == "tpu":
            out = ("pallas", f"MXNET_PALLAS={mode} on tpu")
        elif mode == "on":
            out = ("interpret",
                   f"MXNET_PALLAS=on, non-TPU backend ({backend}): "
                   "kernel body in interpret mode")
        else:
            out = ("xla", f"MXNET_PALLAS=auto, non-TPU backend "
                          f"({backend}): XLA reference")
    _DECISIONS[kernel] = out
    try:
        from ...telemetry import names as tn
        from ...telemetry import registry as treg
        treg().counter(tn.KERNEL_DISPATCH,
                       label_key="path").inc(label=out[0])
    except Exception:   # telemetry must never fail a kernel call
        pass
    return out


def decisions() -> Dict[str, Tuple[str, str]]:
    """Last dispatch decision per kernel: {name: (path, reason)}."""
    return dict(_DECISIONS)


def dispatch_table() -> Dict[str, str]:
    """Current {kernel: path} for every known kernel under the live
    env/backend — the BENCH json's per-leg ``kernel_path`` field (no
    decision is recorded; this is a pure read)."""
    import jax
    mode = pallas_mode()
    backend = jax.default_backend()
    if mode == "off":
        path = "xla"
    elif backend == "tpu":
        path = "pallas"
    elif mode == "on":
        path = "interpret"
    else:
        path = "xla"
    return {k: path for k in KERNELS}
