"""LayerNorm and bias-GELU forward+backward kernels (transformer/BERT).

The transformer block's normalization and activation epilogues are the
classic memory-bound kernels: XLA schedules LayerNorm as a multi-pass
reduce + elementwise chain and the FFN's bias-add + GELU as separate
fusions, each materializing a (tokens, hidden) intermediate to HBM.
These kernels stream a block of rows through VMEM once per pass:

- :func:`layer_norm` — f32 statistics over the trailing axis (same
  accumulation recipe as ops/nn.py ``layer_norm``), forward math
  mirrored expression-for-expression so the fp32 forward is bit-exact
  against the XLA reference for lane-aligned widths; custom-VJP
  backward computes dx in one kernel with dgamma/dbeta accumulated in
  VMEM across row blocks.
- :func:`bias_gelu` — exact (erf) GELU fused with the preceding bias
  add; the backward recomputes z = x + b and applies the closed-form
  dGELU(z) = Φ(z) + z·φ(z).

Widths that are not a multiple of the 128-lane tile are zero-padded
and the statistics masked to the true width (tolerance-level parity —
a padded reduction reassociates). Dispatch: the shared MXNET_PALLAS
gate (ops/kernels/__init__.py); ops/nn.py ``layer_norm`` and
gluon/nn/transformer.py ``PositionwiseFFN`` route through here.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from . import dispatch, vmem_tile_budget

__all__ = ["layer_norm", "bias_gelu", "norm_supported"]

_LANES = 128
_BLOCK_ROWS = 256


def _pad_to(n, m):
    return -(-n // m) * m


def _budget_rows(cp: int, n_tiles: int = 4) -> int:
    """Row-block cap from the SHARED VMEM tile budget
    (ops/kernels.vmem_tile_budget — the same accessor rnn_scan and
    attention size against): ``n_tiles`` concurrent (rows, cp) f32
    tiles (x, dy, dx + the output) must fit. At the default 4 MiB
    budget this only binds for very wide feature axes — the 256-row
    Mosaic-program cap stays the usual limit."""
    rows = vmem_tile_budget() // max(1, n_tiles * cp * 4)
    return max(8, (rows // 8) * 8)


def norm_supported(x, c: int) -> "str | None":
    """None when the kernels cover this call, else the reason the XLA
    reference handles it."""
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return f"dtype {x.dtype} not kernelized (f32/bf16 only)"
    if x.ndim < 2:
        return "expects at least 2 dims (rows, features)"
    if c < 1:
        return "empty feature axis"
    return None


def _rows_layout(x, c):
    """(..., C) → padded (Rp, Cp) plus the geometry."""
    r = 1
    for d in x.shape[:-1]:
        r *= int(d)
    cp = _pad_to(c, _LANES)
    sub = 16 if x.dtype == jnp.bfloat16 else 8
    block_r = min(_BLOCK_ROWS, max(sub, _budget_rows(cp)),
                  _pad_to(max(r, 1), sub))
    rp = _pad_to(max(r, 1), block_r)
    x2 = jnp.pad(x.reshape(r, c), ((0, rp - r), (0, cp - c)))
    return x2, r, rp, cp, block_r


def _col_valid(c, cp):
    if c == cp:
        return None
    return lax.broadcasted_iota(jnp.int32, (1, cp), 1) < c


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_stats(xf, c, valid):
    """mean/var over the trailing axis; the aligned path is literally
    the reference's jnp.mean/jnp.var so the forward stays bit-exact."""
    if valid is None:
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
    else:
        xm = jnp.where(valid, xf, 0.0)
        mean = jnp.sum(xm, axis=-1, keepdims=True) / c
        d = jnp.where(valid, xf - mean, 0.0)
        var = jnp.sum(d * d, axis=-1, keepdims=True) / c
    return mean, var


def _ln_fwd_kernel(eps, c, cp, x_ref, g_ref, b_ref, o_ref):
    xf = x_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(xf, c, _col_valid(c, cp))
    out = (xf - mean) * lax.rsqrt(var + eps)
    out = out * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)


def _ln_bwd_kernel(eps, c, cp, x_ref, g_ref, dy_ref, dx_ref, dg_ref,
                   db_ref, dg_s, db_s):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_s[...] = jnp.zeros_like(dg_s)
        db_s[...] = jnp.zeros_like(db_s)

    valid = _col_valid(c, cp)
    xf = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(xf, c, valid)
    rstd = lax.rsqrt(var + eps)
    xhat = (xf - mean) * rstd
    if valid is not None:
        xhat = jnp.where(valid, xhat, 0.0)
        dy = jnp.where(valid, dy, 0.0)
    dg_s[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_s[...] += jnp.sum(dy, axis=0, keepdims=True)
    dxhat = dy * g_ref[...].astype(jnp.float32)
    m1 = jnp.sum(dxhat, axis=-1, keepdims=True) / c
    m2 = jnp.sum(dxhat * xhat, axis=-1, keepdims=True) / c
    dx = rstd * (dxhat - m1 - xhat * m2)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dg_ref[...] = dg_s[...]
    db_ref[...] = db_s[...]


def _ln_call(x, gamma, beta, eps, interpret, bwd_dy=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    c = int(x.shape[-1])
    x2, r, rp, cp, block_r = _rows_layout(x, c)
    g2 = jnp.pad(gamma, (0, cp - c)).reshape(1, cp)
    blk = pl.BlockSpec((block_r, cp), lambda i: (i, 0))
    row1 = pl.BlockSpec((1, cp), lambda i: (0, 0))
    grid = (rp // block_r,)
    if bwd_dy is None:
        b2 = jnp.pad(beta, (0, cp - c)).reshape(1, cp)
        out = pl.pallas_call(
            functools.partial(_ln_fwd_kernel, eps, c, cp),
            grid=grid,
            in_specs=[blk, row1, row1],
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct((rp, cp), x.dtype),
            compiler_params=_params("parallel"),
            interpret=interpret,
        )(x2, g2, b2)
        return out[:r, :c].reshape(x.shape)
    dy2 = jnp.pad(bwd_dy.astype(x.dtype).reshape(r, c),
                  ((0, rp - r), (0, cp - c)))
    dx, dg, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps, c, cp),
        grid=grid,
        in_specs=[blk, row1, blk],
        out_specs=[blk, row1, row1],
        out_shape=[jax.ShapeDtypeStruct((rp, cp), x.dtype),
                   jax.ShapeDtypeStruct((1, cp), jnp.float32),
                   jax.ShapeDtypeStruct((1, cp), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, cp), jnp.float32),
                        pltpu.VMEM((1, cp), jnp.float32)],
        compiler_params=_params("arbitrary"),
        interpret=interpret,
    )(x2, g2, dy2)
    return (dx[:r, :c].reshape(x.shape),
            dg[0, :c].astype(gamma.dtype),
            db[0, :c].astype(gamma.dtype))


def _params(sem):
    from ..attention import _PLTPU_COMPILER_PARAMS
    return _PLTPU_COMPILER_PARAMS(dimension_semantics=(sem,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ln(eps, interpret, x, gamma, beta):
    return _ln_call(x, gamma, beta, eps, interpret)


def _ln_fwd(eps, interpret, x, gamma, beta):
    return _ln_call(x, gamma, beta, eps, interpret), (x, gamma)


def _ln_bwd(eps, interpret, res, dy):
    x, gamma = res
    return _ln_call(x, gamma, None, eps, interpret, bwd_dy=dy)


_ln.defvjp(_ln_fwd, _ln_bwd)


def layer_norm(x, gamma, beta, eps: float = 1e-5,
               interpret: bool = False):
    """Fused LayerNorm over the trailing axis (f32 statistics,
    activation-dtype output — the ops/nn.py recipe)."""
    return _ln(float(eps), interpret, x, gamma, beta)


# ---------------------------------------------------------------------------
# bias-GELU
# ---------------------------------------------------------------------------

_INV_SQRT2PI = 1.0 / math.sqrt(2.0 * math.pi)


def _bg_fwd_kernel(x_ref, b_ref, o_ref):
    z = x_ref[...] + b_ref[...]
    o_ref[...] = jax.nn.gelu(z, approximate=False).astype(o_ref.dtype)


def _bg_bwd_kernel(c, cp, x_ref, b_ref, dy_ref, dx_ref, db_ref, db_s):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        db_s[...] = jnp.zeros_like(db_s)

    z = (x_ref[...] + b_ref[...]).astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    # dGELU(z) = Phi(z) + z * phi(z) (exact-erf form)
    phi = jnp.exp(-0.5 * z * z) * _INV_SQRT2PI
    cdf = 0.5 * (1.0 + lax.erf(z / jnp.sqrt(jnp.float32(2.0))))
    dx = dy * (cdf + z * phi)
    valid = _col_valid(c, cp)
    if valid is not None:
        dx = jnp.where(valid, dx, 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    db_s[...] += jnp.sum(dx, axis=0, keepdims=True)
    db_ref[...] = db_s[...]


def _bg_call(x, b, interpret, bwd_dy=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    c = int(x.shape[-1])
    x2, r, rp, cp, block_r = _rows_layout(x, c)
    b2 = jnp.pad(b.astype(x.dtype), (0, cp - c)).reshape(1, cp)
    blk = pl.BlockSpec((block_r, cp), lambda i: (i, 0))
    row1 = pl.BlockSpec((1, cp), lambda i: (0, 0))
    grid = (rp // block_r,)
    if bwd_dy is None:
        out = pl.pallas_call(
            _bg_fwd_kernel,
            grid=grid,
            in_specs=[blk, row1],
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct((rp, cp), x.dtype),
            compiler_params=_params("parallel"),
            interpret=interpret,
        )(x2, b2)
        return out[:r, :c].reshape(x.shape)
    dy2 = jnp.pad(bwd_dy.astype(x.dtype).reshape(r, c),
                  ((0, rp - r), (0, cp - c)))
    dx, db = pl.pallas_call(
        functools.partial(_bg_bwd_kernel, c, cp),
        grid=grid,
        in_specs=[blk, row1, blk],
        out_specs=[blk, row1],
        out_shape=[jax.ShapeDtypeStruct((rp, cp), x.dtype),
                   jax.ShapeDtypeStruct((1, cp), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, cp), jnp.float32)],
        compiler_params=_params("arbitrary"),
        interpret=interpret,
    )(x2, b2, dy2)
    return dx[:r, :c].reshape(x.shape), db[0, :c].astype(b.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bg(interpret, x, b):
    return _bg_call(x, b, interpret)


def _bg_fwd(interpret, x, b):
    return _bg_call(x, b, interpret), (x, b)


def _bg_bwd(interpret, res, dy):
    x, b = res
    return _bg_call(x, b, interpret, bwd_dy=dy)


_bg.defvjp(_bg_fwd, _bg_bwd)


def bias_gelu(x, b, interpret: bool = False):
    """Fused ``gelu(x + b)`` (exact erf form, matching
    ``F.Activation(act_type='gelu')``) over the trailing axis."""
    return _bg(interpret, x, b)
