"""Time-fused recurrent scan kernel (LSTM / GRU / vanilla RNN).

The ``lax.scan`` reference in ops/rnn.py compiles into a while loop
whose body is scheduled as separate kernels: the h2h matmul, the gate
fusion and the carry update each round-trip (N, G*H) intermediates
through HBM every timestep, and the backward additionally saves the
per-step linearization residuals — stacked (T, N, G*H) tensors the
fusion census ranks as the worst boundary materializations of the LSTM
leg. This kernel is the whole-program-ownership move for the
recurrence: ONE Pallas program walks a block of timesteps with h (and
c) pinned in VMEM, the weights resident, and only x-projections in /
hidden states out touching HBM; the custom VJP re-derives the gates in
the backward from the saved hidden trajectory (one extra matmul per
step, FlashAttention-style recompute) instead of materializing
residuals.

Gate-order parity with ops/rnn.py (and src/operator/rnn_impl.h):
LSTM [i, f, g, o], GRU [r, z, n] — converted checkpoints drop in, and
the fp32 forward/backward are BIT-exact against the scan reference
(the gate math mirrors the reference expression for expression,
including the cotangent groupings jax's autodiff emits).

Layout: hidden padded to the 128-lane tile, batch to the dtype's
sublane tile, time to the block; gate blocks pad INDEPENDENTLY so gate
g still lives at rows ``[g*Hp, (g+1)*Hp)``. Padded tail timesteps need
no masking: zero-padded inputs keep the tail finite in the forward
(those rows are sliced off), and the reverse-time backward visits the
tail first with zero cotangents, so every tail contribution is an
exact zero.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import dispatch, vmem_tile_budget

__all__ = ["rnn_scan", "rnn_decode_step", "rnn_verify_scan",
           "scan_supported"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}
_MAX_BLOCK_T = 16      # unrolled in-kernel; bounds Mosaic program size

#: test hook: force a timestep-block size (None = auto). The grid-edge
#: tests use it to exercise multi-step blocks with tail padding under
#: interpret mode.
_FORCE_BLOCK_T = None


def _sublane(dtype) -> int:
    return 16 if dtype == jnp.bfloat16 else 8


def _pad_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _block_t(seq: int, np_: int, g: int, hp: int, itemsize: int,
             interpret: bool) -> int:
    """Timesteps per grid step. On TPU: the ``kernels.rnn_block_t``
    tunable when set (autotune override; 0 = auto), else sized so the
    CONCURRENT per-step tiles (xw in, ys/cs out, plus the backward's
    dys/dxw/hprev set — budgeted as ~2 gate-wide + 6 hidden-wide
    tiles) fit the shared VMEM tile budget (``vmem_tile_budget()`` —
    ops.attention._head_group sizes against the same accessor). In
    interpret mode: 1, so the grid loop mirrors the lax.scan
    reference's one-step body structure — that is what makes the fp32
    forward BIT-identical (XLA re-fuses a multi-step unrolled body
    differently, which costs a ulp)."""
    if _FORCE_BLOCK_T is not None:
        return int(min(_FORCE_BLOCK_T, max(1, seq)))
    if interpret:
        return 1
    from ...tuning import space as _tspace
    tuned = _tspace.value("kernels.rnn_block_t", 0)
    try:
        tuned = int(tuned)
    except (TypeError, ValueError):
        tuned = 0
    if tuned > 0:
        return int(min(tuned, _MAX_BLOCK_T, max(1, seq)))
    per_step = np_ * (2 * g * hp + 6 * hp) * itemsize
    bt = max(1, vmem_tile_budget() // max(1, per_step))
    return int(min(bt, _MAX_BLOCK_T, max(1, seq)))


def scan_supported(xw, h0, c0, mode: str) -> Optional[str]:
    """None when the kernel covers this call, else the fallback reason
    (the dispatch gate reports it; the XLA reference handles the call)."""
    if mode not in _GATES:
        return f"unknown mode {mode!r}"
    if xw.dtype not in (jnp.float32, jnp.bfloat16):
        return f"dtype {xw.dtype} not kernelized (f32/bf16 only)"
    if xw.ndim != 3 or xw.shape[0] < 1:
        return "expects (T, N, G*H) with T >= 1"
    return None


def _pad_gated(a, g: int, h: int, hp: int, axis: int):
    """Pad the gate-blocked axis (size g*h) to g*hp keeping gate g's
    block at [g*hp, (g+1)*hp)."""
    shape = a.shape
    split = shape[:axis] + (g, h) + shape[axis + 1:]
    pad = [(0, 0)] * (len(shape) + 1)
    pad[axis + 1] = (0, hp - h)
    out = jnp.pad(a.reshape(split), pad)
    return out.reshape(shape[:axis] + (g * hp,) + shape[axis + 1:])


# ---------------------------------------------------------------------------
# gate math — expression-for-expression mirror of ops/rnn.py _step_fns
# (forward) and of the cotangent chains jax emits for them (backward);
# any re-grouping here breaks fp32 bit parity with the scan reference
# ---------------------------------------------------------------------------

def _fwd_step(mode, xw_t, h, c, hw, b):
    """One timestep from precomputed hw = h @ w_hh.T. Returns (h, c)."""
    if mode == "lstm":
        gates = xw_t + hw + b
        hp = gates.shape[-1] // 4
        gi, gf, gg, go = (gates[:, k * hp:(k + 1) * hp] for k in range(4))
        i, f, o = (jax.nn.sigmoid(gi), jax.nn.sigmoid(gf),
                   jax.nn.sigmoid(go))
        g = jnp.tanh(gg)
        c_new = f * c + i * g
        return o * jnp.tanh(c_new), c_new
    if mode == "gru":
        hwb = hw + b
        hp = hwb.shape[-1] // 3
        xr, xz, xn = (xw_t[:, k * hp:(k + 1) * hp] for k in range(3))
        hr, hz, hn = (hwb[:, k * hp:(k + 1) * hp] for k in range(3))
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1.0 - z) * n + z * h, None
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu
    return act(xw_t + hw + b), None


def _dtanh(t, y):
    """Cotangent through tanh with saved output y, in the exact form
    jax's tanh rule emits — u = t·(1−y); u + u·y — NOT t·(1−y²):
    the two differ in the last ulp and would break bit parity."""
    u = t * (1.0 - y)
    return u + u * y


def _dsigmoid(t, s):
    """Cotangent through logistic with saved output s (jax's form:
    t · (s·(1−s)))."""
    return t * (s * (1.0 - s))


def _bwd_step(mode, xw_t, h_prev, c_prev, c_new, y, hw, b, dy,
              dh_carry, dc_carry):
    """One reverse timestep. Returns (dgates→dxw, dhw-for-weight-grads,
    dh_carry', dc_carry')."""
    dh = dy + dh_carry
    if mode == "lstm":
        gates = xw_t + hw + b
        hp = gates.shape[-1] // 4
        gi, gf, gg, go = (gates[:, k * hp:(k + 1) * hp] for k in range(4))
        i, f, o = (jax.nn.sigmoid(gi), jax.nn.sigmoid(gf),
                   jax.nn.sigmoid(go))
        g = jnp.tanh(gg)
        tc = jnp.tanh(c_new)
        # the scan transpose interleaves the carry add INSIDE the tanh
        # chain: dc = (dc_carry + u) + u*tc — associativity is not
        # bit-free, so mirror the grouping exactly
        u = (dh * o) * (1.0 - tc)
        dc = dc_carry + u + u * tc
        dgi = _dsigmoid(dc * g, i)
        dgf = _dsigmoid(dc * c_prev, f)
        dgg = _dtanh(dc * i, g)
        dgo = _dsigmoid(dh * tc, o)
        dgates = jnp.concatenate([dgi, dgf, dgg, dgo], axis=-1)
        return dgates, dgates, None, dc * f
    if mode == "gru":
        hwb = hw + b
        hp = hwb.shape[-1] // 3
        xr, xz, xn = (xw_t[:, k * hp:(k + 1) * hp] for k in range(3))
        hr, hz, hn = (hwb[:, k * hp:(k + 1) * hp] for k in range(3))
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        dz = dh * h_prev - dh * n
        dn_pre = _dtanh(dh * (1.0 - z), n)
        dr = dn_pre * hn
        dhn = dn_pre * r
        dr_pre = _dsigmoid(dr, r)
        dz_pre = _dsigmoid(dz, z)
        dxw = jnp.concatenate([dr_pre, dz_pre, dn_pre], axis=-1)
        dhw = jnp.concatenate([dr_pre, dz_pre, dhn], axis=-1)
        return dxw, dhw, dh * z, None
    if mode == "rnn_tanh":
        dpre = _dtanh(dh, y)
    else:
        dpre = jnp.where(y > 0, dh, jnp.zeros_like(dh))
    return dpre, dpre, None, None


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(mode, block_t, *refs):
    from jax.experimental import pallas as pl
    lstm = mode == "lstm"
    if lstm:
        (xw_ref, h0_ref, c0_ref, w_ref, b_ref, ys_ref, cs_ref,
         h_s, c_s) = refs
    else:
        xw_ref, h0_ref, w_ref, b_ref, ys_ref, h_s = refs
        c0_ref = cs_ref = c_s = None

    @pl.when(pl.program_id(0) == 0)
    def _init():
        h_s[...] = h0_ref[...]
        if lstm:
            c_s[...] = c0_ref[...]

    w = w_ref[...]                          # (G*Hp, Hp), resident
    b = b_ref[...]                          # (1, G*Hp)
    for i in range(block_t):
        h = h_s[...]
        hw = lax.dot_general(h, w, (((1,), (1,)), ((), ())))
        h_new, c_new = _fwd_step(mode, xw_ref[i], h,
                                 c_s[...] if lstm else None, hw, b)
        h_s[...] = h_new
        ys_ref[i] = h_new
        if lstm:
            c_s[...] = c_new
            cs_ref[i] = c_new


def _bwd_kernel(mode, block_t, nt, seq, *refs):
    from jax.experimental import pallas as pl
    lstm = mode == "lstm"
    if lstm:
        (xw_ref, hp_ref, cp_ref, cs_ref, w_ref, b_ref, dy_ref, dct_ref,
         dxw_ref, dh0_ref, dc0_ref, dw_ref, db_ref,
         dh_s, dc_s, dw_s, db_s) = refs
        ys_ref = None
    else:
        (xw_ref, hp_ref, ys_ref, w_ref, b_ref, dy_ref,
         dxw_ref, dh0_ref, dw_ref, db_ref, dh_s, dw_s, db_s) = refs
        cp_ref = cs_ref = dct_ref = dc0_ref = dc_s = None

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dh_s[...] = jnp.zeros_like(dh_s)
        dw_s[...] = jnp.zeros_like(dw_s)
        db_s[...] = jnp.zeros_like(db_s)
        if lstm:
            dc_s[...] = jnp.zeros_like(dc_s)

    w = w_ref[...]
    b = b_ref[...]
    for i in reversed(range(block_t)):
        h_prev = hp_ref[i]
        hw = lax.dot_general(h_prev, w, (((1,), (1,)), ((), ())))
        dc_in = None
        if lstm:
            # c_T's cotangent seeds the reverse carry exactly at step
            # seq-1 (the scan transpose's init carry); padded tail
            # steps (t >= seq, walked first) keep the zero carry so
            # every tail contribution stays an exact zero
            t_idx = (nt - 1 - pl.program_id(0)) * block_t + i
            dc_in = jnp.where(t_idx == seq - 1, dct_ref[...],
                              dc_s[...])
        dxw, dhw, dh_dir, dc_new = _bwd_step(
            mode, xw_ref[i], h_prev,
            cp_ref[i] if lstm else None,
            cs_ref[i] if lstm else None,
            ys_ref[i] if ys_ref is not None else None,
            hw, b, dy_ref[i],
            dh_s[...], dc_in)
        dxw_ref[i] = dxw.astype(dxw_ref.dtype)
        # dh through the h2h matmul: dgates @ W (contract gate dim)
        dh_mat = lax.dot_general(dhw, w, (((1,), (0,)), ((), ())))
        dh_s[...] = dh_dir + dh_mat if dh_dir is not None else dh_mat
        if lstm:
            dc_s[...] = dc_new
        dw_s[...] += lax.dot_general(dhw, h_prev,
                                     (((0,), (0,)), ((), ())))
        db_s[...] += jnp.sum(dhw, axis=0, keepdims=True)

    dh0_ref[...] = dh_s[...].astype(dh0_ref.dtype)
    dw_ref[...] = dw_s[...].astype(dw_ref.dtype)
    db_ref[...] = db_s[...].astype(db_ref.dtype)
    if lstm:
        dc0_ref[...] = dc_s[...].astype(dc0_ref.dtype)


def _compiler_params():
    from ..attention import _PLTPU_COMPILER_PARAMS
    return _PLTPU_COMPILER_PARAMS(dimension_semantics=("arbitrary",))


def _padded_operands(xw, h0, c0, w_hh, b_hh, mode, interpret):
    t, n, gh = xw.shape
    g = _GATES[mode]
    h = gh // g
    hp = _pad_to(h, 128)
    np_ = _pad_to(n, _sublane(xw.dtype))
    bt = _block_t(t, np_, g, hp, jnp.dtype(xw.dtype).itemsize,
                  interpret)
    tp = _pad_to(t, bt)
    xw_p = _pad_gated(jnp.pad(xw, ((0, tp - t), (0, np_ - n), (0, 0))),
                      g, h, hp, axis=2)
    w_p = jnp.pad(w_hh.reshape(g, h, h),
                  ((0, 0), (0, hp - h), (0, hp - h))).reshape(g * hp, hp)
    b_p = _pad_gated(b_hh, g, h, hp, axis=0).reshape(1, g * hp)
    h0_p = jnp.pad(h0, ((0, np_ - n), (0, hp - h)))
    c0_p = jnp.pad(c0, ((0, np_ - n), (0, hp - h))) \
        if c0 is not None else None
    return xw_p, h0_p, c0_p, w_p, b_p, (t, n, g, h, hp, np_, bt, tp)


def _scan_fwd_pallas(xw, h0, c0, w_hh, b_hh, mode, interpret):
    """→ padded (ys_p[, cs_p]) plus the geometry; callers slice."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    xw_p, h0_p, c0_p, w_p, b_p, geo = _padded_operands(
        xw, h0, c0, w_hh, b_hh, mode, interpret)
    t, n, g, h, hp, np_, bt, tp = geo
    lstm = mode == "lstm"
    dt = xw.dtype

    tspec = pl.BlockSpec((bt, np_, g * hp), lambda k: (k, 0, 0))
    ospec = pl.BlockSpec((bt, np_, hp), lambda k: (k, 0, 0))
    full2 = lambda shape: pl.BlockSpec(shape, lambda k: (0, 0))
    in_specs = [tspec, full2((np_, hp))]
    operands = [xw_p, h0_p]
    if lstm:
        in_specs.append(full2((np_, hp)))
        operands.append(c0_p)
    in_specs += [full2((g * hp, hp)), full2((1, g * hp))]
    operands += [w_p, b_p]
    out_specs = [ospec] + ([ospec] if lstm else [])
    out_shape = [jax.ShapeDtypeStruct((tp, np_, hp), dt)] * (
        2 if lstm else 1)
    scratch = [pltpu.VMEM((np_, hp), dt)] + \
        ([pltpu.VMEM((np_, hp), dt)] if lstm else [])
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel, mode, bt),
        grid=(tp // bt,),
        in_specs=in_specs, out_specs=out_specs, out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands)
    return list(outs), geo


def _scan_bwd_pallas(res, dys, dct, mode, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    xw, h0, c0, w_hh, b_hh, ys_p, cs_p = res
    xw_p, h0_p, c0_p, w_p, b_p, geo = _padded_operands(
        xw, h0, c0, w_hh, b_hh, mode, interpret)
    t, n, g, h, hp, np_, bt, tp = geo
    lstm = mode == "lstm"
    dt = xw.dtype
    nt = tp // bt

    # hidden/cell trajectories shifted one step: hprev[t] = h_{t-1}
    hp_arr = jnp.concatenate([h0_p[None], ys_p[:-1]], axis=0)
    dys_p = jnp.pad(dys.astype(dt),
                    ((0, tp - t), (0, np_ - n), (0, hp - h)))
    if lstm:
        cp_arr = jnp.concatenate([c0_p[None], cs_p[:-1]], axis=0)
        dct_p = jnp.pad(dct.astype(dt), ((0, np_ - n), (0, hp - h)))

    # reverse-time grid: grid step k walks time block nt-1-k
    rts = pl.BlockSpec((bt, np_, g * hp), lambda k: (nt - 1 - k, 0, 0))
    rhs = pl.BlockSpec((bt, np_, hp), lambda k: (nt - 1 - k, 0, 0))
    full2 = lambda shape: pl.BlockSpec(shape, lambda k: (0, 0))

    if lstm:
        in_specs = [rts, rhs, rhs, rhs, full2((g * hp, hp)),
                    full2((1, g * hp)), rhs, full2((np_, hp))]
        operands = [xw_p, hp_arr, cp_arr, cs_p, w_p, b_p, dys_p, dct_p]
    else:
        in_specs = [rts, rhs, rhs, full2((g * hp, hp)),
                    full2((1, g * hp)), rhs]
        operands = [xw_p, hp_arr, ys_p, w_p, b_p, dys_p]
    out_specs = [rts, full2((np_, hp))] + \
        ([full2((np_, hp))] if lstm else []) + \
        [full2((g * hp, hp)), full2((1, g * hp))]
    out_shape = [jax.ShapeDtypeStruct((tp, np_, g * hp), dt),
                 jax.ShapeDtypeStruct((np_, hp), dt)] + \
        ([jax.ShapeDtypeStruct((np_, hp), dt)] if lstm else []) + \
        [jax.ShapeDtypeStruct((g * hp, hp), w_hh.dtype),
         jax.ShapeDtypeStruct((1, g * hp), b_hh.dtype)]
    scratch = [pltpu.VMEM((np_, hp), jnp.float32)] + \
        ([pltpu.VMEM((np_, hp), jnp.float32)] if lstm else []) + \
        [pltpu.VMEM((g * hp, hp), jnp.float32),
         pltpu.VMEM((1, g * hp), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, mode, bt, nt, t),
        grid=(nt,),
        in_specs=in_specs, out_specs=out_specs, out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*operands)
    if lstm:
        dxw_p, dh0_p, dc0_p, dw_p, db_p = outs
    else:
        dxw_p, dh0_p, dw_p, db_p = outs
        dc0_p = None
    dxw = dxw_p.reshape(tp, np_, g, hp)[:t, :n, :, :h].reshape(
        t, n, g * h)
    dh0 = dh0_p[:n, :h]
    dc0 = dc0_p[:n, :h] if dc0_p is not None else None
    dw = dw_p.reshape(g, hp, hp)[:, :h, :h].reshape(g * h, h)
    db = db_p.reshape(g, hp)[:, :h].reshape(g * h)
    return dxw, dh0, dc0, dw, db


# ---------------------------------------------------------------------------
# custom-VJP wrappers (one per carry family)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _scan_lstm(mode, interpret, xw, h0, c0, w_hh, b_hh):
    """→ (ys, c_T). Returning the FINAL cell state (not the full cell
    trajectory) keeps the backward's dc chain structurally identical to
    the scan transpose's carry — the full trajectory stays an internal
    residual only."""
    outs, geo = _scan_fwd_pallas(xw, h0, c0, w_hh, b_hh, mode, interpret)
    t, n, h = geo[0], geo[1], geo[3]
    return outs[0][:t, :n, :h], outs[1][t - 1, :n, :h]


def _scan_lstm_fwd(mode, interpret, xw, h0, c0, w_hh, b_hh):
    outs, geo = _scan_fwd_pallas(xw, h0, c0, w_hh, b_hh, mode, interpret)
    t, n, h = geo[0], geo[1], geo[3]
    ys_p, cs_p = outs
    return ((ys_p[:t, :n, :h], cs_p[t - 1, :n, :h]),
            (xw, h0, c0, w_hh, b_hh, ys_p, cs_p))


def _scan_lstm_bwd(mode, interpret, res, cots):
    dys, dct = cots
    return _scan_bwd_pallas(res, dys, dct, mode, interpret)


_scan_lstm.defvjp(_scan_lstm_fwd, _scan_lstm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _scan_noc(mode, interpret, xw, h0, w_hh, b_hh):
    outs, geo = _scan_fwd_pallas(xw, h0, None, w_hh, b_hh, mode,
                                 interpret)
    t, n, h = geo[0], geo[1], geo[3]
    return outs[0][:t, :n, :h]


def _scan_noc_fwd(mode, interpret, xw, h0, w_hh, b_hh):
    outs, geo = _scan_fwd_pallas(xw, h0, None, w_hh, b_hh, mode,
                                 interpret)
    t, n, h = geo[0], geo[1], geo[3]
    return outs[0][:t, :n, :h], (xw, h0, None, w_hh, b_hh, outs[0],
                                 None)


def _scan_noc_bwd(mode, interpret, res, dys):
    dxw, dh0, _, dw, db = _scan_bwd_pallas(res, dys, None, mode,
                                           interpret)
    return dxw, dh0, dw, db


_scan_noc.defvjp(_scan_noc_fwd, _scan_noc_bwd)


# ---------------------------------------------------------------------------
# single-step decode kernel (the T=1 / block_t=1 variant)
# ---------------------------------------------------------------------------

def _decode_kernel(mode, *refs):
    lstm = mode == "lstm"
    if lstm:
        xw_ref, h0_ref, c0_ref, w_ref, b_ref, hy_ref, cy_ref = refs
    else:
        xw_ref, h0_ref, w_ref, b_ref, hy_ref = refs
        c0_ref = cy_ref = None
    # everything VMEM-resident for the whole call: h (and c), the h2h
    # weights and bias — one matmul + gate fusion, zero HBM round-trips
    # between them (the per-token analogue of the scan kernel's block)
    h = h0_ref[...]
    w = w_ref[...]
    b = b_ref[...]
    hw = lax.dot_general(h, w, (((1,), (1,)), ((), ())))
    h_new, c_new = _fwd_step(mode, xw_ref[...], h,
                             c0_ref[...] if lstm else None, hw, b)
    hy_ref[...] = h_new
    if lstm:
        cy_ref[...] = c_new


def _decode_pallas(xw, h, c, w_hh, b_hh, mode, interpret):
    from jax.experimental import pallas as pl
    n, gh = xw.shape
    g = _GATES[mode]
    hdim = gh // g
    hp = _pad_to(hdim, 128)
    np_ = _pad_to(n, _sublane(xw.dtype))
    xw_p = _pad_gated(jnp.pad(xw, ((0, np_ - n), (0, 0))),
                      g, hdim, hp, axis=1)
    w_p = jnp.pad(w_hh.reshape(g, hdim, hdim),
                  ((0, 0), (0, hp - hdim),
                   (0, hp - hdim))).reshape(g * hp, hp)
    b_p = _pad_gated(b_hh, g, hdim, hp, axis=0).reshape(1, g * hp)
    h_p = jnp.pad(h, ((0, np_ - n), (0, hp - hdim)))
    lstm = mode == "lstm"
    dt = xw.dtype
    full = lambda shape: pl.BlockSpec(shape, lambda: (0, 0))
    in_specs = [full((np_, g * hp)), full((np_, hp))]
    operands = [xw_p, h_p]
    if lstm:
        in_specs.append(full((np_, hp)))
        operands.append(jnp.pad(c, ((0, np_ - n), (0, hp - hdim))))
    in_specs += [full((g * hp, hp)), full((1, g * hp))]
    operands += [w_p, b_p]
    out_specs = [full((np_, hp))] + ([full((np_, hp))] if lstm else [])
    out_shape = [jax.ShapeDtypeStruct((np_, hp), dt)] * (2 if lstm
                                                         else 1)
    outs = pl.pallas_call(
        functools.partial(_decode_kernel, mode),
        in_specs=in_specs, out_specs=out_specs, out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    h_new = outs[0][:n, :hdim]
    c_new = outs[1][:n, :hdim] if lstm else None
    return h_new, c_new


def decode_supported(xw, h, c, mode: str) -> Optional[str]:
    """None when the decode-step kernel covers this call, else the
    fallback reason."""
    if mode not in _GATES:
        return f"unknown mode {mode!r}"
    if xw.dtype not in (jnp.float32, jnp.bfloat16):
        return f"dtype {xw.dtype} not kernelized (f32/bf16 only)"
    if xw.ndim != 2:
        return "expects (N, G*H) — one timestep per call"
    return None


def rnn_decode_step(xw, h, c, w_hh, b_hh, mode: str):
    """ONE recurrence step from a precomputed input projection ``xw``
    (N, G*H) — the autoregressive-decode variant of :func:`rnn_scan`
    (T = 1, block_t = 1): h (and c for LSTM) plus the h2h weights live
    in VMEM for the whole call, so a decode iteration costs one fused
    kernel instead of a scan prologue over a length-1 sequence.

    Dispatches through the shared MXNET_PALLAS gate; the XLA reference
    path is the SAME ``_fwd_step`` gate math the scan reference uses,
    so a token decoded step-by-step is bit-identical to the same token
    position inside a full :func:`rnn_scan` (tier-1 pins it). Returns
    ``(h_new, c_new|None)``; no VJP — decode is inference-only.
    """
    why = decode_supported(xw, h, c, mode)
    path, _ = dispatch("rnn_decode_step", supported=why is None,
                       reason=why)
    if path == "xla":
        hw = lax.dot_general(h, w_hh, (((1,), (1,)), ((), ())))
        return _fwd_step(mode, xw, h, c, hw, b_hh)
    return _decode_pallas(xw, h, c, w_hh, b_hh, mode,
                          path == "interpret")


def rnn_verify_scan(xw, h, c, w_hh, b_hh, mode: str, valid):
    """Masked multi-position scan for speculative-decode verification
    (serving/decode.py): run the SAME single-step cell as
    :func:`rnn_decode_step` over K candidate positions ``xw`` (K, N,
    G*H), bit-preserving the carry wherever ``valid`` (K, N) is False,
    and return the full per-position state TRAJECTORIES ``(hs, cs)``
    (each (K, N, H); ``cs`` None for non-LSTM modes) — the verifier
    needs the state AT EVERY position so acceptance can roll the carry
    back to the last accepted draft. The dispatch decision (Pallas
    decode kernel vs the XLA ``_fwd_step`` reference) is made ONCE and
    the chosen single-step body scans, so each position's math is
    bit-identical to the step :func:`rnn_decode_step` would run —
    parity with plain decode is by construction.
    """
    why = decode_supported(xw[0], h, c, mode)
    path, _ = dispatch("rnn_decode_step", supported=why is None,
                       reason=why)
    lstm = mode == "lstm"
    valid = jnp.asarray(valid)

    def body(carry, inp):
        h, c = carry
        xw_t, v_t = inp
        if path == "xla":
            hw = lax.dot_general(h, w_hh, (((1,), (1,)), ((), ())))
            h2, c2 = _fwd_step(mode, xw_t, h, c, hw, b_hh)
        else:
            h2, c2 = _decode_pallas(xw_t, h, c, w_hh, b_hh, mode,
                                    path == "interpret")
        vm = v_t[:, None]
        h = jnp.where(vm, h2, h)
        c = jnp.where(vm, c2, c) if lstm else None
        return (h, c), (h, c)

    (_, _), (hs, cs) = lax.scan(body, (h, c if lstm else None),
                                (xw, valid))
    return hs, cs


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def rnn_scan(xw, h0, c0, w_hh, b_hh, mode: str, reverse: bool = False):
    """The recurrence of one RNN direction from precomputed input
    projections: ``xw`` (T, N, G*H) = x @ W_ih^T + b_ih.

    Dispatches through the MXNET_PALLAS gate: Pallas kernel on TPU,
    interpret-mode kernel when forced on non-TPU backends, else the
    ``lax.scan`` XLA reference (ops/rnn.py ``scan_reference``) — the
    two paths are fp32 bit-identical by construction (tests pin it).
    Returns ``(ys, h_T, c_T|None)`` with ys in forward time order.
    """
    why = scan_supported(xw, h0, c0, mode)
    path, _ = dispatch("rnn_scan", supported=why is None, reason=why)
    if path == "xla":
        from ..rnn import scan_reference
        return scan_reference(xw, h0, c0, w_hh, b_hh, mode,
                              reverse=reverse)
    interpret = path == "interpret"
    if reverse:
        # flip-scan-flip ≡ lax.scan(reverse=True): identical op
        # sequence, pure data movement around it
        xw = jnp.flip(xw, axis=0)
    if mode == "lstm":
        ys, c_t = _scan_lstm(mode, interpret, xw, h0, c0, w_hh, b_hh)
        h_t = ys[-1]
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return ys, h_t, c_t
    ys = _scan_noc(mode, interpret, xw, h0, w_hh, b_hh)
    h_t = ys[-1]
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, h_t, None
