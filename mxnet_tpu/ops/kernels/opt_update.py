"""Fused multi-tensor optimizer-update kernel (SGD-momentum, Adam).

Under the ZeRO-1 sharded weight update (gluon/fused_step.py) every
parameter update is an ELEMENTWISE rule over a flat padded 1/N shard —
a bucket unit already fuses many small parameters into one buffer with
per-element lr/wd/t vectors (``Optimizer.pack_shard_hparams``). XLA
schedules that update as a chain of small elementwise kernels
interleaved with the state buffers' HBM traffic; this kernel instead
streams ``w, g, m[, v]`` through VMEM ONCE per block and applies the
whole rule (rescale → clip → wd → moments → bias correction → step) in
registers — the reference's multi-tensor ``multi_sgd_mom_update`` /
``multi_adam_update`` discipline (src/operator/optimizer_op.cc) on the
TPU.

The rule bodies mirror ``optimizer.py``'s ``_rule()`` expressions
term for term, and the flat buffers are only reshaped to the (rows,
128) lane layout — elementwise math is shape-independent, so the
kernel path is BIT-exact against the XLA elementwise update
(tests/test_kernels.py pins sgd-mom and adam at dp=4).

Dispatch: the shared MXNET_PALLAS gate (see ops/kernels/__init__.py).
Only exact SGD/Adam instances kernelize — subclasses may override the
rule, so they (and every other optimizer) keep the XLA path.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from . import dispatch, vmem_tile_budget

__all__ = ["kernel_step_fn", "unit_update", "opt_kernel_kind"]

_LANES = 128
_BLOCK_ROWS = 256            # (256, 128) f32 blocks = 128 KiB per ref


def _block_rows_cap() -> int:
    """Row-block cap from the SHARED VMEM tile budget (the accessor
    rnn_scan/attention/norm also size against): up to ~8 concurrent
    (rows, 128) f32 tiles live at once (w, g, m, v, the outputs, the
    per-element hparam vectors). At the default 4 MiB budget the
    256-row Mosaic cap stays the binding limit."""
    rows = vmem_tile_budget() // max(1, 8 * _LANES * 4)
    return min(_BLOCK_ROWS, max(8, (rows // 8) * 8))


def _pad2d(flat, rows, dtype=None, fill=0):
    """(P,) → (rows, 128) zero-padded lane layout."""
    p = int(flat.shape[0])
    total = rows * _LANES
    if p != total:
        flat = jnp.pad(flat, (0, total - p), constant_values=fill)
    out = flat.reshape(rows, _LANES)
    return out.astype(dtype) if dtype is not None else out


def _state_body(kind, cfg, w, g, lr, wd, t, rescale, clip):
    """New optimizer state from loaded blocks (the rule's state half;
    ``lr`` folds into SGD's momentum buffer exactly as in _rule)."""
    g = g * rescale
    if cfg["has_clip"]:
        g = jnp.clip(g, -clip, clip)
    g = g + wd * w
    if kind == "sgd":
        (m,) = cfg["states"]
        return (cfg["momentum"] * m - lr * g,)
    b1, b2 = cfg["beta1"], cfg["beta2"]
    m, v = cfg["states"]
    return (b1 * m + (1 - b1) * g, b2 * v + (1 - b2) * g * g)


def _weight_body(kind, cfg, w, new_states, g, lr, wd, t, rescale,
                 clip):
    """New weight from the NEW state values (plus the prepared grad
    for stateless SGD)."""
    if kind == "sgd":
        if cfg["momentum"] == 0.0:
            g = g * rescale
            if cfg["has_clip"]:
                g = jnp.clip(g, -clip, clip)
            g = g + wd * w
            return w - lr * g
        (m,) = new_states
        return w + m
    b1, b2, eps = cfg["beta1"], cfg["beta2"], cfg["epsilon"]
    m, v = new_states
    mhat = m / (1 - b1 ** t)
    vhat = v / (1 - b2 ** t)
    return w - lr * mhat / (jnp.sqrt(vhat) + eps)


def _rule_body(kind, cfg, w, g, lr, wd, t, rescale, clip):
    """The fused rule (state + weight halves composed in-register) —
    the single-kernel TPU path."""
    if kind == "sgd" and cfg["momentum"] == 0.0:
        return _weight_body(kind, cfg, w, (), g, lr, wd, t, rescale,
                            clip), ()
    new_states = _state_body(kind, cfg, w, g, lr, wd, t, rescale, clip)
    return _weight_body(kind, cfg, w, new_states, g, lr, wd, t,
                        rescale, clip), new_states


def _opt_kernel(kind, cfg, vec, n_states, part, *refs):
    """``part`` is 'fused' today (one kernel, both outputs); the
    'state'/'weight' halves exist for callers that want the two-pass
    form. Note on the last ulp: XLA may DUPLICATE the state
    expression into the weight-output fusion and fp-contract the copy
    differently (it eliminates optimization barriers on the CPU
    backend, so the duplication is not preventable in-program) — the
    stored states are always bit-exact vs the XLA reference chain;
    the weight can sit 1 ulp from `w ± <stored state math>` under
    GSPMD partitioning. tests/test_kernels.py pins exactly this
    contract."""
    refs = list(refs)
    w_ref, g_ref = refs[0], refs[1]
    state_refs = refs[2:2 + n_states]
    lr_ref, wd_ref, t_ref, rs_ref, clip_ref = refs[2 + n_states:
                                                   7 + n_states]
    out_refs = refs[7 + n_states:]
    if vec:
        lr, wd, t = lr_ref[...], wd_ref[...], t_ref[...]
    else:
        lr, wd, t = lr_ref[0, 0], wd_ref[0, 0], t_ref[0, 0]
    states = tuple(s[...] for s in state_refs)
    body_cfg = dict(cfg, states=states)
    args = (w_ref[...], g_ref[...], lr, wd, t, rs_ref[0, 0],
            clip_ref[0, 0])
    if part == "fused":
        new_w, new_states = _rule_body(kind, body_cfg, *args)
        out_refs[0][...] = new_w.astype(out_refs[0].dtype)
        for o, s in zip(out_refs[1:], new_states):
            o[...] = s.astype(o.dtype)
    elif part == "state":
        for o, s in zip(out_refs, _state_body(kind, body_cfg, *args)):
            o[...] = s.astype(o.dtype)
    else:
        # 'weight': the state slots hold the NEW states
        new_w = _weight_body(kind, body_cfg, args[0], states, args[1],
                             lr, wd, t, args[5], args[6])
        out_refs[0][...] = new_w.astype(out_refs[0].dtype)


def unit_update(kind: str, cfg: dict, w, g, lr, wd, t, rescale, clip,
                states, interpret: bool):
    """One flat unit (a whole parameter's shard or a fused bucket
    shard) through the Pallas update kernel. ``lr``/``wd``/``t`` are
    scalars or per-element (P,) vectors (``pack_shard_hparams``).
    Returns ``(new_w, new_states)`` shaped like the inputs."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p = int(w.shape[0])
    rows = -(-p // _LANES)
    block_r = min(_block_rows_cap(), -(-rows // 8) * 8)
    rows = -(-rows // block_r) * block_r
    grid = rows // block_r
    vec = getattr(lr, "ndim", 0) >= 1

    wdt = w.dtype
    w2 = _pad2d(w, rows)
    g2 = _pad2d(jnp.asarray(g, wdt), rows)
    st2 = tuple(_pad2d(s, rows) for s in states)

    blk = pl.BlockSpec((block_r, _LANES), lambda i: (i, 0))
    smem = pl.BlockSpec((1, 1), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)
    as11 = lambda v, dt: jnp.asarray(v, dt).reshape(1, 1)

    in_specs = [blk, blk] + [blk] * len(st2)
    if vec:
        in_specs += [blk, blk, blk]
        # pad tail gets lr=wd=0 / t=1: the pack_shard_hparams pad
        # convention — keeps Adam's 1/(1-beta**t) finite on padding
        hparams = [_pad2d(jnp.asarray(lr, jnp.float32), rows),
                   _pad2d(jnp.asarray(wd, jnp.float32), rows),
                   _pad2d(jnp.asarray(t, jnp.int32), rows, fill=1)]
    else:
        in_specs += [smem, smem, smem]
        hparams = [as11(lr, jnp.float32), as11(wd, jnp.float32),
                   as11(t, jnp.int32)]
    in_specs += [smem, smem]
    hparams += [as11(rescale, jnp.float32), as11(clip, jnp.float32)]

    n_out = 1 + len(st2)
    outs = pl.pallas_call(
        functools.partial(_opt_kernel, kind, cfg, vec, len(st2),
                          "fused"),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[blk] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), wdt)] * n_out,
        compiler_params=_parallel_params(),
        interpret=interpret,
    )(w2, g2, *st2, *hparams)
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    new_w = outs[0].reshape(-1)[:p]
    new_states = tuple(o.reshape(-1)[:p] for o in outs[1:])
    return new_w, new_states


def _parallel_params():
    from ..attention import _PLTPU_COMPILER_PARAMS
    return _PLTPU_COMPILER_PARAMS(dimension_semantics=("parallel",))


def opt_kernel_kind(opt) -> Optional[tuple]:
    """(kind, cfg) when ``opt`` is an EXACT SGD/Adam instance (a
    subclass may override the rule), else None."""
    from ...optimizer.optimizer import SGD, Adam
    if type(opt) is SGD:
        return "sgd", {"momentum": float(opt.momentum),
                       "has_clip": opt.clip_gradient is not None}
    if type(opt) is Adam:
        return "adam", {"beta1": float(opt.beta1),
                        "beta2": float(opt.beta2),
                        "epsilon": float(opt.epsilon),
                        "has_clip": opt.clip_gradient is not None}
    return None


def kernel_step_fn(opt):
    """A drop-in for ``Optimizer.fused_step_fn`` routing every flat
    unit through the Pallas update kernel — or None when the gate
    picks XLA / the optimizer is not kernelized. Only valid for FLAT
    (1-d) units, i.e. the ZeRO shard layout."""
    kk = opt_kernel_kind(opt)
    path, _ = dispatch(
        "opt_update", supported=kk is not None,
        reason=None if kk else
        f"{type(opt).__name__} update rule is not kernelized "
        "(exact SGD/Adam only)")
    if path == "xla":
        return None
    kind, cfg = kk
    interpret = path == "interpret"

    def stepfn(ws, gs, lrs, wds, ts, rescale, clip, states):
        new_ws, new_ss = [], []
        for i, (w, g, st) in enumerate(zip(ws, gs, states)):
            nw, ns = unit_update(kind, cfg, w, g, lrs[i], wds[i],
                                 ts[i], rescale, clip, st, interpret)
            new_ws.append(nw)
            new_ss.append(ns)
        return tuple(new_ws), tuple(new_ss)

    return stepfn
