"""Mixture-of-Experts FFN with expert parallelism (EP).

No reference analog — the reference has no MoE or expert parallelism
(SURVEY §2.3: TP/PP/EP/SP/CP absent); this is a TPU-native extension in the
same spirit as ring attention (ops/attention.py): the idiomatic scale-out
answer for sparse-expert models.

Design (the standard TPU MoE recipe — GShard/Switch style):
- gating: softmax router, top-k expert choice per token, capacity-bounded
  dispatch (capacity = factor * tokens * k / num_experts). Tokens beyond an
  expert's capacity are dropped (their combine weight is zero), keeping all
  shapes static for XLA.
- dense path: dispatch/combine as one-hot einsums onto (E, C, d) buffers,
  experts run as ONE batched einsum over the expert dimension — MXU-friendly,
  no scalar loops.
- EP path (``axis_name``): experts sharded over an 'ep' mesh axis inside
  shard_map. Each device routes its local tokens to ALL experts, then a
  ``lax.all_to_all`` exchanges dispatch buffers so each device holds only its
  local experts' work; a second all_to_all returns expert outputs for the
  combine. The two all-to-alls ride ICI — this is the EP collective pattern.

Everything is differentiable (einsums + where), so jax.grad flows through
router and experts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["moe_gating", "moe_ffn"]


def moe_gating(x, gate_w, num_experts: int, top_k: int = 2,
               capacity: int = 0):
    """Router: returns (dispatch (N,E,C) one-hot, combine (N,E,C) weights,
    aux_loss). ``x`` (N, d); ``gate_w`` (d, E).

    aux_loss is the Switch/GShard load-balance loss: E * sum_e(frac_tokens_e
    * mean_prob_e) — 1.0 when perfectly balanced."""
    n, _ = x.shape
    e = num_experts
    if capacity <= 0:
        capacity = max(1, (n * top_k) // e)
    logits = x @ gate_w                       # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k selection, one expert at a time so positions stay static
    dispatch = jnp.zeros((n, e, capacity), x.dtype)
    combine = jnp.zeros((n, e, capacity), x.dtype)
    masked = probs
    # per-expert fill counters accumulate across the k rounds
    fill = jnp.zeros((e,), jnp.int32)
    routed = jnp.zeros((n, e), x.dtype)  # PRE-capacity assignments
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                    # (N,)
        onehot = jax.nn.one_hot(idx, e, dtype=x.dtype)       # (N, E)
        gate_val = jnp.sum(probs * onehot, axis=-1)          # (N,)
        # position of each token within its chosen expert's buffer
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1.0)        # (N, E)
        pos = jnp.sum(pos_in_e * onehot, axis=-1).astype(jnp.int32) \
            + jnp.sum(fill * onehot.astype(jnp.int32), axis=-1)
        keep = pos < capacity
        pos_c = jnp.clip(pos, 0, capacity - 1)
        slot = jax.nn.one_hot(pos_c, capacity, dtype=x.dtype)  # (N, C)
        d = onehot[:, :, None] * slot[:, None, :] \
            * keep[:, None, None].astype(x.dtype)
        dispatch = dispatch + d
        combine = combine + d * gate_val[:, None, None]
        fill = fill + jnp.sum(onehot, axis=0).astype(jnp.int32)
        routed = routed + onehot
        masked = masked * (1.0 - onehot)                     # exclude chosen

    # load-balance auxiliary (fraction routed vs mean router prob):
    # balanced routing gives frac=k/E and mean_prob=1/E, so
    # E * sum(frac * mean_prob) / k == 1 regardless of E or k. Fractions
    # come from the PRE-capacity router assignments (Switch/GShard): if
    # drops were counted instead, the penalty would plateau exactly when
    # an expert overflows
    frac = jnp.mean(routed, axis=0)                          # (E,)
    mean_prob = jnp.mean(probs, axis=0)                      # (E,)
    aux = e * jnp.sum(frac * mean_prob) / max(top_k, 1)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w1, w2, top_k: int = 2, capacity_factor: float = 1.25,
            axis_name=None, activation=jax.nn.relu):
    """MoE feed-forward. ``x`` (N, d); ``gate_w`` (d, E);
    ``w1`` (E, d, h); ``w2`` (E, h, d) — under ``axis_name`` these hold the
    LOCAL expert shard (E_local = E / ep_size) and x the local tokens.

    Returns (out (N, d), aux_loss)."""
    n, d = x.shape
    if axis_name is None:
        e = w1.shape[0]
        cap = max(1, int(capacity_factor * n * top_k / e))
        dispatch, combine, aux = moe_gating(x, gate_w, e, top_k, cap)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
        h = activation(jnp.einsum("ecd,edh->ech", expert_in, w1))
        expert_out = jnp.einsum("ech,ehd->ecd", h, w2)
        out = jnp.einsum("nec,ecd->nd", combine, expert_out)
        return out, aux

    # lax.psum(1, axis) == axis size on every jax version (lax.axis_size
    # only exists in newer releases)
    ep = lax.psum(1, axis_name)
    e_local = w1.shape[0]
    e = e_local * ep
    # capacity per (expert, source shard): each source device may route up
    # to cap of its local tokens to each global expert, so every expert's
    # total buffer is ep*cap — static shapes throughout
    cap = max(1, int(capacity_factor * n * top_k / e))
    dispatch, combine, aux = moe_gating(x, gate_w, e, top_k, cap)
    # (N, E, C) -> (ep, E_local, C, d): expert inputs grouped by the device
    # that OWNS each expert
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x) \
        .reshape(ep, e_local, cap, d)
    # all-to-all #1: chunk i of dim 0 goes to device i; afterwards dim 0
    # indexes the SOURCE device — each device holds its own experts' tokens
    # from every peer
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)
    ei = expert_in.transpose(1, 0, 2, 3).reshape(e_local, ep * cap, d)
    h = activation(jnp.einsum("esd,edh->esh", ei, w1))
    eo = jnp.einsum("esh,ehd->esd", h, w2) \
        .reshape(e_local, ep, cap, d).transpose(1, 0, 2, 3)
    # all-to-all #2: return expert outputs to the token-owning devices
    eo = lax.all_to_all(eo, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)
    # dim 0 now indexes expert-owner devices again -> (E, C, d) aligns with
    # this device's local (N, E, C) combine weights
    expert_out = eo.reshape(e, cap, d)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    # aux is computed from local stats; average across shards
    aux = lax.pmean(aux, axis_name)
    return out, aux


# ---------------------------------------------------------------------------
# sharding spec pack (analysis/sharding.py expect_spec)
# ---------------------------------------------------------------------------
# Expert parallelism's contract, declared next to the implementation:
# exactly the two all-to-alls above (dispatch out, combine back) per
# application on the 'ep' axis — a THIRD exchange or any all-gather
# above the floor means tokens or expert weights are leaving the
# expert-sharded layout; the aux-loss pmean is a declared reduction;
# and the expert weights (w1/w2, leading dim 'ep'-sharded) must
# actually live at ~1/ep per device (the state-budget check over the
# sharding table).
try:
    from ..analysis import sharding as _asharding

    MOE_EP_SPEC_PACK = _asharding.register_spec_pack(
        _asharding.SpecPack(
            name="ep-moe",
            description="expert-parallel MoE FFN (dispatch/combine "
                        "all-to-all pair over 'ep', GShard/Switch "
                        "capacity-bounded routing)",
            axes=("ep",),
            rules=(_asharding.CollectiveRule(
                "all_to_all", axis="ep", min_count=2),),
            declared=(_asharding.CollectiveRule("all_reduce",
                                                axis="ep"),),
            state_axis="ep"))
except Exception:                        # pragma: no cover - defensive
    pass
