"""Network visualization: ``print_summary`` + ``plot_network``.

Reference analog: python/mxnet/visualization.py (:46 print_summary,
:210 plot_network), importable as ``mx.viz`` exactly like the reference.

TPU-native differences: per-node output shapes come from an abstract
per-node walk under ``jax.eval_shape`` with ``ShapeDtypeStruct``
arguments (XLA shape inference — zero FLOPs, no device contact; only
the data shape is required, parameter shapes are inferred) instead of
the reference's nnvm infer-shape pass over a JSON round-trip; and
parameter counts are derived from real inferred input shapes rather
than string-parsed attr dicts. ``plot_network`` degrades gracefully: it prefers the ``graphviz``
package but falls back to a minimal DOT builder with the same
``.source`` surface when the package is absent (this environment has no
``dot`` binary, so rendering is the caller's concern either way).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .base import MXNetError
from .symbol.symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def _node_shapes(symbol: Symbol, shapes: Dict) -> Dict[int, tuple]:
    """id(node) -> inferred output shape, via an abstract per-node
    ``jax.eval_shape`` walk: every feed enters the trace as a
    ``ShapeDtypeStruct`` argument, so no array is ever materialized and
    no device is touched. Parameter-variable shapes absent from
    ``shapes`` are inferred from op attrs + the data input's (already
    inferred) shape, so reference-style calls
    ``print_summary(sym, shape={'data': ...})`` work the way the
    reference's interior infer-shape pass makes them work
    (reference visualization.py:75)."""
    import jax
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray
    from .symbol.executor import _eval_node

    internals = symbol.get_internals()
    shapes = {k: tuple(int(x) for x in v) for k, v in shapes.items()}
    out_shape: Dict[int, tuple] = {}
    unresolved: List[str] = []

    def _apply(node):
        def f(arrs):
            feeds: Dict[str, NDArray] = {}
            cache: Dict[int, NDArray] = {}
            for inp, a in zip(node._inputs, arrs):
                v = NDArray(a)
                cache[id(inp)] = v
                feeds[inp._name] = v
            return _eval_node(node, feeds, cache)._data
        return f

    for node in internals:
        if node._op is None:
            continue
        _infer_param_shapes(node, shapes, out_shape)
        in_structs = []
        for inp in node._inputs:
            # explicit None checks: a 0-d shape () is falsy but RESOLVED —
            # `or`-chaining would misreport it as missing
            s = out_shape.get(id(inp))
            if s is None:
                s = shapes.get(inp._name)
            if s is None:
                unresolved.append(inp._name)
            else:
                in_structs.append(jax.ShapeDtypeStruct(s, jnp.float32))
        if unresolved:
            raise MXNetError(
                f"Input shape is incomplete: missing {sorted(set(unresolved))}")
        out = jax.eval_shape(_apply(node), in_structs)
        out_shape[id(node)] = tuple(out.shape)
    for node in internals:
        if node._op is None and node._name in shapes:
            out_shape[id(node)] = shapes[node._name]
    return out_shape


def _infer_param_shapes(node: Symbol, shapes: Dict, out_shape: Dict) -> None:
    """Complete missing parameter-variable shapes for ``node`` in place,
    from its op attrs + the data input's inferred shape — the job the
    reference delegates to nnvm's infer-shape pass so users only supply
    the data shape."""
    var_inputs = [i for i in node._inputs
                  if i._op is None and i._name not in shapes]
    if not var_inputs:
        return
    op, attrs = node._op, node._attrs
    data = node._inputs[0]
    in_shape = out_shape.get(id(data))
    if in_shape is None:
        in_shape = shapes.get(data._name)
    if in_shape is None:
        in_shape = ()
    guesses: Dict[str, tuple] = {}
    if op in _CONV_OPS and len(in_shape) > 1:
        nf = int(attrs.get("num_filter", 0) or 0)
        ng = max(int(attrs.get("num_group", 1) or 1), 1)
        guesses["weight"] = (nf, int(in_shape[1]) // ng) + _as_int_tuple(
            attrs.get("kernel"))
        guesses["bias"] = (nf,)
    elif op in _FC_OPS and in_shape:
        nh = int(attrs.get("num_hidden", 0) or 0)
        if attrs.get("flatten", True) in (False, "False", 0):
            in_feat = int(in_shape[-1])
        else:
            in_feat = 1
            for x in in_shape[1:]:
                in_feat *= int(x)
        guesses["weight"] = (nh, in_feat)
        guesses["bias"] = (nh,)
    elif op in _BN_OPS and len(in_shape) > 1:
        ch = (int(in_shape[int(attrs.get("axis", 1) or 1)]),)
        for suffix in ("gamma", "beta", "moving_mean", "moving_var",
                       "running_mean", "running_var"):
            guesses[suffix] = ch
    elif op in _EMBED_OPS:
        guesses["weight"] = (int(attrs.get("input_dim", 0)),
                             int(attrs.get("output_dim", 0)))
    for v in var_inputs:
        for suffix, g in guesses.items():
            if v._name.endswith(suffix):
                shapes[v._name] = g
                break


def _as_int_tuple(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),)


_CONV_OPS = {"Convolution", "convolution", "conv2d"}
_FC_OPS = {"FullyConnected", "fully_connected", "dense"}
_BN_OPS = {"BatchNorm", "batch_norm"}
_EMBED_OPS = {"Embedding", "embedding"}
_ACT_OPS = {"Activation", "activation", "relu", "sigmoid", "tanh",
            "softrelu", "LeakyReLU", "leaky_relu"}
_POOL_OPS = {"Pooling", "pooling", "max_pool2d", "avg_pool2d"}


def _layer_params(node: Symbol, in_shape: tuple,
                  out_shape: tuple) -> int:
    """Parameter count attributable to this node, from its attrs + the
    inferred input-channel count (reference visualization.py:127-174,
    re-derived from real shapes)."""
    op, attrs = node._op, node._attrs
    pre_filter = int(in_shape[1]) if len(in_shape) > 1 else 0
    if op in _CONV_OPS:
        num_filter = int(attrs.get("num_filter", 0))
        num_group = int(attrs.get("num_group", 1) or 1)
        cur = pre_filter * num_filter // max(num_group, 1)
        for k in _as_int_tuple(attrs.get("kernel")):
            cur *= k
        if not attrs.get("no_bias", False):
            cur += num_filter
        return cur
    if op in _FC_OPS:
        num_hidden = int(attrs.get("num_hidden", 0))
        pre = int(in_shape[-1]) if in_shape else 0
        if attrs.get("no_bias", False):
            return pre * num_hidden
        return (pre + 1) * num_hidden
    if op in _BN_OPS:
        ch = int(out_shape[1]) if len(out_shape) > 1 else 0
        return ch * 2
    if op in _EMBED_OPS:
        return int(attrs.get("input_dim", 0)) * int(attrs.get(
            "output_dim", 0))
    return 0


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a per-layer summary table of the symbol's graph
    (reference visualization.py:46): layer name/type, output shape,
    parameter count, previous layer(s), and the total parameter count.

    ``shape`` maps input variable names to shapes; when given, output
    shapes are inferred abstractly and shown (batch axis stripped, as
    the reference does)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = shape is not None
    shape_of: Dict[int, tuple] = _node_shapes(symbol, shape) \
        if show_shape else {}

    positions = list(positions)
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]

    def print_row(fields):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)

    internals = symbol.get_internals()
    total_params = 0
    rows = [node for i, node in enumerate(internals)
            if node._op is not None or node is symbol or i == 0]
    for i, node in enumerate(rows):
        op = node._op or "null"
        out_shape = shape_of.get(id(node), ())
        # shown without the batch axis, reference convention
        shown = out_shape[1:] if len(out_shape) > 1 else out_shape
        pre_nodes = [inp._name for inp in node._inputs
                     if inp._op is not None or not _is_param_name(
                         inp._name)]
        in_shape = ()
        for inp in node._inputs:
            if inp._op is not None or not _is_param_name(inp._name):
                in_shape = shape_of.get(id(inp), ())
                break
        cur = _layer_params(node, in_shape, out_shape) if op != "null" else 0
        total_params += cur
        print_row([f"{node._name}({op})",
                   "x".join(str(x) for x in shown),
                   cur,
                   pre_nodes[0] if pre_nodes else ""])
        for extra in pre_nodes[1:]:
            print_row(["", "", "", extra])
        print(("=" if i == len(rows) - 1 else "_") * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


_PARAM_SUFFIXES = ("weight", "bias", "gamma", "beta", "moving_mean",
                   "moving_var", "running_mean", "running_var")


def _is_param_name(name: str) -> bool:
    return any(name.endswith(s) for s in _PARAM_SUFFIXES)


class _DotDigraph:
    """Minimal stand-in for graphviz.Digraph: accumulates DOT source with
    the same ``.node``/``.edge``/``.source`` surface, so plot_network
    works without the graphviz package (rendering needs the real
    toolchain either way)."""

    def __init__(self, name="plot", format="pdf", graph_attr=None):
        self.name = name
        self.format = format
        self._lines: List[str] = []
        if graph_attr:
            for k, v in graph_attr.items():
                self._lines.append(f'    {k}="{v}";')

    @staticmethod
    def _attrs(kw):
        return "[" + " ".join(f'{k}="{v}"' for k, v in kw.items()) + "]"

    def node(self, name, label=None, **kw):
        if label is not None:
            kw = {"label": label, **kw}
        self._lines.append(f'    "{name}" {self._attrs(kw)};')

    def edge(self, tail, head, label=None, **kw):
        if label is not None:
            kw = {"label": label, **kw}
        self._lines.append(f'    "{tail}" -> "{head}" {self._attrs(kw)};')

    @property
    def source(self) -> str:
        body = "\n".join(self._lines)
        return f'digraph "{self.name}" {{\n{body}\n}}\n'

    def render(self, *a, **k):
        raise MXNetError("rendering requires the graphviz toolchain; "
                         "use .source to get the DOT text")

    view = render


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Build a Graphviz digraph of the computation graph (reference
    visualization.py:210). Returns a ``graphviz.Digraph`` when that
    package is importable, else a source-compatible fallback — either
    way ``.source`` holds the DOT text."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    node_attrs = dict(node_attrs or {})
    draw_shape = shape is not None
    shape_of = _node_shapes(symbol, shape) if draw_shape else {}

    # reference palette (visualization.py:262)
    static_attrs = {"shape": "box", "fixedsize": "true",
                    "width": "1.3", "height": "0.8034", "style": "filled"}
    static_attrs.update(node_attrs)
    cm = ("#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3",
          "#fdb462", "#b3de69", "#fccde5")

    try:
        from graphviz import Digraph
        dot = Digraph(name=title, format=save_format)
    except ImportError:
        dot = _DotDigraph(name=title, format=save_format)

    internals = symbol.get_internals()
    hidden: set = set()
    for node in internals:
        op = node._op
        name = node._name
        attrs = dict(static_attrs)
        label = name
        if op is None:
            if hide_weights and _is_param_name(name):
                hidden.add(id(node))
                continue
            attrs["shape"] = "oval"
            attrs["fillcolor"] = cm[0]
        elif op in _CONV_OPS:
            k = "x".join(str(x) for x in _as_int_tuple(
                node._attrs.get("kernel")))
            s = "x".join(str(x) for x in _as_int_tuple(
                node._attrs.get("stride"))) or "1"
            label = (f"{op}\n{k}/{s}, "
                     f"{node._attrs.get('num_filter', '?')}")
            attrs["fillcolor"] = cm[1]
        elif op in _FC_OPS:
            label = f"{op}\n{node._attrs.get('num_hidden', '?')}"
            attrs["fillcolor"] = cm[1]
        elif op in _BN_OPS:
            attrs["fillcolor"] = cm[3]
        elif op in _ACT_OPS:
            act = node._attrs.get("act_type", op)
            label = f"{act}\n{op}" if op in ("Activation",
                                             "activation") else op
            attrs["fillcolor"] = cm[2]
        elif op in _POOL_OPS:
            pt = node._attrs.get("pool_type", op)
            k = "x".join(str(x) for x in _as_int_tuple(
                node._attrs.get("kernel")))
            s = "x".join(str(x) for x in _as_int_tuple(
                node._attrs.get("stride"))) or "1"
            label = f"Pooling\n{pt}, {k}/{s}"
            attrs["fillcolor"] = cm[4]
        elif op in ("Concat", "concat", "Flatten", "flatten",
                    "Reshape", "reshape"):
            attrs["fillcolor"] = cm[5]
        elif op in ("softmax", "SoftmaxOutput", "log_softmax"):
            attrs["fillcolor"] = cm[6]
        else:
            attrs["fillcolor"] = cm[7]
        dot.node(name=name, label=label, **attrs)

    for node in internals:
        if id(node) in hidden:
            continue
        for inp in node._inputs:
            if id(inp) in hidden:
                continue
            kw = {"arrowtail": "open", "dir": "back"}
            if draw_shape:
                ishape = shape_of.get(id(inp), ())
                kw["label"] = "x".join(str(x) for x in ishape[1:]) \
                    if len(ishape) > 1 else str(ishape)
            # reference draws data flowing bottom-up: edge child <- parent
            dot.edge(tail_name=node._name, head_name=inp._name, **kw) \
                if _is_real_graphviz(dot) else \
                dot.edge(node._name, inp._name, **kw)
    return dot


def _is_real_graphviz(dot) -> bool:
    return not isinstance(dot, _DotDigraph)
