"""Weight initializers (reference: python/mxnet/initializer.py).

Each initializer is a callable producing the initial value for a parameter
shape/dtype. Registered by lowercase alias so ``init="xavier"`` strings work
like the reference's registry.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as onp

import jax
import jax.numpy as jnp

from .base import MXNetError, jx_dtype
from .ndarray import random as nd_random
from .ndarray.ndarray import NDArray

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "Load", "registry", "create"]

registry = {}


def _register(name):
    def deco(cls):
        registry[name.lower()] = cls
        return cls
    return deco


class InitDesc(str):
    """Descriptor for an initialization pattern (reference
    initializer.py:36): a str (the variable name) carrying the
    variable's attrs (from ``Symbol.attr_dict``) and a fallback
    ``global_init``."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer. Subclasses implement _init_weight(name, shape, dtype)
    returning a jax array."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name_or_arr, arr: Optional[NDArray] = None):
        """Either init(name, arr) like the reference or init(arr)."""
        if arr is None:
            name, arr = "", name_or_arr
        else:
            name = str(name_or_arr)
        arr._data = self.init_array(name, arr.shape, arr._data.dtype)._data
        return arr

    def init_array(self, name: str, shape, dtype) -> NDArray:
        lname = name.lower()
        if lname.endswith("bias") or lname.endswith("beta") \
                or lname.endswith("running_mean") or lname.endswith("moving_mean"):
            return NDArray(jnp.zeros(shape, dtype))
        if lname.endswith("gamma") or lname.endswith("running_var") \
                or lname.endswith("moving_var"):
            return NDArray(jnp.ones(shape, dtype))
        return NDArray(self._init_weight(name, shape, dtype))

    def _init_weight(self, name, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@_register("zeros")
@_register("zero")
class Zero(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.zeros(shape, dtype)


@_register("ones")
@_register("one")
class One(Initializer):
    def _init_weight(self, name, shape, dtype):
        return jnp.ones(shape, dtype)


@_register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@_register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, shape, dtype):
        return jax.random.uniform(nd_random.next_key(), shape, dtype,
                                  -self.scale, self.scale)


@_register("normal")
@_register("gaussian")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, shape, dtype):
        return self.sigma * jax.random.normal(nd_random.next_key(), shape, dtype)


@_register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, shape, dtype):
        rows = shape[0]
        cols = int(onp.prod(shape[1:])) if len(shape) > 1 else 1
        key = nd_random.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (max(rows, cols), min(rows, cols)),
                                     jnp.float32, -1.0, 1.0)
        else:
            tmp = jax.random.normal(key, (max(rows, cols), min(rows, cols)),
                                    jnp.float32)
        q, _ = jnp.linalg.qr(tmp)
        q = q.T if rows < cols else q
        return (self.scale * q[:rows, :cols]).reshape(shape).astype(dtype)


@_register("xavier")
class Xavier(Initializer):
    """Glorot init (reference initializer.py Xavier): factor by fan avg/in/out,
    magnitude scales the bound."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _fans(self, shape):
        hw = int(onp.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
        fan_out = shape[0] * hw
        return fan_in, fan_out

    def _init_weight(self, name, shape, dtype):
        fan_in, fan_out = self._fans(shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / factor)
        key = nd_random.next_key()
        if self.rnd_type == "uniform":
            return jax.random.uniform(key, shape, dtype, -scale, scale)
        return scale * jax.random.normal(key, shape, dtype)


@_register("msraprelu")
class MSRAPrelu(Xavier):
    """He init variant (reference MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@_register("bilinear")
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference Bilinear init for Deconv)."""

    def _init_weight(self, name, shape, dtype):
        weight = onp.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@_register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias init (reference LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, shape, dtype):
        b = onp.zeros(shape, dtype="float32")
        n = shape[0] // 4
        b[n:2 * n] = self.forget_bias
        return jnp.asarray(b, dtype)


class Mixed(Initializer):
    """Pattern-dispatched initializer (reference Mixed): the first regex
    matching the parameter name picks the initializer. Overrides
    ``init_array`` (like the reference overrides __call__) so pattern
    dispatch wins over the base bias/gamma suffix rules — the chosen
    initializer then applies its own suffix handling."""

    def __init__(self, patterns, initializers):
        import re
        super().__init__()
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed needs one initializer per pattern")
        self._map = [(re.compile(p), create(i))
                     for p, i in zip(patterns, initializers)]

    def init_array(self, name: str, shape, dtype) -> NDArray:
        for pat, ini in self._map:
            if pat.match(name):
                return ini.init_array(name, shape, dtype)
        raise MXNetError(
            f"no initializer pattern matched parameter {name!r}; add a "
            f"catch-all '.*' pattern (reference Mixed semantics)")

    def _init_weight(self, name, shape, dtype):
        return self.init_array(name, shape, dtype)._data


class Load(Initializer):
    """Initialize from saved arrays by name (reference Load): a dict (or
    nd.load result) of name->NDArray, with an optional default for missing
    names. Overrides ``init_array`` so saved values win over the base
    bias/gamma suffix rules (reference Load overrides __call__ for the
    same reason — a restored bias must not be re-zeroed)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self._params = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self._default = create(default_init) if default_init else None
        self._verbose = verbose

    def init_array(self, name: str, shape, dtype) -> NDArray:
        if name in self._params:
            arr = self._params[name]
            data = arr._data if hasattr(arr, "_data") else jnp.asarray(arr)
            if tuple(data.shape) != tuple(shape):
                raise MXNetError(
                    f"Load: parameter {name!r} has shape {tuple(data.shape)}"
                    f" in the file but {tuple(shape)} in the model")
            if self._verbose:
                print(f"Load: initialized {name} from saved array")
            return NDArray(jnp.asarray(data, dtype))
        if self._default is None:
            raise MXNetError(
                f"Load: no saved array for {name!r} and no default_init")
        return self._default.init_array(name, shape, dtype)

    def _init_weight(self, name, shape, dtype):
        return self.init_array(name, shape, dtype)._data


def create(init, **kwargs) -> Initializer:
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform()
    if isinstance(init, str):
        try:
            return registry[init.lower()](**kwargs)
        except KeyError as e:
            raise MXNetError(f"unknown initializer {init!r}") from e
    raise MXNetError(f"cannot create initializer from {init!r}")
