"""Fusion census: static audit of XLA's fusion decisions in the
optimized HLO, after the method of "Operator Fusion in XLA: Analysis
and Evaluation" (arXiv:2301.13062).

The BENCH legs put LSTM at MFU 0.17 and ResNet at 0.275 against the
measured roofline — and the first question for any MFU gap is *where
does the program touch HBM that it didn't have to*.  XLA answers it
implicitly through fusion: everything inside one fusion kernel streams
through registers/VMEM, everything AT a kernel boundary is written to
and re-read from HBM.  This pass makes those boundaries inspectable
and regression-testable:

1. **Fusion graph** (:func:`fusion_census`): every ``fusion`` op (and
   every standalone compute kernel — dot, convolution, reduce,
   custom-call, …) in the *schedulable* computations (entry + while
   bodies + conditional branches; fusion bodies execute inside one
   kernel and are walked, not scheduled), with its kind
   (loop/input/output/custom), an opcode census of its body, a FLOP
   estimate, and the bytes it reads/writes at its boundary.
2. **Ideal-fusion diff**: (a) *stranded ops* — unfused elementwise /
   broadcast / convert / transpose ops sitting between two fusions
   above a size floor, each one two avoidable HBM round-trips per
   step; (b) *boundary materializations* — intermediates crossing a
   kernel boundary, ranked by bytes, flagged above a floor; (c)
   per-kernel **arithmetic intensity** (FLOPs / boundary bytes)
   classified compute- vs memory-bound against the measured BENCH
   roofline ridge point.
3. **Regression gate** (:func:`check_baseline`): checked-in per-leg
   baselines (``tests/fixtures/fusion_baselines.json``) with tolerance
   bands over {fusion count, stranded count, boundary bytes} — a jax
   bump or model edit that silently degrades fusion fails the tier-1
   sweep (and ``analyze='raise'`` under ``MXNET_FUSION_BASELINE``)
   instead of surfacing as an MFU drop three PRs later.

FLOP numbers are *estimates* from shapes (2·M·K·N dots, window-sized
convs, element-count elementwise) — good for ranking and bound
classification, not for billing. Boundary bytes inside while bodies
count once, not per trip (trip counts are not in the HLO text).
Sharded programs: a partitioned module (entry ``*_spmd``) already has
per-shard shapes and counts unchanged; an UNpartitioned
``num_partitions>1`` module still carries global shapes with
``sharding=`` annotations, and every annotated op's FLOPs/bytes are
divided by its tile factor so bound classification and the census
totals the MFU gauge sanity-checks against stay per-shard
(:func:`_shard_divisors`).
"""
from __future__ import annotations

import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .hlo import HloModule, HloOp, parse_hlo, parse_shape_elements
from .report import Finding

__all__ = ["FusionKernel", "StrandedOp", "Boundary", "FusionReport",
           "fusion_census", "op_flops", "register_custom_call_flops",
           "load_baselines", "check_baseline", "baseline_from_env",
           "publish", "STRANDED_FLOOR_BYTES", "BOUNDARY_FLOOR_BYTES",
           "RIDGE_FLOPS_PER_BYTE"]

_LOG = logging.getLogger("mxnet_tpu.analysis")

#: BENCH_r05 measured matmul roofline (TFLOP/s, TPU v5 lite) and the
#: chip's HBM bandwidth (GB/s, public spec) — their ratio is the
#: roofline ridge point that splits compute- from memory-bound kernels
BENCH_ROOFLINE_TFLOPS = 147.8
HBM_BANDWIDTH_GBPS = 819.0
RIDGE_FLOPS_PER_BYTE = BENCH_ROOFLINE_TFLOPS * 1e12 / \
    (HBM_BANDWIDTH_GBPS * 1e9)

#: byte floor below which a stranded op is scalar glue, not a finding
STRANDED_FLOOR_BYTES = 4096
#: byte floor above which a boundary materialization earns a finding
BOUNDARY_FLOOR_BYTES = 1 << 20

# opcodes XLA's fusion passes can absorb for free — an entry-level op
# from this set between two fusions is a missed fusion, not a kernel.
# `copy` is deliberately NOT here: optimized-HLO copies are buffer
# assignment / donation artifacts, not fusion misses.
_FUSABLE_OPCODES = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "abs", "negate", "exponential", "exponential-minus-one", "log",
    "log-plus-one", "tanh", "logistic", "sqrt", "rsqrt", "cbrt",
    "power", "sign", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "sine", "cosine", "tan", "atan2", "compare",
    "select", "clamp", "and", "or", "xor", "not",
    "shift-left", "shift-right-arithmetic", "shift-right-logical",
    "remainder", "is-finite", "convert", "broadcast", "transpose",
    "reshape", "reverse", "slice", "concatenate", "pad", "iota",
})

# elementwise opcodes for the FLOP model: ~1 flop per output element
_EW_FLOP_OPCODES = _FUSABLE_OPCODES | {"copy", "map", "select-and-scatter",
                                       "dynamic-slice",
                                       "dynamic-update-slice"}

# standalone ops that ARE kernels of their own at a schedulable level
# (the fusion graph's non-fusion nodes)
_KERNEL_OPCODES = frozenset({
    "dot", "convolution", "custom-call", "reduce", "reduce-window",
    "sort", "scatter", "gather", "cholesky", "triangular-solve", "fft",
    "rng", "rng-bit-generator", "topk",
})

# data-free plumbing: resolve through these when walking producer /
# consumer adjacency (they move no bytes)
_TRANSPARENT_OPCODES = frozenset({
    "get-tuple-element", "tuple", "bitcast", "copy-start", "copy-done",
})

# never "intermediates": inputs, module outputs, scalar immediates
_NON_MATERIAL_OPCODES = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id",
})


# ---------------------------------------------------------------------------
# FLOP model
# ---------------------------------------------------------------------------

def _dims_of(type_str: Optional[str]) -> List[int]:
    if not type_str:
        return []
    m = re.search(r"\[([\d,]*)\]", type_str)
    if not m or not m.group(1):
        return []
    return [int(d) for d in m.group(1).split(",") if d]


def _prod(dims: List[int]) -> int:
    out = 1
    for d in dims:
        out *= d
    return out


# custom-call FLOP estimators: without these every hand-written kernel
# (flash attention today, the ops/kernels layer's scan/optimizer/norm
# kernels tomorrow) counts ZERO FLOPs in the census — its arithmetic
# intensity degenerates to 0, it classifies memory-bound, and
# compute_bound_pct under-counts the very kernels written to be
# compute-dense. Matchers are substrings tested against the op's full
# HLO line (Mosaic kernels all share the `tpu_custom_call` target; the
# kernel function name survives in the op_name metadata).
_CUSTOM_CALL_FLOPS: List[tuple] = []


def register_custom_call_flops(name: str, fn, match: Optional[str] = None):
    """Register a FLOP estimator for custom-call kernels.

    ``fn(op: HloOp, mod: HloModule|None) -> int`` runs when ``match``
    (default: ``name``) appears in the custom-call's HLO line (target
    or metadata op_name). First match in registration order wins on
    overlap; re-registering an existing ``name`` replaces it
    (idempotent module reloads)."""
    key = (match or name).lower()
    for i, (n, _, _) in enumerate(_CUSTOM_CALL_FLOPS):
        if n == name:
            _CUSTOM_CALL_FLOPS[i] = (name, key, fn)
            return
    _CUSTOM_CALL_FLOPS.append((name, key, fn))


def _custom_call_flops(op: HloOp, mod: Optional[HloModule]) -> int:
    line = op.line.lower()
    for _, key, fn in _CUSTOM_CALL_FLOPS:
        if key in line:
            try:
                return int(fn(op, mod))
            except Exception:      # estimator bug must not kill a census
                _LOG.debug("custom-call flop estimator failed for %s",
                           op.name, exc_info=True)
                return 0
    return 0


def _operand_dims(op: HloOp, mod: Optional[HloModule],
                  i: int) -> List[int]:
    """Dims of operand ``i``: from the inline operand type when the
    HLO carries it, else resolved through the producing op."""
    if i < len(op.operand_types) and op.operand_types[i]:
        return _dims_of(op.operand_types[i])
    if mod is not None and i < len(op.operands):
        prod = mod.ops.get(op.operands[i])
        if prod is not None:
            return _dims_of(prod.type_str)
    return []


def _flash_fwd_flops(op: HloOp, mod=None) -> int:
    # q (BH, Sq, D), k (BH, Sk, D): two (Sq x Sk x D) matmuls
    q = _operand_dims(op, mod, 0)
    k = _operand_dims(op, mod, 1)
    if len(q) < 3 or len(k) < 3:
        return 0
    return 4 * q[0] * q[1] * k[1] * q[2]


def _flash_bwd_flops(factor: int):
    def fn(op: HloOp, mod=None) -> int:
        base = _flash_fwd_flops(op, mod)
        return base // 4 * factor
    return fn


def _rnn_scan_flops(op: HloOp, mod=None) -> int:
    # xw (T, N, G*H) + resident w_hh (G*H, H): T h2h matmuls + gates
    xw = _operand_dims(op, mod, 0)
    if len(xw) < 3:
        return 0
    t, n, gh = xw[0], xw[1], xw[2]
    w = next((d for d in (_operand_dims(op, mod, i)
                          for i in range(1, len(op.operands)))
              if len(d) == 2 and d[0] == gh), None)
    h = w[1] if w else gh
    return 2 * t * n * gh * h + 10 * t * n * gh


def _elementwise_flops(per_element: int):
    def fn(op: HloOp, mod=None) -> int:
        widest = max((_prod(_operand_dims(op, mod, i))
                      for i in range(len(op.operands))), default=0)
        return per_element * max(op.elements, widest)
    return fn


# the built-in kernel layer (ops/attention.py + ops/kernels/)
register_custom_call_flops("flash_attention_fwd", _flash_fwd_flops,
                           match="_flash_kernel")
register_custom_call_flops("flash_attention_bwd_dq",
                           _flash_bwd_flops(6), match="_flash_bwd_dq")
register_custom_call_flops("flash_attention_bwd_dkv",
                           _flash_bwd_flops(8), match="_flash_bwd_dkv")
register_custom_call_flops("flash_attention_bwd_fused",
                           _flash_bwd_flops(10),
                           match="_flash_bwd_fused")
register_custom_call_flops("rnn_scan_fwd", _rnn_scan_flops,
                           match="_fwd_kernel")
register_custom_call_flops("rnn_scan_bwd", _rnn_scan_flops,
                           match="_bwd_kernel")
register_custom_call_flops("opt_update", _elementwise_flops(10),
                           match="_opt_kernel")
register_custom_call_flops("layernorm_fwd", _elementwise_flops(8),
                           match="_ln_fwd_kernel")
register_custom_call_flops("layernorm_bwd", _elementwise_flops(12),
                           match="_ln_bwd_kernel")
register_custom_call_flops("bias_gelu_fwd", _elementwise_flops(15),
                           match="_bg_fwd_kernel")
register_custom_call_flops("bias_gelu_bwd", _elementwise_flops(18),
                           match="_bg_bwd_kernel")


def op_flops(op: HloOp, mod: Optional[HloModule] = None) -> int:
    """Estimated FLOPs of one HLO op from its line's shapes.

    dot: 2 · out_elements · contracted_size (contracting dims parsed
    from the line); convolution: 2 · out_elements · kernel_elems /
    out_features (dim_labels parsed); reduce/reduce-window: input
    elements; elementwise: output elements; fusion: sum over its body
    (``mod`` required to resolve the body). Unknown opcodes: 0."""
    if op.opcode == "fusion":
        if mod is None:
            return 0
        return sum(op_flops(b, mod) for b in mod.fused_ops(op)
                   if b.opcode != "fusion")
    if op.opcode == "dot":
        lhs_dims = _dims_of(op.operand_types[0]
                            if op.operand_types else None)
        m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", op.line)
        contracted = 1
        if lhs_dims and m and m.group(1):
            for i in (int(x) for x in m.group(1).split(",") if x):
                if i < len(lhs_dims):
                    contracted *= lhs_dims[i]
        return 2 * op.elements * max(1, contracted)
    if op.opcode == "convolution":
        k_dims = _dims_of(op.operand_types[1]
                          if len(op.operand_types) > 1 else None)
        k_elems = 1
        for d in k_dims:
            k_elems *= d
        out_features = 1
        m = re.search(r"dim_labels=\w+_(\w+)->", op.line)
        if m and k_dims:
            o_at = m.group(1).find("o")
            if 0 <= o_at < len(k_dims):
                out_features = max(1, k_dims[o_at])
        return 2 * op.elements * max(1, k_elems // out_features)
    if op.opcode in ("reduce", "reduce-window"):
        in_bytes = op.operand_bytes(0)
        if in_bytes is not None and op.operand_types[0]:
            return parse_shape_elements(op.operand_types[0])[0]
        return op.elements
    if op.opcode == "custom-call":
        return _custom_call_flops(op, mod)
    if op.opcode in _EW_FLOP_OPCODES:
        return op.elements
    return 0


# ---------------------------------------------------------------------------
# report structures
# ---------------------------------------------------------------------------

@dataclass
class FusionKernel:
    """One kernel in the schedulable program: a ``fusion`` op (kind
    loop/input/output/custom) or a standalone compute op (kind = its
    opcode: dot, convolution, custom-call, …)."""
    name: str
    kind: str
    computation: str
    n_ops: int
    op_census: Dict[str, int]
    flops: int
    bytes_in: int
    bytes_out: int

    @property
    def boundary_bytes(self) -> int:
        return self.bytes_in + self.bytes_out

    @property
    def intensity(self) -> float:
        """Arithmetic intensity: FLOPs per HBM boundary byte."""
        return self.flops / self.boundary_bytes \
            if self.boundary_bytes else 0.0

    def bound(self, ridge: float = RIDGE_FLOPS_PER_BYTE) -> str:
        return "compute" if self.intensity >= ridge else "memory"

    def to_dict(self, ridge: float = RIDGE_FLOPS_PER_BYTE):
        return {"name": self.name, "kind": self.kind,
                "computation": self.computation, "n_ops": self.n_ops,
                "op_census": dict(self.op_census), "flops": self.flops,
                "bytes_in": self.bytes_in, "bytes_out": self.bytes_out,
                "intensity": round(self.intensity, 4),
                "bound": self.bound(ridge)}


@dataclass
class StrandedOp:
    """An unfused fusable op between two fusions: XLA materializes its
    input AND its output to HBM where either neighbor fusion could
    have absorbed it."""
    name: str
    opcode: str
    bytes: int
    producer: str           # the upstream fusion/kernel
    consumers: List[str]    # downstream fusions
    computation: str

    def to_dict(self):
        return {"name": self.name, "opcode": self.opcode,
                "bytes": self.bytes, "producer": self.producer,
                "consumers": list(self.consumers),
                "computation": self.computation}


@dataclass
class Boundary:
    """One intermediate tensor materialized at a kernel boundary
    (written to HBM by its producer, read back by each consumer)."""
    name: str
    opcode: str
    bytes: int
    consumers: List[str]
    computation: str

    def to_dict(self):
        return {"name": self.name, "opcode": self.opcode,
                "bytes": self.bytes, "consumers": list(self.consumers),
                "computation": self.computation}


@dataclass
class FusionReport:
    """Everything the fusion census measured about ONE optimized
    program, plus the ideal-diff findings."""
    kernels: List[FusionKernel] = field(default_factory=list)
    stranded: List[StrandedOp] = field(default_factory=list)
    boundaries: List[Boundary] = field(default_factory=list)
    boundary_bytes: int = 0
    stranded_floor: int = STRANDED_FLOOR_BYTES
    boundary_floor: int = BOUNDARY_FLOOR_BYTES
    ridge: float = RIDGE_FLOPS_PER_BYTE
    findings: List[Finding] = field(default_factory=list)

    @property
    def fusions(self) -> List[FusionKernel]:
        return [k for k in self.kernels
                if k.kind in ("loop", "input", "output", "custom")]

    @property
    def n_fusions(self) -> int:
        return len(self.fusions)

    @property
    def n_kernels(self) -> int:
        return len(self.kernels)

    @property
    def total_flops(self) -> int:
        return sum(k.flops for k in self.kernels)

    @property
    def compute_bound_pct(self) -> float:
        """FLOP-weighted share (0–100) of kernels whose arithmetic
        intensity clears the roofline ridge point."""
        total = self.total_flops
        if not total:
            return 0.0
        cb = sum(k.flops for k in self.kernels
                 if k.bound(self.ridge) == "compute")
        return round(100.0 * cb / total, 2)

    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for k in self.kernels:
            out[k.kind] = out.get(k.kind, 0) + 1
        return out

    def brief(self) -> Dict[str, Any]:
        """The four headline numbers (ProgramReport.to_dict / the BENCH
        json's per-leg fusion posture)."""
        return {"n_fusions": self.n_fusions,
                "stranded_ops": len(self.stranded),
                "boundary_bytes": self.boundary_bytes,
                "compute_bound_pct": self.compute_bound_pct}

    def to_dict(self):
        return {
            "n_fusions": self.n_fusions,
            "n_kernels": self.n_kernels,
            "by_kind": self.by_kind(),
            "stranded_ops": len(self.stranded),
            "boundary_bytes": self.boundary_bytes,
            "compute_bound_pct": self.compute_bound_pct,
            "stranded": [s.to_dict() for s in self.stranded[:16]],
            "top_boundaries": [b.to_dict()
                               for b in self.boundaries[:16]],
            "kernels": [k.to_dict(self.ridge) for k in self.kernels],
        }

    def summary_line(self) -> str:
        return (f"fusions={self.n_fusions} kernels={self.n_kernels} "
                f"stranded={len(self.stranded)} "
                f"boundary_bytes={self.boundary_bytes} "
                f"compute_bound={self.compute_bound_pct}%")

    def table(self, top: int = 24) -> str:
        """Human-readable kernel table (tools/diagnose.py --fusion)."""
        rows = sorted(self.kernels, key=lambda k: -k.flops)[:top]
        lines = [f"{'kernel':<42s}{'kind':<8s}{'ops':>4s}{'flops':>12s}"
                 f"{'bound B':>10s}{'fl/B':>8s}  bound"]
        for k in rows:
            census = ",".join(f"{o}x{n}" for o, n in sorted(
                k.op_census.items(), key=lambda kv: -kv[1])[:3])
            lines.append(
                f"{k.name[:40]:<42s}{k.kind:<8s}{k.n_ops:>4d}"
                f"{k.flops:>12d}{k.boundary_bytes:>10d}"
                f"{k.intensity:>8.2f}  {k.bound(self.ridge)}"
                + (f"  [{census}]" if census else ""))
        if len(self.kernels) > top:
            lines.append(f"  ... {len(self.kernels) - top} more kernels")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# the census
# ---------------------------------------------------------------------------

def _resolve_through(mod: HloModule, name: str, downstream: bool,
                     _depth: int = 0) -> List[HloOp]:
    """Real neighbors of an op, looking through data-free plumbing
    (get-tuple-element / tuple / bitcast)."""
    if _depth > 8:
        return []
    out: List[HloOp] = []
    if downstream:
        neigh = mod.consumers(name)
    else:
        op = mod.ops.get(name)
        neigh = [mod.ops[o] for o in (op.operands if op else ())
                 if o in mod.ops]
    for n in neigh:
        if n.opcode in _TRANSPARENT_OPCODES:
            out.extend(_resolve_through(mod, n.name, downstream,
                                        _depth + 1))
        else:
            out.append(n)
    return out


def _shard_divisors(mod: HloModule):
    """Per-op byte/FLOP divisor for SPMD-sharded modules.

    The optimized HLO of a partitioned program (entry ``*_spmd``)
    already has PER-SHARD shapes — divisor 1 everywhere.  A
    ``num_partitions>1`` module the partitioner has NOT rewritten
    (pre-partitioning dumps, Shardy-style annotated modules, canned
    test programs) still carries GLOBAL logical shapes with
    ``sharding=`` annotations: counting those at face value overcounts
    FLOPs and boundary bytes by the tile factor, misclassifies
    memory-bound kernels as compute-bound, and inflates the census
    totals the MFU gauge is sanity-checked against.  Here every
    annotated op contributes its ``shard_count``; unannotated ops stay
    at 1 (conservative — only provably-sharded work is scaled)."""
    if mod.num_partitions <= 1 or mod.spmd_partitioned:
        return lambda op: 1
    from .sharding import parse_op_sharding
    cache: Dict[str, int] = {}

    def divisor(op: HloOp) -> int:
        f = cache.get(op.name)
        if f is not None:
            return f
        f = 1
        if op.sharding:
            sh = parse_op_sharding(op.sharding)
            if sh is not None and sh.kind == "tiled":
                f = max(1, sh.shard_count)
        cache[op.name] = f
        return f

    return divisor


def _kernel_of(mod: HloModule, op: HloOp) -> Optional[str]:
    """The kernel an op's data lives in at a schedulable level: the op
    itself when it IS a kernel (fusion / standalone compute), else
    None (it is a loose op or plumbing)."""
    if op.opcode == "fusion" or op.opcode in _KERNEL_OPCODES:
        return op.name
    return None


def fusion_census(hlo: Union[str, HloModule],
                  stranded_floor_bytes: int = STRANDED_FLOOR_BYTES,
                  boundary_floor_bytes: int = BOUNDARY_FLOOR_BYTES,
                  ridge_flops_per_byte: float = RIDGE_FLOPS_PER_BYTE) \
        -> FusionReport:
    """Audit fusion boundaries in one optimized HLO program.

    ``hlo`` is the ``compiled.as_text()`` dump (or an already-parsed
    :class:`HloModule`). Returns a :class:`FusionReport`; never raises
    on malformed text (an analyzer must not take down the run it
    observes) — unparseable programs yield an empty report."""
    mod = parse_hlo(hlo) if isinstance(hlo, str) else hlo
    report = FusionReport(stranded_floor=stranded_floor_bytes,
                          boundary_floor=boundary_floor_bytes,
                          ridge=ridge_flops_per_byte)
    sched = {c.name for c in mod.schedulable_computations()}
    if not sched:      # headerless canned snippets: treat all as entry
        sched = {None}
    shard_div = _shard_divisors(mod)

    for op in mod.ops.values():
        if op.computation not in sched and sched != {None}:
            continue
        # per-shard correction: global-shape sharded modules divide by
        # the op's tile factor (partitioned modules divide by 1)
        div = shard_div(op)
        op_bytes = op.bytes // div
        # --- kernel nodes: fusions + standalone compute ops ----------
        if op.opcode == "fusion":
            body = mod.fused_ops(op)
            census: Dict[str, int] = {}
            for b in body:
                if b.opcode in ("parameter", "constant"):
                    continue
                census[b.opcode] = census.get(b.opcode, 0) + 1
            bytes_in = 0
            for i in range(len(op.operands)):
                bytes_in += op.operand_bytes(i) or 0
            report.kernels.append(FusionKernel(
                name=op.name, kind=op.fusion_kind or "loop",
                computation=op.computation or "?",
                n_ops=sum(census.values()), op_census=census,
                flops=op_flops(op, mod) // div, bytes_in=bytes_in // div,
                bytes_out=op_bytes))
        elif op.opcode in _KERNEL_OPCODES:
            bytes_in = 0
            for i in range(len(op.operands)):
                bytes_in += op.operand_bytes(i) or 0
            report.kernels.append(FusionKernel(
                name=op.name,
                kind="custom-call" if op.opcode == "custom-call"
                else op.opcode,
                computation=op.computation or "?",
                n_ops=1, op_census={op.opcode: 1},
                flops=op_flops(op, mod) // div, bytes_in=bytes_in // div,
                bytes_out=op_bytes))

        # --- boundary materializations -------------------------------
        if op.opcode in _NON_MATERIAL_OPCODES or op.bytes == 0:
            continue
        consumers = [c for c in _resolve_through(mod, op.name, True)
                     if c.computation == op.computation]
        if not consumers or op.is_root:
            continue             # module/computation output, not a
            # boundary between two kernels
        report.boundary_bytes += op_bytes
        report.boundaries.append(Boundary(
            name=op.name, opcode=op.opcode, bytes=op_bytes,
            consumers=[c.name for c in consumers],
            computation=op.computation or "?"))

        # --- stranded fusable ops ------------------------------------
        if op.opcode in _FUSABLE_OPCODES and \
                op_bytes >= stranded_floor_bytes:
            producers = _resolve_through(mod, op.name, False)
            fused_prod = [p for p in producers
                          if p.opcode == "fusion"]
            fused_cons = [c for c in consumers
                          if c.opcode == "fusion"]
            if fused_prod and fused_cons:
                report.stranded.append(StrandedOp(
                    name=op.name, opcode=op.opcode, bytes=op_bytes,
                    producer=fused_prod[0].name,
                    consumers=[c.name for c in fused_cons],
                    computation=op.computation or "?"))

    report.boundaries.sort(key=lambda b: -b.bytes)
    report.stranded.sort(key=lambda s: -s.bytes)

    for s in report.stranded[:8]:
        report.findings.append(Finding(
            checker="fusion", rule="stranded-op", severity="warn",
            message=f"unfused `{s.opcode}` ({s.bytes} B) stranded "
                    f"between fusion `{s.producer}` and "
                    f"{len(s.consumers)} downstream fusion(s) — two "
                    "avoidable HBM round-trips per step "
                    "(arXiv:2301.13062 ideal-fusion diff)",
            where=s.name))
    for b in report.boundaries[:5]:
        if b.bytes < boundary_floor_bytes:
            break
        report.findings.append(Finding(
            checker="fusion", rule="fusion-boundary", severity="warn",
            message=f"kernel boundary materializes {b.bytes} B of "
                    f"`{b.opcode}` output to HBM (read back by "
                    f"{len(b.consumers)} consumer(s)) — candidates "
                    "for fusion or recomputation",
            where=b.name))
    return report


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def load_baselines(path: str) -> Dict[str, Any]:
    """Per-leg fusion baselines: ``{leg: {n_fusions, stranded_ops,
    boundary_bytes, tol_pct}}`` (``_comment`` keys ignored)."""
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    return {k: v for k, v in raw.items() if not k.startswith("_")}


def check_baseline(report: FusionReport, baselines: Dict[str, Any],
                   leg: str) -> List[Finding]:
    """Diff a program's fusion posture against a checked-in baseline.

    Bands: ``n_fusions`` must stay within ±tol_pct (min ±1 — fusion
    counts move both ways when XLA repartitions, either direction is a
    posture change to re-baseline consciously); ``stranded_ops`` and
    ``boundary_bytes`` are one-sided — fewer/less is an improvement,
    more than baseline (+tol for bytes) is a regression.  Every
    violation is an error-severity ``fusion-regression`` finding, so
    ``analyze='raise'`` fails fast (docs/ANALYSIS.md documents the
    refresh workflow for legitimate jax-upgrade shifts)."""
    base = baselines.get(leg)
    findings: List[Finding] = []
    if base is None:
        findings.append(Finding(
            checker="fusion", rule="fusion-regression", severity="warn",
            message=f"no fusion baseline for leg {leg!r} — add it to "
                    "the baselines file (docs/ANALYSIS.md)",
            where=leg))
        return findings
    tol = float(base.get("tol_pct", 25.0)) / 100.0
    n_base = int(base.get("n_fusions", 0))
    band = max(1, int(round(n_base * tol)))
    if abs(report.n_fusions - n_base) > band:
        findings.append(Finding(
            checker="fusion", rule="fusion-regression",
            message=f"[{leg}] fusion count {report.n_fusions} left the "
                    f"baseline band {n_base}±{band} — XLA's fusion "
                    "partitioning changed; investigate, then refresh "
                    "the baseline if intentional (docs/ANALYSIS.md)",
            where=leg))
    s_base = int(base.get("stranded_ops", 0))
    if len(report.stranded) > s_base:
        worst = report.stranded[0]
        findings.append(Finding(
            checker="fusion", rule="fusion-regression",
            message=f"[{leg}] {len(report.stranded)} stranded op(s) vs "
                    f"baseline {s_base} — new unfused op(s) between "
                    f"fusions (worst: `{worst.opcode}` {worst.bytes} B "
                    f"at {worst.name})",
            where=leg))
    b_base = int(base.get("boundary_bytes", 0))
    if b_base and report.boundary_bytes > b_base * (1.0 + tol):
        findings.append(Finding(
            checker="fusion", rule="fusion-regression",
            message=f"[{leg}] materialized boundary bytes "
                    f"{report.boundary_bytes} exceed baseline {b_base} "
                    f"by more than {base.get('tol_pct', 25.0)}% — the "
                    "program round-trips more intermediate data "
                    "through HBM than it used to",
            where=leg))
    return findings


def baseline_from_env() -> Optional[tuple]:
    """``MXNET_FUSION_BASELINE=<path>[:<leg>]`` → (baselines dict,
    leg-or-None); None when unset or unreadable (logged, never
    raises)."""
    spec = os.environ.get("MXNET_FUSION_BASELINE")
    if not spec:
        return None
    path, leg = spec, None
    if ":" in spec and not os.path.exists(spec):
        path, leg = spec.rsplit(":", 1)
    try:
        return load_baselines(path), leg
    except Exception as e:       # pragma: no cover - defensive
        _LOG.warning("MXNET_FUSION_BASELINE=%r unreadable (%s: %s)",
                     spec, type(e).__name__, e)
        return None


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def publish(report: FusionReport):
    """Refresh the ``mx_fusion_*`` gauges from one census (the latest
    analyzed program wins — one step program is live at a time)."""
    try:
        from ..telemetry import names as tn
        from ..telemetry import registry as treg
        reg = treg()
        reg.gauge(tn.FUSION_REGIONS).set(report.n_fusions)
        reg.gauge(tn.FUSION_STRANDED).set(len(report.stranded))
        reg.gauge(tn.FUSION_BOUNDARY_BYTES).set(report.boundary_bytes)
        reg.gauge(tn.FUSION_COMPUTE_BOUND).set(
            report.compute_bound_pct / 100.0)
    except Exception:            # pragma: no cover - defensive
        _LOG.debug("fusion gauge publish failed", exc_info=True)
