"""SPMD sharding static analysis: sharding-flow audit, implicit-reshard
detection, per-mesh-axis communication cost model, spec invariant packs.

The collective census (PR 4) can say *which* collectives a compiled
program runs and on which mesh axes; it cannot say whether the program's
sharding matches the user's INTENT, or what the communication costs.
This pass closes both gaps, the checker spine the unified sharding
frontend (`compile_step(mesh=, spec=)`) will stand on — built before the
refactor the same way the PR 9 fusion census preceded the PR 10 kernel
layer:

1. **Sharding-flow audit** (:func:`sharding_table`): GSPMD
   ``sharding={...}`` annotations on the optimized HLO's entry
   parameters / outputs / annotated ops (and ``mhlo.sharding`` attrs on
   the StableHLO side) parsed into structured :class:`OpSharding`
   objects — iota tile assignments (``devices=[2,2]<=[4]``, with
   ``T(...)`` source transposes), explicit device lists, partial
   replication (``last_tile_dim_replicate``), ``replicated`` /
   ``manual`` / ``maximal``, and tuple shardings — resolved against the
   mesh's axis names into PartitionSpec-shaped per-dim axis tuples.
   The result is the per-parameter/per-activation sharding table of the
   entry computation: what layout each buffer ACTUALLY got.
2. **Implicit-reshard detection** (:func:`implicit_reshards`):
   SPMD-partitioner-inserted all-gathers / all-to-alls /
   collective-permutes that are not implied by the declared spec (a
   ``P("dp", None)`` input silently gathered to replicated before a
   matmul), ranked by wire bytes moved per step, each naming the
   producing and consuming op.  "Implied" is declarative: a
   :class:`SpecPack` blesses the collectives its parallelism pattern is
   SUPPOSED to run (ZeRO's reduce-scatter + weight all-gather, MoE's
   two all-to-alls, the pipeline/ring ppermutes); everything else above
   the byte floor is a reshard the user did not ask for.
3. **Per-axis communication cost model** (:func:`comm_cost`): every
   collective costed in estimated seconds from ring-algorithm wire
   bytes over a per-axis bandwidth profile — ICI vs DCN vs the measured
   CPU fallback, the machine profile checked in next to the fusion
   census's roofline constants (``MXNET_SHARDING_BANDWIDTH``
   overrides).  This upgrades the PR 4 census from counting to costing
   and publishes the ``mx_sharding_*`` gauges.
4. **``expect_spec`` invariant packs** (:class:`SpecPack`,
   :func:`expect_spec`): ``expect_mode``'s fused/zero/predict
   expectations generalized to declarative packs over arbitrary
   mesh+PartitionSpec layouts — each pack asserts its collective
   signature (min/max per kind×axis), zero implicit reshards above its
   floor, and its sharded-state byte budget (table-derived: params laid
   out on the pack's state axis must actually be ~1/N per replica).
   Packs for the five existing parallelism paths register from their
   home modules (dp/ZeRO here in analysis/program.py's expect_mode,
   tp + sequence-parallel ring attention from ops/attention.py,
   expert-parallel from ops/moe.py, pipeline from parallel/pipeline.py).
5. **Baseline regression gate** (:func:`check_baseline`): checked-in
   per-leg ``{implicit_reshards, reshard_bytes}`` baselines
   (``tests/fixtures/sharding_baselines.json``) enforced by the tier-1
   sweep and by ``MXNET_SHARDING_BASELINE=<path>[:<leg>]`` inside any
   ``analyze()`` — a jax bump or model edit that silently starts
   gathering a sharded tensor fails fast instead of surfacing as a
   step-time regression three PRs later.

Like every analyzer here: parsing failures degrade to unresolved
fields, never exceptions — an analyzer must not take down the run it
observes.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .hlo import HloModule, HloOp, parse_hlo, parse_shape_elements
from .report import CollectiveOp, CollectiveStats, Finding

__all__ = [
    "OpSharding", "parse_op_sharding", "ParamSharding", "ShardingTable",
    "sharding_table", "stablehlo_shardings", "Reshard",
    "implicit_reshards", "BandwidthProfile", "bandwidth_profile",
    "collective_wire_bytes", "CommCost", "comm_cost", "CollectiveRule",
    "SpecPack", "register_spec_pack", "get_spec_pack", "spec_packs",
    "expect_spec", "ShardingAudit", "audit_sharding", "publish",
    "load_baselines", "check_baseline", "baseline_from_env",
    "RESHARD_FLOOR_BYTES", "ICI_BANDWIDTH_GBPS", "DCN_BANDWIDTH_GBPS",
    "CPU_BANDWIDTH_GBPS",
]

_LOG = logging.getLogger("mxnet_tpu.analysis")

#: byte floor below which an undeclared collective is scalar glue
#: (partition-id bookkeeping, loss/metric gathers), not a reshard
#: finding — same spirit as the fusion census's stranded floor
RESHARD_FLOOR_BYTES = 4096

#: per-link bandwidth profile, checked in next to the fusion census's
#: roofline constants (fusion.BENCH_ROOFLINE_TFLOPS / HBM 819 GB/s):
#: ICI = one inter-chip ring link of the BENCH_r05 machine (TPU v5
#: lite, public spec ~200 GB/s per chip; one ring direction), DCN = the
#: data-center NIC path pods cross between slices (~200 Gbit/s), CPU =
#: the measured host-loopback fallback the 8-device virtual mesh
#: actually moves bytes over.  Estimates rank and budget — they are not
#: a network simulator (MXNET_SHARDING_BANDWIDTH overrides).
ICI_BANDWIDTH_GBPS = 180.0
DCN_BANDWIDTH_GBPS = 25.0
CPU_BANDWIDTH_GBPS = 10.0

_LINK_GBPS = {"ici": ICI_BANDWIDTH_GBPS, "dcn": DCN_BANDWIDTH_GBPS,
              "cpu": CPU_BANDWIDTH_GBPS}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: collective kinds the SPMD partitioner inserts to MOVE data between
#: layouts (vs reduce it) — the implicit-reshard candidates.  A healthy
#: all-reduce is a declared reduction (grad psum, loss mean); gathers /
#: exchanges / permutes not named by the spec pack are layout changes
#: the user did not ask for.
RESHARD_KINDS = ("all_gather", "all_to_all", "collective_permute")


# ---------------------------------------------------------------------------
# OpSharding: the GSPMD sharding-annotation grammar
# ---------------------------------------------------------------------------

_DEVICES_RE = re.compile(
    r"devices=\[([\d,]+)\]"                       # tile dims
    r"(?:<=\[([\d,]+)\](?:T\(([\d,]+)\))?"        # iota [+ transpose]
    r"|([\d][\d,\s]*))?")                         # | explicit id list
_LAST_TILE_REPL_RE = re.compile(r"last_tile_dim_replicate")
_LAST_TILE_DIMS_RE = re.compile(r"last_tile_dims=\{([^}]*)\}")
_MAXIMAL_RE = re.compile(r"maximal.*?device=(\d+)|\{(\d+)\}")


@dataclass
class OpSharding:
    """One parsed GSPMD sharding annotation.

    ``kind``: ``replicated`` | ``tiled`` | ``manual`` | ``maximal`` |
    ``tuple`` | ``unknown``.  For ``tiled``, ``tile_dims`` holds the
    full tile-assignment shape (INCLUDING any trailing replication /
    manual subgroup dims — ``n_subgroup_dims`` of them) and
    ``device_order`` the flattened device ids in assignment order.
    ``spec`` is filled by :meth:`resolve`: one entry per TENSOR dim —
    ``None`` (unsharded), an axis name, or a tuple of axis names."""
    kind: str
    raw: str = ""
    tile_dims: Tuple[int, ...] = ()
    n_subgroup_dims: int = 0
    device_order: Optional[Tuple[int, ...]] = None
    maximal_device: Optional[int] = None
    parts: Optional[List["OpSharding"]] = None      # tuple shardings
    spec: Optional[Tuple[Any, ...]] = None          # resolved vs mesh

    @property
    def data_tile_dims(self) -> Tuple[int, ...]:
        """Tile dims that partition TENSOR data (subgroup dims — the
        ``last_tile_dim_replicate`` replication dim, ``last_tile_dims``
        manual dims — stripped)."""
        if self.n_subgroup_dims:
            return self.tile_dims[:-self.n_subgroup_dims]
        return self.tile_dims

    @property
    def shard_count(self) -> int:
        """Shards the data is split into (1 for replicated/manual)."""
        n = 1
        for d in self.data_tile_dims:
            n *= d
        return n

    def local_shape(self, global_shape: Sequence[int]) -> Tuple[int, ...]:
        """Per-shard shape of a ``global_shape`` tensor under this
        sharding (ceil-divided, as GSPMD pads)."""
        dims = self.data_tile_dims
        out = []
        for i, g in enumerate(global_shape):
            t = dims[i] if i < len(dims) else 1
            out.append(-(-int(g) // max(1, t)))
        return tuple(out)

    def global_shape(self, local_shape: Sequence[int]) -> Tuple[int, ...]:
        """Global logical shape reconstructed from a per-shard shape
        (exact when the global dim divided evenly; an upper bound
        otherwise — GSPMD pads the last shard)."""
        dims = self.data_tile_dims
        out = []
        for i, l in enumerate(local_shape):
            t = dims[i] if i < len(dims) else 1
            out.append(int(l) * max(1, t))
        return tuple(out)

    def resolve(self, mesh) -> Optional[Tuple[Any, ...]]:
        """Fill ``spec`` with the mesh axis (or axis tuple) each tensor
        dim is sharded over, by matching the tile assignment's device
        order against the mesh's device-id array.  ``None`` when the
        assignment doesn't correspond to this mesh (wrong world, or an
        explicit order no axis permutation explains)."""
        self.spec = _resolve_spec(self, mesh)
        return self.spec

    def describe(self) -> str:
        if self.kind == "tiled":
            if self.spec is not None:
                parts = []
                for s in self.spec:
                    if s is None:
                        parts.append("-")
                    elif isinstance(s, tuple):
                        parts.append("(" + ",".join(s) + ")")
                    else:
                        parts.append(str(s))
                body = "P(" + ", ".join(parts) + ")"
            else:
                body = "tiled" + str(list(self.data_tile_dims))
            if self.n_subgroup_dims:
                body += "+partial"
            return body
        if self.kind == "tuple":
            return "(" + ", ".join(p.describe()
                                   for p in (self.parts or [])) + ")"
        return self.kind

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "tile_dims": list(self.tile_dims),
                "shard_count": self.shard_count,
                "spec": [list(s) if isinstance(s, tuple) else s
                         for s in self.spec] if self.spec is not None
                else None,
                "describe": self.describe()}


def parse_op_sharding(text: Optional[str]) -> Optional[OpSharding]:
    """Parse one ``sharding={...}`` / ``mhlo.sharding`` annotation body.

    Accepts the braces-included raw attr (``{devices=[2,2]<=[4]}``) or
    its bare contents; tuple shardings (``{{replicated}, {devices=...}}``)
    return kind ``tuple`` with ``parts``.  Unrecognized text degrades to
    kind ``unknown``, never raises."""
    if not text:
        return None
    body = text.strip()
    if body.startswith("{") and body.endswith("}"):
        body = body[1:-1].strip()
    if body.startswith("{"):
        # tuple-of-shardings: split top-level {...} groups
        parts, depth, start = [], 0, None
        for i, ch in enumerate(body):
            if ch == "{":
                if depth == 0:
                    start = i
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0 and start is not None:
                    sub = parse_op_sharding(body[start:i + 1])
                    if sub is not None:
                        parts.append(sub)
        return OpSharding(kind="tuple", raw=text, parts=parts)
    if body == "replicated":
        return OpSharding(kind="replicated", raw=text)
    if body.startswith("manual"):
        return OpSharding(kind="manual", raw=text)
    if body.startswith("maximal") or re.fullmatch(r"\d+", body):
        m = _MAXIMAL_RE.search(body)
        dev = None
        if m:
            dev = int(m.group(1) or m.group(2))
        return OpSharding(kind="maximal", raw=text, maximal_device=dev)
    m = _DEVICES_RE.search(body)
    if m is None:
        return OpSharding(kind="unknown", raw=text)
    tile_dims = tuple(int(d) for d in m.group(1).split(",") if d)
    order: Optional[Tuple[int, ...]] = None
    n = 1
    for d in tile_dims:
        n *= d
    if m.group(2):                                    # iota form
        try:
            import numpy as onp
            src = [int(x) for x in m.group(2).split(",") if x]
            ids = onp.arange(int(onp.prod(src))).reshape(src)
            if m.group(3):
                perm = [int(x) for x in m.group(3).split(",") if x]
                ids = ids.transpose(perm)
            order = tuple(int(x) for x in ids.reshape(-1))
        except Exception:                # pragma: no cover - defensive
            order = None
    elif m.group(4):                                  # explicit list
        order = tuple(int(x) for x in
                      m.group(4).replace(" ", "").split(",") if x != "")
    if order is not None and len(order) != n:
        order = None
    subgroups = 0
    if _LAST_TILE_REPL_RE.search(body):
        subgroups = 1
    ltd = _LAST_TILE_DIMS_RE.search(body)
    if ltd:
        subgroups = max(subgroups,
                        len([x for x in ltd.group(1).split(",") if x]))
    return OpSharding(kind="tiled", raw=text, tile_dims=tile_dims,
                      n_subgroup_dims=subgroups, device_order=order)


def _mesh_coords(mesh):
    """{device_id: (coord per mesh axis)} + axis names/sizes, for any
    DeviceMesh / jax Mesh; None when unavailable."""
    jmesh = getattr(mesh, "mesh", mesh)
    if jmesh is None:
        return None
    try:
        import numpy as onp
        dev_ids = onp.array([d.id for d in jmesh.devices.flat]).reshape(
            jmesh.devices.shape)
        axis_names = list(jmesh.axis_names)
        coords: Dict[int, Tuple[int, ...]] = {}
        for idx in onp.ndindex(dev_ids.shape):
            coords[int(dev_ids[idx])] = tuple(int(i) for i in idx)
        return coords, axis_names, dev_ids.shape
    except Exception:                    # pragma: no cover - defensive
        return None


def _resolve_spec(sh: OpSharding, mesh) -> Optional[Tuple[Any, ...]]:
    if sh.kind != "tiled" or sh.device_order is None:
        return None
    info = _mesh_coords(mesh)
    if info is None:
        return None
    coords, axis_names, axis_sizes = info
    if any(i not in coords for i in sh.device_order):
        return None                      # annotation from another world
    try:
        import numpy as onp
        assignment = onp.array(sh.device_order).reshape(sh.tile_dims)
        n_axes = len(axis_names)
        # per-tile-dim: which mesh-axis coordinates vary along it
        spec: List[Any] = []
        varies = []                      # [dim][axis] -> bool
        for dim in range(len(sh.tile_dims)):
            moved = onp.moveaxis(assignment, dim, -1).reshape(
                -1, sh.tile_dims[dim])
            v = [False] * n_axes
            for row in moved:
                base = coords[int(row[0])]
                for dev in row[1:]:
                    c = coords[int(dev)]
                    for a in range(n_axes):
                        if c[a] != base[a]:
                            v[a] = True
            varies.append(v)
        for dim in range(len(sh.data_tile_dims)):
            t = sh.tile_dims[dim]
            if t == 1:
                spec.append(None)
                continue
            axes = tuple(axis_names[a] for a in range(n_axes)
                         if varies[dim][a]
                         # an axis belongs to ONE tensor dim; exclude
                         # axes that also vary along another data dim
                         and not any(varies[d2][a]
                                     for d2 in range(
                                         len(sh.data_tile_dims))
                                     if d2 != dim))
            ext = 1
            for ax in axes:
                ext *= int(axis_sizes[axis_names.index(ax)])
            if not axes or ext != t:
                spec.append(None)        # unresolvable against this mesh
            elif len(axes) == 1:
                spec.append(axes[0])
            else:
                spec.append(axes)
        return tuple(spec)
    except Exception:                    # pragma: no cover - defensive
        return None


# ---------------------------------------------------------------------------
# sharding-flow audit: the per-buffer sharding table
# ---------------------------------------------------------------------------

_PARAM_IDX_RE = re.compile(r"parameter\((\d+)\)")
# StableHLO:  %arg0: tensor<8x16xf32> ... mhlo.sharding = "{...}"
_MHLO_ARG_RE = re.compile(
    r"%arg(\d+):\s*tensor<((?:\d+x)*)([a-z][a-z0-9]*)>"
    r"[^)]*?mhlo\.sharding\s*=\s*\"([^\"]+)\"")


@dataclass
class ParamSharding:
    """One entry-computation buffer's resolved layout."""
    index: int
    name: str                            # op_name metadata (jax label)
    role: str                            # parameter | output | op
    local_shape: Tuple[int, ...]
    global_shape: Tuple[int, ...]
    dtype: str
    bytes_local: int
    bytes_global: int
    sharding: Optional[OpSharding]

    @property
    def describe(self) -> str:
        return self.sharding.describe() if self.sharding else "?"

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "name": self.name, "role": self.role,
                "local_shape": list(self.local_shape),
                "global_shape": list(self.global_shape),
                "dtype": self.dtype, "bytes_local": self.bytes_local,
                "bytes_global": self.bytes_global,
                "sharding": self.sharding.to_dict()
                if self.sharding else None}


@dataclass
class ShardingTable:
    """Per-parameter/per-activation sharding of one entry computation."""
    params: List[ParamSharding] = field(default_factory=list)
    outputs: List[ParamSharding] = field(default_factory=list)
    annotated: List[ParamSharding] = field(default_factory=list)
    num_partitions: int = 1
    mesh_axes: Tuple[str, ...] = ()

    @property
    def rows(self) -> List[ParamSharding]:
        return self.params + self.outputs + self.annotated

    def digest(self) -> str:
        """Stable fingerprint of the program's layout decisions — two
        captures with the same digest shard every buffer identically."""
        h = hashlib.sha1()
        for r in sorted(self.rows, key=lambda r: (r.role, r.index,
                                                  r.name)):
            h.update(f"{r.role}:{r.index}:{r.name}:{r.dtype}:"
                     f"{r.local_shape}:"
                     f"{r.sharding.raw if r.sharding else '-'}"
                     .encode())
        return h.hexdigest()[:12]

    def sharded_bytes(self, axis: str) -> Tuple[int, int]:
        """(local, global) bytes summed over params whose resolved spec
        names ``axis`` — the table-derived state footprint a spec
        pack's byte budget checks."""
        loc = glob = 0
        for r in self.params:
            spec = r.sharding.spec if r.sharding else None
            if not spec:
                continue
            hit = any(s == axis or (isinstance(s, tuple) and axis in s)
                      for s in spec)
            if hit:
                loc += r.bytes_local
                glob += r.bytes_global
        return loc, glob

    def to_dict(self) -> Dict[str, Any]:
        return {"num_partitions": self.num_partitions,
                "mesh_axes": list(self.mesh_axes),
                "digest": self.digest(),
                "params": [r.to_dict() for r in self.params],
                "outputs": [r.to_dict() for r in self.outputs],
                "annotated": [r.to_dict() for r in self.annotated]}

    def table_str(self, top: int = 32) -> str:
        short = {"parameter": "param", "output": "out", "op": "op"}
        lines = [f"{'#':>3s} {'role':<7s}{'buffer':<34s}{'dtype':<7s}"
                 f"{'local':<16s}{'global':<16s}layout"]
        for r in self.rows[:top]:
            lines.append(
                f"{r.index:>3d} {short.get(r.role, r.role):<7s}"
                f"{r.name[:32]:<34s}"
                f"{r.dtype:<7s}{str(list(r.local_shape)):<16s}"
                f"{str(list(r.global_shape)):<16s}{r.describe}")
        if len(self.rows) > top:
            lines.append(f"  ... {len(self.rows) - top} more buffers")
        return "\n".join(lines)


def stablehlo_shardings(text: str) -> Dict[int, Tuple[Tuple[int, ...],
                                                      str, OpSharding]]:
    """``mhlo.sharding`` annotations of a lowered StableHLO module:
    {arg index: (GLOBAL shape, dtype, OpSharding)} — StableHLO is
    pre-partitioning, so its shapes are the global logical ones."""
    out: Dict[int, Tuple[Tuple[int, ...], str, OpSharding]] = {}
    for m in _MHLO_ARG_RE.finditer(text or ""):
        idx = int(m.group(1))
        dims = tuple(int(d) for d in m.group(2).split("x") if d)
        if idx in out:
            continue                     # first mention wins
        sh = parse_op_sharding(m.group(4))
        if sh is not None:
            out[idx] = (dims, m.group(3), sh)
    return out


def _shape_of(type_str: str) -> Tuple[int, ...]:
    m = re.search(r"\[([\d,]*)\]", type_str or "")
    if not m or not m.group(1):
        return ()
    return tuple(int(d) for d in m.group(1).split(",") if d)


def sharding_table(hlo: Union[str, HloModule], mesh=None,
                   stablehlo: str = "") -> ShardingTable:
    """Build the sharding-flow table of one optimized program.

    Entry parameters and the entry ROOT (with their ``sharding=``
    attrs), plus any annotated non-parameter op, resolved against
    ``mesh`` when given.  ``stablehlo`` (the lowered pre-partitioning
    text) supplies exact global shapes where available; otherwise
    global = local x tile dims."""
    mod = parse_hlo(hlo) if isinstance(hlo, str) else hlo
    jmesh = getattr(mesh, "mesh", mesh)
    table = ShardingTable(num_partitions=mod.num_partitions,
                          mesh_axes=tuple(jmesh.axis_names)
                          if jmesh is not None else ())
    mhlo = stablehlo_shardings(stablehlo)
    entry = mod.computations.get(mod.entry or "")
    names = entry.op_names if entry is not None else list(mod.ops)
    for op_name in names:
        op = mod.ops.get(op_name)
        if op is None:
            continue
        sh = parse_op_sharding(op.sharding) if op.sharding else None
        if sh is not None and mesh is not None:
            sh.resolve(mesh)
        local = _shape_of(op.type_str)
        if op.opcode == "parameter":
            im = _PARAM_IDX_RE.search(op.line)
            idx = int(im.group(1)) if im else len(table.params)
            glob = None
            if idx in mhlo:
                glob = mhlo[idx][0]
                if sh is None:
                    sh = mhlo[idx][2]
                    if mesh is not None:
                        sh.resolve(mesh)
            if glob is None:
                glob = sh.global_shape(local) if sh else local
            gelems = 1
            for d in glob:
                gelems *= d
            table.params.append(ParamSharding(
                index=idx, name=op.op_name or op.name, role="parameter",
                local_shape=local, global_shape=tuple(glob),
                dtype=op.dtype or "?", bytes_local=op.bytes,
                bytes_global=gelems * _DTYPE_BYTES.get(op.dtype or "f32",
                                                       4),
                sharding=sh))
        elif op.is_root:
            glob = sh.global_shape(local) if sh else local
            table.outputs.append(ParamSharding(
                index=0, name=op.op_name or op.name, role="output",
                local_shape=local, global_shape=tuple(glob),
                dtype=op.dtype or "?", bytes_local=op.bytes,
                bytes_global=op.bytes * (sh.shard_count if sh else 1),
                sharding=sh))
        elif sh is not None:
            glob = sh.global_shape(local)
            table.annotated.append(ParamSharding(
                index=len(table.annotated), name=op.op_name or op.name,
                role="op", local_shape=local, global_shape=tuple(glob),
                dtype=op.dtype or "?", bytes_local=op.bytes,
                bytes_global=op.bytes * sh.shard_count, sharding=sh))
    table.params.sort(key=lambda r: r.index)
    return table


# ---------------------------------------------------------------------------
# per-axis communication cost model
# ---------------------------------------------------------------------------

class BandwidthProfile:
    """Per-mesh-axis link bandwidth, GB/s.

    Built from a spec string (``MXNET_SHARDING_BANDWIDTH``): a bare link
    kind (``ici`` | ``dcn`` | ``cpu``) or GB/s number applies to every
    axis; ``axis=kind_or_GBps`` entries override per axis
    (``"dp=ici,pp=dcn"`` models a two-slice pod).  Default: ``ici`` on
    TPU backends, the measured ``cpu`` fallback elsewhere."""

    def __init__(self, default_gbps: float,
                 axis_gbps: Optional[Dict[str, float]] = None,
                 name: str = "custom"):
        self.default_gbps = float(default_gbps)
        self.axis_gbps = dict(axis_gbps or {})
        self.name = name

    def gbps(self, axes: Sequence[str] = ()) -> float:
        for ax in axes or ():
            if ax in self.axis_gbps:
                return self.axis_gbps[ax]
        return self.default_gbps

    @staticmethod
    def _term(term: str) -> Optional[float]:
        term = term.strip().lower()
        if term in _LINK_GBPS:
            return _LINK_GBPS[term]
        try:
            return float(term)
        except ValueError:
            return None

    @classmethod
    def parse(cls, spec: str) -> "BandwidthProfile":
        default = None
        axis: Dict[str, float] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                ax, val = part.split("=", 1)
                g = cls._term(val)
                if g is not None:
                    if ax.strip() in ("default", "*"):
                        default = g
                    else:
                        axis[ax.strip()] = g
            else:
                g = cls._term(part)
                if g is not None:
                    default = g
        if default is None:
            default = _default_link_gbps()
        return cls(default, axis, name=spec)


def _default_link_gbps() -> float:
    try:
        import jax
        backend = jax.default_backend()
    except Exception:                    # pragma: no cover - defensive
        backend = "cpu"
    return ICI_BANDWIDTH_GBPS if backend == "tpu" else CPU_BANDWIDTH_GBPS


def bandwidth_profile(spec: Optional[str] = None) -> BandwidthProfile:
    """The active profile: ``spec`` > ``MXNET_SHARDING_BANDWIDTH`` env >
    backend default (ICI on TPU, measured CPU fallback elsewhere)."""
    spec = spec if spec is not None \
        else os.environ.get("MXNET_SHARDING_BANDWIDTH")
    if spec:
        return BandwidthProfile.parse(spec)
    g = _default_link_gbps()
    name = "ici" if g == ICI_BANDWIDTH_GBPS else "cpu"
    return BandwidthProfile(g, name=name)


def collective_wire_fraction(kind: str, group_size: int,
                             decomposed: bool = False) -> float:
    """Ring-model wire traffic as a FRACTION of the census record's
    payload bytes.  Costing through this fraction prices collectives
    per payload byte rather than per op, so N bucketed collectives of B
    bytes each cost the same as one collective of N*B bytes — bucketing
    the ZeRO gradient for overlap must not inflate the modeled cost."""
    n = max(1, group_size)
    if n == 1:
        return 0.0
    if kind == "all_gather":
        return (n - 1) / n
    if kind == "reduce_scatter":
        if decomposed:                    # payload = full input
            return (n - 1) / n
        return float(n - 1)               # payload = the 1/n shard
    if kind == "all_reduce":
        return 2 * (n - 1) / n
    if kind == "all_to_all":
        return (n - 1) / n
    return 1.0                            # collective_permute: one hop


def collective_wire_bytes(op: CollectiveOp) -> int:
    """Ring-algorithm bytes each participant moves over its link for
    one collective, from the census record's RESULT payload.

    all_gather: result is the full gathered buffer -> (n-1)/n x result.
    reduce_scatter: result is the 1/n shard -> (n-1) x result ((n-1)/n
    of the full input; a DECOMPOSED record's payload is the full
    all-reduce result, so (n-1)/n x payload).  all_reduce: ring
    reduce-scatter + all-gather = 2(n-1)/n x payload.  all_to_all:
    (n-1)/n of the buffer changes shards.  collective_permute: the
    whole payload moves one hop."""
    n = max(1, op.group_size)
    b = op.elements * _DTYPE_BYTES.get(op.dtype, 4)
    if n == 1:
        return 0
    if op.kind == "all_gather":
        return b * (n - 1) // n
    if op.kind == "reduce_scatter":
        if op.decomposed:                 # payload = full input
            return b * (n - 1) // n
        return b * (n - 1)                # payload = the 1/n shard
    if op.kind == "all_reduce":
        return 2 * b * (n - 1) // n
    if op.kind == "all_to_all":
        return b * (n - 1) // n
    if op.kind == "collective_permute":
        return b
    return b


@dataclass
class CommCost:
    """Estimated per-step communication cost of one program's census."""
    per_op: List[Dict[str, Any]] = field(default_factory=list)
    per_axis_s: Dict[str, float] = field(default_factory=dict)
    per_axis_bytes: Dict[str, int] = field(default_factory=dict)
    total_s: float = 0.0
    total_bytes: int = 0
    profile: str = "cpu"

    def to_dict(self) -> Dict[str, Any]:
        return {"total_s": self.total_s, "total_bytes": self.total_bytes,
                "per_axis_s": dict(self.per_axis_s),
                "per_axis_bytes": dict(self.per_axis_bytes),
                "profile": self.profile,
                "per_op": self.per_op[:24]}

    def table_str(self, top: int = 12) -> str:
        lines = [f"{'collective':<28s}{'kind':<20s}{'axis':<8s}"
                 f"{'wire B':>12s}{'est s':>12s}"]
        for r in sorted(self.per_op, key=lambda r: -r["seconds"])[:top]:
            lines.append(f"{r['name'][:26]:<28s}{r['kind']:<20s}"
                         f"{(r['axes'][0] if r['axes'] else '?'):<8s}"
                         f"{r['wire_bytes']:>12d}{r['seconds']:>12.3e}")
        for ax in sorted(self.per_axis_s):
            lines.append(f"  axis {ax!r}: {self.per_axis_bytes[ax]} B, "
                         f"~{self.per_axis_s[ax]:.3e} s/step")
        return "\n".join(lines)


def comm_cost(census: CollectiveStats,
              profile: Optional[BandwidthProfile] = None) -> CommCost:
    """Cost every collective in a census against the bandwidth profile
    — the per-axis estimate that turns the PR 4 census from counting
    into costing (arXiv:1909.09756's first-order pod-scaling
    question).

    Seconds are priced PER PAYLOAD BYTE (``collective_wire_fraction``
    x payload / bandwidth), not per op — N bucketed collectives of B
    bytes each sum to the cost of one collective of N*B bytes, so the
    overlap-motivated bucketing of the ZeRO gradient leaves the modeled
    comm budget unchanged (the ``wire_bytes`` per-op records keep the
    floor-divided integer form pinned by the ring-formula goldens)."""
    profile = profile or bandwidth_profile()
    cost = CommCost(profile=profile.name)
    for op in census.ops:
        wire = collective_wire_bytes(op)
        payload = op.elements * _DTYPE_BYTES.get(op.dtype, 4)
        frac = collective_wire_fraction(
            op.kind, op.group_size, op.decomposed)
        gbps = profile.gbps(op.axes)
        sec = payload * frac / (gbps * 1e9) if gbps > 0 else 0.0
        ax = op.axes[0] if op.axes else "?"
        cost.per_op.append({"name": op.name, "kind": op.kind,
                            "axes": list(op.axes), "wire_bytes": wire,
                            "seconds": sec})
        cost.per_axis_s[ax] = cost.per_axis_s.get(ax, 0.0) + sec
        cost.per_axis_bytes[ax] = cost.per_axis_bytes.get(ax, 0) + wire
        cost.total_s += sec
        cost.total_bytes += wire
    cost.per_op.sort(key=lambda r: -r["seconds"])
    return cost


# ---------------------------------------------------------------------------
# implicit-reshard detection
# ---------------------------------------------------------------------------

@dataclass
class CollectiveRule:
    """One declared/asserted collective pattern of a spec pack.

    ``kind`` is the census kind (a tuple allows alternatives — "a
    gradient reduction is an all_reduce OR a reduce_scatter"; ``"*"``
    matches every collective); ``axis`` restricts to collectives whose
    replica groups span that mesh axis (None = any); ``elements``
    restricts payload element counts (the zero pack declares its weight
    all-gathers by their padded unit sizes so anything ELSE gathering is
    a reshard); ``min_count``/``max_count`` make the rule an assertion
    (0/None = declaration only — blessed, not required).  ``rule_id``
    and ``severity`` control the finding a violation emits —
    ``expect_mode``'s packs keep the historical ``collective-mismatch``
    / ``per-param-allreduce`` ids the tier-1 fixtures assert on."""
    kind: Union[str, Tuple[str, ...]]
    axis: Optional[str] = None
    min_count: int = 0
    max_count: Optional[int] = None
    elements: Optional[frozenset] = None
    rule_id: str = "spec-mismatch"
    severity: str = "error"

    @property
    def kinds(self) -> Tuple[str, ...]:
        return (self.kind,) if isinstance(self.kind, str) \
            else tuple(self.kind)

    def matches(self, op: CollectiveOp) -> bool:
        if "*" not in self.kinds and op.kind not in self.kinds:
            return False
        if self.axis is not None and op.axes and \
                self.axis not in op.axes:
            return False
        if self.elements is not None and \
                op.elements not in self.elements:
            return False
        return True

    def describe_kind(self) -> str:
        return "|".join(self.kinds)


@dataclass
class Reshard:
    """One SPMD-partitioner-inserted layout change the declared spec
    did not imply."""
    name: str
    kind: str
    axes: Tuple[str, ...]
    group_size: int
    elements: int
    dtype: str
    payload_bytes: int
    wire_bytes: int
    seconds: float
    producer: str = ""
    consumers: Tuple[str, ...] = ()

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "axes": list(self.axes), "group_size": self.group_size,
                "elements": self.elements, "dtype": self.dtype,
                "payload_bytes": self.payload_bytes,
                "wire_bytes": self.wire_bytes, "seconds": self.seconds,
                "producer": self.producer,
                "consumers": list(self.consumers)}


def _neighbors(mod: Optional[HloModule], name: str):
    """(producer, consumers) of a collective, looking through
    get-tuple-element/tuple/bitcast plumbing."""
    if mod is None or name not in mod.ops:
        return "", ()
    transparent = {"get-tuple-element", "tuple", "bitcast"}
    op = mod.ops[name]
    producer = ""
    for o in op.operands:
        p = mod.ops.get(o)
        seen = 0
        while p is not None and p.opcode in transparent and seen < 8:
            p = mod.ops.get(p.operands[0]) if p.operands else None
            seen += 1
        if p is not None and p.opcode not in ("constant", "parameter"):
            producer = p.name
            break
        if p is not None and not producer:
            producer = p.name
    cons: List[str] = []
    stack = [name]
    seen = 0
    while stack and seen < 32:
        cur = stack.pop()
        seen += 1
        for c in mod.consumers(cur):
            if c.opcode in transparent:
                stack.append(c.name)
            else:
                cons.append(c.name)
    return producer, tuple(dict.fromkeys(cons))


def implicit_reshards(census: CollectiveStats,
                      mod: Optional[HloModule] = None,
                      declared: Sequence[CollectiveRule] = (),
                      floor_bytes: int = RESHARD_FLOOR_BYTES,
                      profile: Optional[BandwidthProfile] = None) \
        -> List[Reshard]:
    """Collectives that MOVE data (all-gather / all-to-all /
    collective-permute) yet match no declared rule and clear the byte
    floor — ranked by wire bytes, each naming its producing and
    consuming ops.  A ``P("dp", None)`` input silently gathered to
    replicated before a matmul shows up here with the gather's full
    byte count."""
    profile = profile or bandwidth_profile()
    out: List[Reshard] = []
    for op in census.ops:
        if op.kind not in RESHARD_KINDS:
            continue
        if any(r.matches(op) for r in declared):
            continue
        payload = op.elements * _DTYPE_BYTES.get(op.dtype, 4)
        if payload < floor_bytes:
            continue
        wire = collective_wire_bytes(op)
        gbps = profile.gbps(op.axes)
        producer, consumers = _neighbors(mod, op.name)
        out.append(Reshard(
            name=op.name, kind=op.kind, axes=op.axes,
            group_size=op.group_size, elements=op.elements,
            dtype=op.dtype, payload_bytes=payload, wire_bytes=wire,
            seconds=wire / (gbps * 1e9) if gbps > 0 else 0.0,
            producer=producer, consumers=consumers))
    out.sort(key=lambda r: -r.wire_bytes)
    return out


# ---------------------------------------------------------------------------
# spec invariant packs
# ---------------------------------------------------------------------------

@dataclass
class SpecPack:
    """Declarative invariant pack for one mesh+PartitionSpec layout.

    ``rules`` are asserted (min/max collective counts per kind x axis);
    ``declared`` adds blessing-only patterns; both bless their matches
    for reshard detection.  ``max_reshard_bytes`` bounds the total wire
    bytes of implicit reshards above ``reshard_floor`` (0 = none
    allowed; None = report reshards as warnings only and leave
    regression protection to the baseline gate — the mode packs use
    None because XLA legitimately trades small activation gathers
    against gradient reductions at its own discretion).
    ``state_axis`` arms the table-derived byte budget:
    params resolved onto that axis must sum to <= global/N x
    (1 + ``state_pad_tol``) per replica — the sharded-state contract of
    arXiv:2004.13336, checked structurally."""
    name: str
    description: str = ""
    axes: Tuple[str, ...] = ()
    rules: Tuple[CollectiveRule, ...] = ()
    declared: Tuple[CollectiveRule, ...] = ()
    reshard_floor: int = RESHARD_FLOOR_BYTES
    max_reshard_bytes: Optional[int] = 0
    state_axis: Optional[str] = None
    state_pad_tol: float = 0.5

    def all_declared(self) -> Tuple[CollectiveRule, ...]:
        return tuple(self.rules) + tuple(self.declared)


_SPEC_PACKS: Dict[str, SpecPack] = {}


def register_spec_pack(pack: SpecPack) -> SpecPack:
    """Register (or replace — idempotent module reloads) a pack in the
    process-wide catalog. Parallelism paths register their own pack
    next to their implementation (ops/attention.py, ops/moe.py,
    parallel/pipeline.py)."""
    _SPEC_PACKS[pack.name] = pack
    return pack


def get_spec_pack(name: str) -> SpecPack:
    from ..base import MXNetError
    if name not in _SPEC_PACKS:
        raise MXNetError(
            f"no spec pack {name!r} registered; known: "
            f"{sorted(_SPEC_PACKS)} (docs/ANALYSIS.md 'Sharding "
            "analysis')")
    return _SPEC_PACKS[name]


def spec_packs() -> Dict[str, SpecPack]:
    return dict(_SPEC_PACKS)


def expect_spec(report, pack: Union[SpecPack, str], mod=None, mesh=None,
                hlo_text: str = "") -> List[Finding]:
    """Assert one pack's invariants against a ProgramReport (or a bare
    CollectiveStats) and append the findings.

    Checks, in order: the collective signature (every rule's min/max
    count per kind x axis), implicit reshards above the pack floor
    (bounded by ``max_reshard_bytes``), and the sharded-state byte
    budget from the report's sharding table.  Returns the findings it
    appended."""
    if isinstance(pack, str):
        pack = get_spec_pack(pack)
    census = getattr(report, "collectives", report)
    audit = getattr(report, "sharding", None)
    if audit is not None:
        mod = mod if mod is not None else audit.mod
        mesh = mesh if mesh is not None else audit.mesh
    findings: List[Finding] = []
    # --- collective signature -----------------------------------------
    for rule in pack.rules:
        hits = [op for op in census.ops if rule.matches(op)]
        n = len(hits)
        where = f"{rule.describe_kind()}@{rule.axis or '*'}"
        if n < rule.min_count:
            findings.append(Finding(
                checker="sharding", rule=rule.rule_id,
                severity=rule.severity,
                message=f"[{pack.name}] expected >= {rule.min_count} "
                        f"`{rule.describe_kind()}` on axis "
                        f"{rule.axis!r}, found {n} — the "
                        f"{pack.description or pack.name} collective "
                        f"signature regressed "
                        f"(census: {census.by_kind})",
                where=where))
        if rule.max_count is not None and n > rule.max_count:
            if rule.elements is not None:
                msg = (f"[{pack.name}] {n} "
                       f"`{rule.describe_kind()}`(s) carry exactly a "
                       "declared unit's payload "
                       f"({sorted(set(o.elements for o in hits))} "
                       "elements) — the sharded update is paying "
                       "replicated reductions")
                where = ", ".join(o.name for o in hits[:4])
            else:
                msg = (f"[{pack.name}] {n} `{rule.describe_kind()}` "
                       f"on axis {rule.axis!r} exceed the declared "
                       f"maximum {rule.max_count} — the program runs "
                       f"collectives the spec did not imply "
                       f"(census: {census.by_kind})")
            findings.append(Finding(
                checker="sharding", rule=rule.rule_id,
                severity=rule.severity, message=msg, where=where))
    # --- implicit reshards --------------------------------------------
    if mod is None and hlo_text:
        mod = parse_hlo(hlo_text)
    reshards = implicit_reshards(census, mod=mod,
                                 declared=pack.all_declared(),
                                 floor_bytes=pack.reshard_floor)
    if audit is not None:
        audit.reshards = reshards
        audit.reshard_floor = pack.reshard_floor
        audit.pack = pack.name
    total = sum(r.wire_bytes for r in reshards)
    for r in reshards[:8]:
        findings.append(Finding(
            checker="sharding", rule="implicit-reshard", severity="warn",
            message=f"[{pack.name}] SPMD partitioner inserted "
                    f"`{r.kind}` of {r.payload_bytes} B "
                    f"({r.wire_bytes} B on the wire, "
                    f"~{r.seconds:.2e} s) on axis "
                    f"{r.axes[0] if r.axes else '?'} not implied by the "
                    f"declared spec — produced by `{r.producer or '?'}`"
                    f", consumed by "
                    f"{', '.join(r.consumers[:3]) or '?'}",
            where=r.name))
    if pack.max_reshard_bytes is not None and \
            total > pack.max_reshard_bytes:
        worst = reshards[0]
        findings.append(Finding(
            checker="sharding", rule="implicit-reshard",
            message=f"[{pack.name}] {len(reshards)} implicit reshard(s) "
                    f"move {total} B/step above the "
                    f"{pack.reshard_floor} B floor (budget "
                    f"{pack.max_reshard_bytes} B) — worst: "
                    f"`{worst.kind}` {worst.payload_bytes} B at "
                    f"{worst.name} (producer `{worst.producer or '?'}`)",
            where=worst.name))
    # --- sharded-state byte budget ------------------------------------
    if pack.state_axis and audit is not None and \
            audit.table is not None and mesh is not None:
        jmesh = getattr(mesh, "mesh", mesh)
        try:
            n = int(dict(jmesh.shape).get(pack.state_axis, 0))
        except Exception:                # pragma: no cover - defensive
            n = 0
        loc, glob = audit.table.sharded_bytes(pack.state_axis)
        if n >= 2 and glob:
            budget = int(glob / n * (1.0 + pack.state_pad_tol))
            if loc > budget:
                findings.append(Finding(
                    checker="sharding", rule="state-budget",
                    message=f"[{pack.name}] buffers sharded on "
                            f"{pack.state_axis!r} hold {loc} B per "
                            f"replica, over the ~1/{n} budget "
                            f"{budget} B (global {glob} B) — the "
                            "sharded-state contract regressed toward "
                            "replication",
                    where=f"axis {pack.state_axis}"))
    if hasattr(report, "add"):
        for f in findings:
            report.add(f)
    return findings


# ---------------------------------------------------------------------------
# whole-program audit + report plumbing
# ---------------------------------------------------------------------------

@dataclass
class ShardingAudit:
    """Everything the sharding analysis measured about ONE program:
    the flow table, the (pack-aware) implicit reshards, and the comm
    cost.  ``ProgramReport.sharding`` carries one of these."""
    table: Optional[ShardingTable] = None
    reshards: List[Reshard] = field(default_factory=list)
    cost: Optional[CommCost] = None
    reshard_floor: int = RESHARD_FLOOR_BYTES
    pack: Optional[str] = None
    #: parse/mesh context for pack re-audits (expect_mode) — not
    #: serialized
    mod: Optional[HloModule] = field(default=None, repr=False)
    mesh: Any = field(default=None, repr=False)

    @property
    def reshard_bytes(self) -> int:
        return sum(r.wire_bytes for r in self.reshards)

    def brief(self) -> Dict[str, Any]:
        """The headline numbers bench.py attaches per leg."""
        return {"implicit_reshards": len(self.reshards),
                "reshard_bytes": self.reshard_bytes,
                "comm_cost_est_s": self.cost.total_s if self.cost
                else 0.0,
                "sharding_table_digest": self.table.digest()
                if self.table else None}

    def to_dict(self) -> Dict[str, Any]:
        d = self.brief()
        d["pack"] = self.pack
        d["per_axis_cost_s"] = dict(self.cost.per_axis_s) \
            if self.cost else {}
        d["reshards"] = [r.to_dict() for r in self.reshards[:16]]
        d["table"] = self.table.to_dict() if self.table else None
        return d

    def summary_line(self) -> str:
        return (f"params={len(self.table.params) if self.table else 0} "
                f"reshards={len(self.reshards)} "
                f"reshard_bytes={self.reshard_bytes} "
                f"comm~{self.cost.total_s if self.cost else 0.0:.2e}s "
                f"digest={self.table.digest() if self.table else '-'}")


def audit_sharding(hlo: Union[str, HloModule],
                   census: Optional[CollectiveStats] = None, mesh=None,
                   stablehlo: str = "",
                   declared: Sequence[CollectiveRule] = (),
                   floor_bytes: int = RESHARD_FLOOR_BYTES,
                   profile: Optional[BandwidthProfile] = None) \
        -> ShardingAudit:
    """Run the full sharding analysis over one optimized program:
    flow table + implicit reshards (against ``declared``, typically a
    pack's blessings) + comm cost.  Never raises."""
    try:
        mod = parse_hlo(hlo) if isinstance(hlo, str) else hlo
        if census is None:
            from .program import collective_census
            census = collective_census(
                hlo if isinstance(hlo, str) else "", mesh=mesh)
        profile = profile or bandwidth_profile()
        return ShardingAudit(
            table=sharding_table(mod, mesh=mesh, stablehlo=stablehlo),
            reshards=implicit_reshards(census, mod=mod,
                                       declared=declared,
                                       floor_bytes=floor_bytes,
                                       profile=profile),
            cost=comm_cost(census, profile=profile),
            reshard_floor=floor_bytes, mod=mod, mesh=mesh)
    except Exception:                    # pragma: no cover - defensive
        _LOG.debug("sharding audit failed", exc_info=True)
        return ShardingAudit()


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def load_baselines(path: str) -> Dict[str, Any]:
    """Per-leg sharding baselines: ``{leg: {implicit_reshards,
    reshard_bytes, tol_pct}}`` (``_comment`` keys ignored)."""
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    return {k: v for k, v in raw.items() if not k.startswith("_")}


def check_baseline(audit: ShardingAudit, baselines: Dict[str, Any],
                   leg: str) -> List[Finding]:
    """Diff one program's reshard posture against a checked-in
    baseline.  Both bands are one-sided — fewer reshards / fewer bytes
    is an improvement; more is an error-severity ``sharding-regression``
    finding, so ``analyze='raise'`` fails fast
    (docs/ANALYSIS.md documents the refresh workflow)."""
    base = baselines.get(leg)
    findings: List[Finding] = []
    if base is None:
        findings.append(Finding(
            checker="sharding", rule="sharding-regression",
            severity="warn",
            message=f"no sharding baseline for leg {leg!r} — add it to "
                    "the baselines file (docs/ANALYSIS.md)",
            where=leg))
        return findings
    tol = float(base.get("tol_pct", 25.0)) / 100.0
    r_base = int(base.get("implicit_reshards", 0))
    if len(audit.reshards) > r_base:
        worst = audit.reshards[0] if audit.reshards else None
        detail = (f" (worst: `{worst.kind}` {worst.payload_bytes} B "
                  f"at {worst.name})") if worst else ""
        findings.append(Finding(
            checker="sharding", rule="sharding-regression",
            message=f"[{leg}] {len(audit.reshards)} implicit reshard(s) "
                    f"vs baseline {r_base} — the partitioner now moves "
                    f"data the spec does not imply{detail}",
            where=leg))
    b_base = int(base.get("reshard_bytes", 0))
    if audit.reshard_bytes > max(b_base * (1.0 + tol),
                                 b_base + audit.reshard_floor):
        findings.append(Finding(
            checker="sharding", rule="sharding-regression",
            message=f"[{leg}] implicit-reshard wire bytes "
                    f"{audit.reshard_bytes} exceed baseline {b_base} by "
                    f"more than {base.get('tol_pct', 25.0)}% — more "
                    "data resharded per step than the captured posture",
            where=leg))
    return findings


def baseline_from_env() -> Optional[tuple]:
    """``MXNET_SHARDING_BASELINE=<path>[:<leg>]`` -> (baselines dict,
    leg-or-None); None when unset or unreadable (logged, never
    raises)."""
    spec = os.environ.get("MXNET_SHARDING_BASELINE")
    if not spec:
        return None
    path, leg = spec, None
    if ":" in spec and not os.path.exists(spec):
        path, leg = spec.rsplit(":", 1)
    try:
        return load_baselines(path), leg
    except Exception as e:               # pragma: no cover - defensive
        _LOG.warning("MXNET_SHARDING_BASELINE=%r unreadable (%s: %s)",
                     spec, type(e).__name__, e)
        return None


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def publish(audit: ShardingAudit):
    """Refresh the ``mx_sharding_*`` gauges from one audit (the latest
    analyzed program wins — one step program is live at a time)."""
    try:
        from ..telemetry import names as tn
        from ..telemetry import registry as treg
        reg = treg()
        reg.gauge(tn.SHARDING_RESHARDS).set(len(audit.reshards))
        reg.gauge(tn.SHARDING_RESHARD_BYTES).set(audit.reshard_bytes)
        if audit.cost is not None:
            g_cost = reg.gauge(tn.SHARDING_COMM_COST)
            g_bytes = reg.gauge(tn.SHARDING_COLLECTIVE_BYTES)
            for ax, sec in audit.cost.per_axis_s.items():
                g_cost.set(sec, label=ax)
            for ax, b in audit.cost.per_axis_bytes.items():
                g_bytes.set(b, label=ax)
    except Exception:                    # pragma: no cover - defensive
        _LOG.debug("sharding gauge publish failed", exc_info=True)
