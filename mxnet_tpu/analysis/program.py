"""Program lint: static analysis of the compiled train step.

Value-level tests prove a step computes the right numbers; this pass
proves the PROGRAM is the right program — the invariants PRs 1-3 built
(one reduce-scatter per unit instead of N all-reduces, buffers actually
donated, no host round-trip per step, bf16 staying bf16 outside blessed
fp32 masters) are asserted against the jaxpr and the optimized HLO that
XLA actually scheduled, the analysis practice of arXiv:2301.13062 and
the sharded-update contract of arXiv:2004.13336 turned into a checker.

Entry points:

- :func:`analyze_step` — lower+compile a ``CompiledTrainStep``'s program
  for one example batch (no optimizer counts advance) and run every
  checker; returns a :class:`~.report.ProgramReport`.
- :func:`analyze_lowered` — the same checkers over any ``jax.stages.
  Lowered`` (bench sidecars, golden known-bad programs in tests).
- :func:`collective_census` — HLO-text census alone.
- :func:`expect_mode` — mode-specific invariant pack (plain-fused,
  zero-sharded, dp=1) appended as findings; what the tier-1 fixtures
  assert.

CPU-backend note: XLA:CPU has no native reduce-scatter thunk — its
``reduce-scatter-decomposer`` pass rewrites every reduce-scatter into
all-reduce + dynamic-slice BEFORE the final text we read.  The census
re-classifies that pattern (an all-reduce whose only real consumers
slice exactly a 1/group_size shard) as ``reduce_scatter`` with
``decomposed=True``, so zero-shard assertions hold on the 8-device
virtual CPU mesh and on real TPU slices alike.
"""
from __future__ import annotations

import logging
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .hlo import HloModule, HloOp, parse_hlo
from .report import (CollectiveOp, CollectiveStats, DonationAudit, Finding,
                     ProgramReport)

__all__ = ["collective_census", "donation_audit", "host_transfer_scan",
           "dtype_drift_scan", "analyze_lowered", "analyze_step",
           "expect_mode", "explain_signature_diff"]

_LOG = logging.getLogger("mxnet_tpu.analysis")

_COLLECTIVE_KINDS = {
    "all-reduce": "all_reduce", "all-reduce-start": "all_reduce",
    "all-gather": "all_gather", "all-gather-start": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "reduce-scatter-start": "reduce_scatter",
    "collective-permute": "collective_permute",
    "collective-permute-start": "collective_permute",
    "all-to-all": "all_to_all",
    "all-to-all-start": "all_to_all",
}

# host-transfer primitives at the jaxpr level (jax's callback family) and
# custom-call targets at the HLO level
_HOST_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "python_callback",
    "callback", "outside_call", "host_callback_call",
}
_HOST_CUSTOM_CALL_MARKERS = (
    "callback", "xla_python", "HostTransfer", "tpu_host",
)
_HOST_OPCODES = {"infeed", "outfeed", "send", "recv", "send-done",
                 "recv-done"}

# dtype widths for drift direction checks
_WIDTH = {"bool": 0, "int8": 1, "uint8": 1, "bfloat16": 2, "float16": 2,
          "int16": 2, "uint16": 2, "float32": 4, "int32": 4, "uint32": 4,
          "float64": 8, "int64": 8, "uint64": 8}


# ---------------------------------------------------------------------------
# collective census
# ---------------------------------------------------------------------------

def _axes_for_groups(groups, mesh) -> Tuple[str, ...]:
    """Which mesh axes a collective's replica groups span.

    For each axis of the mesh, the set of device groups that vary only
    that axis is precomputed; a collective whose groups partition the
    devices the same way is attributed to that axis.  Groups spanning
    several axes at once report every axis whose extent they cover."""
    if not groups or mesh is None:
        return ()
    try:
        import numpy as onp
        dev_ids = onp.array([d.id for d in mesh.devices.flat]).reshape(
            mesh.devices.shape)
        axis_names = list(mesh.axis_names)
        got = {frozenset(g) for g in groups}
        matched = []
        for i, ax in enumerate(axis_names):
            # groups that vary ONLY axis i: move axis i last, flatten rest
            moved = onp.moveaxis(dev_ids, i, -1)
            want = {frozenset(int(x) for x in grp)
                    for grp in moved.reshape(-1, dev_ids.shape[i])}
            if got == want:
                return (ax,)
            # collective spanning axis i among others (its groups are
            # unions of axis-i groups)
            if all(any(w <= g for g in got) for w in want):
                matched.append(ax)
        return tuple(matched)
    except Exception:       # pragma: no cover - defensive
        return ()


def _classify_decomposed(mod: HloModule, op: HloOp, group: int) -> bool:
    """True when ``op`` (an all-reduce) is the CPU decomposition of a
    reduce-scatter: every real consumer takes exactly a 1/group shard
    (dynamic-slice by partition id, usually fused).

    Transparent consumers (get-tuple-element / bitcast / copy) are
    followed recursively with THEIR OWN element counts — XLA's
    all-reduce combiner merges bucketed gradient all-reduces into one
    variadic tuple all-reduce whose direct consumers are only GTEs, and
    judging those at the tuple's total element count would misclassify
    the combined op as a plain all-reduce (2(n-1)/n wire pricing, a 2x
    overcount of the decomposed reduce-scatter's (n-1)/n)."""
    if group <= 1 or op.elements == 0 or op.elements % group:
        return False
    sliced = 0

    def walk(name: str, elements: int, depth: int) -> bool:
        nonlocal sliced
        if elements == 0 or elements % group:
            return False
        shard = elements // group
        consumers = mod.consumers(name)
        if not consumers:
            # a dangling transparent hop vetoes nothing; a dangling
            # all-reduce result is not a reduce-scatter
            return depth > 0
        for c in consumers:
            if c.opcode in ("dynamic-slice", "fusion") and \
                    c.elements == shard:
                # a consumer producing exactly the 1/group shard is the
                # partition-id dynamic-slice (usually fused into the
                # shard-local compute that follows it)
                sliced += 1
            elif c.opcode in ("get-tuple-element", "bitcast", "copy") \
                    and depth < 4:
                if not walk(c.name, c.elements, depth + 1):
                    return False
            else:
                return False
        return True

    return walk(op.name, op.elements, 0) and sliced > 0


def collective_census(hlo_text: str, mesh=None,
                      num_devices: Optional[int] = None) -> CollectiveStats:
    """Count and classify every collective in an optimized HLO dump.

    ``mesh`` (a ``jax.sharding.Mesh`` or this framework's ``DeviceMesh``)
    enables per-axis attribution of replica groups."""
    jmesh = getattr(mesh, "mesh", mesh)   # DeviceMesh wraps .mesh
    if num_devices is None:
        num_devices = int(jmesh.devices.size) if jmesh is not None else 1
    mod = parse_hlo(hlo_text, num_devices=num_devices)
    stats = CollectiveStats()
    for op in mod.ops.values():
        kind = _COLLECTIVE_KINDS.get(op.opcode)
        if kind is None:
            continue
        groups = op.replica_groups
        group_size = len(groups[0]) if groups else num_devices
        axes = _axes_for_groups(groups, jmesh)
        decomposed = False
        if kind == "all_reduce" and \
                _classify_decomposed(mod, op, group_size):
            kind, decomposed = "reduce_scatter", True
        stats.ops.append(CollectiveOp(
            kind=kind, name=op.name, elements=op.elements,
            dtype=op.dtype or "?", axes=axes, group_size=group_size,
            operand_count=max(1, len(op.operands)),
            decomposed=decomposed))
    return stats


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def donation_audit(stablehlo_text: str, compiled_text: str,
                   memory_stats=None,
                   expected: Optional[int] = None) -> DonationAudit:
    """Compare donation DECLARED at the jax level against aliasing XLA
    actually performed.  A declared-but-unaliased input is a silent copy
    per step (the regression class test_fused_step's writeback test can't
    see — numerics stay right, HBM pays double)."""
    audit = DonationAudit(expected=expected)
    declared_params: List[int] = []
    # lowered StableHLO marks donated args per-parameter:
    #   %arg0: tensor<..> {jax.buffer_donor = true}   (jax >= 0.4.30)
    #   %arg1: tensor<..> {tf.aliasing_output = 1}    (pre-decided alias)
    # the annotation block belongs to ONE argument — stop the match at
    # the next argument (comma) so a donor deep in the list is never
    # credited to an earlier undonated arg
    for m in re.finditer(r"%arg(\d+): [^,{]*\{[^{}]*?"
                         r"(jax\.buffer_donor = true"
                         r"|tf\.aliasing_output = \d+)",
                         stablehlo_text or ""):
        declared_params.append(int(m.group(1)))
    audit.declared = len(declared_params)
    mod = parse_hlo(compiled_text or "")
    audit.aliased_params = sorted(p for _, p in mod.input_output_alias)
    audit.aliased = len(audit.aliased_params)
    if declared_params:
        aliased = set(audit.aliased_params)
        audit.copied = [p for p in declared_params if p not in aliased]
    if memory_stats is not None:
        audit.donated_bytes = int(
            getattr(memory_stats, "alias_size_in_bytes", 0))
    return audit


# ---------------------------------------------------------------------------
# host-transfer scan (jaxpr + HLO)
# ---------------------------------------------------------------------------

def _iter_eqns(jaxpr) -> Iterable:
    """All eqns of a (Closed)Jaxpr, recursing into sub-jaxprs (pjit,
    scan, cond, while, remat...)."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jx.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                yield from _iter_eqns(sub)


def _subjaxprs(v):
    from jax.core import Jaxpr, ClosedJaxpr
    if isinstance(v, (Jaxpr, ClosedJaxpr)):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _subjaxprs(x)


def _eqn_where(eqn) -> str:
    try:
        frame = eqn.source_info.traceback.frames[0]
        return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        return ""


def host_transfer_scan(closed_jaxpr, hlo_text: str = "") -> List[Finding]:
    """Host callbacks / infeed / outfeed inside the step program — each
    one is a device->host (or host->device) synchronization per call."""
    findings: List[Finding] = []
    if closed_jaxpr is not None:
        for eqn in _iter_eqns(closed_jaxpr):
            name = eqn.primitive.name
            if name in _HOST_PRIMITIVES or "callback" in name:
                cb = eqn.params.get("callback", None)
                findings.append(Finding(
                    checker="program", rule="host-transfer",
                    message=f"host callback primitive `{name}` inside the "
                            "compiled step" +
                            (f" (callback={cb!r})" if cb else ""),
                    where=_eqn_where(eqn)))
    mod = parse_hlo(hlo_text or "")

    def _where(op):
        # an op XLA already pulled into a fusion body is still a host
        # round-trip per step — name the kernel it hides in
        parent = mod.parent_fusion(op)
        return f"{op.name} (inside fusion %{parent.name})" if parent \
            else op.name

    for op in mod.ops.values():
        if op.opcode in _HOST_OPCODES:
            findings.append(Finding(
                checker="program", rule="host-transfer",
                message=f"`{op.opcode}` op in the optimized program",
                where=_where(op)))
        elif op.opcode == "custom-call" and op.custom_call_target and \
                any(k in op.custom_call_target
                    for k in _HOST_CUSTOM_CALL_MARKERS):
            findings.append(Finding(
                checker="program", rule="host-transfer",
                message="host-callback custom-call "
                        f"`{op.custom_call_target}`",
                where=_where(op)))
    return findings


# ---------------------------------------------------------------------------
# dtype drift
# ---------------------------------------------------------------------------

_HLO_DTYPE_NAMES = {"bf16": "bfloat16", "f16": "float16",
                    "f32": "float32", "f64": "float64"}


def _dtype_drift_scan_hlo(hlo_text: str, blessed) -> List[Finding]:
    """HLO-level widening-``convert`` scan — the fallback when no
    jaxpr is available (canned programs, lowered-only analysis).
    Walks EVERY computation, so converts XLA already pulled into a
    fusion body are seen and attributed to their kernel."""
    mod = parse_hlo(hlo_text or "")
    findings: List[Finding] = []
    for op in mod.ops.values():
        if op.opcode != "convert":
            continue
        src_t = op.operand_types[0] if op.operand_types else None
        src = _HLO_DTYPE_NAMES.get(
            (src_t or "").split("[", 1)[0])
        dst = _HLO_DTYPE_NAMES.get(op.dtype or "")
        if not src or not dst:
            continue
        if _WIDTH.get(dst, 0) <= _WIDTH.get(src, 0):
            continue
        is_blessed = (src, dst) in blessed and dst != "float64"
        parent = mod.parent_fusion(op)
        findings.append(Finding(
            checker="program", rule="dtype-drift",
            severity="error" if dst == "float64" else "warn",
            blessed=is_blessed,
            message=f"widening convert {src} -> {dst} in the optimized "
                    "program" + (" (blessed by the multi-precision "
                                 "master list)" if is_blessed else ""),
            where=f"{op.name} (inside fusion %{parent.name})" if parent
            else op.name))
    return findings


def dtype_drift_scan(closed_jaxpr,
                     blessed: Optional[Sequence[Tuple[str, str]]] = None,
                     hlo_text: str = "") -> List[Finding]:
    """Unexpected widening ``convert_element_type`` chains.

    Narrowing (f32->bf16 AMP casts) is free; widening silently doubles
    activation/state HBM and MXU time.  ``blessed`` lists (src, dst)
    dtype-name pairs that are intentional — the multi-precision master
    list blesses ('bfloat16','float32')/('float16','float32') because
    fp32 masters are the POINT of that mode.  f32->f64 is never blessed
    (nothing in this framework wants f64).

    The jaxpr (pre-optimization) sees every convert, fused or not;
    when no jaxpr is available the scan falls back to the optimized
    HLO's ``convert`` ops — walking fusion BODIES too, which the old
    entry-only reading silently skipped once XLA fused a convert."""
    blessed = {tuple(b) for b in (blessed or ())}
    findings: List[Finding] = []
    if closed_jaxpr is None:
        return _dtype_drift_scan_hlo(hlo_text, blessed)
    for eqn in _iter_eqns(closed_jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        try:
            src = str(eqn.invars[0].aval.dtype)
            dst = str(eqn.params.get("new_dtype"))
        except Exception:
            continue
        if src not in _WIDTH or dst not in _WIDTH:
            continue
        if _WIDTH[dst] <= _WIDTH[src]:
            continue
        if not (src.startswith(("float", "bfloat"))
                and dst.startswith(("float", "bfloat"))):
            continue   # integer index promotions are not drift
        is_blessed = (src, dst) in blessed and dst != "float64"
        findings.append(Finding(
            checker="program", rule="dtype-drift",
            severity="error" if dst == "float64" else "warn",
            blessed=is_blessed,
            message=f"widening convert {src} -> {dst} in the compiled "
                    "step" + (" (blessed by the multi-precision master "
                              "list)" if is_blessed else ""),
            where=_eqn_where(eqn)))
    return findings


# ---------------------------------------------------------------------------
# whole-program analysis
# ---------------------------------------------------------------------------

def analyze_lowered(lowered, mesh=None, expected_donated=None,
                    blessed_dtypes=None, mode: str = "?",
                    compiled=None, jaxpr=None) -> ProgramReport:
    """Run every program checker over a ``jax.stages.Lowered`` (and its
    compiled executable — compiled here when not supplied).  Pass the
    ``jaxpr`` (from ``jax.make_jaxpr`` of the same function+args) to
    enable the jaxpr-level checks (host callbacks, dtype drift)."""
    report = ProgramReport(mode=mode)
    try:
        stablehlo = lowered.as_text()
    except Exception:               # pragma: no cover - defensive
        stablehlo = ""
    if compiled is None:
        compiled = lowered.compile()
    try:
        hlo_text = compiled.as_text()
    except Exception:               # pragma: no cover - defensive
        hlo_text = ""
    try:
        mem = compiled.memory_analysis()
        mem = mem[0] if isinstance(mem, (list, tuple)) else mem
    except Exception:               # pragma: no cover - defensive
        mem = None
    if mem is not None:
        try:
            from ..telemetry.memory import MemoryReport
            report.memory = MemoryReport.from_compiled(compiled).to_dict()
        except Exception:           # pragma: no cover - defensive
            report.memory = None
    report.collectives = collective_census(hlo_text, mesh=mesh)
    if hlo_text:
        try:
            from . import sharding as _sharding
            report.sharding = _sharding.audit_sharding(
                hlo_text, census=report.collectives, mesh=mesh,
                stablehlo=stablehlo)
            _sharding.publish(report.sharding)
        except Exception:       # pragma: no cover - defensive
            _LOG.debug("sharding audit failed", exc_info=True)
    report.donation = donation_audit(stablehlo, hlo_text, mem,
                                     expected=expected_donated)
    report.host_transfers = host_transfer_scan(jaxpr, hlo_text)
    report.dtype_drift = dtype_drift_scan(jaxpr, blessed=blessed_dtypes,
                                          hlo_text=hlo_text)
    if hlo_text:
        try:
            from . import fusion as _fusion
            report.fusion = _fusion.fusion_census(hlo_text)
            report.findings.extend(report.fusion.findings)
            env = _fusion.baseline_from_env()
            if env is not None:
                baselines, leg = env
                report.findings.extend(_fusion.check_baseline(
                    report.fusion, baselines, leg or mode))
            _fusion.publish(report.fusion)
        except Exception:       # pragma: no cover - defensive
            _LOG.debug("fusion census failed", exc_info=True)
    if hlo_text:
        try:
            from . import overlap as _overlap
            report.overlap = _overlap.overlap_census(
                hlo_text, mesh=mesh)
            report.findings.extend(report.overlap.findings)
            env = _overlap.baseline_from_env()
            if env is not None:
                baselines, leg = env
                report.findings.extend(_overlap.check_baseline(
                    report.overlap, baselines, leg or mode))
            _overlap.publish(report.overlap)
        except Exception:       # pragma: no cover - defensive
            _LOG.debug("overlap census failed", exc_info=True)
    for p in report.donation.copied:
        report.add(Finding(
            checker="program", rule="donation-copy",
            message=f"input #{p} was declared donated but XLA did not "
                    "alias it — a full buffer copy every step",
            where=f"param {p}"))
    if expected_donated is not None and \
            report.donation.aliased < expected_donated:
        report.add(Finding(
            checker="program", rule="donation-copy",
            message=f"only {report.donation.aliased} of "
                    f"{expected_donated} param/state buffers aliased — "
                    "donation fell back to copies",
            where="input_output_alias"))
    return report


def _trace_jaxpr(fn, *args, **kwargs):
    import jax
    try:
        return jax.make_jaxpr(fn)(*args, **kwargs)
    except Exception:               # pragma: no cover - defensive
        return None


def analyze_step(step, *args, batch_size=None, **kwargs) -> ProgramReport:
    """Lower + compile one ``CompiledTrainStep`` entry for this example
    batch (no optimizer counts advance, the live weights are untouched)
    and run the full program lint.  The result is cached on the step's
    shape-bucket entry — repeated calls are free."""
    info = step.lower_entry(*args, batch_size=batch_size, **kwargs)
    if info is None:
        report = ProgramReport(mode=step.mode or "eager")
        report.n_traces = step.n_traces
        report.add(Finding(
            checker="program", rule="not-compiled", severity="warn",
            message="step runs on the eager tape path "
                    f"({step.mode!r}); there is no compiled program to "
                    "lint — the transfer guard (MXNET_TRANSFER_GUARD) "
                    "still covers its hot loop"))
        return report
    if info.get("report") is not None:
        return info["report"]
    report = analyze_lowered(
        info["lowered"], mesh=info.get("mesh"),
        expected_donated=info.get("expected_donated"),
        blessed_dtypes=info.get("blessed_dtypes"),
        mode=info.get("mode", "?"), jaxpr=info.get("jaxpr"))
    report.n_traces = step.n_traces
    report.meta.update({k: v for k, v in info.items()
                        if k in ("mode", "axis", "unit_sizes", "n_params",
                                 "n_state_leaves")})
    expect_mode(report)
    info["report"] = report
    return report


# ---------------------------------------------------------------------------
# mode expectations (the tier-1 contract)
# ---------------------------------------------------------------------------

def mode_spec_pack(mode: str, axis: Optional[str] = None,
                   unit_sizes=()) -> Optional["object"]:
    """The declarative :class:`~.sharding.SpecPack` behind one compiled
    mode's historical expectations — ``expect_mode`` is now a thin
    dispatcher over these (docs/ANALYSIS.md "Sharding analysis"):

    - ``zero``: >=1 reduce_scatter and >=1 all_gather on the dp axis,
      ZERO all-reduces carrying exactly one shard unit's gradient (a
      unit-sized all-reduce means the reduce-scatter transformation of
      arXiv:2004.13336 regressed to replicate-everywhere), weight
      re-replication gathers declared by their padded unit sizes so
      any OTHER big gather is an implicit reshard.
    - ``fused-mesh``: the dp gradient reduction must exist.
    - ``fused`` dp=1 / ``predict``: no collectives at all (warn).
    """
    from . import sharding as _sharding
    R = _sharding.CollectiveRule
    units = frozenset(int(u) for u in (unit_sizes or ()))
    if mode == "zero":
        rules = [
            R("reduce_scatter", axis=axis, min_count=1,
              rule_id="collective-mismatch"),
            R("all_gather", axis=axis, min_count=1,
              rule_id="collective-mismatch"),
        ]
        if units:
            rules.append(R("all_reduce", axis=axis, max_count=0,
                           elements=units,
                           rule_id="per-param-allreduce"))
        return _sharding.SpecPack(
            name="zero-dp",
            description="ZeRO-1 sharded update (reduce-scatter grads, "
                        "shard-local update, all-gather weights)",
            axes=(axis,) if axis else (),
            rules=tuple(rules),
            declared=(
                # the batch/loss psums and the numerics-stat psums are
                # reductions the step declares
                R("all_reduce", axis=axis),
                # weight re-replication: all-gathers whose payload is a
                # padded shard unit
                R("all_gather", axis=axis, elements=units or None),
            ),
            # reshards surface as warnings + the baseline gate; no hard
            # budget — XLA legitimately gathers small activations
            # instead of psumming weight grads when that moves less
            max_reshard_bytes=None,
            state_axis=axis)
    if mode == "fused-mesh":
        return _sharding.SpecPack(
            name="fused-mesh-dp",
            description="mesh-aware fused step (replicated params, "
                        "dp-sharded batch, in-program grad psum)",
            axes=(axis,) if axis else (),
            rules=(R(("all_reduce", "reduce_scatter"), axis=axis,
                     min_count=1, rule_id="collective-mismatch"),),
            declared=(R("all_reduce", axis=axis),
                      R("reduce_scatter", axis=axis)),
            max_reshard_bytes=None)
    if mode in ("fused", "predict"):
        what = "single-device fused step" if mode == "fused" \
            else "serving predict program"
        return _sharding.SpecPack(
            name=f"{mode}-single",
            description=f"{what} (no partitioning expected)",
            rules=(R("*", max_count=0, rule_id="collective-mismatch",
                     severity="warn"),))
    return None


def expect_mode(report: ProgramReport, mode: Optional[str] = None,
                axis: Optional[str] = None) -> ProgramReport:
    """Append the per-mode structural invariants as findings.

    The historical fused/zero/predict expectations are now declarative
    :class:`~.sharding.SpecPack` s (:func:`mode_spec_pack`) enforced
    through :func:`~.sharding.expect_spec` — which also runs the
    implicit-reshard audit against the pack's declared collectives and
    the sharded-state byte budget, and re-checks the
    ``MXNET_SHARDING_BASELINE`` regression gate.  Every mode: all
    declared donations aliased, no host transfers.
    """
    from . import sharding as _sharding
    mode = mode or report.mode
    axis = axis or report.meta.get("axis")
    pack = mode_spec_pack(mode, axis=axis,
                          unit_sizes=report.meta.get("unit_sizes") or ())
    if pack is not None:
        _sharding.expect_spec(report, pack)
    audit = report.sharding
    if audit is not None:
        env = _sharding.baseline_from_env()
        if env is not None:
            baselines, leg = env
            report.findings.extend(_sharding.check_baseline(
                audit, baselines, leg or mode))
        _sharding.publish(audit)
    # fusion pack (every compiled mode): the optimized program must
    # have NO fusable elementwise/broadcast/convert op stranded between
    # two fusions above the size floor — each one is two avoidable HBM
    # round-trips per step the value-level tests cannot see
    # (arXiv:2301.13062; the fusion census produces the evidence)
    fr = report.fusion
    if mode in ("fused", "fused-mesh", "zero", "predict") \
            and fr is not None and fr.stranded:
        worst = fr.stranded[0]
        report.add(Finding(
            checker="fusion", rule="stranded-op",
            message=f"{len(fr.stranded)} fusable op(s) above the "
                    f"{fr.stranded_floor} B floor stranded between "
                    f"fusions in the {mode} step (worst: "
                    f"`{worst.opcode}` {worst.bytes} B at {worst.name})"
                    " — the ideal-fusion contract regressed",
            where=worst.name))
    return report


# ---------------------------------------------------------------------------
# retrace accounting
# ---------------------------------------------------------------------------

_SIG_FIELDS = ("train_mode", "arg_treedef", "static_spec", "nd_mask",
               "shapes_dtypes", "numerics_mode")


def explain_signature_diff(old, new) -> str:
    """Human-readable diff of two CompiledTrainStep cache keys — WHY the
    second one retraced."""
    if old is None:
        return "first trace (no prior signature to compare)"
    parts = []
    for i, fieldname in enumerate(_SIG_FIELDS):
        a = old[i] if i < len(old) else None
        b = new[i] if i < len(new) else None
        if a == b:
            continue
        if fieldname == "shapes_dtypes":
            a, b = list(a or ()), list(b or ())
            n = max(len(a), len(b))
            diffs = []
            for j in range(n):
                sa = a[j] if j < len(a) else None
                sb = b[j] if j < len(b) else None
                if sa != sb:
                    diffs.append(f"arg[{j}]: {sa} -> {sb}")
            parts.append("traced argument shapes/dtypes changed ("
                         + "; ".join(diffs[:6])
                         + ("; ..." if len(diffs) > 6 else "") + ")")
        elif fieldname == "arg_treedef":
            parts.append(f"argument STRUCTURE changed ({a} -> {b})")
        elif fieldname == "static_spec":
            parts.append("non-array (static) argument values changed — "
                         "each distinct value compiles its own program")
        elif fieldname == "nd_mask":
            parts.append("NDArray-vs-raw-array argument mix changed")
        else:
            parts.append(f"{fieldname} changed ({a} -> {b})")
    return "; ".join(parts) if parts else \
        "signatures identical (cache eviction, not a retrace trigger)"
