"""Source lint: jit-unsafe Python in HybridBlock forwards and loss fns.

The program lint inspects what DID compile; this pass reads the Python
that is ABOUT to be traced and flags the constructs that either break
the trace (demoting the fused step to eager, silently) or bake a bug
into it:

====== =====================================================
rule   what it catches
====== =====================================================
MXA001 host materialization on a traced value — ``.asnumpy()``,
       ``.item()``, ``.asscalar()``, ``.wait_to_read()``,
       ``numpy.asarray(x)``, ``jax.device_get(x)``
MXA002 Python scalar cast of a non-literal — ``float(x)`` /
       ``int(x)`` / ``bool(x)`` concretize a tracer
MXA003 Python ``if``/``while``/``assert`` on a tracer-dependent
       condition — the branch is baked in at trace time
MXA004 unkeyed host randomness — ``numpy.random.*`` / stdlib
       ``random.*`` inside a forward runs ONCE at trace time and
       becomes a constant (use ``mx.nd.random``, which threads the
       per-step key through the compiled program)
MXA005 Python ``for`` loop over a tracer/tensor dimension — ``for i
       in range(x.shape[0])`` (or iterating a traced array directly)
       unrolls into one long unfusable op chain at trace time; XLA
       cannot fuse across the unrolled iterations and the fusion
       census shows the fragmentation (use ``lax.scan`` semantics —
       ``gluon.rnn``'s fused layers — or vectorize).  Literal
       ``range(<const>)`` loops are not flagged; intentionally-small
       dynamic loops are blessed via the allowlist
MXA006 sharding-opaque placement / raw collectives —
       ``jax.device_put(x)`` or ``place_on_mesh(...)`` inside a
       forward WITHOUT an explicit sharding/axis bakes whatever device
       layout trace time happened to see into the compiled program
       (the sharding analysis cannot attribute it to a declared spec);
       and raw ``lax`` collectives (``lax.psum``/``all_gather``/
       ``ppermute``/``all_to_all``/...) anywhere outside
       ``parallel/collectives.py`` bypass the version-compat shims and
       the spec packs that bless the framework's collective patterns —
       route them through ``mxnet_tpu.parallel.collectives``
MXA007 blocking call inside a ``with <lock>`` body — ``queue.get/put``,
       ``Future.result``, ``wait_to_read``, ``time.sleep``,
       ``Thread.join``, predictor/step dispatch (``.predict``,
       ``block_until_ready``).  Holding a lock across a blocking call
       convoys every other acquirer and is one ordering edge away from
       deadlock; move the blocking work outside the critical section
MXA008 attribute mutated both from a thread body (``Thread(target=
       self.m)`` and its transitive self-call closure) and from a
       public method, with neither site inside a ``with <lock>`` — the
       classic unguarded cross-thread write
MXA009 bare ``threading.Lock()``/``RLock()``/``Condition()`` in
       framework code instead of ``analysis.threads.mx_lock`` — an
       unaudited lock is invisible to the lock-order graph and the
       deadlock forensics; the audit stays total only while this rule
       stays clean
====== =====================================================

Scope: MXA001-006 lint ``forward`` / ``hybrid_forward`` method bodies
(and functions nested in them) — code outside a forward may sync
freely and is never flagged.  The THREAD rules MXA007-009 have module
scope instead (whole files, via :func:`lint_threads_source` /
:func:`lint_threads_path`) and run only over framework code: the
tier-1 sweep covers ``mxnet_tpu/``, not examples or tests.

Blessing an intentional violation: append ``# mx-lint: allow`` (or
``# mx-lint: allow=MXA001``) to the offending line, or list
``<path-suffix>::<rule>`` entries in an allowlist file (the tier-1
sweep uses ``tests/fixtures/lint_allowlist.txt`` — docs/ANALYSIS.md).

CLI::

    python -m mxnet_tpu.analysis.lint <module-or-path> [...]
    python -m mxnet_tpu.analysis.lint --allowlist FILE mxnet_tpu/gluon
"""
from __future__ import annotations

import ast
import importlib.util
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .report import Finding

__all__ = ["lint_source", "lint_path", "lint_module", "lint_function",
           "lint_threads_source", "lint_threads_path",
           "load_allowlist", "filter_allowed", "main"]

_SYNC_METHODS = {"asnumpy", "item", "asscalar", "wait_to_read",
                 "wait_to_write", "tolist"}
_NUMPY_SYNC_FUNCS = {"asarray", "array", "copy"}
_NUMPY_ALIASES = {"numpy", "np", "onp"}
_SCALAR_CASTS = {"float", "int", "bool"}
# attributes that yield trace-static values — reading them off a traced
# array is safe and UNtaints the expression
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "stype", "context",
                 "ctx", "device", "name", "dtype_name"}
_SAFE_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
               "range", "enumerate", "zip"}
# raw lax collectives (MXA006): communication primitives that must
# route through parallel/collectives.py (version-compat shims + the
# spec-pack blessing surface)
_LAX_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                    "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                    "pgather", "pbroadcast", "pvary", "pcast"}
#: path suffix exempt from the raw-collective rule — the one module
#: whose JOB is wrapping lax collectives
_COLLECTIVES_HOME = "parallel/collectives.py"


def _allow_marker(line: str) -> Optional[Set[str]]:
    """Rules blessed by an inline ``# mx-lint: allow[=MXA001[,MXA002]]``
    comment; empty set means allow everything on the line."""
    if "mx-lint:" not in line:
        return None
    frag = line.split("mx-lint:", 1)[1].strip()
    if not frag.startswith("allow"):
        return None
    if "=" in frag:
        return {r.strip() for r in
                frag.split("=", 1)[1].split(",") if r.strip()}
    return set()


class _ForwardLint(ast.NodeVisitor):
    """Lints ONE forward/loss function body with name-level taint
    tracking: data arguments are tainted; assignments propagate; reading
    a static attribute (``x.shape``) or calling a safe builtin
    sanitizes."""

    def __init__(self, filename: str, lines: Sequence[str], qualname: str,
                 tainted: Set[str],
                 rules: Optional[Set[str]] = None):
        self.filename = filename
        self.lines = lines
        self.qualname = qualname
        self.tainted = set(tainted)
        self.rules = rules            # None = every rule
        self.findings: List[Finding] = []

    # ---------------- reporting ----------------
    def _flag(self, node, rule: str, message: str, severity="error"):
        if self.rules is not None and rule not in self.rules:
            return
        lineno = getattr(node, "lineno", 0)
        line = self.lines[lineno - 1] if 0 < lineno <= len(self.lines) \
            else ""
        allowed = _allow_marker(line)
        blessed = allowed is not None and (not allowed or rule in allowed)
        self.findings.append(Finding(
            checker="source", rule=rule, message=message,
            where=f"{self.filename}:{lineno}", severity=severity,
            blessed=blessed))

    # ---------------- taint machinery ----------------
    def _is_tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False                      # x.shape is static
            return self._is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in _SAFE_CALLS:
                return False                      # len(x), isinstance(..)
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _STATIC_ATTRS | {"astype", "reshape"}:
                # x.astype(..)/x.reshape(..) stay tainted via receiver
                return self._is_tainted(fn.value)
            # any call fed a tainted argument taints the result
            return any(self._is_tainted(a) for a in node.args) or \
                any(self._is_tainted(k.value) for k in node.keywords) or \
                (isinstance(fn, ast.Attribute)
                 and self._is_tainted(fn.value))
        if isinstance(node, (ast.BinOp,)):
            return self._is_tainted(node.left) or \
                self._is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` check argument STRUCTURE
            # (which call pattern this trace is), not traced values —
            # identity comparisons are trace-static by convention
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return self._is_tainted(node.left) or \
                any(self._is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._is_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._is_tainted(node.body) or \
                self._is_tainted(node.orelse)
        if isinstance(node, ast.Starred):
            return self._is_tainted(node.value)
        return False

    def _bind(self, target, tainted: bool):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    # ---------------- statements ----------------
    def visit_Assign(self, node):
        t = self._is_tainted(node.value)
        for tgt in node.targets:
            self._bind(tgt, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if self._is_tainted(node.value):
            self._bind(node.target, True)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._bind(node.target, self._is_tainted(node.value))
        self.generic_visit(node)

    def visit_For(self, node):
        self._bind(node.target, self._is_tainted(node.iter))
        self._check_unrolled_loop(node)
        self.generic_visit(node)

    def _check_unrolled_loop(self, node):
        """MXA005: a ``for`` that unrolls tensor work at trace time.

        Candidates: ``range(<non-literal>)`` (shape-derived or variable
        trip counts — ``range(3)`` is visibly small and static, never
        flagged) and direct iteration over a traced array.  Only loops
        whose BODY touches traced values fire — a loop over config
        lists or child blocks is ordinary Python."""
        it = node.iter
        over = None
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range" and it.args:
            if not all(isinstance(a, ast.Constant) for a in it.args):
                over = "range(<dynamic>)"
        elif self._is_tainted(it):
            over = "a traced array"
        if over is None:
            return
        body_touches_tracer = any(
            isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            and n.id in self.tainted
            for stmt in node.body for n in ast.walk(stmt))
        if not body_touches_tracer:
            return
        self._flag(node, "MXA005",
                   f"Python `for` over {over} inside a forward unrolls "
                   "into one long unfusable op chain at trace time "
                   "(every iteration compiles its own ops; XLA cannot "
                   "fuse across them) — use lax.scan semantics "
                   "(gluon.rnn fused layers) or vectorize; bless "
                   "intentionally-small static loops via the allowlist",
                   severity="warn")

    def visit_If(self, node):
        if self._is_tainted(node.test):
            self._flag(node, "MXA003",
                       "Python `if` on a tracer-dependent condition — "
                       "the branch taken at trace time is baked into the "
                       "compiled program (use nd.where / lax.cond "
                       "semantics instead)")
        self.generic_visit(node)

    def visit_While(self, node):
        if self._is_tainted(node.test):
            self._flag(node, "MXA003",
                       "Python `while` on a tracer-dependent condition — "
                       "cannot trace; the step will fall back to eager")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self._is_tainted(node.test):
            self._flag(node, "MXA003",
                       "assert on a tracer-dependent condition "
                       "concretizes the value at trace time",
                       severity="warn")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        if self._is_tainted(node.test):
            self._flag(node, "MXA003",
                       "conditional expression on a tracer-dependent "
                       "condition is baked in at trace time")
        self.generic_visit(node)

    # ---------------- calls ----------------
    def visit_Call(self, node):
        fn = node.func
        # x.asnumpy() / x.item() / ...
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
            self._flag(node, "MXA001",
                       f"`.{fn.attr}()` inside a forward/loss "
                       "materializes the value on host — breaks the "
                       "fused-step trace (or costs a device sync "
                       "every step on the eager path)")
        # numpy.asarray(x) / onp.array(x) on tainted values
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in _NUMPY_ALIASES and \
                fn.attr in _NUMPY_SYNC_FUNCS and \
                any(self._is_tainted(a) for a in node.args):
            self._flag(node, "MXA001",
                       f"`{fn.value.id}.{fn.attr}()` of a traced value "
                       "pulls it to host at trace time")
        # jax.device_get
        if isinstance(fn, ast.Attribute) and fn.attr == "device_get":
            self._flag(node, "MXA001",
                       "`device_get` inside a forward/loss is a host "
                       "transfer per step")
        # float(x) / int(x) / bool(x)
        if isinstance(fn, ast.Name) and fn.id in _SCALAR_CASTS and \
                node.args and not isinstance(node.args[0], ast.Constant):
            if self._is_tainted(node.args[0]):
                self._flag(node, "MXA002",
                           f"`{fn.id}()` of a traced value concretizes "
                           "it on host — breaks the trace")
            else:
                self._flag(node, "MXA002",
                           f"`{fn.id}()` of a non-literal inside a "
                           "forward — if the argument derives from a "
                           "traced array this concretizes it",
                           severity="warn")
        # MXA006: sharding-opaque placement — device_put/place_on_mesh
        # without an explicit sharding/destination
        if isinstance(fn, (ast.Attribute, ast.Name)):
            callee = fn.attr if isinstance(fn, ast.Attribute) else fn.id
            kwnames = {k.arg for k in node.keywords}
            if callee == "device_put" and len(node.args) < 2 and \
                    not kwnames & {"device", "dst", "sharding"}:
                self._flag(node, "MXA006",
                           "`device_put` without an explicit sharding "
                           "inside a forward bakes trace-time placement "
                           "into the compiled program — pass a "
                           "NamedSharding (or use parallel.mesh."
                           "place_on_mesh with mesh+axis) so the "
                           "sharding analysis can attribute the layout")
            elif callee == "place_on_mesh" and len(node.args) < 3 and \
                    not kwnames & {"axis"}:
                self._flag(node, "MXA006",
                           "`place_on_mesh` without an explicit "
                           "mesh+axis inside a forward hides the "
                           "intended layout from the compiled program "
                           "and the sharding analysis")
        # MXA006: raw lax collectives outside parallel/collectives.py
        if isinstance(fn, ast.Attribute) and \
                fn.attr in _LAX_COLLECTIVES:
            base = fn.value
            is_lax = (isinstance(base, ast.Name) and base.id == "lax") \
                or (isinstance(base, ast.Attribute)
                    and base.attr == "lax")
            norm = self.filename.replace(os.sep, "/")
            if is_lax and not norm.endswith(_COLLECTIVES_HOME):
                self._flag(node, "MXA006",
                           f"raw `lax.{fn.attr}` inside a forward "
                           "bypasses parallel/collectives.py (the "
                           "version-compat shims and the spec packs "
                           "that bless the framework's collective "
                           "patterns) — route it through "
                           "mxnet_tpu.parallel.collectives",
                           severity="warn")
        # unkeyed randomness: numpy.random.* / random.*
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Attribute) and \
                    base.attr == "random" and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id in _NUMPY_ALIASES:
                self._flag(node, "MXA004",
                           f"`{base.value.id}.random.{fn.attr}` inside a "
                           "forward runs ONCE at trace time and becomes "
                           "a compiled-in constant — use mx.nd.random "
                           "(keyed per step)")
            elif isinstance(base, ast.Name) and base.id == "random" and \
                    fn.attr in ("random", "randint", "uniform", "gauss",
                                "choice", "shuffle", "sample",
                                "randrange"):
                self._flag(node, "MXA004",
                           f"stdlib `random.{fn.attr}` inside a forward "
                           "is evaluated at trace time, not per step")
        self.generic_visit(node)


def _iter_forward_functions(tree: ast.Module):
    """(qualname, FunctionDef, tainted-arg-names, rule-subset) for every
    forward/hybrid_forward method in the module — plus ``unroll``
    methods (the rnn API's forward-over-time), scanned for the
    loop-unrolling rule MXA005 only: unroll takes config flags
    (``layout``, ``merge_outputs``) that the all-args-tainted forward
    convention would false-flag under the other rules."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name in ("forward", "hybrid_forward",
                                      "unroll"):
                args = [a.arg for a in item.args.args
                        + item.args.posonlyargs + item.args.kwonlyargs]
                if item.args.vararg:
                    args.append(item.args.vararg.arg)
                tainted = {a for a in args if a not in ("self", "F")}
                rules = {"MXA005"} if item.name == "unroll" else None
                yield f"{cls.name}.{item.name}", item, tainted, rules


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    """Lint one file's source text; returns findings (blessed ones
    included, marked)."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Finding(checker="source", rule="MXA000", severity="warn",
                        message=f"could not parse: {e}",
                        where=f"{filename}:{e.lineno or 0}")]
    lines = src.splitlines()
    findings: List[Finding] = []
    for qualname, fn, tainted, rules in _iter_forward_functions(tree):
        linter = _ForwardLint(filename, lines, qualname, tainted,
                              rules=rules)
        for stmt in fn.body:
            linter.visit(stmt)
        findings.extend(linter.findings)
    return findings


def lint_function(fn) -> List[Finding]:
    """Lint a live function/lambda (loss functions handed to
    ``Trainer.compile_step``): every parameter is treated as traced."""
    import inspect
    import textwrap
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        filename = inspect.getsourcefile(fn) or "<function>"
        lineno = fn.__code__.co_firstlineno
    except (OSError, TypeError):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    node = None
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            node = n
            break
    if node is None:
        return []
    args = [a.arg for a in node.args.args + node.args.posonlyargs]
    tainted = {a for a in args if a not in ("self", "F")}
    lines = src.splitlines()
    linter = _ForwardLint(filename, lines, getattr(fn, "__name__", "<fn>"),
                          tainted)
    body = node.body if isinstance(node.body, list) else [node.body]
    for stmt in body:
        linter.visit(stmt)
    for f in linter.findings:     # rebase onto real file line numbers
        try:
            path, ln = f.where.rsplit(":", 1)
            f.where = f"{path}:{int(ln) + lineno - 1}"
        except ValueError:
            pass
    return linter.findings


def lint_path(path: str) -> List[Finding]:
    """Lint a file, or every ``*.py`` under a directory."""
    findings: List[Finding] = []
    if os.path.isdir(path):
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git")]
            for f in sorted(files):
                if f.endswith(".py"):
                    findings.extend(lint_path(os.path.join(root, f)))
        return findings
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), filename=path)


def lint_module(name: str) -> List[Finding]:
    """Lint an importable module (or package) by name, without
    importing it."""
    spec = importlib.util.find_spec(name)
    if spec is None or not spec.origin:
        raise ImportError(f"cannot locate module {name!r}")
    if spec.submodule_search_locations:
        out: List[Finding] = []
        for loc in spec.submodule_search_locations:
            out.extend(lint_path(loc))
        return out
    return lint_path(spec.origin)


# ---------------------------------------------------------------------------
# thread rules (MXA007-009): module-scope, framework code only
# ---------------------------------------------------------------------------

#: receiver/context names that look like a mutual-exclusion primitive
_LOCKISH = re.compile(r"(lock|mutex|(^|_)mu$|(^|_)cv$|cond)", re.I)
#: receiver names that look like a queue
_QUEUEISH = re.compile(r"(queue|(^|_)q$)", re.I)
#: attribute calls that block on device/predictor work (MXA007)
_DISPATCH_CALLS = {"predict", "block_until_ready", "dispatch",
                   "_dispatch", "_dispatch_inner"}
#: blocking attribute calls flagged unconditionally under a lock
_BLOCKING_ATTRS = {"result", "wait_to_read", "wait_to_write"}
#: bare-primitive constructors MXA009 keeps out of framework code
_BARE_PRIMITIVES = {"Lock", "RLock", "Condition"}


def _terminal_name(expr) -> Optional[str]:
    """The last identifier of a Name/Attribute chain (``self._lock`` ->
    ``_lock``); None for anything else."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _lockish_name(expr) -> Optional[str]:
    nm = _terminal_name(expr)
    if nm is not None and _LOCKISH.search(nm):
        return nm
    return None


def _is_queue_get(node: ast.Call) -> bool:
    """``Queue.get`` takes only ``block``/``timeout`` (bools/numbers);
    a ``.get(key)`` with an arbitrary positional is a dict lookup on a
    queue-ISH name, not a blocking dequeue."""
    if any(k.arg not in ("block", "timeout") for k in node.keywords):
        return False
    return all(isinstance(a, ast.Constant)
               and isinstance(a.value, (bool, int, float))
               for a in node.args)


def _is_join_blocking(node: ast.Call) -> bool:
    """``.join()`` is Thread.join when it takes no argument, a numeric
    timeout, or a ``timeout=`` keyword — ``", ".join(parts)`` (one
    non-numeric positional) is str.join and never flagged."""
    if any(k.arg == "timeout" for k in node.keywords):
        return True
    if not node.args and not node.keywords:
        return True
    if len(node.args) == 1 and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, (int, float)):
        return True
    return False


class _ThreadLint(ast.NodeVisitor):
    """MXA007 (blocking under lock) + MXA009 (bare primitive) over one
    module. Lock context is LEXICAL: statements inside a ``with
    <lockish>`` body; nested function definitions do not inherit it
    (a closure defined under a lock runs later, lock-free)."""

    def __init__(self, filename: str, lines: Sequence[str]):
        self.filename = filename
        self.lines = lines
        self._locks: List[str] = []
        self.findings: List[Finding] = []

    def _flag(self, node, rule: str, message: str, severity="error"):
        lineno = getattr(node, "lineno", 0)
        line = self.lines[lineno - 1] if 0 < lineno <= len(self.lines) \
            else ""
        allowed = _allow_marker(line)
        blessed = allowed is not None and (not allowed or rule in allowed)
        self.findings.append(Finding(
            checker="source", rule=rule, message=message,
            where=f"{self.filename}:{lineno}", severity=severity,
            blessed=blessed))

    # -------- lexical lock context --------
    def visit_With(self, node):
        held = [n for n in (_lockish_name(i.context_expr)
                            for i in node.items) if n]
        self._locks.extend(held)
        for item in node.items:
            self.visit(item)
        for stmt in node.body:
            self.visit(stmt)
        if held:
            del self._locks[-len(held):]

    def _visit_fn(self, node):
        saved, self._locks = self._locks, []
        self.generic_visit(node)
        self._locks = saved

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -------- calls --------
    def visit_Call(self, node):
        fn = node.func
        # MXA009 everywhere (lock context irrelevant)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "threading" and \
                fn.attr in _BARE_PRIMITIVES:
            self._flag(node, "MXA009",
                       f"bare `threading.{fn.attr}()` in framework code "
                       "is invisible to the lock-order audit and the "
                       "deadlock forensics — use analysis.threads."
                       f"{'mx_condition' if fn.attr == 'Condition' else 'mx_rlock' if fn.attr == 'RLock' else 'mx_lock'}"
                       "(name) (or bless the few legitimate bare locks "
                       "inline)")
        if not self._locks:
            self.generic_visit(node)
            return
        lock = self._locks[-1]
        # MXA007: blocking calls lexically under a lock
        blocked = None
        if isinstance(fn, ast.Attribute):
            recv = _terminal_name(fn.value)
            if fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "time":
                blocked = "time.sleep"
            elif fn.attr == "join" and _is_join_blocking(node):
                blocked = f"{recv or '?'}.join"
            elif fn.attr in _BLOCKING_ATTRS:
                blocked = f"{recv or '?'}.{fn.attr}"
            elif fn.attr in ("get", "put") and recv is not None \
                    and _QUEUEISH.search(recv) \
                    and (fn.attr == "put" or _is_queue_get(node)):
                blocked = f"{recv}.{fn.attr}"
            elif fn.attr in _DISPATCH_CALLS:
                blocked = f"{recv or '?'}.{fn.attr}"
        if blocked is not None:
            self._flag(node, "MXA007",
                       f"blocking call `{blocked}(...)` inside `with "
                       f"{lock}:` — every other acquirer of {lock} "
                       "convoys behind this wait (and it is one "
                       "lock-order edge away from deadlock); move the "
                       "blocking work outside the critical section")
        self.generic_visit(node)


class _ClassShareAudit:
    """MXA008 over one ClassDef: attributes mutated WITHOUT a lock both
    from the class's thread-body closure (``Thread(target=self.m)``
    plus transitive self-calls) and from a public method."""

    def __init__(self, linter: "_ThreadLint", cls: ast.ClassDef):
        self.linter = linter
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {
            it.name: it for it in cls.body
            if isinstance(it, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # method -> attr -> [(lineno, guarded)]
        self.mutations: Dict[str, Dict[str, list]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.entries: Set[str] = set()

    def run(self):
        for name, fn in self.methods.items():
            self._scan_method(name, fn)
        closure = self._closure()
        if not closure:
            return
        public = [m for m in self.methods
                  if not m.startswith("_") and m not in closure]
        for attr in sorted({a for m in closure
                            for a in self.mutations.get(m, ())}):
            t_sites = [(m, ln) for m in closure
                       for ln, g in self.mutations.get(m, {}).get(attr, ())
                       if not g]
            if not t_sites:
                continue
            p_sites = [(m, ln) for m in public
                       for ln, g in self.mutations.get(m, {}).get(attr, ())
                       if not g]
            if not p_sites:
                continue
            tm, tl = t_sites[0]
            pm, pl = p_sites[0]
            self.linter._flag(
                _Loc(pl), "MXA008",
                f"`self.{attr}` is written without a lock from the "
                f"thread body `{self.cls.name}.{tm}` (line {tl}) AND "
                f"from public `{self.cls.name}.{pm}` (line {pl}) — "
                "guard both writes with one mx_lock, or bless with a "
                "comment naming why the race is benign")

    # ---- per-method scan: mutations + lock context + self-calls ----
    def _scan_method(self, name: str, fn):
        muts: Dict[str, list] = self.mutations.setdefault(name, {})
        calls: Set[str] = self.calls.setdefault(name, set())

        def self_attr(expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                return expr.attr
            return None

        def mutated_attr(tgt) -> Optional[str]:
            a = self_attr(tgt)
            if a is not None:
                return a
            if isinstance(tgt, ast.Subscript):
                return self_attr(tgt.value)
            return None

        def walk(node, depth: int):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                for child in ast.iter_child_nodes(node):
                    walk(child, 0)      # closures run lock-free later
                return
            if isinstance(node, ast.With):
                held = sum(1 for i in node.items
                           if _lockish_name(i.context_expr))
                for i in node.items:
                    walk(i, depth)
                for stmt in node.body:
                    walk(stmt, depth + held)
                return
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    a = mutated_attr(tgt)
                    if a is not None:
                        muts.setdefault(a, []).append(
                            (node.lineno, depth > 0))
            elif isinstance(node, ast.AugAssign):
                a = mutated_attr(node.target)
                if a is not None:
                    muts.setdefault(a, []).append(
                        (node.lineno, depth > 0))
            elif isinstance(node, ast.Call):
                callee = node.func
                m = self_attr(callee)
                if m is not None and m in self.methods:
                    calls.add(m)
                if isinstance(callee, (ast.Name, ast.Attribute)) and \
                        _terminal_name(callee) == "Thread":
                    for k in node.keywords:
                        if k.arg == "target":
                            t = self_attr(k.value)
                            if t is not None:
                                self.entries.add(t)
            for child in ast.iter_child_nodes(node):
                walk(child, depth)

        walk(fn, 0)

    def _closure(self) -> Set[str]:
        out: Set[str] = set()
        todo = [m for m in self.entries if m in self.methods]
        while todo:
            m = todo.pop()
            if m in out:
                continue
            out.add(m)
            todo.extend(c for c in self.calls.get(m, ())
                        if c in self.methods and c not in out)
        return out


class _Loc:
    """Minimal lineno carrier for _flag on synthesized findings."""

    def __init__(self, lineno: int):
        self.lineno = lineno


def lint_threads_source(src: str,
                        filename: str = "<string>") -> List[Finding]:
    """MXA007-009 over one file (module scope — not just forwards);
    blessed findings included, marked."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Finding(checker="source", rule="MXA000", severity="warn",
                        message=f"could not parse: {e}",
                        where=f"{filename}:{e.lineno or 0}")]
    lines = src.splitlines()
    linter = _ThreadLint(filename, lines)
    linter.visit(tree)
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            _ClassShareAudit(linter, cls).run()
    return linter.findings


def lint_threads_path(path: str) -> List[Finding]:
    """Thread rules over a file or every ``*.py`` under a directory."""
    findings: List[Finding] = []
    if os.path.isdir(path):
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git")]
            for f in sorted(files):
                if f.endswith(".py"):
                    findings.extend(
                        lint_threads_path(os.path.join(root, f)))
        return findings
    with open(path, "r", encoding="utf-8") as fh:
        return lint_threads_source(fh.read(), filename=path)


# ---------------------------------------------------------------------------
# allowlist
# ---------------------------------------------------------------------------

def load_allowlist(path: str) -> List[Tuple[str, str]]:
    """``<path-suffix>::<rule>`` entries (# comments and blanks
    skipped); rule ``*`` blesses every rule at that path."""
    entries: List[Tuple[str, str]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if "::" not in line:
                entries.append((line, "*"))
                continue
            p, rule = line.rsplit("::", 1)
            entries.append((p.strip(), rule.strip() or "*"))
    return entries


def filter_allowed(findings: Iterable[Finding],
                   allowlist: Sequence[Tuple[str, str]]) -> List[Finding]:
    """Findings NOT blessed by inline markers or allowlist entries."""
    out = []
    for f in findings:
        if f.blessed:
            continue
        fpath = f.where.rsplit(":", 1)[0].replace(os.sep, "/")
        hit = False
        for suffix, rule in allowlist:
            if fpath.endswith(suffix.replace(os.sep, "/")) and \
                    rule in ("*", f.rule):
                hit = True
                break
        if not hit:
            out.append(f)
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis.lint",
        description="jit-safety lint for HybridBlock forward/loss code")
    parser.add_argument("targets", nargs="+",
                        help="files, directories, or importable module "
                             "names")
    parser.add_argument("--allowlist", default=None,
                        help="file of <path-suffix>::<rule> blessed "
                             "entries")
    parser.add_argument("--show-blessed", action="store_true",
                        help="also print violations blessed inline or by "
                             "the allowlist")
    parser.add_argument("--threads", action="store_true",
                        help="run the module-scope thread rules "
                             "MXA007-009 instead of the forward rules")
    args = parser.parse_args(argv)
    lint_fn = lint_threads_path if args.threads else lint_path
    findings: List[Finding] = []
    for target in args.targets:
        if os.path.exists(target):
            findings.extend(lint_fn(target))
        elif args.threads:
            spec = importlib.util.find_spec(target)
            if spec is None or not spec.origin:
                raise ImportError(f"cannot locate module {target!r}")
            for loc in (spec.submodule_search_locations or [spec.origin]):
                findings.extend(lint_threads_path(loc))
        else:
            findings.extend(lint_module(target))
    allow = load_allowlist(args.allowlist) if args.allowlist else []
    active = filter_allowed(findings, allow)
    shown = findings if args.show_blessed else active
    for f in shown:
        print(f)
    n_blessed = len(findings) - len(active)
    print(f"{len(active)} violation(s), {n_blessed} blessed",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
