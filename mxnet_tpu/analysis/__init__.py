"""mxnet_tpu.analysis — static analysis of compiled train-step programs.

Four cooperating checkers (docs/ANALYSIS.md):

- **program lint** (:mod:`.program`): walks the jaxpr and optimized HLO
  of a ``Trainer.compile_step`` program — collective census per mesh
  axis, donation audit, host-transfer detection, dtype-drift detection,
  retrace accounting.  ``mx.analysis.analyze_step(step, *batch)``.
- **fusion census** (:mod:`.fusion`): audits XLA's fusion decisions in
  the optimized HLO against the ideal-fusion diff of arXiv:2301.13062 —
  stranded elementwise ops, HBM boundary materializations, per-kernel
  arithmetic intensity, and a checked-in per-leg regression gate
  (``MXNET_FUSION_BASELINE``).  ``mx.analysis.fusion_census(hlo)``.
- **sharding analysis** (:mod:`.sharding`): GSPMD sharding-flow audit
  (the per-buffer sharding table), implicit-reshard detection, the
  per-mesh-axis communication cost model, declarative ``expect_spec``
  invariant packs for every parallelism path, and a checked-in per-leg
  reshard regression gate (``MXNET_SHARDING_BASELINE``).
  ``mx.analysis.audit_sharding(hlo, mesh=...)``.
- **overlap analysis** (:mod:`.overlap`): exposed-communication pass
  over the optimized-HLO schedule — per-axis exposed vs total comm
  seconds and the overlap fraction, with a checked-in per-leg
  regression gate (``MXNET_OVERLAP_BASELINE``).
  ``mx.analysis.overlap_census(hlo, mesh=...)``.
- **source lint** (:mod:`.lint`): AST pass over HybridBlock forwards /
  loss functions for jit-unsafe Python (``.asnumpy()``, tracer-dependent
  ``if``, unkeyed randomness).  ``python -m mxnet_tpu.analysis.lint``.
- **runtime transfer guard** (:mod:`.guard`):
  ``MXNET_TRANSFER_GUARD=log|raise`` catches silent device->host syncs
  inside the training hot loop at run time.

This ``__init__`` stays import-light (PEP 562 lazy submodules): the
NDArray sync sites import :mod:`.guard` on the framework's critical
import path.
"""
from .report import (CollectiveOp, CollectiveStats, DonationAudit,  # noqa
                     Finding, ProgramReport)
from .guard import (allow_transfers, hot_scope, transfer_guard)      # noqa

__all__ = [
    "Finding", "ProgramReport", "CollectiveOp", "CollectiveStats",
    "DonationAudit", "FusionReport",
    "analyze_step", "analyze_lowered", "collective_census",
    "donation_audit", "host_transfer_scan", "dtype_drift_scan",
    "expect_mode", "mode_spec_pack", "explain_signature_diff",
    "fusion_census", "check_baseline", "load_baselines",
    "lint_source", "lint_path", "lint_module", "lint_function",
    "lint_threads_source", "lint_threads_path",
    "load_allowlist", "filter_allowed",
    "mx_lock", "mx_rlock", "mx_condition", "ThreadReport",
    "transfer_guard", "hot_scope", "allow_transfers",
    "OpSharding", "ShardingTable", "ShardingAudit", "SpecPack",
    "CollectiveRule", "audit_sharding", "sharding_table",
    "implicit_reshards", "comm_cost", "bandwidth_profile",
    "expect_spec", "register_spec_pack", "get_spec_pack", "spec_packs",
    "overlap_census", "OverlapReport",
]

_LAZY = {
    "analyze_step": "program", "analyze_lowered": "program",
    "collective_census": "program", "donation_audit": "program",
    "host_transfer_scan": "program", "dtype_drift_scan": "program",
    "expect_mode": "program", "mode_spec_pack": "program",
    "explain_signature_diff": "program",
    "fusion_census": "fusion", "check_baseline": "fusion",
    "load_baselines": "fusion", "FusionReport": "fusion",
    "lint_source": "lint", "lint_path": "lint", "lint_module": "lint",
    "lint_function": "lint", "load_allowlist": "lint",
    "filter_allowed": "lint",
    "lint_threads_source": "lint", "lint_threads_path": "lint",
    "mx_lock": "threads", "mx_rlock": "threads",
    "mx_condition": "threads", "ThreadReport": "threads",
    "OpSharding": "sharding", "ShardingTable": "sharding",
    "ShardingAudit": "sharding", "SpecPack": "sharding",
    "CollectiveRule": "sharding", "audit_sharding": "sharding",
    "sharding_table": "sharding", "implicit_reshards": "sharding",
    "comm_cost": "sharding", "bandwidth_profile": "sharding",
    "expect_spec": "sharding", "register_spec_pack": "sharding",
    "get_spec_pack": "sharding", "spec_packs": "sharding",
    "overlap_census": "overlap", "OverlapReport": "overlap",
    "program": None, "lint": None, "guard": None, "hlo": None,
    "report": None, "fusion": None, "sharding": None, "overlap": None,
    "threads": None,
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(
            f".{_LAZY[name] or name}", __name__)
        if _LAZY[name] is None:
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
