"""Minimal parser for XLA's optimized HLO text dumps.

The program lint needs five things out of ``compiled.as_text()``: every
op's result shape, opcode and operands (def-use edges, to classify the
CPU backend's decomposed reduce-scatters), the ``input_output_alias``
table in the module header (donation ground truth), replica groups on
collectives (mesh-axis attribution), custom-call targets (host
callbacks), and — since the fusion census — the COMPUTATION STRUCTURE:
which ops live inside which computation, which computations are fusion
bodies (``calls=`` from a ``fusion`` op), scalar appliers
(``to_apply=`` on reduces), or control-flow bodies (``body=`` /
``condition=`` / ``branch_computations=`` — these run as sequences of
kernels, like the entry).  A full HLO grammar is overkill — module text
is one op per line with a stable ``%name = type opcode(operands),
attrs`` shape, which this parses with regexes.  Parsing failures
degrade to ``None`` fields, never exceptions: an analyzer must not take
down the run it observes.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["HloOp", "HloComputation", "HloModule", "parse_hlo",
           "parse_shape_elements", "parse_replica_groups",
           "parse_source_target_pairs"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = f32[2,3]{1,0} opcode(...)` | `%name = (f32[2]{0}, ...) opcode(...)`
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\(.*?\)|[\w]+\[[^\]]*\](?:\{[^}]*\})?)"
    r"\s+([\w\-]+)\((.*)$")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
# `f32[8,4]{1,0} %operand` — typed operand inside the operand list
_TYPED_OPERAND_RE = re.compile(
    r"(\w+\[[^\]]*\](?:\{[^}]*\})?)\s+%([\w.\-]+)")
# `%fused_computation.1 (param_0: f32[8]) -> f32[8] {`  |
# `ENTRY %main.22 (Arg_0.1: f32[8,8], ...) -> (f32[8], ...) {`
_COMPUTATION_RE = re.compile(
    r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\)\s*->\s*.+\{\s*$")
# computation references in op attributes, by role
_CALLED_RE = re.compile(
    r"(calls|to_apply|condition|body|true_computation|false_computation"
    r"|comparator|select|scatter)=%([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_FUSION_KIND_RE = re.compile(r"kind=k(\w+)")

#: computation roles whose ops execute INSIDE a single kernel (fusion
#: bodies, scalar reduction/sort appliers) — everything else (entry,
#: while bodies, conditional branches) schedules its ops as kernels
_KERNEL_INTERNAL_ROLES = frozenset(
    {"calls", "to_apply", "comparator", "select", "scatter"})


def parse_shape_elements(type_str: str) -> Tuple[int, Optional[str], int]:
    """(total elements, dtype of first array part, total bytes) of an HLO
    result type — tuple types sum over their parts."""
    total, first_dtype, total_bytes = 0, None, 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype == "token":
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                if d:
                    n *= int(d)
        total += n
        total_bytes += n * _DTYPE_BYTES.get(dtype, 4)
        if first_dtype is None:
            first_dtype = dtype
    return total, first_dtype, total_bytes


@dataclass
class HloOp:
    name: str
    opcode: str
    type_str: str
    elements: int
    dtype: Optional[str]
    bytes: int
    operands: List[str]
    line: str
    replica_groups: Optional[List[Tuple[int, ...]]] = None
    custom_call_target: Optional[str] = None
    #: name of the computation this op's line appeared in
    computation: Optional[str] = None
    #: fusion ops: kind=kLoop|kInput|kOutput|kCustom, lowercased
    fusion_kind: Optional[str] = None
    #: computations referenced from this op's attributes, by role
    #: ({"calls": [...], "body": [...], ...})
    called: Dict[str, List[str]] = field(default_factory=dict)
    #: HLO result type of each operand where the line names it
    #: (aligned with ``operands``; None where untyped, e.g. tuples)
    operand_types: List[Optional[str]] = field(default_factory=list)
    #: True for a computation's ROOT op (its output, not a boundary)
    is_root: bool = False
    #: raw ``sharding={...}`` attribute text (braces included) — the
    #: GSPMD sharding annotation, parsed by analysis/sharding.py
    sharding: Optional[str] = None
    #: ``metadata={op_name="..."}`` — the jax-level name (parameter
    #: label, or the producing primitive's path)
    op_name: Optional[str] = None

    def operand_bytes(self, i: int) -> Optional[int]:
        """Bytes of operand ``i``, from its typed mention on this line
        (None where the operand is untyped in the text)."""
        if i < len(self.operand_types) and self.operand_types[i]:
            return parse_shape_elements(self.operand_types[i])[2]
        return None


@dataclass
class HloComputation:
    """One named computation: the entry, a fusion body, a reduction
    applier, or a control-flow body. ``op_names`` preserve text order."""
    name: str
    is_entry: bool = False
    op_names: List[str] = field(default_factory=list)
    #: (op name, role) pairs that reference this computation
    called_by: List[Tuple[str, str]] = field(default_factory=list)

    @property
    def kernel_internal(self) -> bool:
        """True when this computation's ops execute inside ONE kernel
        (a fusion body or a scalar to_apply) rather than as a schedule
        of kernels (the entry, while bodies, cond branches)."""
        return any(role in _KERNEL_INTERNAL_ROLES
                   for _, role in self.called_by)


@dataclass
class HloModule:
    ops: Dict[str, HloOp] = field(default_factory=dict)
    # consumers: producer op name -> list of consumer op names
    uses: Dict[str, List[str]] = field(default_factory=dict)
    input_output_alias: List[Tuple[int, int]] = field(default_factory=list)
    num_partitions: int = 1
    computations: Dict[str, HloComputation] = field(default_factory=dict)
    entry: Optional[str] = None
    #: module header carried ``is_scheduled=true`` — op text order IS
    #: the compiler's final kernel schedule (optimized dumps from
    #: ``compiled.as_text()`` have it; pre-optimization dumps don't)
    is_scheduled: bool = False

    @property
    def spmd_partitioned(self) -> bool:
        """True when the SPMD partitioner has already run over this
        module — its shapes are PER-SHARD (XLA renames the entry with
        an ``_spmd`` suffix).  A ``num_partitions>1`` module WITHOUT
        the suffix still carries global logical shapes annotated with
        ``sharding=`` attrs (pre-partitioning dumps, canned programs) —
        byte/FLOP accounting must divide those by the tile factor."""
        return bool(self.entry and self.entry.endswith("_spmd"))

    def consumers(self, name: str) -> List[HloOp]:
        return [self.ops[u] for u in self.uses.get(name, [])
                if u in self.ops]

    def by_opcode(self, *opcodes: str) -> List[HloOp]:
        return [op for op in self.ops.values() if op.opcode in opcodes]

    def fused_ops(self, op: HloOp) -> List[HloOp]:
        """The ops inside a fusion op's body computation (``calls=``),
        text order; [] for non-fusion ops or unresolvable bodies."""
        out: List[HloOp] = []
        for comp_name in op.called.get("calls", ()):
            comp = self.computations.get(comp_name)
            if comp is None:
                continue
            out.extend(self.ops[n] for n in comp.op_names
                       if n in self.ops)
        return out

    def schedulable_computations(self) -> List[HloComputation]:
        """Computations whose ops run as a SCHEDULE of kernels: the
        entry plus control-flow bodies (while body/cond, conditional
        branches). Fusion bodies and scalar appliers are excluded —
        their ops live inside one kernel."""
        return [c for c in self.computations.values()
                if not c.kernel_internal]

    def parent_fusion(self, op: HloOp) -> Optional[HloOp]:
        """The fusion op whose body contains ``op`` (None for ops at a
        schedulable level or in non-fusion computations)."""
        comp = self.computations.get(op.computation or "")
        if comp is None:
            return None
        for caller, role in comp.called_by:
            if role == "calls" and caller in self.ops:
                return self.ops[caller]
        return None


def parse_replica_groups(line: str, num_devices: int) \
        -> Optional[List[Tuple[int, ...]]]:
    """Replica groups of a collective line, as explicit device-id tuples.

    Handles the explicit form ``replica_groups={{0,1},{2,3}}`` and the
    iota form ``replica_groups=[G,S]<=[N]`` (reshape iota(N) to GxS) with
    an optional source-shape transpose ``<=[a,b]T(1,0)``."""
    m = re.search(r"replica_groups=\{\{([\d,{}\s]*)\}\}", line)
    if m:
        groups = []
        for grp in re.findall(r"[\d,\s]+", m.group(1)):
            ids = tuple(int(x) for x in grp.replace(" ", "").split(",")
                        if x != "")
            if ids:
                groups.append(ids)
        return groups or None
    m = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
        line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        src_dims = [int(x) for x in m.group(3).split(",")]
        n = 1
        for d in src_dims:
            n *= d
        if g * s != n:
            return None
        ids = list(range(n))
        if m.group(4):
            try:
                import numpy as onp
                perm = [int(x) for x in m.group(4).split(",")]
                ids = list(onp.arange(n).reshape(src_dims)
                           .transpose(perm).reshape(-1))
            except Exception:
                return None
        return [tuple(int(i) for i in ids[i * s:(i + 1) * s])
                for i in range(g)]
    return None


def parse_source_target_pairs(line: str) \
        -> Optional[List[Tuple[int, ...]]]:
    """``source_target_pairs={{0,1},{1,2},...}`` of a collective-permute,
    folded into replica-group-shaped device sets: the connected
    components of the permutation graph (a ring over one mesh axis
    becomes one group spanning that axis — which is exactly what the
    census's per-axis attribution needs)."""
    m = re.search(r"source_target_pairs=\{\{([\d,{}\s]*)\}\}", line)
    if not m:
        return None
    edges = []
    for pair in re.findall(r"(\d+)\s*,\s*(\d+)", m.group(1)):
        edges.append((int(pair[0]), int(pair[1])))
    if not edges:
        return None
    # union-find over the permutation edges
    parent: Dict[int, int] = {}

    def find(x):
        parent.setdefault(x, x)
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in edges:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb
    groups: Dict[int, List[int]] = {}
    for n in parent:
        groups.setdefault(find(n), []).append(n)
    return [tuple(sorted(g)) for g in groups.values()]


def _balanced_braces(text: str, start: int) -> str:
    """The ``{...}`` block starting at ``start`` (which must point at a
    ``{``), contents only, handling nesting."""
    depth, i = 0, start
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
    return text[start + 1:]


def parse_hlo(text: str, num_devices: int = 1) -> HloModule:
    mod = HloModule(num_partitions=num_devices)
    header = text.splitlines()[0] if text else ""
    at = header.find("input_output_alias={")
    if at >= 0:
        body = _balanced_braces(header, at + len("input_output_alias="))
        # entries look like `{1}: (0, {}, may-alias)` — (output index
        # tuple): (param number, param index, kind)
        for om, pm in re.findall(r"\{([\d,\s]*)\}:\s*\((\d+)", body):
            out_idx = int(om.split(",")[0]) if om.strip() else 0
            mod.input_output_alias.append((out_idx, int(pm)))
    np_m = re.search(r"num_partitions=(\d+)", text[:2000] if text else "")
    if np_m:
        mod.num_partitions = int(np_m.group(1))
    if re.search(r"is_scheduled=true", text[:2000] if text else ""):
        mod.is_scheduled = True
    current: Optional[HloComputation] = None
    for line in (text or "").splitlines():
        cm = _COMPUTATION_RE.match(line)
        if cm and "=" not in line.split("(", 1)[0]:
            name = cm.group(2)
            current = mod.computations.setdefault(
                name, HloComputation(name=name))
            if cm.group(1):
                current.is_entry = True
                mod.entry = name
            continue
        if line.strip() == "}":
            current = None
            continue
        om = _OP_RE.match(line)
        if not om:
            continue
        name, type_str, opcode, rest = om.groups()
        elems, dtype, nbytes = parse_shape_elements(type_str)
        # operands = %refs inside the top-level parens (attrs after the
        # closing paren also contain %refs for to_apply etc.; cut at the
        # first `),` boundary which ends the operand list in practice)
        operand_src = rest.split("), ")[0]
        operands = _OPERAND_RE.findall(operand_src)
        typed = dict(
            (n, t) for t, n in _TYPED_OPERAND_RE.findall(operand_src))
        op = HloOp(name=name, opcode=opcode, type_str=type_str,
                   elements=elems, dtype=dtype, bytes=nbytes,
                   operands=operands, line=line,
                   computation=current.name if current else None,
                   operand_types=[typed.get(o) for o in operands],
                   is_root=line.lstrip().startswith("ROOT "))
        if opcode in ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all",
                      "all-reduce-start", "all-gather-start",
                      "reduce-scatter-start", "collective-permute-start",
                      "all-to-all-start", "async-start"):
            op.replica_groups = parse_replica_groups(line, num_devices)
            if op.replica_groups is None and \
                    opcode.startswith("collective-permute"):
                op.replica_groups = parse_source_target_pairs(line)
        if opcode == "custom-call":
            tm = re.search(r'custom_call_target="([^"]+)"', line)
            if tm:
                op.custom_call_target = tm.group(1)
        sh_at = line.find("sharding=")
        if sh_at >= 0 and line[sh_at + len("sharding="):].lstrip()[:1] \
                == "{":
            brace = line.index("{", sh_at)
            op.sharding = "{" + _balanced_braces(line, brace) + "}"
        nm = re.search(r'op_name="([^"]*)"', line)
        if nm:
            op.op_name = nm.group(1)
        if opcode == "fusion":
            km = _FUSION_KIND_RE.search(rest)
            if km:
                op.fusion_kind = km.group(1).lower()
        for role, comp_name in _CALLED_RE.findall(rest):
            op.called.setdefault(role, []).append(comp_name)
        bm = _BRANCHES_RE.search(rest)
        if bm:
            for ref in _OPERAND_RE.findall(bm.group(1)):
                op.called.setdefault("branch", []).append(ref)
        if current is not None:
            current.op_names.append(name)
        # keep the first definition (entry computation ops can collide
        # with fusion-internal names; censuses only need one)
        if name not in mod.ops:
            mod.ops[name] = op
        for src in operands:
            mod.uses.setdefault(src, []).append(name)
    # link computation <- caller references
    for op in mod.ops.values():
        for role, comps in op.called.items():
            for comp_name in comps:
                comp = mod.computations.get(comp_name)
                if comp is not None:
                    comp.called_by.append((op.name, role))
    return mod
