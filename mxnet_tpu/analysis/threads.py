"""Concurrency audit: named locks, lock-order graph, deadlock forensics.

The compiled program has had static checkers since PR 4 (fusion,
sharding, overlap); the host-side thread layer — window retires, the
batcher dispatcher, checkpoint writers, prefetch staging, heartbeats,
fleet failover — had none. This module is the audit substrate:

- :func:`mx_lock` / :func:`mx_rlock` / :func:`mx_condition` return
  NAMED, instrumented primitives that behave exactly like their
  ``threading`` counterparts but additionally record, per thread, the
  stack of locks currently held. Every acquisition made while other
  audited locks are held adds a ``held -> acquired`` edge (with both
  call sites) to a process-global :class:`LockOrderGraph`.
- A CYCLE in that graph is a potential deadlock: two threads can
  interleave the two orderings and wedge. :func:`find_cycles` /
  :func:`cycle_findings` report each one with the owning stacks named.
- The blessed hierarchy lives in ``tests/fixtures/lock_hierarchy.json``;
  :func:`check_hierarchy` fails on any edge outside it (the checked-in
  baseline discipline the fusion/sharding audits use). Refresh with
  :func:`save_baseline` after reviewing the new edge.
- RUNTIME forensics: a thread blocked on an audited lock for longer
  than ``MXNET_LOCK_STALL_SEC`` fires exactly one ``deadlock`` episode
  anomaly on the watchdog channel and writes one atomic ranked dump
  (ownership graph, per-thread stacks, queue depths) to
  ``MXNET_THREADS_DUMP_DIR`` — the OOM/NaN post-mortem pattern.
- ``mx_threads_*`` metrics (held-lock gauge, longest-wait gauge,
  per-lock wait histogram, dump counter) feed the always-on registry.

The deterministic-schedule harness (``testing/sched.py``) interposes on
these same primitives: while a ``VirtualScheduler`` is installed via
:func:`set_scheduler`, acquire/release/wait/notify on its managed
threads become cooperative yield points, making thread interleavings
replayable from a seed.

Import discipline: this module must stay light (no jax, no telemetry at
import time) — engine.py and telemetry/exporters.py import it at module
scope. Telemetry is reached lazily, the package-wide idiom.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .report import Finding

__all__ = [
    "MxLock", "MxCondition", "LockOrderGraph", "ThreadReport",
    "mx_lock", "mx_rlock", "mx_condition",
    "graph", "snapshot", "find_cycles", "cycle_findings",
    "check_hierarchy", "load_baseline", "save_baseline",
    "describe_locks", "register_queue", "write_dump", "dump_payload",
    "stall_seconds", "dump_dir", "reset",
    "set_scheduler", "scheduler",
]

_LOG = logging.getLogger("mxnet_tpu.analysis")

# The instrument's own mutex — the ONE lock that must stay outside the
# audited universe (auditing the auditor would recurse). Kept bare on
# purpose.
_MU = threading.Lock()  # mx-lint: allow=MXA009

# telemetry is imported lazily (package initializes in dependency
# order) and cached — the idiom engine.py uses
_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def stall_seconds(default: float = 0.0) -> float:
    """``MXNET_LOCK_STALL_SEC``: a thread blocked on an audited lock
    longer than this fires the ``deadlock`` watchdog episode + dump.
    Unset/<=0 disables the detector (the default — training loops own
    their own latency budget)."""
    try:
        v = float(os.environ.get("MXNET_LOCK_STALL_SEC", default))
    except (TypeError, ValueError):
        return default
    return v if v > 0 else 0.0


def dump_dir() -> Optional[str]:
    """``MXNET_THREADS_DUMP_DIR``: where stall dumps land (None = no
    dumps, the anomaly event still fires)."""
    d = os.environ.get("MXNET_THREADS_DUMP_DIR", "").strip()
    return d or None


# ---------------------------------------------------------------------------
# per-thread held-lock stack + call sites
# ---------------------------------------------------------------------------

class _Held:
    __slots__ = ("lock", "site", "count")

    def __init__(self, lock, site, count=1):
        self.lock = lock
        self.site = site
        self.count = count


_TLS = threading.local()


def _held_stack() -> list:
    st = getattr(_TLS, "held", None)
    if st is None:
        st = _TLS.held = []
    return st


def _call_site(limit: int = 3) -> Tuple[str, ...]:
    """Up to ``limit`` frames of the caller's stack, innermost first,
    skipping this module — cheap frame walk, no traceback objects."""
    try:
        f = sys._getframe(1)
    except ValueError:      # pragma: no cover - no caller frame
        return ()
    here = __file__
    out = []
    while f is not None and len(out) < limit:
        co = f.f_code
        if co.co_filename != here:
            out.append("%s:%d in %s" % (
                os.path.basename(co.co_filename), f.f_lineno, co.co_name))
        f = f.f_back
    return tuple(out)


# ---------------------------------------------------------------------------
# the lock-order graph
# ---------------------------------------------------------------------------

class LockOrderGraph:
    """Directed graph of observed lock acquisition orderings.

    Edge ``a -> b`` means: some thread acquired ``b`` while holding
    ``a``. The first observation's call sites (both sides) and thread
    name are kept; later observations only bump the count. A cycle is a
    potential deadlock."""

    def __init__(self):
        self._edges: Dict[Tuple[str, str], dict] = {}

    def record(self, frm: str, to: str,
               frm_site: Sequence[str], to_site: Sequence[str]):
        key = (frm, to)
        with _MU:
            e = self._edges.get(key)
            if e is None:
                self._edges[key] = {
                    "from": frm, "to": to, "count": 1,
                    "from_site": list(frm_site),
                    "to_site": list(to_site),
                    "thread": threading.current_thread().name,
                }
            else:
                e["count"] += 1

    def edges(self) -> List[dict]:
        with _MU:
            return [dict(e) for e in self._edges.values()]

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        with _MU:
            return set(self._edges)

    def clear(self):
        with _MU:
            self._edges.clear()

    def find_cycles(self) -> List[List[str]]:
        """Simple cycles as node-name lists ``[a, b, ..., a]`` — one
        representative per distinct node set, DFS back-edge extraction
        (the graph has tens of nodes, recursion is fine)."""
        pairs = self.edge_pairs()
        adj: Dict[str, List[str]] = {}
        nodes: Set[str] = set()
        for a, b in pairs:
            adj.setdefault(a, []).append(b)
            nodes.add(a)
            nodes.add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in nodes}
        cycles: List[List[str]] = []
        seen: Set[frozenset] = set()
        path: List[str] = []

        def dfs(n):
            color[n] = GRAY
            path.append(n)
            for m in sorted(adj.get(n, ())):
                c = color.get(m, WHITE)
                if c == GRAY:
                    cyc = path[path.index(m):] + [m]
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(cyc)
                elif c == WHITE:
                    dfs(m)
            path.pop()
            color[n] = BLACK

        for n in sorted(nodes):
            if color[n] == WHITE:
                dfs(n)
        return cycles


_GRAPH = LockOrderGraph()


def graph() -> LockOrderGraph:
    """The process-global lock-order graph every audited lock feeds."""
    return _GRAPH


# ---------------------------------------------------------------------------
# scheduler hook (testing/sched.py installs itself here)
# ---------------------------------------------------------------------------

_SCHED = None


def set_scheduler(s) -> None:
    """Install/clear the live VirtualScheduler (testing/sched.py).
    While installed, audited-lock operations on threads the scheduler
    MANAGES become cooperative yield points; every other thread keeps
    real blocking semantics."""
    global _SCHED
    _SCHED = s


def scheduler():
    return _SCHED


def _sched_for_current():
    s = _SCHED
    if s is not None and s.manages_current_thread():
        return s
    return None


# ---------------------------------------------------------------------------
# metrics (lazy; cached — registry.reset() zeroes in place)
# ---------------------------------------------------------------------------

_METRICS = None
_HELD_TOTAL = 0
_LONGEST = 0.0


def _metrics():
    global _METRICS
    if _METRICS is None:
        t = _telemetry()
        reg = t.registry()
        _METRICS = (reg.gauge(t.names.THREADS_HELD),
                    reg.gauge(t.names.THREADS_LONGEST_WAIT),
                    reg.histogram(t.names.THREADS_LOCK_WAIT),
                    reg.counter(t.names.THREADS_DUMPS))
    return _METRICS


def _set_held_gauge(total: int):
    try:
        _metrics()[0].set(total)
    except Exception:       # metrics must never break locking
        pass


def _note_wait(waited: float):
    """Live longest-wait gauge update while a waiter is still blocked —
    so a wedged process shows the stall before (or without) resolving."""
    global _LONGEST
    try:
        with _MU:
            if waited > _LONGEST:
                _LONGEST = waited
            longest = _LONGEST
        _metrics()[1].set(longest)
    except Exception:
        pass


def _observe_wait(name: str, waited: float):
    global _LONGEST
    try:
        with _MU:
            if waited > _LONGEST:
                _LONGEST = waited
            longest = _LONGEST
        _metrics()[2].observe(waited, label=name)
        _metrics()[1].set(longest)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# audited lock
# ---------------------------------------------------------------------------

#: live audited-lock instances, for dumps/diagnose
_LOCKS: "weakref.WeakSet" = weakref.WeakSet()

#: contended-acquire poll slice: bounds stall-detection latency without
#: adding wakeup latency (a timed raw acquire returns the moment the
#: lock frees)
_WAIT_SLICE = 0.05


class MxLock:
    """A named, audited Lock/RLock — drop-in for ``threading.Lock()`` /
    ``threading.RLock()`` with ordering audit, stall forensics and
    sched-harness yield points. See the module docstring."""

    def __init__(self, name: str, reentrant: bool = False, graph=None):
        self.name = name
        self._reentrant = bool(reentrant)
        # the raw primitive under audit — the one place a bare
        # constructor is the point
        if reentrant:
            self._raw = threading.RLock()  # mx-lint: allow=MXA009
        else:
            self._raw = threading.Lock()  # mx-lint: allow=MXA009
        self._graph = graph if graph is not None else _GRAPH
        self._owner = None          # thread ident while held
        self._owner_name = None
        self._owner_site = None
        self._waiters: Dict[int, tuple] = {}   # ident -> (name, t0)
        with _MU:
            _LOCKS.add(self)

    # -------------- acquire --------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held_stack()
        if self._reentrant:
            for e in held:
                if e.lock is self:
                    self._raw.acquire()
                    e.count += 1
                    return True
        site = _call_site()
        # record ordering edges BEFORE blocking: the would-be edge
        # matters most when the acquire is the one that deadlocks
        for e in held:
            if e.lock.name != self.name:
                self._graph.record(e.lock.name, self.name, e.site, site)
        s = _sched_for_current()
        if s is not None:
            ok = s.acquire_lock(self, blocking=blocking, timeout=timeout)
        else:
            ok = self._acquire_real(blocking, timeout)
        if ok:
            self._mark_acquired(site, held)
        return ok

    def _acquire_real(self, blocking: bool, timeout: float) -> bool:
        raw = self._raw
        if not blocking:
            return raw.acquire(False)
        if raw.acquire(False):
            return True
        # contended slow path: poll in slices so the stall detector and
        # the longest-wait gauge see the wait while it is happening
        t0 = time.perf_counter()
        deadline = None if timeout is None or timeout < 0 \
            else t0 + timeout
        me = threading.current_thread()
        with _MU:
            self._waiters[me.ident] = (me.name, t0)
        stall = stall_seconds()
        fired = False
        ok = False
        try:
            while True:
                slc = _WAIT_SLICE
                if deadline is not None:
                    rem = deadline - time.perf_counter()
                    if rem <= 0:
                        break
                    slc = min(slc, rem)
                if raw.acquire(timeout=slc):
                    ok = True
                    break
                waited = time.perf_counter() - t0
                _note_wait(waited)
                if stall > 0 and waited >= stall and not fired:
                    fired = True
                    self._report_stall(me, waited)
        finally:
            with _MU:
                self._waiters.pop(me.ident, None)
            _observe_wait(self.name, time.perf_counter() - t0)
            if fired and ok:
                # the stall resolved — re-arm the episode channel so
                # the NEXT stall is a new episode
                try:
                    _telemetry().watchdog().episode("deadlock", False)
                except Exception:   # pragma: no cover - defensive
                    pass
        return ok

    def _mark_acquired(self, site, held):
        held.append(_Held(self, site))
        t = threading.current_thread()
        global _HELD_TOTAL
        with _MU:
            self._owner = t.ident
            self._owner_name = t.name
            self._owner_site = site
            _HELD_TOTAL += 1
            total = _HELD_TOTAL
        _set_held_gauge(total)

    def _report_stall(self, me, waited: float):
        """Exactly one ``deadlock`` anomaly + one atomic dump per
        episode: the watchdog's episode() transition gates both."""
        try:
            with _MU:
                owner = self._owner_name
                osite = self._owner_site
            if owner:
                own = f"held by {owner!r}"
                if osite:
                    own += f" (acquired at {osite[0]})"
            else:
                own = "owner unknown"
            msg = (f"thread {me.name!r} blocked {waited:.2f}s "
                   f"(> MXNET_LOCK_STALL_SEC={stall_seconds():g}) "
                   f"acquiring mx_lock {self.name!r}; {own}")
            fired = _telemetry().watchdog().episode(
                "deadlock", True, message=msg, value=waited)
            if fired:
                write_dump("lock-stall", stalled={
                    "lock": self.name, "thread": me.name,
                    "waited_s": round(waited, 3), "owner": owner,
                    "owner_site": list(osite or ())})
        except Exception:   # forensics must never kill the waiter
            _LOG.warning("deadlock forensics failed", exc_info=True)

    # -------------- release --------------
    def release(self):
        held = _held_stack()
        entry = None
        for e in reversed(held):
            if e.lock is self:
                entry = e
                break
        if entry is not None and entry.count > 1:
            entry.count -= 1
            self._raw.release()
            return
        if entry is not None:
            held.remove(entry)
        # entry may be None: threading.Lock permits cross-thread
        # release (the signal idiom); keep the books consistent anyway
        global _HELD_TOTAL
        with _MU:
            self._owner = self._owner_name = self._owner_site = None
            _HELD_TOTAL = max(0, _HELD_TOTAL - 1)
            total = _HELD_TOTAL
        self._raw.release()
        _set_held_gauge(total)
        s = _sched_for_current()
        if s is not None:
            s.yield_point()

    # -------------- condition support --------------
    def _suspend_for_wait(self):
        """Condition.wait fully releases the raw lock; mirror that in
        the audit books and hand back the held entry for restore."""
        held = _held_stack()
        entry = None
        for e in reversed(held):
            if e.lock is self:
                entry = e
                break
        if entry is not None:
            held.remove(entry)
            global _HELD_TOTAL
            with _MU:
                self._owner = self._owner_name = self._owner_site = None
                _HELD_TOTAL = max(0, _HELD_TOTAL - 1)
                total = _HELD_TOTAL
            _set_held_gauge(total)
        return entry

    def _resume_after_wait(self, entry):
        if entry is None:
            return
        _held_stack().append(entry)
        t = threading.current_thread()
        global _HELD_TOTAL
        with _MU:
            self._owner = t.ident
            self._owner_name = t.name
            self._owner_site = entry.site
            _HELD_TOTAL += 1
            total = _HELD_TOTAL
        _set_held_gauge(total)

    def _sched_release_for_wait(self):
        """Scheduler-path cond wait: fully release the raw lock (all
        reentrant counts) and return the saved entry."""
        entry = self._suspend_for_wait()
        for _ in range(entry.count if entry is not None else 1):
            self._raw.release()
        return entry

    def _sched_reacquire_after_wait(self, entry):
        self.acquire()      # routes back through the scheduler
        if entry is not None and entry.count > 1:
            for _ in range(entry.count - 1):
                self._raw.acquire()
            _held_stack()[-1].count = entry.count

    # -------------- sugar --------------
    def locked(self) -> bool:
        with _MU:
            return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):     # pragma: no cover - debugging aid
        kind = "rlock" if self._reentrant else "lock"
        return f"<MxLock {self.name!r} ({kind}) owner={self._owner_name!r}>"


class MxCondition:
    """A named, audited ``threading.Condition`` — built on an
    :class:`MxLock` (reentrant by default, mirroring the stdlib) so
    enter/exit feed the ordering audit and wait/notify become
    sched-harness yield points."""

    def __init__(self, name: str, lock: Optional[MxLock] = None,
                 graph=None):
        self._lock = lock if lock is not None \
            else MxLock(name, reentrant=True, graph=graph)
        self.name = self._lock.name
        # wraps the audited raw primitive — not a second bare lock
        self._cond = threading.Condition(self._lock._raw)  # mx-lint: allow=MXA009

    # lock protocol delegates
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = _sched_for_current()
        if s is not None:
            return s.cond_wait(self, timeout)
        entry = self._lock._suspend_for_wait()
        try:
            return self._cond.wait(timeout)
        finally:
            self._lock._resume_after_wait(entry)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1):
        s = _SCHED
        if s is not None:
            s.cond_notify(self, n)
        self._cond.notify(n)

    def notify_all(self):
        s = _SCHED
        if s is not None:
            s.cond_notify(self, None)
        self._cond.notify_all()

    def __repr__(self):     # pragma: no cover - debugging aid
        return f"<MxCondition {self.name!r}>"


def mx_lock(name: str, graph=None) -> MxLock:
    """A named audited mutex (``threading.Lock`` semantics)."""
    return MxLock(name, reentrant=False, graph=graph)


def mx_rlock(name: str, graph=None) -> MxLock:
    """A named audited reentrant mutex (``threading.RLock`` semantics)."""
    return MxLock(name, reentrant=True, graph=graph)


def mx_condition(name: str, lock: Optional[MxLock] = None,
                 graph=None) -> MxCondition:
    """A named audited condition variable (``threading.Condition``)."""
    return MxCondition(name, lock=lock, graph=graph)


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------

@dataclass
class ThreadReport:
    """One audit snapshot: live locks, the ordering graph, its cycles
    and any findings (cycles and/or off-baseline edges)."""

    locks: List[dict]
    edges: List[dict]
    cycles: List[List[str]]
    findings: List[Finding] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.cycles and not self.findings

    def __str__(self):
        lines = [f"ThreadReport: {len(self.locks)} lock name(s), "
                 f"{len(self.edges)} ordering edge(s), "
                 f"{len(self.cycles)} cycle(s)"]
        for f in self.findings:
            lines.append("  " + str(f))
        return "\n".join(lines)


def describe_locks() -> List[dict]:
    """Live audited locks aggregated by name (several instances may
    share a name — e.g. every ServingFuture's condition)."""
    with _MU:
        locks = list(_LOCKS)
    now = time.perf_counter()
    agg: Dict[str, dict] = {}
    for lk in locks:
        with _MU:
            owner = lk._owner_name
            osite = lk._owner_site
            waiters = list(lk._waiters.values())
        a = agg.setdefault(lk.name, {
            "name": lk.name,
            "kind": "rlock" if lk._reentrant else "lock",
            "instances": 0, "held": 0, "waiters": 0,
            "owner": None, "owner_site": [], "longest_wait_s": 0.0})
        a["instances"] += 1
        a["waiters"] += len(waiters)
        for _n, t0 in waiters:
            a["longest_wait_s"] = max(a["longest_wait_s"],
                                      round(now - t0, 3))
        if owner is not None:
            a["held"] += 1
            a["owner"] = owner
            a["owner_site"] = list(osite or ())
    return [agg[k] for k in sorted(agg)]


def _fmt_site(site) -> str:
    return site[0] if site else "?"


def cycle_findings(g: Optional[LockOrderGraph] = None) -> List[Finding]:
    """One Finding per lock-order cycle, naming each hop's thread and
    both call sites — the 'two stacks printed' contract."""
    g = g if g is not None else _GRAPH
    emap = {(e["from"], e["to"]): e for e in g.edges()}
    out = []
    for cyc in g.find_cycles():
        hops = []
        for a, b in zip(cyc, cyc[1:]):
            e = emap.get((a, b), {})
            hops.append(
                f"{a}->{b} [thread {e.get('thread', '?')}: holds {a} "
                f"from {_fmt_site(e.get('from_site'))}, acquires {b} "
                f"at {_fmt_site(e.get('to_site'))}]")
        out.append(Finding(
            checker="threads", rule="lock-cycle",
            message="potential deadlock, lock-order cycle: "
                    + "; ".join(hops),
            where="->".join(cyc), severity="error"))
    return out


def check_hierarchy(baseline: Set[Tuple[str, str]],
                    g: Optional[LockOrderGraph] = None) -> List[Finding]:
    """Findings for every observed edge outside the blessed baseline
    (with both acquisition stacks) plus every cycle. Empty list = the
    observed ordering is inside the checked-in hierarchy."""
    g = g if g is not None else _GRAPH
    out = cycle_findings(g)
    for e in g.edges():
        if (e["from"], e["to"]) in baseline:
            continue
        out.append(Finding(
            checker="threads", rule="lock-order",
            message=(f"new lock-order edge {e['from']} -> {e['to']} "
                     f"(x{e['count']}, thread {e['thread']}): held "
                     f"{e['from']} from [{' <- '.join(e['from_site']) or '?'}]"
                     f", acquired {e['to']} at "
                     f"[{' <- '.join(e['to_site']) or '?'}] — review, "
                     "then bless in tests/fixtures/lock_hierarchy.json"),
            where=f"{e['from']}->{e['to']}", severity="error"))
    return out


def find_cycles() -> List[List[str]]:
    return _GRAPH.find_cycles()


def snapshot(baseline: Optional[Set[Tuple[str, str]]] = None
             ) -> ThreadReport:
    """The current audit state as a :class:`ThreadReport`; pass a
    baseline edge set to include hierarchy findings."""
    findings = check_hierarchy(baseline) if baseline is not None \
        else cycle_findings()
    return ThreadReport(locks=describe_locks(), edges=_GRAPH.edges(),
                        cycles=_GRAPH.find_cycles(), findings=findings)


def load_baseline(path: str) -> Set[Tuple[str, str]]:
    """``lock_hierarchy.json`` -> blessed edge-pair set."""
    with open(path) as f:
        data = json.load(f)
    return {(str(a), str(b)) for a, b in data["edges"]}


def save_baseline(path: str, g: Optional[LockOrderGraph] = None):
    """Refresh workflow: write the CURRENT graph as the blessed
    hierarchy (review the diff before committing)."""
    g = g if g is not None else _GRAPH
    pairs = sorted(g.edge_pairs())
    payload = {"schema": 1,
               "comment": "blessed lock-order hierarchy; refresh via "
                          "analysis.threads.save_baseline after "
                          "reviewing new edges",
               "edges": [list(p) for p in pairs]}
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------------
# queue census (dump enrichment)
# ---------------------------------------------------------------------------

_QUEUES: Dict[str, "weakref.ref"] = {}


def register_queue(name: str, q) -> None:
    """Register a queue for the forensics dump's depth census (weakly
    held; dead entries are pruned at dump time)."""
    with _MU:
        _QUEUES[name] = weakref.ref(q)


def _queue_depths() -> List[dict]:
    with _MU:
        items = list(_QUEUES.items())
    out = []
    for name, ref in sorted(items):
        q = ref()
        if q is None:
            with _MU:
                if _QUEUES.get(name) is ref:
                    del _QUEUES[name]
            continue
        try:
            out.append({"name": name, "depth": q.qsize(),
                        "maxsize": getattr(q, "maxsize", None)})
        except Exception:       # pragma: no cover - exotic queues
            pass
    return out


# ---------------------------------------------------------------------------
# forensics dump
# ---------------------------------------------------------------------------

def dump_payload(reason: str, stalled: Optional[dict] = None) -> dict:
    """The ranked dump: stalled thread first, then lock owners, then
    the rest — per-thread stacks via sys._current_frames."""
    locks = describe_locks()
    owner_names = {l["owner"] for l in locks if l["owner"]}
    stalled_name = (stalled or {}).get("thread")
    frames = sys._current_frames()

    def rank(t):
        if t.name == stalled_name:
            return 0
        if t.name in owner_names:
            return 1
        return 2

    threads_out = []
    for t in sorted(threading.enumerate(), key=lambda t: (rank(t), t.name)):
        fr = frames.get(t.ident)
        stack = traceback.format_stack(fr) if fr is not None else []
        threads_out.append({
            "name": t.name, "ident": t.ident, "daemon": t.daemon,
            "rank": rank(t),
            "stack": [ln.strip().replace("\n", " | ")
                      for ln in stack][-12:]})
    return {"schema": 1, "kind": "deadlock", "reason": reason,
            "time_unix": time.time(), "pid": os.getpid(),
            "stalled": stalled,
            "locks": locks,
            "edges": _GRAPH.edges(),
            "threads": threads_out,
            "queues": _queue_depths()}


def write_dump(reason: str, stalled: Optional[dict] = None
               ) -> Optional[str]:
    """Atomically (tmp + fsync + rename) write one forensics dump to
    ``MXNET_THREADS_DUMP_DIR``; returns the path (None when unset)."""
    d = dump_dir()
    if d is None:
        return None
    payload = dump_payload(reason, stalled)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"mx-threads-{os.getpid()}-{int(time.time() * 1e3)}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        _metrics()[3].inc()
    except Exception:           # pragma: no cover - defensive
        pass
    _LOG.warning("mx-threads dump written: %s", path)
    return path


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def reset():
    """Clear audit HISTORY (ordering edges, longest-wait, queue
    census). Live lock state (owners, held counts) is reality, not
    history — it stays."""
    global _LONGEST
    _GRAPH.clear()
    with _MU:
        _LONGEST = 0.0
        _QUEUES.clear()
