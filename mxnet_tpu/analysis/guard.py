"""Runtime transfer guard: catch silent device->host syncs in hot loops.

The program lint (analysis/program.py) catches host transfers that made
it INTO a compiled program; this guard catches the ones that keep a
program from compiling at all — a stray ``.asnumpy()`` / ``.item()`` /
``float(loss)`` in a loss function silently demotes the whole fused
step to the eager tape path, where it then costs one device round-trip
per step, forever, with no error anywhere.

``MXNET_TRANSFER_GUARD=log|raise`` arms the guard; the hot regions
(``CompiledTrainStep.__call__`` — and through it ``TrainLoop.step``)
declare themselves with :func:`hot_scope`, and every
``NDArray.asnumpy``/``item``/``wait_to_read`` inside such a region
logs the offending Python stack (``log``) or raises an ``MXNetError``
(``raise``).  Syncs OUTSIDE a hot region — printing the loss after the
step, metric updates between epochs — are never flagged.

Explicit use, independent of the env var::

    with mx.analysis.transfer_guard("raise"):
        loss = step(x, y)        # any host sync inside raises

Framework code that must legitimately sync inside a hot region (the
dist-kvstore's one blessed host sync per step) wraps itself in
:func:`allow_transfers`.
"""
from __future__ import annotations

import logging
import os
import threading
import traceback
from contextlib import contextmanager
from typing import List, Optional, Tuple

__all__ = ["transfer_guard", "hot_scope", "allow_transfers", "armed",
           "on_sync", "events", "clear_events", "env_mode",
           "count_sync", "sync_counts", "reset_sync_counts"]

_LOG = logging.getLogger("mxnet_tpu.analysis.guard")

_MODES = ("log", "raise")


class _State(threading.local):
    def __init__(self):
        self.mode: Optional[str] = None   # active mode inside a scope
        self.suppress: int = 0            # allow_transfers depth
        self.scope: str = ""              # hot-region label for messages
        self.events: List[Tuple[str, str]] = []   # (kind, where)
        self.counts: dict = {}            # kind -> total syncs (always on)


_STATE = _State()


def env_mode() -> Optional[str]:
    """The MXNET_TRANSFER_GUARD env setting (None when unset/off)."""
    v = os.environ.get("MXNET_TRANSFER_GUARD", "").strip().lower()
    if not v or v in ("0", "off", "false", "no"):
        return None
    if v not in _MODES:
        _LOG.warning("MXNET_TRANSFER_GUARD=%r is not one of %s; "
                     "treating as 'log'", v, _MODES)
        return "log"
    return v


def armed() -> bool:
    """Fast check for the NDArray sync sites."""
    return _STATE.mode is not None and _STATE.suppress == 0


def events() -> List[Tuple[str, str]]:
    """(kind, caller) tuples recorded by 'log' mode since the last
    :func:`clear_events` — test hook."""
    return list(_STATE.events)


def clear_events():
    _STATE.events.clear()


#: the process-global mx_guard_host_syncs_total{kind=} counter, bound on
#: first use (the thread-local dict above it stays for per-region deltas)
_SYNC_COUNTER = None


def count_sync(kind: str):
    """Always-on census of device->host sync points — an int increment,
    independent of whether the guard is armed. ``wait_to_read`` counts
    every NDArray-level sync (asnumpy/item route through it);
    ``window_retire`` counts the engine's designed in-flight-window
    boundary waits (engine.DispatchWindow). The per-thread dict feeds
    region deltas (:func:`sync_counts`); the process-global
    ``mx_guard_host_syncs_total{kind=}`` series feeds the telemetry
    exporters (docs/OBSERVABILITY.md)."""
    global _SYNC_COUNTER
    st = _STATE
    st.counts[kind] = st.counts.get(kind, 0) + 1
    if _SYNC_COUNTER is None:
        from ..telemetry import names as _tnames
        from ..telemetry.registry import default as _treg
        _SYNC_COUNTER = _treg().counter(_tnames.HOST_SYNCS,
                                        label_key="kind")
    _SYNC_COUNTER.inc(label=kind)


def sync_counts() -> dict:
    """Per-kind sync totals on this thread since the last
    :func:`reset_sync_counts`."""
    return dict(_STATE.counts)


def reset_sync_counts():
    _STATE.counts.clear()


def _caller() -> str:
    """First stack frame outside this framework — the user line that
    triggered the sync."""
    import mxnet_tpu
    pkg = os.path.dirname(os.path.abspath(mxnet_tpu.__file__))
    for frame in reversed(traceback.extract_stack()):
        fn = os.path.abspath(frame.filename)
        if not fn.startswith(pkg):
            return f"{frame.filename}:{frame.lineno} ({frame.name})"
    return "<unknown>"


def on_sync(kind: str, what: str = ""):
    """Called from NDArray sync sites when :func:`armed`."""
    st = _STATE
    where = _caller()
    st.events.append((kind, where))
    desc = (f"device->host sync `{kind}` inside the hot region "
            f"{st.scope or 'transfer_guard'}"
            + (f" on {what}" if what else "")
            + f" — triggered at {where}")
    if st.mode == "raise":
        from ..base import MXNetError
        raise MXNetError(
            desc + ". A sync here runs every step and blocks the device "
            "pipeline; move it outside the loop, or wrap it in "
            "mx.analysis.allow_transfers() if intentional. "
            "(MXNET_TRANSFER_GUARD=log to only warn; docs/ANALYSIS.md)")
    _LOG.warning("%s\n%s", desc,
                 "".join(traceback.format_stack(limit=8)[:-1]))


@contextmanager
def transfer_guard(mode: str = "raise", scope: str = ""):
    """Explicitly guard a region regardless of MXNET_TRANSFER_GUARD."""
    if mode not in _MODES:
        raise ValueError(f"transfer_guard mode must be one of {_MODES}, "
                         f"got {mode!r}")
    st = _STATE
    prev_mode, prev_scope = st.mode, st.scope
    st.mode, st.scope = mode, scope or "transfer_guard"
    try:
        yield
    finally:
        st.mode, st.scope = prev_mode, prev_scope


@contextmanager
def hot_scope(name: str):
    """Declare a hot region; activates only when MXNET_TRANSFER_GUARD is
    set (or an enclosing transfer_guard is already active)."""
    st = _STATE
    if st.mode is not None:          # nested: keep the outer mode
        yield
        return
    mode = env_mode()
    if mode is None:
        yield
        return
    prev_scope = st.scope
    st.mode, st.scope = mode, name
    try:
        yield
    finally:
        st.mode, st.scope = None, prev_scope


@contextmanager
def allow_transfers(reason: str = ""):
    """Bless syncs in a sub-region of a guarded scope (the dist store's
    one host sync per step, checkpoint capture, ...)."""
    _STATE.suppress += 1
    try:
        yield
    finally:
        _STATE.suppress -= 1
