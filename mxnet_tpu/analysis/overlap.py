"""Exposed-communication analysis over the optimized-HLO schedule.

The sharding cost model (analysis/sharding.py) prices every collective
in seconds, but a priced collective only costs wall-clock time where
nothing computes while it is on the wire.  This pass walks the
compiler's FINAL kernel schedule (optimized dumps carry
``is_scheduled=true`` — text order is the schedule) and measures, per
collective, how much independent compute the scheduler placed inside
its *overlap window*:

* async pairs (``all-reduce-start``/``-done`` etc., TPU/GPU dumps) —
  the window is exactly the scheduler's explicit start..done span;
* synchronous collectives (XLA:CPU has no async pairs) — the window is
  the dependency slack ``(last producer .. first consumer that NEEDS
  the bytes)``: the span in which a latency-hiding runtime could run
  the transfer asynchronously without reordering the schedule.
  Zero-FLOP data movement (pads, slices, converts, concatenations, GTE
  plumbing) does not end a window — the scheduler pins those right
  behind the collective, but they carry no deadline; the walk follows
  them to the first flops-bearing kernel or collective.  A value that
  reaches the outputs without any such consumer (new weights gathered
  straight into the root tuple) has program completion as its
  deadline, so everything scheduled after the collective can hide it.

Kernels inside the window that do NOT transitively depend on the
collective (forward taint through operands) could hide it; their
roofline seconds (the fusion census's FLOP/byte model) are credited
against the collective's wire seconds (ring model over the
``BandwidthProfile``).  Whatever is left is **exposed** comm:

    exposed_s = max(0, comm_s - hide_s)        per collective
    overlap_fraction = 1 - sum(exposed) / sum(comm)

The monolithic serial ZeRO step (``zero.bucket_bytes <= 0``: one
packed collective payload over every unit) measures fraction ~0 —
every kernel after the reduce-scatter depends on it, and nothing but
zero-FLOP writeback slices trails the weight all-gather (the only
residual hider is the nanoseconds-scale loss tail the scheduler may
park after it).  The
bucketed step (gluon/fused_step.py) measures fraction > 0 — bucket
k's all-gather is independent of bucket k+1's optimizer update by
construction, and the scheduler demonstrably interleaves them.
Consumer chains through plumbing are followed transparently when
locating the first real consumer; the taint walk still treats them as
dependency edges, so ordering stays exact.

Like the fusion/sharding passes this one is an observer: parse or
model failures degrade to an empty report, never exceptions.
"""
from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .hlo import HloModule, HloOp, parse_hlo
from .report import CollectiveOp, Finding

_LOG = logging.getLogger(__name__)

__all__ = [
    "CollectiveWindow", "OverlapReport", "overlap_census",
    "load_baselines", "check_baseline", "baseline_from_env", "publish",
]

#: async collective start opcodes -> their matching done opcode (the
#: scheduler's explicit overlap region on backends that emit them)
_ASYNC_DONE = {
    "all-reduce-start": "all-reduce-done",
    "all-gather-start": "all-gather-done",
    "reduce-scatter-start": "reduce-scatter-done",
    "collective-permute-start": "collective-permute-done",
    "all-to-all-start": "all-to-all-done",
    "async-start": "async-done",
}
_DONE_OPCODES = frozenset(_ASYNC_DONE.values())

#: data plumbing followed when locating a collective's first REAL
#: consumer (the taint walk still sees these as dependency edges)
_TRANSPARENT_OPCODES = frozenset(
    {"get-tuple-element", "bitcast", "copy", "tuple", "opt-barrier"})

#: pure data-movement opcodes: a kernel whose body holds ONLY these
#: re-routes bytes — it carries no compute deadline for a collective's
#: result and cannot hide wire time behind arithmetic either (the
#: fusion census prices element copies as FLOPs, so the flops field
#: alone cannot make this call)
_MOVEMENT_OPCODES = frozenset({
    "bitcast", "broadcast", "concatenate", "constant", "convert",
    "copy", "dynamic-slice", "dynamic-update-slice",
    "get-tuple-element", "iota", "pad", "parameter", "reshape",
    "reverse", "slice", "transpose", "tuple", "opt-barrier"})


@dataclass
class CollectiveWindow:
    """One collective's overlap accounting on the schedule."""
    name: str
    kind: str
    axis: str
    comm_s: float
    hide_s: float
    exposed_s: float
    n_hiders: int
    window: Tuple[int, int]
    computation: str = "?"
    is_async: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind, "axis": self.axis,
                "comm_s": self.comm_s, "hide_s": self.hide_s,
                "exposed_s": self.exposed_s, "n_hiders": self.n_hiders,
                "window": list(self.window), "is_async": self.is_async}


@dataclass
class OverlapReport:
    """Exposed-vs-total communication posture of one program."""
    windows: List[CollectiveWindow] = field(default_factory=list)
    per_axis_total_s: Dict[str, float] = field(default_factory=dict)
    per_axis_exposed_s: Dict[str, float] = field(default_factory=dict)
    total_comm_s: float = 0.0
    exposed_comm_s: float = 0.0
    n_async: int = 0
    #: the dump carried ``is_scheduled=true`` (when False, text order
    #: merely approximates the schedule)
    scheduled: bool = False
    profile: str = "cpu"
    #: active ``zero.bucket_bytes`` at census time (None outside the
    #: fused-step context) — rides along so bench legs/autotuner trials
    #: record which bucketing produced this posture
    zero_bucket_bytes: Optional[int] = None
    findings: List[Finding] = field(default_factory=list)

    @property
    def n_collectives(self) -> int:
        return len(self.windows)

    @property
    def overlap_fraction(self) -> float:
        """Share of modeled comm seconds hidden behind independent
        compute (0 = fully exposed/serial, 1 = fully hidden)."""
        if self.total_comm_s <= 0.0:
            return 0.0
        return max(0.0, 1.0 - self.exposed_comm_s / self.total_comm_s)

    def brief(self) -> Dict[str, Any]:
        return {"exposed_comm_s": self.exposed_comm_s,
                "total_comm_s": self.total_comm_s,
                "overlap_fraction": self.overlap_fraction,
                "n_collectives": self.n_collectives,
                "n_async": self.n_async,
                "zero_bucket_bytes": self.zero_bucket_bytes}

    def to_dict(self) -> Dict[str, Any]:
        d = self.brief()
        d.update({
            "scheduled": self.scheduled, "profile": self.profile,
            "per_axis_total_s": dict(self.per_axis_total_s),
            "per_axis_exposed_s": dict(self.per_axis_exposed_s),
            "windows": [w.to_dict() for w in self.windows[:24]],
        })
        return d

    def summary_line(self) -> str:
        return (f"exposed={self.exposed_comm_s:.3e}s of "
                f"{self.total_comm_s:.3e}s comm "
                f"(fraction={self.overlap_fraction:.2f}, "
                f"{self.n_collectives} collectives, "
                f"{self.n_async} async)")

    def table_str(self, top: int = 16) -> str:
        lines = [f"{'collective':<30s}{'kind':<18s}{'axis':<6s}"
                 f"{'comm s':>11s}{'hide s':>11s}{'exposed s':>11s}"
                 f"{'hiders':>7s}"]
        rows = sorted(self.windows, key=lambda w: -w.exposed_s)[:top]
        for w in rows:
            lines.append(
                f"{w.name[:28]:<30s}{w.kind:<18s}{w.axis:<6s}"
                f"{w.comm_s:>11.3e}{w.hide_s:>11.3e}"
                f"{w.exposed_s:>11.3e}{w.n_hiders:>7d}")
        for ax in sorted(self.per_axis_total_s):
            lines.append(
                f"  axis {ax!r}: exposed "
                f"{self.per_axis_exposed_s.get(ax, 0.0):.3e} s of "
                f"{self.per_axis_total_s[ax]:.3e} s")
        lines.append("  " + self.summary_line())
        return "\n".join(lines)


def _kernel_tables(hlo_text: str):
    """``(seconds, movement)`` over every kernel in the schedule:
    roofline seconds by op name (the fusion census's FLOP/byte model
    over the checked-in roofline constants), and the set of
    movement-only kernel names — fusions whose whole body is data
    movement.  Those neither hide comm (crediting element copies as
    compute would let plumbing mask wire time) nor impose a deadline
    on a collective's result."""
    from . import fusion as _fusion
    secs: Dict[str, float] = {}
    movement: set = set()
    try:
        rep = _fusion.fusion_census(hlo_text)
    except Exception:            # pragma: no cover - defensive
        _LOG.debug("fusion census for overlap failed", exc_info=True)
        return secs, movement
    flops_s = _fusion.BENCH_ROOFLINE_TFLOPS * 1e12
    bytes_s = _fusion.HBM_BANDWIDTH_GBPS * 1e9
    for k in rep.kernels:
        if all(oc in _MOVEMENT_OPCODES for oc in k.op_census):
            movement.add(k.name)
            continue
        if k.flops <= 0:
            continue
        secs[k.name] = max(k.flops / flops_s,
                           k.boundary_bytes / bytes_s)
    return secs, movement


def _first_real_consumer_pos(mod: HloModule, op: HloOp,
                             pos: Dict[str, int],
                             movement: set) -> Optional[int]:
    """Schedule position of the first consumer that actually NEEDS the
    collective's result: arithmetic compute or another collective.
    Data movement (GTE/bitcast/copy/tuple plumbing, but also pads,
    slices, converts and whole movement-only fusions) is followed
    transparently: the scheduler pins those right behind the
    collective, yet they only re-route bytes and represent no deadline
    a latency-hiding runtime would have to meet.  ``None`` when the
    value only escapes through such plumbing (e.g. straight into the
    root tuple)."""
    best: Optional[int] = None
    seen = {op.name}
    frontier = [op.name]
    for _ in range(10):
        nxt: List[str] = []
        for name in frontier:
            for c in mod.consumers(name):
                if c.name in seen:
                    continue
                seen.add(c.name)
                if (c.name in movement
                        or c.opcode in _MOVEMENT_OPCODES):
                    nxt.append(c.name)
                elif c.name in pos:
                    best = pos[c.name] if best is None \
                        else min(best, pos[c.name])
        if not nxt:
            break
        frontier = nxt
    return best


def _window_for(mod: HloModule, op: HloOp, order: List[str],
                pos: Dict[str, int],
                movement: set) -> Tuple[int, int, bool]:
    """(start, end, is_async) overlap window of one collective, as
    schedule positions exclusive of the endpoints."""
    p = pos[op.name]
    if op.opcode in _ASYNC_DONE:
        done = _ASYNC_DONE[op.opcode]
        end = p + 1
        for c in mod.consumers(op.name):
            if c.opcode == done and c.name in pos:
                end = max(end, pos[c.name])
        return p, end, True
    start = -1
    for src in op.operands:
        if src in pos:
            start = max(start, pos[src])
    end = _first_real_consumer_pos(mod, op, pos, movement)
    if end is None:
        # the value reaches the outputs without any compute needing it
        # (e.g. new weights all-gathered straight into the root tuple):
        # its deadline is program completion, so every independent
        # kernel scheduled AFTER the collective can hide it.  An
        # end-of-schedule resharding collective self-corrects — nothing
        # trails it, so it stays fully exposed.
        end = len(order)
    return start, max(end, p + 1), False


def _tainted_in_window(mod: HloModule, op: HloOp, order: List[str],
                       pos: Dict[str, int], end: int) -> set:
    """Names in ``(pos(op), end)`` transitively dependent on ``op`` —
    one forward pass in schedule order (valid schedules place every
    consumer after its producer)."""
    tainted = {op.name}
    for i in range(pos[op.name] + 1, min(end, len(order))):
        o = mod.ops.get(order[i])
        if o is not None and any(s in tainted for s in o.operands):
            tainted.add(o.name)
    return tainted


def _active_bucket_bytes() -> Optional[int]:
    try:
        from ..gluon.fused_step import _zero_bucket_bytes
        return int(_zero_bucket_bytes())
    except Exception:            # pragma: no cover - defensive
        return None


def overlap_census(hlo_text: str, mesh=None,
                   num_devices: Optional[int] = None,
                   profile=None) -> OverlapReport:
    """Measure exposed (non-overlapped) communication seconds per mesh
    axis on one optimized-HLO schedule.

    ``mesh`` enables per-axis attribution (same contract as
    ``collective_census``); ``profile`` is a ``BandwidthProfile``
    (default: the active ``MXNET_SHARDING_BANDWIDTH`` profile)."""
    from . import program as _program
    from . import sharding as _sharding

    report = OverlapReport()
    try:
        jmesh = getattr(mesh, "mesh", mesh)
        if num_devices is None:
            num_devices = int(jmesh.devices.size) \
                if jmesh is not None else 1
        profile = profile or _sharding.bandwidth_profile()
        report.profile = profile.name
        report.zero_bucket_bytes = _active_bucket_bytes()
        mod = parse_hlo(hlo_text, num_devices=num_devices)
        report.scheduled = mod.is_scheduled
        census = _program.collective_census(
            hlo_text, mesh=mesh, num_devices=num_devices)
        by_name: Dict[str, CollectiveOp] = \
            {c.name: c for c in census.ops}
        kernel_s, movement = _kernel_tables(hlo_text)
        for comp in mod.schedulable_computations():
            order = comp.op_names
            pos = {n: i for i, n in enumerate(order)}
            for name in order:
                op = mod.ops.get(name)
                if op is None:
                    continue
                cop = by_name.get(name)
                if cop is None:
                    if op.opcode not in _ASYNC_DONE:
                        continue
                    # async starts the census's sync grammar missed:
                    # account them with an unattributed record
                    cop = CollectiveOp(
                        kind=op.opcode.replace("-start", "")
                        .replace("-", "_"),
                        name=name, elements=op.elements,
                        dtype=op.dtype or "?", axes=(),
                        group_size=num_devices, operand_count=1)
                if op.opcode in _DONE_OPCODES:
                    continue
                wire = _sharding.collective_wire_bytes(cop)
                gbps = profile.gbps(cop.axes)
                comm_s = wire / (gbps * 1e9) if gbps > 0 else 0.0
                start, end, is_async = _window_for(mod, op, order, pos,
                                                   movement)
                tainted = _tainted_in_window(mod, op, order, pos, end)
                hide_s, n_hiders = 0.0, 0
                for i in range(max(0, start + 1), min(end, len(order))):
                    hname = order[i]
                    if hname == name or hname in tainted:
                        continue
                    other = mod.ops.get(hname)
                    if other is not None and (
                            other.name in by_name
                            or other.opcode in _ASYNC_DONE
                            or other.opcode in _DONE_OPCODES):
                        continue    # comm can't hide comm
                    s = kernel_s.get(hname, 0.0)
                    if s > 0.0:
                        hide_s += s
                        n_hiders += 1
                exposed = max(0.0, comm_s - hide_s)
                ax = cop.axes[0] if cop.axes else "?"
                report.windows.append(CollectiveWindow(
                    name=name, kind=cop.kind, axis=ax, comm_s=comm_s,
                    hide_s=hide_s, exposed_s=exposed,
                    n_hiders=n_hiders, window=(start, end),
                    computation=comp.name, is_async=is_async))
                report.n_async += 1 if is_async else 0
                report.total_comm_s += comm_s
                report.exposed_comm_s += exposed
                report.per_axis_total_s[ax] = \
                    report.per_axis_total_s.get(ax, 0.0) + comm_s
                report.per_axis_exposed_s[ax] = \
                    report.per_axis_exposed_s.get(ax, 0.0) + exposed
    except Exception:            # pragma: no cover - defensive
        _LOG.debug("overlap census failed", exc_info=True)
    report.windows.sort(key=lambda w: -w.exposed_s)
    return report


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def load_baselines(path: str) -> Dict[str, Any]:
    """Per-leg overlap baselines: ``{leg: {exposed_comm_s,
    overlap_fraction, tol_pct}}`` (``_comment`` keys ignored)."""
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    return {k: v for k, v in raw.items() if not k.startswith("_")}


def check_baseline(report: OverlapReport, baselines: Dict[str, Any],
                   leg: str) -> List[Finding]:
    """Diff a program's overlap posture against a checked-in baseline.

    Both bands are one-sided regressions: ``exposed_comm_s`` may only
    GROW by tol_pct over the captured posture (less exposure is an
    improvement), and ``overlap_fraction`` may only FALL below the
    captured fraction by tol_pct (relative) or 0.05 (absolute floor —
    fractions near 0 need an absolute band).  Violations are
    error-severity ``overlap-regression`` findings so
    ``analyze='raise'`` fails fast on a change that re-serializes
    hidden communication (docs/ANALYSIS.md refresh workflow)."""
    base = baselines.get(leg)
    findings: List[Finding] = []
    if base is None:
        findings.append(Finding(
            checker="overlap", rule="overlap-regression",
            severity="warn",
            message=f"no overlap baseline for leg {leg!r} — add it to "
                    "the baselines file (docs/ANALYSIS.md)",
            where=leg))
        return findings
    tol = float(base.get("tol_pct", 50.0)) / 100.0
    e_base = float(base.get("exposed_comm_s", 0.0))
    # exposed seconds near zero need an absolute floor too (1 us)
    e_band = max(e_base * (1.0 + tol), e_base + 1e-6)
    if report.exposed_comm_s > e_band:
        findings.append(Finding(
            checker="overlap", rule="overlap-regression",
            message=f"[{leg}] exposed comm {report.exposed_comm_s:.3e}"
                    f" s exceeds baseline {e_base:.3e} s by more than "
                    f"{base.get('tol_pct', 50.0)}% — communication "
                    "this program used to hide behind compute is "
                    "exposed wall-clock again (docs/PERF_NOTES.md "
                    "\"Communication overlap\")",
            where=leg))
    f_base = base.get("overlap_fraction")
    if f_base is not None:
        f_floor = min(float(f_base) * (1.0 - tol),
                      float(f_base) - 0.05)
        if report.overlap_fraction < f_floor:
            findings.append(Finding(
                checker="overlap", rule="overlap-regression",
                message=f"[{leg}] overlap fraction "
                        f"{report.overlap_fraction:.3f} fell below "
                        f"baseline {float(f_base):.3f} — the schedule "
                        "stopped interleaving collectives with "
                        "independent compute; investigate, then "
                        "refresh the baseline if intentional "
                        "(docs/ANALYSIS.md)",
                where=leg))
    return findings


def baseline_from_env() -> Optional[tuple]:
    """``MXNET_OVERLAP_BASELINE=<path>[:<leg>]`` → (baselines dict,
    leg-or-None); None when unset or unreadable (logged, never
    raises)."""
    spec = os.environ.get("MXNET_OVERLAP_BASELINE")
    if not spec:
        return None
    path, leg = spec, None
    if ":" in spec and not os.path.exists(spec):
        path, leg = spec.rsplit(":", 1)
    try:
        return load_baselines(path), leg
    except Exception as e:       # pragma: no cover - defensive
        _LOG.warning("MXNET_OVERLAP_BASELINE=%r unreadable (%s: %s)",
                     spec, type(e).__name__, e)
        return None


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def publish(report: OverlapReport):
    """Refresh the exposed-comm gauges from one census (the latest
    analyzed program wins — one step program is live at a time)."""
    try:
        from ..telemetry import names as tn
        from ..telemetry import registry as treg
        reg = treg()
        for ax in report.per_axis_exposed_s:
            reg.gauge(tn.SHARDING_EXPOSED_COMM).set(
                report.per_axis_exposed_s[ax], label=ax)
        reg.gauge(tn.OVERLAP_FRACTION).set(report.overlap_fraction)
    except Exception:            # pragma: no cover - defensive
        _LOG.debug("overlap gauge publish failed", exc_info=True)
