"""Structured findings and reports for the compiled-program checkers.

Every checker in ``mxnet_tpu.analysis`` speaks one vocabulary: a
``Finding`` names the rule that fired, where, and how bad it is; a
``ProgramReport`` aggregates one compiled train-step's census numbers
(collectives, donation, host transfers, dtype drift, retraces) plus the
findings derived from them. The report is the machine-checkable contract
tier-1 asserts on (tests/test_fused_step.py, tests/test_zero_shard.py)
and the structural diff bench.py attaches to its BENCH json — numerics
tests prove the step computes the right thing, the report proves the
program IS the right program (docs/ANALYSIS.md).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Finding", "CollectiveOp", "CollectiveStats", "DonationAudit",
           "ProgramReport"]

# severity order for filtering
_SEV = {"error": 2, "warn": 1, "info": 0}


@dataclass
class Finding:
    """One rule violation (or blessed exception) from any checker.

    ``checker`` is the pass that produced it (``program`` | ``source`` |
    ``guard``), ``rule`` the stable machine id (``host-transfer``,
    ``donation-copy``, ``dtype-drift``, ``collective-mismatch``,
    ``MXA0xx`` for source rules), ``where`` a human location
    (``file:line``, an HLO op name, or an argument label)."""
    checker: str
    rule: str
    message: str
    where: str = ""
    severity: str = "error"
    blessed: bool = False

    def __str__(self):
        tag = f"[{self.rule}]" + (" (blessed)" if self.blessed else "")
        loc = f" at {self.where}" if self.where else ""
        return f"{self.severity.upper()} {tag}{loc}: {self.message}"


@dataclass
class CollectiveOp:
    """One collective in the optimized program. ``kind`` is the LOGICAL
    kind: an all-reduce the CPU backend's reduce-scatter-decomposer split
    into all-reduce+dynamic-slice is reported as ``reduce_scatter`` with
    ``decomposed=True`` (XLA:CPU has no native reduce-scatter thunk;
    see analysis/program.py:_classify_decomposed)."""
    kind: str                 # all_reduce|all_gather|reduce_scatter|...
    name: str                 # HLO result name, e.g. %all-reduce.3
    elements: int             # result element count (sum over tuple parts)
    dtype: str
    axes: Tuple[str, ...]     # mesh axes the replica groups span, if known
    group_size: int           # devices participating per group
    operand_count: int = 1    # tensors carried (combined/tupled ops > 1)
    decomposed: bool = False

    def to_dict(self):
        return {"kind": self.kind, "name": self.name,
                "elements": self.elements, "dtype": self.dtype,
                "axes": list(self.axes), "group_size": self.group_size,
                "operand_count": self.operand_count,
                "decomposed": self.decomposed}


@dataclass
class CollectiveStats:
    """Census over every collective in one compiled program."""
    ops: List[CollectiveOp] = field(default_factory=list)

    def count(self, kind: Optional[str] = None,
              axis: Optional[str] = None) -> int:
        n = 0
        for op in self.ops:
            if kind is not None and op.kind != kind:
                continue
            if axis is not None and op.axes and axis not in op.axes:
                continue
            n += 1
        return n

    @property
    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def per_axis(self) -> Dict[str, Dict[str, int]]:
        """kind counts per mesh axis (ops with unknown groups land under
        the pseudo-axis ``'?'``)."""
        out: Dict[str, Dict[str, int]] = {}
        for op in self.ops:
            for ax in (op.axes or ("?",)):
                out.setdefault(ax, {})
                out[ax][op.kind] = out[ax].get(op.kind, 0) + 1
        return out

    def total_elements(self, kind: Optional[str] = None) -> int:
        return sum(op.elements for op in self.ops
                   if kind is None or op.kind == kind)

    def matching(self, kind: str, sizes) -> List[CollectiveOp]:
        """Collectives of ``kind`` whose payload element count equals one
        of ``sizes`` — the per-parameter-collective detector."""
        sizes = set(int(s) for s in sizes)
        return [op for op in self.ops
                if op.kind == kind and op.elements in sizes]

    def to_dict(self):
        return {"by_kind": self.by_kind, "per_axis": self.per_axis(),
                "ops": [op.to_dict() for op in self.ops]}


@dataclass
class DonationAudit:
    """Did the buffers we declared donated actually alias in the
    executable?  ``declared`` counts flat args marked for donation at
    the jax level (``jax.buffer_donor``/``tf.aliasing_output`` in the
    lowered StableHLO), ``aliased`` the entries XLA's buffer assignment
    actually aliased (``input_output_alias`` of the optimized module),
    ``copied`` the declared-but-unaliased parameter numbers — each one
    is a full buffer copy per step that donation was supposed to
    eliminate."""
    declared: int = 0
    aliased: int = 0
    copied: List[int] = field(default_factory=list)
    donated_bytes: int = 0          # memory_analysis alias_size_in_bytes
    aliased_params: List[int] = field(default_factory=list)
    expected: Optional[int] = None  # caller's expectation (param+state)

    @property
    def ok(self) -> bool:
        if self.copied:
            return False
        if self.expected is not None:
            return self.aliased >= self.expected
        return True

    def to_dict(self):
        return {"declared": self.declared, "aliased": self.aliased,
                "copied": self.copied, "donated_bytes": self.donated_bytes,
                "expected": self.expected}


@dataclass
class ProgramReport:
    """Everything the program lint measured about ONE compiled step
    program, plus the findings the checkers derived.  ``mode`` and
    ``meta`` carry the CompiledTrainStep context (fused/zero/split,
    mesh axes, unit sizes) the expectation helpers key on."""
    mode: str = "?"
    collectives: CollectiveStats = field(default_factory=CollectiveStats)
    donation: DonationAudit = field(default_factory=DonationAudit)
    host_transfers: List[Finding] = field(default_factory=list)
    dtype_drift: List[Finding] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)
    n_traces: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)
    #: compiled-program memory accounting (telemetry.MemoryReport
    #: .to_dict(): argument/output/temp/generated_code/donated bytes +
    #: peak estimate) — None where memory_analysis is unavailable
    memory: Optional[Dict[str, int]] = None
    #: fusion census of the optimized program (analysis.fusion
    #: .FusionReport) — None where there was no HLO text to audit
    fusion: Optional[Any] = None
    #: SPMD sharding audit (analysis.sharding.ShardingAudit): the
    #: per-buffer sharding table, implicit reshards, and per-axis comm
    #: cost — None where there was no HLO text to audit
    sharding: Optional[Any] = None
    #: exposed-communication analysis (analysis.overlap.OverlapReport):
    #: per-axis exposed vs total comm seconds and the overlap fraction
    #: measured on the optimized-HLO schedule
    overlap: Optional[Any] = None

    def add(self, finding: Finding):
        self.findings.append(finding)

    def all_findings(self, min_severity: str = "info",
                     include_blessed: bool = False) -> List[Finding]:
        floor = _SEV[min_severity]
        out = []
        for f in (self.findings + self.host_transfers + self.dtype_drift):
            if f.blessed and not include_blessed:
                continue
            if _SEV.get(f.severity, 0) >= floor:
                out.append(f)
        return out

    @property
    def ok(self) -> bool:
        """No error-severity findings survived blessing."""
        return not self.all_findings(min_severity="error")

    def raise_if_findings(self, min_severity: str = "error"):
        bad = self.all_findings(min_severity=min_severity)
        if bad:
            from ..base import MXNetError
            raise MXNetError(
                "program analysis found "
                f"{len(bad)} violation(s) in the compiled step "
                f"(mode={self.mode}):\n" +
                "\n".join(f"  {f}" for f in bad) +
                "\n(see docs/ANALYSIS.md for how to bless intentional "
                "violations)")

    def _unblessed(self, fs: List[Finding]) -> List[Finding]:
        return [f for f in fs if not f.blessed]

    def to_dict(self):
        return {
            "mode": self.mode,
            "n_traces": self.n_traces,
            "collectives": self.collectives.by_kind,
            "collectives_per_axis": self.collectives.per_axis(),
            "donated_bytes": self.donation.donated_bytes,
            "donation": self.donation.to_dict(),
            "host_transfers": len(self._unblessed(self.host_transfers)),
            "dtype_drift": len(self._unblessed(self.dtype_drift)),
            "memory": self.memory,
            "fusion": self.fusion.brief() if self.fusion is not None
            else None,
            "sharding": self.sharding.brief()
            if self.sharding is not None else None,
            "overlap": self.overlap.brief()
            if self.overlap is not None else None,
            "findings": [str(f) for f in self.all_findings()],
        }

    def summary(self) -> str:
        lines = [f"ProgramReport(mode={self.mode}, "
                 f"n_traces={self.n_traces})"]
        bk = self.collectives.by_kind
        lines.append("  collectives : " +
                     (", ".join(f"{k}={v}" for k, v in sorted(bk.items()))
                      if bk else "none"))
        pa = self.collectives.per_axis()
        for ax in sorted(pa):
            lines.append(f"    axis {ax!r}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(pa[ax].items())))
        d = self.donation
        lines.append(f"  donation    : declared={d.declared} "
                     f"aliased={d.aliased} copied={len(d.copied)} "
                     f"bytes={d.donated_bytes}")
        if self.memory:
            m = self.memory
            lines.append(f"  memory      : peak~{m['peak_bytes']} "
                         f"(args={m['argument_bytes']} "
                         f"temp={m['temp_bytes']} "
                         f"out={m['output_bytes']} "
                         f"code={m['generated_code_bytes']} "
                         f"donated={m['donated_bytes']})")
        if self.fusion is not None:
            lines.append("  fusion      : " + self.fusion.summary_line())
        if self.sharding is not None:
            lines.append("  sharding    : "
                         + self.sharding.summary_line())
        if self.overlap is not None:
            lines.append("  overlap     : "
                         + self.overlap.summary_line())
        n_bless = len(self.host_transfers) + len(self.dtype_drift) \
            - len(self._unblessed(self.host_transfers)) \
            - len(self._unblessed(self.dtype_drift))
        lines.append("  host xfers  : "
                     f"{len(self._unblessed(self.host_transfers))}")
        lines.append("  dtype drift : "
                     f"{len(self._unblessed(self.dtype_drift))}"
                     + (f" (+{n_bless} blessed)" if n_bless else ""))
        fl = self.all_findings()
        lines.append(f"  findings    : {len(fl)}")
        for f in fl:
            lines.append(f"    {f}")
        return "\n".join(lines)
