"""RecordIO container (reference: python/mxnet/recordio.py).

``MXRecordIO`` / ``MXIndexedRecordIO`` expose the reference API over the
native C++ reader/writer (src/native/recordio.cc) when available, with a
pure-Python implementation of the same dmlc wire format otherwise — the
two interoperate byte-for-byte.

``IRHeader``/``pack``/``unpack``/``pack_img``-style helpers mirror the
reference's image-record framing (reference recordio.py IRHeader struct
'IfQQ': flag, label, id, id2; multi-label via flag>0).
"""
from __future__ import annotations

import collections
import os
import struct
from typing import Optional

import numpy as onp

from . import _native
from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "unpack_img"]

_MAGIC = 0xced7230a
_LREC_MASK = (1 << 29) - 1

IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class _PyWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self._pos = 0

    def write(self, data: bytes) -> int:
        if len(data) >= (1 << 29):
            raise MXNetError("recordio: record too large (>512MB)")
        pos = self._pos
        pad = (4 - (len(data) & 3)) & 3
        self._f.write(struct.pack("<II", _MAGIC, len(data)))
        self._f.write(data)
        if pad:
            self._f.write(b"\x00" * pad)
        self._pos += 8 + len(data) + pad
        return pos

    def tell(self) -> int:
        return self._pos

    def close(self):
        self._f.close()


class _PyReader:
    def __init__(self, path):
        self._f = open(path, "rb")

    def read(self) -> Optional[bytes]:
        hdr = self._f.read(4)
        if not hdr:
            return None
        if len(hdr) != 4 or struct.unpack("<I", hdr)[0] != _MAGIC:
            raise MXNetError("recordio: bad magic (corrupt or misaligned)")
        lbytes = self._f.read(4)
        if len(lbytes) != 4:
            raise MXNetError("recordio: truncated header")
        (lrec,) = struct.unpack("<I", lbytes)
        length = lrec & _LREC_MASK
        data = self._f.read(length)
        if len(data) != length:
            raise MXNetError("recordio: truncated payload")
        pad = (4 - (length & 3)) & 3
        if pad:
            self._f.read(pad)
        return data

    def seek(self, pos):
        self._f.seek(pos)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py:37).

    Parameters: ``uri`` file path, ``flag`` 'r' or 'w'.
    """

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self._rec = None
        self.open()

    def open(self):
        use_native = _native.available()
        if self.flag == "w":
            self._rec = (_native.NativeRecordIOWriter(self.uri) if use_native
                         else _PyWriter(self.uri))
        elif self.flag == "r":
            self._rec = (_native.NativeRecordIOReader(self.uri) if use_native
                         else _PyReader(self.uri))
        else:
            raise MXNetError(f"invalid flag {self.flag!r}, expected 'r'/'w'")
        self.is_open = True

    def write(self, buf: bytes):
        if self.flag != "w":
            raise MXNetError("recordio: not opened for writing")
        return self._rec.write(bytes(buf))

    def read(self) -> Optional[bytes]:
        if self.flag != "r":
            raise MXNetError("recordio: not opened for reading")
        return self._rec.read()

    def reset(self):
        self.close()
        self.open()

    def close(self):
        if self._rec is not None:
            self._rec.close()
            self._rec = None
        self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with a text index file (reference
    recordio.py:169: lines of "key\\tpos")."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.key_type = key_type
        self.idx = {}
        self.keys = []
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        k = key_type(parts[0])
                        self.idx[k] = int(parts[1])
                        self.keys.append(k)

    def close(self):
        if self.flag == "w" and self.idx:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        if self.flag != "r":
            raise MXNetError("recordio: seek requires read mode")
        self._rec.seek(self.idx[idx])

    def tell(self) -> int:
        return self._rec.tell()

    def read_idx(self, idx) -> bytes:
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self.write(buf)
        self.idx[self.key_type(idx)] = pos
        self.keys.append(self.key_type(idx))


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a label header + payload into one record (reference
    recordio.py pack: flag>0 means `label` is a flag-length vector)."""
    label = header.label
    if isinstance(label, (onp.ndarray, list, tuple)):
        label = onp.asarray(label, onp.float32)
        header = header._replace(flag=label.size, label=0.0)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s: bytes):
    """Inverse of pack → (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg") -> bytes:
    """Encode an image array and pack it into one record (reference
    recordio.py:469; PIL replaces cv2.imencode in this environment —
    JPEG ``quality`` 1-100 or PNG ``quality`` as compress level 0-9).
    Round-trips through :func:`unpack_img`."""
    import io as _io
    from PIL import Image

    from .base import MXNetError
    arr = onp.asarray(img)
    if arr.dtype != onp.uint8:
        arr = onp.clip(arr, 0, 255).astype(onp.uint8)
    im = Image.fromarray(arr)
    buf = _io.BytesIO()
    fmt = img_fmt.lower()
    if fmt in (".jpg", ".jpeg"):
        im.save(buf, format="JPEG", quality=int(quality))
    elif fmt == ".png":
        im.save(buf, format="PNG",
                compress_level=min(max(int(quality), 0), 9))
    else:
        raise MXNetError(f"unsupported image format {img_fmt!r}; "
                         "use .jpg or .png")
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=1):
    """unpack + image decode (reference recordio.py unpack_img). Uses
    PIL/raw numpy fallback since OpenCV isn't in this environment."""
    header, img_bytes = unpack(s)
    img = _decode_img(img_bytes, iscolor)
    return header, img


def _decode_img(img_bytes: bytes, iscolor=1):
    try:
        import io as _io
        from PIL import Image  # optional dependency
        im = Image.open(_io.BytesIO(img_bytes))
        if iscolor:
            im = im.convert("RGB")
        return onp.asarray(im)
    except ImportError:
        raise MXNetError("image decoding requires PIL (not installed); "
                         "store raw arrays or install pillow")
