"""Automatic mixed precision (reference: python/mxnet/contrib/amp/).

Reference mechanics: fp16 allow/deny op lists (contrib/amp/lists/
symbol_fp16.py), runtime patching of op invocation (amp.py:282), dynamic
``LossScaler`` (loss_scaler.py), and a ``ReducePrecision`` graph pass.

TPU-native redesign: the mixed dtype is **bfloat16** — same exponent range
as f32, so no loss scaling is *required* (the LossScaler is kept for API
parity and for true fp16). ``amp.init()`` installs an invoke wrapper with
the reference's list semantics (amp.py:282 runtime patching):

- TARGET_DTYPE_OPS (MXU-bound: matmul/conv/attention/rnn) cast f32 inputs
  down and their outputs FLOW in the low dtype — exactly like the
  reference's FP16_FUNCS, whose fp16 outputs propagate. This is the
  performance-critical half: activations between ops live in bf16, halving
  HBM traffic (the TPU bottleneck), while master weights stay f32.
- FP32_OPS (softmax/loss/exp-log reductions) cast low-precision inputs UP
  to f32 (reference FP32_FUNCS).
- Everything else follows its input dtypes (reference WIDEST_TYPE_CASTS
  falls out of jnp promotion).

Normalization layers are in FP32_OPS only for true fp16; under bf16 they
flow bf16 — safe because every norm kernel computes its statistics in f32
internally (ops/nn.py _stat_dtype), which is the half the reference's
FP32 pinning actually protects.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax.numpy as jnp

from ..base import MXNetError
from ..ops import registry as _registry
from .loss_scaler import LossScaler

__all__ = ["init", "uninit", "is_enabled", "init_trainer", "scale_loss",
           "convert_hybrid_block", "LossScaler", "TARGET_DTYPE_OPS",
           "FP32_OPS"]

# MXU-bound ops by their INVOKE-FUNNEL names (ops/registry.py invoke_raw
# call sites — the names the wrapper actually sees): cast inputs to the
# target dtype (reference lists/symbol_fp16.py FP16_FUNCS analog). The
# fused RNN layers invoke as "rnn_<mode>", matched by prefix below.
TARGET_DTYPE_OPS = {
    "fully_connected", "convolution", "deconvolution", "dot", "batch_dot",
    "linalg_gemm2", "flash_attention", "flash_attention_vl",
    "masked_attention", "bert_decoder_proj", "moe_ffn",
    "Correlation", "DeformableConvolution",
}

# Norm ops: f32-pinned only for true fp16 (their kernels already compute
# statistics in f32 internally — ops/nn.py _stat_dtype — so bf16 may flow).
NORM_OPS = {
    "batch_norm", "layer_norm", "group_norm", "instance_norm",
    "SyncBatchNorm",
}

# Numerically-sensitive ops pinned to f32 (reference FP32_FUNCS analog):
# low-precision inputs are cast UP. Everything else runs in whatever dtype
# flows in (WIDEST_TYPE_CASTS behavior falls out of jnp promotion).
FP32_OPS = NORM_OPS | {
    "softmax", "log_softmax", "softmax_cross_entropy", "norm", "moments",
    "exp", "log", "l2_normalization", "lrn",
}

_state = {"enabled": False, "dtype": None, "wrapper": None}


def _cast_down(x, dtype):
    if hasattr(x, "dtype") and hasattr(x, "astype") and \
            x.dtype == jnp.float32:
        return x.astype(dtype)
    return x


def _cast_up(x, dtype):
    if hasattr(x, "dtype") and hasattr(x, "astype") and x.dtype == dtype:
        return x.astype(jnp.float32)
    return x


def _make_wrapper(target_dtype):
    fp32_ops = FP32_OPS if target_dtype == jnp.float16 \
        else FP32_OPS - NORM_OPS

    def wrapper(name, fn):
        if name in TARGET_DTYPE_OPS or name.startswith("rnn_"):
            def amp_fn(*args, **kwargs):
                cast_args = [_cast_down(a, target_dtype) for a in args]
                # output flows in target_dtype (reference FP16_FUNCS
                # semantics): activations stay low-precision between ops
                return fn(*cast_args, **kwargs)
            return amp_fn
        if name in fp32_ops:
            def fp32_fn(*args, **kwargs):
                cast_args = [_cast_up(a, target_dtype) for a in args]
                return fn(*cast_args, **kwargs)
            return fp32_fn
        return fn
    return wrapper


def init(target_dtype: str = "bfloat16"):
    """Enable AMP process-wide (reference amp.init, amp.py:282)."""
    if _state["enabled"]:
        return
    if target_dtype in ("bfloat16", "bf16"):
        dt = jnp.bfloat16
    elif target_dtype in ("float16", "fp16"):
        dt = jnp.float16
    else:
        raise MXNetError(f"unsupported AMP target dtype {target_dtype!r}")
    w = _make_wrapper(dt)
    _registry.add_invoke_wrapper(w)
    _state.update(enabled=True, dtype=dt, wrapper=w)


def uninit():
    """Disable AMP (test/debug helper; the reference has no un-init)."""
    if _state["enabled"]:
        _registry.remove_invoke_wrapper(_state["wrapper"])
        _state.update(enabled=False, dtype=None, wrapper=None)


def is_enabled() -> bool:
    return _state["enabled"]


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Gluon Trainer (reference
    amp.init_trainer). A no-op numerically for bf16 (scale stays 1) but
    the scaler object is attached for API parity and fp16 use."""
    scaler = LossScaler(
        init_scale=1.0 if _state["dtype"] == jnp.bfloat16 else 2. ** 16)
    trainer._amp_loss_scaler = scaler
    return scaler


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Yield the scaled loss; trainer.step unscales via trainer._scale
    (reference amp.scale_loss contextmanager)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        scaler = init_trainer(trainer)
    # trainer._scale must keep dividing out the loss scale through the
    # trainer.step() that follows this context — set it persistently,
    # against the original scale (idempotent across steps as the dynamic
    # scale changes).
    if not hasattr(trainer, "_amp_original_scale"):
        trainer._amp_original_scale = trainer._scale
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if scaler.loss_scale == 1.0:  # bf16 default: no-op passthrough
        yield loss
    elif isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def convert_hybrid_block(block, target_dtype: str = "bfloat16"):
    """Cast a Gluon block's parameters for low-precision *inference*
    (reference amp.convert_hybrid_block): all params to target dtype
    except normalization-layer params, which stay f32."""
    from ..gluon import nn as _nn
    norm_types = (_nn.BatchNorm, _nn.LayerNorm, _nn.GroupNorm,
                  _nn.InstanceNorm)
    # cast every parameter not owned by a norm layer
    norm_params = set()
    stack = [block]
    while stack:
        b = stack.pop()
        if isinstance(b, norm_types):
            for p in b.collect_params().values():
                norm_params.add(id(p))
        stack.extend(getattr(b, "_children", {}).values())
    for p in block.collect_params().values():
        if id(p) not in norm_params and p._data is not None and \
                p.dtype == "float32":
            p.cast(target_dtype)
    return block
