"""Automatic mixed precision (reference: python/mxnet/contrib/amp/).

Reference mechanics: fp16 allow/deny op lists (contrib/amp/lists/
symbol_fp16.py), runtime patching of op invocation (amp.py:282), dynamic
``LossScaler`` (loss_scaler.py), and a ``ReducePrecision`` graph pass.

TPU-native redesign: the mixed dtype is **bfloat16** — same exponent range
as f32, so no loss scaling is *required* (the LossScaler is kept for API
parity and for true fp16). ``amp.init()`` installs an invoke wrapper that
casts inputs of MXU-bound ops (matmul/conv/attention/rnn) to bf16 and
returns f32 outputs — XLA then runs the MXU in its native
bf16-multiply/f32-accumulate mode, which is exactly the reference's
"fp16 compute, fp32 master weights" recipe with the fragile parts removed.
Reduction/normalization/loss ops stay f32 (the reference's FP32_FUNCS list).
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax.numpy as jnp

from ..base import MXNetError
from ..ops import registry as _registry
from .loss_scaler import LossScaler

__all__ = ["init", "uninit", "is_enabled", "init_trainer", "scale_loss",
           "convert_hybrid_block", "LossScaler", "TARGET_DTYPE_OPS",
           "FP32_OPS"]

# MXU-bound ops: cast inputs to the target dtype (reference
# lists/symbol_fp16.py FP16_FUNCS analog).
TARGET_DTYPE_OPS = {
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "flash_attention", "flash_attention_vl", "masked_attention", "rnn",
    "conv", "conv_transpose",
}

# Numerically-sensitive ops pinned to f32 (reference FP32_FUNCS analog).
# Everything else runs in whatever dtype flows in (WIDEST_TYPE_CASTS
# behavior falls out of jnp promotion).
FP32_OPS = {
    "softmax", "log_softmax", "SoftmaxOutput", "BatchNorm", "LayerNorm",
    "GroupNorm", "InstanceNorm", "batch_norm_train", "batch_norm_infer",
    "layer_norm", "group_norm", "instance_norm", "norm", "mean", "sum",
    "exp", "log", "erf", "smooth_l1",
}

_state = {"enabled": False, "dtype": None, "wrapper": None}


def _cast_tree(x, dtype):
    if hasattr(x, "dtype") and hasattr(x, "astype") and \
            x.dtype == jnp.float32:
        return x.astype(dtype)
    return x


def _make_wrapper(target_dtype):
    def wrapper(name, fn):
        if name not in TARGET_DTYPE_OPS:
            return fn

        def amp_fn(*args, **kwargs):
            cast_args = [_cast_tree(a, target_dtype) for a in args]
            out = fn(*cast_args, **kwargs)
            if isinstance(out, (tuple, list)):
                return type(out)(
                    o.astype(jnp.float32)
                    if hasattr(o, "dtype") and o.dtype == target_dtype else o
                    for o in out)
            if hasattr(out, "dtype") and out.dtype == target_dtype:
                return out.astype(jnp.float32)
            return out
        return amp_fn
    return wrapper


def init(target_dtype: str = "bfloat16"):
    """Enable AMP process-wide (reference amp.init, amp.py:282)."""
    if _state["enabled"]:
        return
    if target_dtype in ("bfloat16", "bf16"):
        dt = jnp.bfloat16
    elif target_dtype in ("float16", "fp16"):
        dt = jnp.float16
    else:
        raise MXNetError(f"unsupported AMP target dtype {target_dtype!r}")
    w = _make_wrapper(dt)
    _registry.add_invoke_wrapper(w)
    _state.update(enabled=True, dtype=dt, wrapper=w)


def uninit():
    """Disable AMP (test/debug helper; the reference has no un-init)."""
    if _state["enabled"]:
        _registry.remove_invoke_wrapper(_state["wrapper"])
        _state.update(enabled=False, dtype=None, wrapper=None)


def is_enabled() -> bool:
    return _state["enabled"]


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Gluon Trainer (reference
    amp.init_trainer). A no-op numerically for bf16 (scale stays 1) but
    the scaler object is attached for API parity and fp16 use."""
    scaler = LossScaler(
        init_scale=1.0 if _state["dtype"] == jnp.bfloat16 else 2. ** 16)
    trainer._amp_loss_scaler = scaler
    return scaler


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Yield the scaled loss; trainer.step unscales via trainer._scale
    (reference amp.scale_loss contextmanager)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        scaler = init_trainer(trainer)
    # trainer._scale must keep dividing out the loss scale through the
    # trainer.step() that follows this context — set it persistently,
    # against the original scale (idempotent across steps as the dynamic
    # scale changes).
    if not hasattr(trainer, "_amp_original_scale"):
        trainer._amp_original_scale = trainer._scale
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if scaler.loss_scale == 1.0:  # bf16 default: no-op passthrough
        yield loss
    elif isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def convert_hybrid_block(block, target_dtype: str = "bfloat16"):
    """Cast a Gluon block's parameters for low-precision *inference*
    (reference amp.convert_hybrid_block): all params to target dtype
    except normalization-layer params, which stay f32."""
    from ..gluon import nn as _nn
    norm_types = (_nn.BatchNorm, _nn.LayerNorm, _nn.GroupNorm,
                  _nn.InstanceNorm)
    # cast every parameter not owned by a norm layer
    norm_params = set()
    stack = [block]
    while stack:
        b = stack.pop()
        if isinstance(b, norm_types):
            for p in b.collect_params().values():
                norm_params.add(id(p))
        stack.extend(getattr(b, "_children", {}).values())
    for p in block.collect_params().values():
        if id(p) not in norm_params and p._data is not None and \
                p.dtype == "float32":
            p.cast(target_dtype)
    return block
