"""Dynamic loss scaling (reference: python/mxnet/contrib/amp/loss_scaler.py).

Needed for true fp16 (5-bit exponent underflows gradients); a no-op for
bf16, which shares f32's exponent range — the reason AMP-on-TPU defaults to
scale 1.0.
"""
from __future__ import annotations

import numpy as onp

__all__ = ["LossScaler"]


class LossScaler:
    """Multiplicative dynamic scaler: halve on overflow, double after
    ``scale_window`` clean steps (reference loss_scaler.py semantics)."""

    def __init__(self, init_scale: float = 2. ** 16, scale_factor: float = 2.,
                 scale_window: int = 2000, min_scale: float = 1.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_scale = min_scale
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        """True if any parameter gradient is non-finite. ``params`` is an
        iterable of Parameters (or NDArrays treated as grads)."""
        for p in params:
            g = p.grad() if hasattr(p, "grad") and callable(
                getattr(p, "grad", None)) else p
            if g is None:
                continue
            arr = g.asnumpy() if hasattr(g, "asnumpy") else onp.asarray(g)
            if not onp.isfinite(arr).all():
                return True
        return False

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
