#!/usr/bin/env python
"""Expert + pipeline parallelism on a device mesh.

No reference analog (the reference stops at data parallelism + manual
placement); this is the TPU-native scale-out surface: a mixture-of-experts
FFN sharded over an 'ep' mesh axis (two all_to_all exchanges per layer,
ops/moe.py) and a GPipe microbatch pipeline over a 'pp' axis
(parallel/pipeline.py). Runs on real chips or the virtual CPU mesh.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/moe_pipeline_parallel.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as onp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx  # noqa: F401  (framework import sets up platform)
from mxnet_tpu.ops import moe as moe_ops
from mxnet_tpu.parallel import shard_map
from mxnet_tpu.parallel.pipeline import run_pipeline


def expert_parallel_demo():
    devs = jax.devices()
    ep = min(4, len(devs))
    if len(devs) < 2:
        print(f"expert-parallel demo needs >=2 devices, have {len(devs)}; "
              "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "JAX_PLATFORMS=cpu")
        return
    mesh = Mesh(onp.array(devs[:ep]), ("ep",))
    rng = onp.random.RandomState(0)
    n, d, h, e, k = 64, 32, 64, 2 * ep, 2
    x = jnp.asarray(rng.randn(n, d).astype("float32"))
    gate = jnp.asarray(rng.randn(d, e).astype("float32") * 0.3)
    w1 = jnp.asarray(rng.randn(e, d, h).astype("float32") * 0.1)
    w2 = jnp.asarray(rng.randn(e, h, d).astype("float32") * 0.1)

    def shard_fn(xs, gw, w1s, w2s):
        return moe_ops.moe_ffn(xs, gw, w1s, w2s, top_k=k,
                               capacity_factor=2.0, axis_name="ep")

    f = jax.jit(shard_map(shard_fn, mesh,
                          (P(), P(), P("ep"), P("ep")), (P(), P())))
    out, aux = f(x, gate, w1, w2)
    print(f"MoE: {e} experts over {ep} devices, out {out.shape}, "
          f"balance aux {float(aux):.3f}")


def pipeline_demo():
    devs = jax.devices()
    pp = min(4, len(devs))
    if pp < 2:
        print("pipeline demo needs >=2 devices")
        return
    mesh = Mesh(onp.array(devs[:pp]), ("pp",))
    rng = onp.random.RandomState(1)
    d, b, m = 32, 64, 8
    stages = jnp.asarray(rng.randn(pp, d, d).astype("float32") * 0.3)
    x = jnp.asarray(rng.randn(b, d).astype("float32"))

    def stage_fn(p, h):
        return jnp.tanh(h @ p)

    out = run_pipeline(stage_fn, stages, x, num_microbatches=m, mesh=mesh)
    seq = onp.asarray(x)
    for s in range(pp):
        seq = onp.tanh(seq @ onp.asarray(stages[s]))
    err = float(abs(onp.asarray(out) - seq).max())
    print(f"pipeline: {pp} stages x {m} microbatches, max |pipeline - "
          f"sequential| = {err:.2e}")


if __name__ == "__main__":
    expert_parallel_demo()
    pipeline_demo()
