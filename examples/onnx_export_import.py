#!/usr/bin/env python
"""ONNX round trip (reference: example/onnx + contrib.onnx docs).

Builds a small convnet as an mx.sym graph, exports a standard opset-13
.onnx file (written by the framework's own protobuf serializer — no onnx
package needed), re-imports it, and checks the two graphs agree.

Run: python examples/onnx_export_import.py [--out /tmp/model.onnx]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib import onnx as mxonnx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/mxnet_tpu_model.onnx")
    args = ap.parse_args()
    rng = onp.random.RandomState(0)

    x = sym.Variable("data")
    c = sym.Convolution(x, sym.Variable("w"), sym.Variable("b"),
                        kernel=(3, 3), pad=(1, 1), num_filter=8,
                        name="conv1")
    r = sym.Activation(c, act_type="relu", name="relu1")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    f = sym.Flatten(p, name="flat")
    out = sym.softmax(sym.FullyConnected(
        f, sym.Variable("fw"), sym.Variable("fb"), name="fc"), name="prob")

    params = {
        "w": nd.array(rng.randn(8, 3, 3, 3).astype("float32") * 0.1),
        "b": nd.array(rng.randn(8).astype("float32") * 0.1),
        "fw": nd.array(rng.randn(10, 8 * 16 * 16).astype("float32") * 0.02),
        "fb": nd.array(rng.randn(10).astype("float32") * 0.1),
    }
    path = mxonnx.export_model(out, params, in_shapes=[(4, 3, 32, 32)],
                               onnx_file_path=args.out, verbose=True)
    meta = mxonnx.get_model_metadata(path)
    print("inputs:", meta["input_tensor_data"])

    sym2, arg_params, aux_params = mxonnx.import_model(path)
    xv = nd.array(rng.randn(4, 3, 32, 32).astype("float32"))
    want = out.eval(data=xv, **params).asnumpy()
    got = sym2.eval(data=xv, **arg_params, **aux_params).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    print(f"round trip OK: {_os.path.getsize(path)} byte model, "
          f"max |diff| = {abs(got - want).max():.2e}")


if __name__ == "__main__":
    main()
