#!/usr/bin/env python
"""ConvLSTM next-frame prediction (reference: the contrib
Conv2DLSTMCell use case from gluon/contrib/rnn/conv_rnn_cell.py; Shi et
al. 2015 precipitation nowcasting).

A moving bright square bounces around a grid; a Conv2DLSTMCell encoder
unrolls over the input clip and a 1x1 conv head predicts the NEXT frame.
Falling loss + the predicted square landing on the true next position
prove the contrib conv-recurrent path end to end. Every timestep is two
MXU convolutions; hybridize-style unrolling keeps the whole clip one XLA
program under the jitted CachedOp when wrapped in a HybridBlock.

Run: python examples/convlstm_video.py [--steps 60]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import rnn as crnn


def make_clip(rng, batch, length=6, size=16):
    """Square moving with constant velocity; returns clip and next frame."""
    clips = onp.zeros((batch, length, 1, size, size), "float32")
    nxt = onp.zeros((batch, 1, size, size), "float32")
    for b in range(batch):
        x, y = rng.randint(2, size - 6, 2)
        dx, dy = rng.choice([-1, 1], 2)
        for t in range(length + 1):
            xx = int(onp.clip(x + dx * t, 0, size - 4))
            yy = int(onp.clip(y + dy * t, 0, size - 4))
            target = clips[b, t] if t < length else nxt[b]
            target[0, yy:yy + 4, xx:xx + 4] = 1.0
    return clips, nxt


class NextFrame(gluon.Block):
    def __init__(self, size=16):
        super().__init__()
        self.cell = crnn.Conv2DLSTMCell(input_shape=(1, size, size),
                                        hidden_channels=8, i2h_kernel=3,
                                        h2h_kernel=3, i2h_pad=1)
        self.head = nn.Conv2D(1, 1, in_channels=8)

    def forward(self, clip):
        # clip: (B, T, 1, H, W)
        outs, _ = self.cell.unroll(clip.shape[1], clip, layout="NTC")
        return self.head(outs[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    rng = onp.random.RandomState(0)
    mx.random.seed(0)  # initializer draws from the framework RNG stream

    net = NextFrame()
    # Xavier at conv-RNN scale: the default tiny-uniform init leaves the
    # gate pre-activations so small the model stalls at the base rate
    net.initialize(mx.init.Xavier(magnitude=2.5))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3}, kvstore="tpu")
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)

    first = last = None
    for step in range(args.steps):
        clips, nxt = make_clip(rng, args.batch)
        with autograd.record():
            pred = net(nd.array(clips))
            loss = loss_fn(pred, nd.array(nxt)).mean()
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        first, last = (v if first is None else first), v
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {v:.4f}")
    assert last < first, (first, last)

    # the predicted square should overlap the true next position
    clips, nxt = make_clip(rng, 4)
    pred = 1 / (1 + onp.exp(-net(nd.array(clips)).asnumpy()))
    hits = 0
    for b in range(4):
        mask = nxt[b, 0] > 0.5
        hits += pred[b, 0][mask].mean() > pred[b, 0][~mask].mean()
    print(f"ConvLSTM: loss {first:.4f} -> {last:.4f}; "
          f"{hits}/4 predictions localize the moving square")
    assert hits >= 3


if __name__ == "__main__":
    main()
