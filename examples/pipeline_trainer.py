#!/usr/bin/env python
"""GPipe pipeline-parallel training through the Gluon Trainer surface.

A deep residual-MLP regressor is partitioned into ``--stages`` stages,
each owning identical blocks; ``PipelineTrainer.forward_backward`` runs
the whole microbatched fill/drain schedule (parallel/pipeline.py:
lax.scan over ppermute ring hops inside shard_map) as ONE compiled XLA
program, and ``trainer.step`` applies the standard fused optimizer
update. On hardware with >= stages devices the stages genuinely live on
different chips; on fewer devices the same program runs degenerate
(single-chip) with identical numerics.

Run: python examples/pipeline_trainer.py [--stages 4] [--micro 4]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--width", type=int, default=64)
    args = ap.parse_args()

    onp.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(args.stages):
        net.add(nn.Dense(args.width, activation="tanh",
                         in_units=args.width))
    net.initialize()

    trainer = gluon.PipelineTrainer(
        net, "adam", {"learning_rate": 3e-3},
        num_stages=args.stages, num_microbatches=args.micro,
        loss=gluon.loss.L2Loss())

    rng = onp.random.RandomState(0)
    w_true = rng.randn(args.width, args.width).astype("float32") * 0.2
    first = last = None
    for step in range(args.steps):
        x = rng.randn(32, args.width).astype("float32")
        y = onp.tanh(x @ w_true)
        loss = trainer.forward_backward(nd.array(x), nd.array(y))
        trainer.step(1)
        v = float(loss.asnumpy())
        first, last = (v if first is None else first), v
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {v:.5f}")
    assert last < first, (first, last)
    print(f"pipeline({args.stages} stages x {args.micro} microbatches): "
          f"loss {first:.4f} -> {last:.4f}")


if __name__ == "__main__":
    main()
