#!/usr/bin/env python
"""Gluon MNIST training (reference: example/gluon/mnist/mnist.py).

Run: python examples/train_mnist_gluon.py [--epochs 3] [--hybridize]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import MNIST, transforms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--hybridize", action="store_true")
    args = ap.parse_args()

    train_ds = MNIST(train=True).transform_first(transforms.ToTensor())
    val_ds = MNIST(train=False).transform_first(transforms.ToTensor())
    train = gluon.data.DataLoader(train_ds, args.batch_size, shuffle=True)
    val = gluon.data.DataLoader(val_ds, args.batch_size)

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    net.initialize()
    if args.hybridize:
        net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr}, kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for x, y in train:
            x = x.reshape((x.shape[0], -1))
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
        _, train_acc = metric.get()

        metric.reset()
        for x, y in val:
            metric.update(y, net(x.reshape((x.shape[0], -1))))
        _, val_acc = metric.get()
        print(f"epoch {epoch}: train_acc={train_acc:.4f} "
              f"val_acc={val_acc:.4f}")


if __name__ == "__main__":
    main()
