#!/usr/bin/env python
"""LSTM language model (reference: example/rnn/word_lm — the BASELINE.md
"LSTM LM, XLA scan" config).

The fused gluon.rnn.LSTM lowers to one lax.scan (the cuDNN-RNN analog);
hybridizing the whole model compiles forward+backward+update into a single
XLA program. Trains on a synthetic character stream whose next token is a
deterministic function of the previous two — learnable, so perplexity
falling proves the recurrent path carries state.

Run: python examples/train_lstm_lm.py [--steps 60]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn


class WordLM(gluon.HybridBlock):
    def __init__(self, vocab, embed, hidden, layers):
        super().__init__()
        self.emb = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=layers, layout="NTC")
        self.head = nn.Dense(vocab, flatten=False)

    def forward(self, tokens):
        h = self.lstm(self.emb(tokens))
        return self.head(h)


def synthetic_stream(rng, n, vocab):
    """x[t] = (x[t-1] + x[t-2]) % vocab with noise-free transitions — a
    2nd-order recurrence the LSTM must carry state to predict."""
    s = onp.zeros(n, "int32")
    s[0], s[1] = rng.randint(0, vocab, 2)
    for t in range(2, n):
        s[t] = (s[t - 1] + s[t - 2]) % vocab
    return s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=16)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=32)
    args = ap.parse_args()
    rng = onp.random.RandomState(0)

    net = WordLM(args.vocab, 16, 64, layers=2)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3}, kvstore="tpu")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    stream = synthetic_stream(rng, args.batch * (args.seq + 1) * 4,
                              args.vocab)
    t0 = time.perf_counter()
    tokens_seen = 0
    first = last = None
    for step in range(args.steps):
        offs = rng.randint(0, len(stream) - args.seq - 1, size=args.batch)
        x = onp.stack([stream[o:o + args.seq] for o in offs])
        y = onp.stack([stream[o + 1:o + args.seq + 1] for o in offs])
        with autograd.record():
            logits = net(nd.array(x))
            loss = loss_fn(logits, nd.array(y))
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.mean().asnumpy())
        tokens_seen += args.batch * args.seq
        if first is None:
            first = v
        last = v
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step:3d} ce {v:.4f} "
                  f"ppl {onp.exp(min(v, 20)):.2f}")
    dt = time.perf_counter() - t0
    assert last < first * 0.9, (first, last)
    print(f"LSTM LM: ce {first:.3f} -> {last:.3f}; "
          f"{tokens_seen / dt:,.0f} tokens/s incl. compile")


if __name__ == "__main__":
    main()
