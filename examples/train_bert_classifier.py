#!/usr/bin/env python
"""Fine-tune a BERT classifier on synthetic sequence data — demonstrates
the flash-attention-backed transformer stack (Pallas kernels on TPU).

Run: python examples/train_bert_classifier.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import bert


def synthetic_batch(rng, vocab, batch, seqlen):
    """Class 1 sequences open with a run of marker tokens."""
    tokens = rng.randint(10, vocab, (batch, seqlen))
    labels = rng.randint(0, 2, (batch,))
    for i, l in enumerate(labels):
        if l:
            tokens[i, 1:4] = 7  # position 0 is the [CLS] slot
    vlen = rng.randint(seqlen // 2, seqlen + 1, (batch,))
    return (mx.nd.array(tokens, dtype="int32"),
            mx.nd.array(vlen, dtype="int32"),
            mx.nd.array(labels, dtype="int32"))


def main():
    rng = onp.random.RandomState(0)
    net = bert.BERTClassifier(
        bert.BERTModel(vocab_size=256, units=64, hidden_size=128,
                       num_layers=2, num_heads=4, max_length=64),
        num_classes=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for step in range(30):
        tokens, vlen, y = synthetic_batch(rng, 256, 16, 48)
        with autograd.record():
            logits = net(tokens, None, vlen)
            loss = loss_fn(logits, y)
        loss.backward()
        trainer.step(16)
        metric.update(y, logits)
        if (step + 1) % 10 == 0:
            name, acc = metric.get()
            print(f"step {step + 1}: loss={float(loss.mean().asnumpy()):.3f} "
                  f"{name}={acc:.3f}")
            metric.reset()


if __name__ == "__main__":
    main()
