#!/usr/bin/env python
"""Sparse-embedding bag-of-words classifier (reference:
example/sparse/matrix_factorization + the row_sparse Embedding docs).

Demonstrates the O(rows-touched) path: ``Embedding(sparse_grad=True)``
produces a row_sparse weight gradient whose dense (vocab, dim) mirror is
never materialized, and lazy Adam (``lazy_update=True`` — opt-in, as in
the reference) updates only the rows a batch touched — vocabulary rows
outside the batch stay bitwise identical.

Run: python examples/sparse_embedding_lm.py [--vocab 50000] [--steps 30]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray.sparse import RowSparseNDArray


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=20000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=16)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)

    class BowClassifier(gluon.Block):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(args.vocab, args.dim, sparse_grad=True)
            self.head = nn.Dense(2)

        def forward(self, tokens):
            return self.head(self.emb(tokens).mean(axis=1))

    net = BowClassifier()
    net.initialize()
    w0 = net.emb.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3, "lazy_update": True},
                            kvstore="tpu")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # synthetic task: class = whether the batch's tokens skew low or high
    used = set()
    for step in range(args.steps):
        ids = rng.randint(0, args.vocab // 10, size=(args.batch, args.seq))
        y = (ids.mean(axis=1) > args.vocab // 20).astype("int32")
        used.update(ids.reshape(-1).tolist())
        x = nd.array(ids.astype("int32"))
        with autograd.record():
            loss = loss_fn(net(x), nd.array(y))
        loss.backward()
        g = net.emb.weight.data()._grad
        assert isinstance(g, RowSparseNDArray)
        trainer.step(args.batch)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {float(loss.mean().asnumpy()):.4f} "
                  f"grad rows {g.indices.shape[0]}/{args.vocab}")

    w_now = net.emb.weight.data().asnumpy()
    untouched = onp.setdiff1d(onp.arange(args.vocab),
                              onp.array(sorted(used)))
    onp.testing.assert_array_equal(w_now[untouched], w0[untouched])
    print(f"{len(untouched)} untouched vocabulary rows bitwise unchanged — "
          f"updates were O(rows-touched)")


if __name__ == "__main__":
    main()
