#!/usr/bin/env python
"""WikiText language model with a pretrained token embedding.

Reference analog: example/gluon/word_language_model + the
contrib.text docs' GloVe workflow — build a Vocabulary from the
corpus, initialize the model's embedding table from a pretrained
token-embedding file via ``update_token_vectors``-style loading, and
train an LSTM LM with truncated BPTT.

This run is self-contained: WikiText2 falls back to its deterministic
synthetic corpus when the token files are absent, and the "pretrained"
embedding is a CustomEmbedding file generated on the fly (structure
identical to a GloVe text file) — swap in real files under
~/.mxnet/embedding to reproduce the reference workflow byte-for-byte.

Run: python examples/wikitext_lm_pretrained_embedding.py [--steps 40]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse
import tempfile

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.contrib import text
from mxnet_tpu.gluon import nn, rnn


class WordLM(gluon.HybridBlock):
    def __init__(self, vocab_size, embed, hidden):
        super().__init__()
        self.emb = nn.Embedding(vocab_size, embed)
        self.lstm = rnn.LSTM(hidden, layout="NTC")
        self.out = nn.Dense(vocab_size, flatten=False)

    def forward(self, x):
        return self.out(self.lstm(self.emb(x)))


def synthetic_pretrained_file(vocab, dim, path):
    """Write a GloVe-format embedding file covering the vocabulary."""
    rng = onp.random.RandomState(7)
    with open(path, "w", encoding="utf8") as f:
        for tok in vocab.idx_to_token[1:]:
            vec = rng.randn(dim) * 0.1
            f.write(tok + " " + " ".join(f"{v:.5f}" for v in vec) + "\n")
    return path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    ds = gluon.contrib.data.WikiText2(segment="train",
                                      seq_len=args.seq_len)
    print(f"WikiText2[{ds.source}]: {len(ds)} sequences, "
          f"vocab={len(ds.vocabulary)}")

    # pretrained-embedding workflow (reference contrib/text/embedding.py)
    with tempfile.TemporaryDirectory() as td:
        emb_file = synthetic_pretrained_file(
            ds.vocabulary, args.embed, _os.path.join(td, "pre.txt"))
        emb = text.embedding.CustomEmbedding(emb_file,
                                             vocabulary=ds.vocabulary)
    assert emb.idx_to_vec.shape == (len(ds.vocabulary), args.embed)

    net = WordLM(len(ds.vocabulary), args.embed, args.hidden)
    net.initialize()
    net(ds[0][0].reshape(1, -1))  # materialize shapes
    # seed the embedding table with the pretrained vectors
    net.emb.weight.set_data(emb.idx_to_vec)
    net.hybridize()

    loader = gluon.data.DataLoader(ds, args.batch_size, shuffle=True,
                                   last_batch="discard")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr}, kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    step = 0
    first_ppl = last_ppl = None
    while step < args.steps:
        for data, label in loader:
            if step >= args.steps:
                break
            with autograd.record():
                logits = net(data)
                loss = loss_fn(logits.reshape(-1, logits.shape[-1]),
                               label.reshape(-1))
            loss.backward()
            trainer.step(data.shape[0])
            ppl = float(onp.exp(min(loss.mean().asnumpy(), 20.0)))
            if first_ppl is None:
                first_ppl = ppl
            last_ppl = ppl
            if step % 10 == 0:
                print(f"step {step}: perplexity {ppl:.1f}")
            step += 1
    print(f"perplexity {first_ppl:.1f} -> {last_ppl:.1f}")
    assert last_ppl < first_ppl, "LM did not learn"


if __name__ == "__main__":
    main()
