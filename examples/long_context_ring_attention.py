#!/usr/bin/env python
"""Context parallelism demo: a sequence too long for one device's memory
budget attends across an 8-device mesh with ring attention (K/V shards
rotate over ICI via ppermute).

Run (CPU simulation of an 8-chip mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context_ring_attention.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as onp  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from mxnet_tpu.ops.attention import (attention_reference,  # noqa: E402
                                     ring_attention_sharded)


def main():
    devs = jax.devices()[:8]
    mesh = Mesh(onp.array(devs), ("sp",))
    rng = onp.random.RandomState(0)
    B, H, S, D = 2, 4, 4096, 64  # S shards to 512 per device
    q = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))

    out = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=True)
    ref = attention_reference(q, k, v, causal=True)
    err = float(jnp.abs(out - ref).max())
    print(f"ring attention over {len(devs)} devices, seq={S}: "
          f"max|ring - reference| = {err:.2e}")
    assert err < 1e-4


if __name__ == "__main__":
    main()
