#!/usr/bin/env python
"""INT8 post-training quantization served through the inference engine
(reference: example/quantization/imagenet_gen_qsym.py workflow): train
briefly in f32, calibrate, swap in int8 MXU kernels, and serve BOTH
variants through ``mx.serving`` — the AOT-compiled bucketed predictor
plus the dynamic batcher (docs/SERVING.md) — comparing accuracy.

Run: python examples/quantize_inference.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, serving
from mxnet_tpu.gluon import nn


def main():
    rng = onp.random.RandomState(0)
    w = rng.randn(16, 4).astype("float32")
    x_all = rng.uniform(-1, 1, (512, 16)).astype("float32")
    y_all = x_all.dot(w).argmax(1).astype("int32")

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(4, in_units=32))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for i in range(0, 512, 32):
        xb = mx.nd.array(x_all[i:i + 32])
        yb = mx.nd.array(y_all[i:i + 32])
        with autograd.record():
            loss = loss_fn(net(xb), yb)
        loss.backward()
        trainer.step(32)

    def accuracy(predictor):
        """Serve the eval set through the dynamic batcher: concurrent
        32-row requests coalesced into the predictor's shape buckets,
        pipelined through the dispatch window — the production read
        path, not an ad-hoc net(x) sweep."""
        with serving.DynamicBatcher(predictor, max_batch=64,
                                    timeout_ms=2.0) as batcher:
            futs = [batcher.submit(mx.nd.array(x_all[i:i + 32]))
                    for i in range(0, 512, 32)]
            preds = onp.concatenate(
                [f.result(60).asnumpy().argmax(1) for f in futs])
        return (preds == y_all).mean()

    buckets = (32, 64)
    fp32_pred = serving.CompiledPredictor(net, bucket_sizes=buckets)
    fp32_pred.warmup(mx.nd.array(x_all[:1]), buckets=buckets)
    fp32_acc = accuracy(fp32_pred)

    calib = [mx.nd.array(x_all[i:i + 32]) for i in range(0, 128, 32)]
    int8_pred = serving.predictor_for(net, dtype="int8",
                                      calib_data=calib,
                                      calib_mode="naive",
                                      bucket_sizes=buckets)
    int8_acc = accuracy(int8_pred)
    print(f"fp32 accuracy:  {fp32_acc:.4f} "
          f"(serving programs: {fp32_pred.n_traces})")
    print(f"int8 accuracy:  {int8_acc:.4f} "
          f"(layers now: {[type(b).__name__ for b in net]})")
    assert int8_acc > fp32_acc - 0.02


if __name__ == "__main__":
    main()
