#!/usr/bin/env python
"""INT8 post-training quantization (reference:
example/quantization/imagenet_gen_qsym.py workflow): train briefly in f32,
calibrate, swap in int8 MXU kernels, compare accuracy.

Run: python examples/quantize_inference.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def main():
    rng = onp.random.RandomState(0)
    w = rng.randn(16, 4).astype("float32")
    x_all = rng.uniform(-1, 1, (512, 16)).astype("float32")
    y_all = x_all.dot(w).argmax(1).astype("int32")

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(4, in_units=32))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for i in range(0, 512, 32):
        xb = mx.nd.array(x_all[i:i + 32])
        yb = mx.nd.array(y_all[i:i + 32])
        with autograd.record():
            loss = loss_fn(net(xb), yb)
        loss.backward()
        trainer.step(32)

    def accuracy(model):
        pred = model(mx.nd.array(x_all)).asnumpy().argmax(1)
        return (pred == y_all).mean()

    fp32_acc = accuracy(net)
    calib = [mx.nd.array(x_all[i:i + 32]) for i in range(0, 128, 32)]
    q.quantize_net(net, calib, calib_mode="naive")
    int8_acc = accuracy(net)
    print(f"fp32 accuracy:  {fp32_acc:.4f}")
    print(f"int8 accuracy:  {int8_acc:.4f} "
          f"(layers now: {[type(b).__name__ for b in net]})")
    assert int8_acc > fp32_acc - 0.02


if __name__ == "__main__":
    main()
