#!/usr/bin/env python
"""Tiny SSD-style detector (reference: example/ssd — the BASELINE.md
"SSD, on-device NMS" config).

A small conv backbone emits one feature map; MultiBoxPrior generates
anchors, conv heads predict per-anchor class scores and box offsets,
MultiBoxTarget builds training targets, and inference decodes with
MultiBoxDetection — whose NMS runs ON DEVICE as one XLA program (the
reference needed a custom CUDA NMS kernel; here box_nms is a lax.fori_loop
the compiler fuses). Data rides ``ImageDetIter`` + ``CreateDetAugmenter``
(reference python/mxnet/image/detection.py): synthetic scenes with one
bright square are augmented with label-aware random crop / pad / mirror,
so falling loss + a sane detection prove the whole detection pipeline —
iterator, box-transforming augmenters, anchor/target matching, and NMS —
end to end.

Run: python examples/ssd_detection.py [--steps 40]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

SIZES = (0.3, 0.5)
RATIOS = (1.0, 2.0)
NUM_ANCHORS = len(SIZES) + len(RATIOS) - 1


class TinySSD(gluon.Block):
    def __init__(self, num_classes=1):
        super().__init__()
        self.backbone = nn.Sequential()
        for ch in (16, 32):
            self.backbone.add(nn.Conv2D(ch, 3, strides=2, padding=1,
                                        activation="relu"))
        self.cls_head = nn.Conv2D(NUM_ANCHORS * (num_classes + 1), 3,
                                  padding=1)
        self.loc_head = nn.Conv2D(NUM_ANCHORS * 4, 3, padding=1)
        self._nc = num_classes

    def forward(self, x):
        feat = self.backbone(x)                       # (B, C, H, W)
        anchors = nd.contrib.MultiBoxPrior(feat, sizes=SIZES,
                                           ratios=RATIOS)   # (1, N, 4)
        cls = self.cls_head(feat)                     # (B, A*(nc+1), H, W)
        b, _, h, w = cls.shape
        cls = cls.transpose((0, 2, 3, 1)).reshape(
            (b, h * w * NUM_ANCHORS, self._nc + 1))
        loc = self.loc_head(feat).transpose((0, 2, 3, 1)).reshape((b, -1))
        return anchors, cls, loc


def make_dataset(rng, n, size=32):
    """Bright 8px squares on noise; labels are the corner boxes — the
    (label, image) pairs ImageDetIter consumes."""
    items = []
    for _ in range(n):
        img = (rng.rand(size, size, 3) * 25).astype("uint8")
        x0 = rng.randint(0, size - 8)
        y0 = rng.randint(0, size - 8)
        img[y0:y0 + 8, x0:x0 + 8] = 255
        label = onp.array([[0, x0 / size, y0 / size,
                            (x0 + 8) / size, (y0 + 8) / size]], "float32")
        items.append((label, img))
    return items


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=25)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    rng = onp.random.RandomState(0)

    from mxnet_tpu.image.detection import ImageDetIter
    train_iter = ImageDetIter(
        batch_size=args.batch, data_shape=(3, 32, 32),
        imglist=make_dataset(rng, 64), shuffle=True,
        rand_crop=0.3, rand_pad=0.3, rand_mirror=True,
        min_object_covered=0.9, area_range=(0.5, 1.5),
        mean=True, std=True)

    net = TinySSD()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3}, kvstore="tpu")
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    l1 = gluon.loss.L1Loss()

    first = last = None
    step = 0
    while step < args.steps:
        try:
            batch = train_iter.next()
        except StopIteration:
            train_iter.reset()
            continue
        imgs, labels = batch.data[0], batch.label[0]
        step += 1
        with autograd.record():
            anchors, cls, loc = net(imgs)
            loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
                anchors, labels, cls.transpose((0, 2, 1)))
            loss = ce(cls, cls_t).mean() + \
                (l1(loc * loc_mask, loc_t * loc_mask)).mean()
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        if first is None:
            first = v
        last = v
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {v:.4f}")
    assert last < first, (first, last)

    # inference: decode + ON-DEVICE NMS via MultiBoxDetection; eval data
    # rides the same iterator without random augmentation
    eval_iter = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                             imglist=make_dataset(rng, 4),
                             mean=True, std=True)
    anchors, cls, loc = net(eval_iter.next().data[0])
    probs = nd.softmax(cls.transpose((0, 2, 1)), axis=1)
    det = nd.contrib.MultiBoxDetection(probs, loc, anchors,
                                       nms_threshold=0.45, threshold=0.01)
    det0 = det.asnumpy()[0]
    kept = det0[det0[:, 0] >= 0]
    print(f"SSD: loss {first:.3f} -> {last:.3f}; "
          f"{len(kept)} detections after on-device NMS; "
          f"top score {kept[0, 1]:.3f}" if len(kept)
          else "SSD: no detections (increase --steps)")


if __name__ == "__main__":
    main()
