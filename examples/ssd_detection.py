#!/usr/bin/env python
"""Tiny SSD-style detector (reference: example/ssd — the BASELINE.md
"SSD, on-device NMS" config).

A small conv backbone emits one feature map; MultiBoxPrior generates
anchors, conv heads predict per-anchor class scores and box offsets,
MultiBoxTarget builds training targets, and inference decodes with
MultiBoxDetection — whose NMS runs ON DEVICE as one XLA program (the
reference needed a custom CUDA NMS kernel; here box_nms is a lax.fori_loop
the compiler fuses). Synthetic scenes contain one bright square whose
location is the label, so falling loss + a sane detection prove the
anchor/target/NMS plumbing end to end.

Run: python examples/ssd_detection.py [--steps 40]
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

SIZES = (0.3, 0.5)
RATIOS = (1.0, 2.0)
NUM_ANCHORS = len(SIZES) + len(RATIOS) - 1


class TinySSD(gluon.Block):
    def __init__(self, num_classes=1):
        super().__init__()
        self.backbone = nn.Sequential()
        for ch in (16, 32):
            self.backbone.add(nn.Conv2D(ch, 3, strides=2, padding=1,
                                        activation="relu"))
        self.cls_head = nn.Conv2D(NUM_ANCHORS * (num_classes + 1), 3,
                                  padding=1)
        self.loc_head = nn.Conv2D(NUM_ANCHORS * 4, 3, padding=1)
        self._nc = num_classes

    def forward(self, x):
        feat = self.backbone(x)                       # (B, C, H, W)
        anchors = nd.contrib.MultiBoxPrior(feat, sizes=SIZES,
                                           ratios=RATIOS)   # (1, N, 4)
        cls = self.cls_head(feat)                     # (B, A*(nc+1), H, W)
        b, _, h, w = cls.shape
        cls = cls.transpose((0, 2, 3, 1)).reshape(
            (b, h * w * NUM_ANCHORS, self._nc + 1))
        loc = self.loc_head(feat).transpose((0, 2, 3, 1)).reshape((b, -1))
        return anchors, cls, loc


def make_scene(rng, n, size=32):
    """One bright 8px square per image; label = its corner box."""
    imgs = rng.rand(n, 1, size, size).astype("float32") * 0.1
    labels = onp.zeros((n, 1, 5), "float32")
    for i in range(n):
        x0 = rng.randint(0, size - 8)
        y0 = rng.randint(0, size - 8)
        imgs[i, 0, y0:y0 + 8, x0:x0 + 8] = 1.0
        labels[i, 0] = [0, x0 / size, y0 / size,
                        (x0 + 8) / size, (y0 + 8) / size]
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=25)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    rng = onp.random.RandomState(0)

    net = TinySSD()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3}, kvstore="tpu")
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    l1 = gluon.loss.L1Loss()

    first = last = None
    for step in range(args.steps):
        imgs, labels = make_scene(rng, args.batch)
        with autograd.record():
            anchors, cls, loc = net(nd.array(imgs))
            loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
                anchors, nd.array(labels), cls.transpose((0, 2, 1)))
            loss = ce(cls, cls_t).mean() + \
                (l1(loc * loc_mask, loc_t * loc_mask)).mean()
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        if first is None:
            first = v
        last = v
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {v:.4f}")
    assert last < first, (first, last)

    # inference: decode + ON-DEVICE NMS via MultiBoxDetection
    imgs, labels = make_scene(rng, 4)
    anchors, cls, loc = net(nd.array(imgs))
    probs = nd.softmax(cls.transpose((0, 2, 1)), axis=1)
    det = nd.contrib.MultiBoxDetection(probs, loc, anchors,
                                       nms_threshold=0.45, threshold=0.01)
    det0 = det.asnumpy()[0]
    kept = det0[det0[:, 0] >= 0]
    print(f"SSD: loss {first:.3f} -> {last:.3f}; "
          f"{len(kept)} detections after on-device NMS; "
          f"top score {kept[0, 1]:.3f}" if len(kept)
          else "SSD: no detections (increase --steps)")


if __name__ == "__main__":
    main()
