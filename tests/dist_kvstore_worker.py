"""Worker body for the 2-process dist kvstore test (launched by
tools/launch.py --launcher local; the analog of reference
tests/nightly/dist_sync_kvstore.py run under
tests/nightly/test_distributed_training-gpu.sh:25-39).

Each rank joins the jax.distributed job via DMLC_* env vars, exercises
KVStoreDist (broadcast-on-init, cross-process pushpull reduction,
update-on-store SGD convergence to identical weights), and writes its
observations as JSON for the parent test to compare.
"""
import json
import os
import sys

# one CPU device per process; must be configured before first backend
# initialization. jax may already be imported (sitecustomize), so flip the
# platform through jax.config as well (same pattern as tests/conftest.py).
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count"))
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as onp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.parallel import dist  # noqa: E402


def main(outdir):
    dist.initialize()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    kv = mx.kvstore.create("dist_sync")
    assert kv.type == "dist_sync"
    assert kv.num_workers == 2 and kv.rank == rank
    results = {"rank": rank}

    # init broadcasts rank0's value (reference: server holds init value)
    w = nd.array(onp.full((4,), 10.0 if rank == 0 else -99.0, dtype="float32"))
    kv.init("w", w)
    results["init_bcast"] = w.asnumpy().tolist()

    # pushpull sums across processes: rank0 sends 1s, rank1 sends 2s -> 3s
    g = nd.array(onp.full((4,), float(rank + 1), dtype="float32"))
    kv.pushpull("g", g)
    results["pushpull_sum"] = g.asnumpy().tolist()

    # update-on-store training: ranks contribute different grads each step;
    # both must converge to identical weights (the dist_sync_kvstore.py
    # invariant)
    from mxnet_tpu import optimizer as opt
    kv2 = mx.kvstore.create("dist_sync")
    kv2.set_optimizer(opt.SGD(learning_rate=0.1))
    w2 = nd.array(onp.zeros((3,), dtype="float32"))
    kv2.init(0, w2)
    rng = onp.random.RandomState(100 + rank)
    for _ in range(5):
        grad = nd.array(rng.uniform(-1, 1, size=(3,)).astype("float32"))
        kv2.push(0, grad)
        out = nd.zeros((3,))
        kv2.pull(0, out=out)
    results["trained_w"] = out.asnumpy().tolist()

    # async store: dispatch-without-block mode still reduces correctly
    kva = mx.kvstore.create("dist_async")
    a = nd.array(onp.full((2,), float(rank + 1), dtype="float32"))
    kva.pushpull("a", a)
    results["async_sum"] = a.asnumpy().tolist()

    # gradient compression ACROSS processes (reference kCompressedPushPull,
    # kvstore_dist_server.h:52): 2bit quantization with per-rank error
    # feedback applied before the cross-process reduction
    kvc = mx.kvstore.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    g1 = nd.array(onp.array([1.0, 0.2, -1.0, 0.0], "float32"))
    kvc.pushpull("cg", g1)
    # per rank quantized to [0.5, 0, -0.5, 0]; summed over 2 ranks
    results["compressed_round1"] = g1.asnumpy().tolist()
    # round 2 with zero grads: the residual [0.5, 0.2, -0.5, 0] re-emits
    # the 0.5 magnitudes (error feedback survives the process boundary)
    g2 = nd.zeros((4,))
    kvc.pushpull("cg", g2)
    results["compressed_round2"] = g2.asnumpy().tolist()

    # fused multi-key pushpull vs per-key: same sums, ~1 collective + 1
    # host sync per STEP instead of one per key (VERDICT r2 item 3;
    # reference ps-lite batching / kvstore_dist.h slicing)
    nkeys = 8
    kvf = mx.kvstore.create("dist_sync")
    gs = [nd.array(onp.full((16 + 7 * i,), float(rank + 1), "float32"))
          for i in range(nkeys)]
    kvf.pushpull_list(list(range(nkeys)), gs)
    results["fused_sums_ok"] = all(
        bool((g.asnumpy() == 3.0).all()) for g in gs)
    results["fused_stats"] = dict(kvf.stats)
    kvp = mx.kvstore.create("dist_sync")
    gs2 = [nd.array(onp.full((16 + 7 * i,), float(rank + 1), "float32"))
           for i in range(nkeys)]
    for i, g in enumerate(gs2):
        kvp.pushpull(i, g)
    results["perkey_stats"] = dict(kvp.stats)

    # Trainer end-to-end over dist_sync (VERDICT r2 item 4; reference
    # tests/nightly/dist_sync_kvstore.py:60-120): identical converged
    # weights on both ranks, equal to the serial summed-gradient run,
    # with update_on_kvstore both ways
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    def make_net():
        onp.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(8, in_units=5, activation="relu"),
                nn.Dense(1, in_units=8))
        net.initialize()
        for p in net.collect_params().values():
            p.set_data(nd.array(
                onp.random.RandomState(len(p.shape) * 13 + p.shape[0])
                .uniform(-0.5, 0.5, size=p.shape).astype("float32")))
        return net

    def batches(r, step):
        rng = onp.random.RandomState(1000 * r + step)
        x = rng.randn(6, 5).astype("float32")
        y = rng.randn(6, 1).astype("float32")
        return nd.array(x), nd.array(y)

    for upd_kv in (False, True):
        net = make_net()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore="dist_sync",
                           update_on_kvstore=upd_kv)
        for step in range(4):
            x, y = batches(rank, step)
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            tr.step(6)
        results[f"trainer_w_updkv{int(upd_kv)}"] = [
            p.data().asnumpy().ravel().tolist()
            for p in net.collect_params().values()]

    # serial reference computed locally: one net fed BOTH ranks' batches,
    # loss = L0 + L1 per step (grad == the dist summed gradient)
    net_s = make_net()
    tr_s = gluon.Trainer(net_s.collect_params(), "sgd",
                         {"learning_rate": 0.05}, kvstore="tpu",
                         update_on_kvstore=False)
    for step in range(4):
        x0, y0 = batches(0, step)
        x1, y1 = batches(1, step)
        with autograd.record():
            loss = ((net_s(x0) - y0) ** 2).mean() \
                + ((net_s(x1) - y1) ** 2).mean()
        loss.backward()
        tr_s.step(6)
    results["trainer_w_serial"] = [
        p.data().asnumpy().ravel().tolist()
        for p in net_s.collect_params().values()]

    kv.barrier()
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)
    print(f"worker {rank} done", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
