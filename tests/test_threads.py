"""Concurrency static analysis + deterministic-schedule harness
(docs/ANALYSIS.md "Concurrency analysis").

Pins the mxthreads contracts:

- the lock-order audit: nested ``mx_lock`` acquisitions form edges with
  both call sites; a planted two-lock inversion yields exactly ONE
  lock-cycle finding naming both stacks; the real codebase's observed
  graph stays cycle-free and inside the checked-in
  ``tests/fixtures/lock_hierarchy.json`` baseline (refresh: run tier-1
  with ``MXNET_REFRESH_LOCK_BASELINE=1``, review the diff, commit);
- the MXA007 (blocking under lock) / MXA008 (unguarded cross-thread
  attribute) / MXA009 (bare threading primitive) lint rules: planted
  goldens produce exactly one named finding each, inline
  ``# mx-lint: allow=`` blesses, and the framework tree sweeps clean;
- runtime deadlock forensics: a thread blocked past
  ``MXNET_LOCK_STALL_SEC`` fires exactly one ``deadlock`` watchdog
  episode anomaly and writes exactly one atomic ranked dump to
  ``MXNET_THREADS_DUMP_DIR`` (stalled thread first, owners next);
- the seeded-schedule harness: same seed replays the same
  interleaving, a planted AB/BA deadlock is caught as
  ``SchedDeadlock`` in microseconds, and the three product invariants
  hold across >= 64 seeds each with MXNET_TRANSFER_GUARD=raise and
  zero unblessed host syncs: ServingFuture exactly-once re-arm under
  replica loss, FleetRouter submit-vs-drain (accepted requests never
  hang; rejected ones fail typed), and DispatchWindow
  retire-vs-abandon (each in-flight entry retires or abandons exactly
  once) — plus the Heartbeat stop/beat double-flush regression.
"""
import glob
import json
import os
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.analysis import lint, threads
from mxnet_tpu.analysis.threads import LockOrderGraph, mx_lock, mx_rlock
from mxnet_tpu.base import MXNetError
from mxnet_tpu.elastic import detect
from mxnet_tpu.engine import DispatchWindow
from mxnet_tpu.serving import Overloaded, ServingShutdown
from mxnet_tpu.serving.batcher import ServingFuture
from mxnet_tpu.telemetry.exporters import Heartbeat
from mxnet_tpu.testing import faults
from mxnet_tpu.testing.sched import (SchedDeadlock, SchedQueue,
                                     VirtualScheduler, explore)

PKG_DIR = os.path.dirname(mx.__file__)
BASELINE = os.path.join(os.path.dirname(__file__), "fixtures",
                        "lock_hierarchy.json")
SEEDS = 64


@pytest.fixture(autouse=True)
def _clean_harness():
    """Leave the chaos harness disarmed, notices cleared and the
    watchdog episode channel re-armed for whoever runs next."""
    yield
    faults.reset()
    detect.notice().clear()
    detect.clear_scoped_notices()
    telemetry.watchdog().reset()
    import gc
    gc.collect()


# ---------------------------------------------------------------------------
# lock-order audit
# ---------------------------------------------------------------------------

def test_nested_acquire_records_edge_with_sites():
    g = LockOrderGraph()
    a = mx_lock("test.edge.a", graph=g)
    b = mx_lock("test.edge.b", graph=g)
    with a:
        with b:
            pass
    edges = g.edges()
    assert len(edges) == 1
    e = edges[0]
    assert (e["from"], e["to"]) == ("test.edge.a", "test.edge.b")
    assert e["count"] == 1
    # both call sites captured, pointing at this test file
    assert e["from_site"] and e["to_site"]
    assert "test_threads.py" in e["to_site"][0]
    # same ordering again only bumps the count
    with a:
        with b:
            pass
    assert g.edges()[0]["count"] == 2
    assert g.find_cycles() == []


def test_planted_inversion_exactly_one_cycle_finding():
    """The acceptance golden: an AB/BA inversion is ONE lock-cycle
    finding naming both locks and both acquisition stacks."""
    g = LockOrderGraph()
    a = mx_lock("test.inv.a", graph=g)
    b = mx_lock("test.inv.b", graph=g)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    findings = threads.cycle_findings(g)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "lock-cycle" and f.severity == "error"
    assert "test.inv.a" in f.message and "test.inv.b" in f.message
    assert "test_threads.py" in f.message     # the stacks are named
    assert len(g.find_cycles()) == 1


def test_rlock_reacquire_is_not_an_edge():
    g = LockOrderGraph()
    r = mx_rlock("test.re.r", graph=g)
    with r:
        with r:                  # reentrant: not an ordering event
            pass
    assert g.edges() == []


def test_check_hierarchy_flags_off_baseline_edge():
    g = LockOrderGraph()
    a = mx_lock("test.base.a", graph=g)
    b = mx_lock("test.base.b", graph=g)
    with a:
        with b:
            pass
    ok = threads.check_hierarchy({("test.base.a", "test.base.b")}, g)
    assert ok == []
    bad = threads.check_hierarchy(set(), g)
    assert len(bad) == 1 and bad[0].rule == "lock-order"
    assert "lock_hierarchy.json" in bad[0].message


def test_baseline_save_load_roundtrip(tmp_path):
    g = LockOrderGraph()
    a = mx_lock("test.rt.a", graph=g)
    b = mx_lock("test.rt.b", graph=g)
    with a:
        with b:
            pass
    p = str(tmp_path / "hier.json")
    threads.save_baseline(p, g)
    data = json.load(open(p))
    assert data["schema"] == 1
    assert threads.load_baseline(p) == {("test.rt.a", "test.rt.b")}


def test_describe_locks_and_queue_census():
    lk = mx_lock("test.desc.lk")
    import queue
    q = queue.Queue()
    q.put(1)
    threads.register_queue("test.desc.q", q)
    with lk:
        d = {l["name"]: l for l in threads.describe_locks()}
        assert d["test.desc.lk"]["held"] == 1
        assert d["test.desc.lk"]["owner"] == threading.current_thread().name
    payload = threads.dump_payload("unit")
    qd = {e["name"]: e for e in payload["queues"]}
    assert qd["test.desc.q"]["depth"] == 1


# ---------------------------------------------------------------------------
# MXA007-009 goldens
# ---------------------------------------------------------------------------

_MXA007_SRC = """
import time

class Worker:
    def step(self):
        with self._lock:
            time.sleep(0.1)
"""

_MXA007_BLESSED = """
import time

class Worker:
    def step(self):
        with self._lock:
            time.sleep(0.1)  # mx-lint: allow=MXA007
"""

_MXA008_SRC = """
import threading

class Counter:
    def __init__(self):
        self.count = 0
        self.t = threading.Thread(target=self._run)  # mx-lint: allow=MXA009

    def _run(self):
        self.count += 1

    def bump(self):
        self.count += 1
"""

_MXA008_GUARDED = """
import threading

class Counter:
    def __init__(self):
        self.count = 0
        self.t = threading.Thread(target=self._run)  # mx-lint: allow=MXA009

    def _run(self):
        with self._mu:
            self.count += 1

    def bump(self):
        with self._mu:
            self.count += 1
"""

_MXA009_SRC = "import threading\nlk = threading.Lock()\n"
_MXA009_BLESSED = ("import threading\n"
                   "lk = threading.Lock()  # mx-lint: allow=MXA009\n")


def _active(findings):
    return [f for f in findings if not f.blessed]


def test_mxa007_blocking_under_lock_exactly_one_finding():
    """The planted blocking-under-lock acceptance golden."""
    fs = _active(lint.lint_threads_source(_MXA007_SRC, "w.py"))
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "MXA007"
    assert "time.sleep" in f.message and "_lock" in f.message


def test_mxa007_inline_blessing():
    assert _active(lint.lint_threads_source(_MXA007_BLESSED, "w.py")) == []
    # the finding is still reported, just marked blessed
    all_f = lint.lint_threads_source(_MXA007_BLESSED, "w.py")
    assert any(f.rule == "MXA007" and f.blessed for f in all_f)


def test_mxa008_unguarded_shared_attr_exactly_one_finding():
    fs = _active(lint.lint_threads_source(_MXA008_SRC, "c.py"))
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "MXA008"
    assert "count" in f.message and "bump" in f.message \
        and "_run" in f.message


def test_mxa008_lock_guard_silences():
    assert _active(lint.lint_threads_source(_MXA008_GUARDED, "c.py")) == []


def test_mxa009_bare_primitive_and_blessing():
    fs = _active(lint.lint_threads_source(_MXA009_SRC, "m.py"))
    assert len(fs) == 1 and fs[0].rule == "MXA009"
    assert "mx_lock" in fs[0].message
    assert _active(lint.lint_threads_source(_MXA009_BLESSED, "m.py")) == []


@pytest.mark.lint
def test_framework_tree_thread_lint_clean():
    """MXA007-009 over the whole mxnet_tpu/ tree: zero unblessed
    findings (every legitimate bare lock / benign race carries an
    inline blessing with its why-comment)."""
    findings = _active(lint.lint_threads_path(PKG_DIR))
    assert not findings, "unblessed thread-lint findings:\n" + \
        "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# deadlock forensics (stall detector + ranked dump)
# ---------------------------------------------------------------------------

def test_planted_stall_one_anomaly_one_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_LOCK_STALL_SEC", "0.12")
    monkeypatch.setenv("MXNET_THREADS_DUMP_DIR", str(tmp_path))
    wd = telemetry.watchdog()
    wd.reset()
    dumps0 = telemetry.value(telemetry.names.THREADS_DUMPS) or 0
    lk = mx_lock("test.stall.planted")
    release = threading.Event()

    def holder():
        with lk:
            release.wait(5.0)

    def waiter():
        with lk:
            pass

    h = threading.Thread(target=holder, name="stall-holder", daemon=True)
    h.start()
    for _ in range(500):
        if lk.locked():
            break
        time.sleep(0.005)
    assert lk.locked()
    w = threading.Thread(target=waiter, name="stall-waiter", daemon=True)
    w.start()
    time.sleep(0.4)              # well past the 0.12 s stall threshold
    release.set()
    h.join(5.0)
    w.join(5.0)
    assert not h.is_alive() and not w.is_alive()

    evs = wd.anomalies("deadlock")
    assert len(evs) == 1, evs    # one episode, however long the stall
    msg = evs[0]["message"]
    assert "test.stall.planted" in msg
    assert "stall-waiter" in msg and "stall-holder" in msg
    assert evs[0]["value"] >= 0.12

    paths = sorted(glob.glob(os.path.join(str(tmp_path),
                                          "mx-threads-*.json")))
    assert len(paths) == 1, paths
    assert not glob.glob(os.path.join(str(tmp_path), "*.tmp"))
    payload = json.load(open(paths[0]))
    assert payload["schema"] == 1
    assert payload["kind"] == "deadlock"
    assert payload["stalled"]["lock"] == "test.stall.planted"
    assert payload["stalled"]["thread"] == "stall-waiter"
    assert payload["stalled"]["owner"] == "stall-holder"
    # ranked: the stalled thread leads, the owner next, with stacks
    assert payload["threads"][0]["name"] == "stall-waiter"
    names_ranked = [t["name"] for t in payload["threads"]]
    assert names_ranked.index("stall-waiter") \
        < names_ranked.index("stall-holder")
    assert (telemetry.value(telemetry.names.THREADS_DUMPS) or 0) \
        - dumps0 == 1
    # the resolved stall re-armed the episode channel
    assert wd.episode("deadlock", True, message="re-armed?") is True
    wd.reset()


def test_stall_detector_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_LOCK_STALL_SEC", raising=False)
    assert threads.stall_seconds() == 0.0
    monkeypatch.setenv("MXNET_LOCK_STALL_SEC", "not-a-number")
    assert threads.stall_seconds() == 0.0
    monkeypatch.setenv("MXNET_LOCK_STALL_SEC", "-3")
    assert threads.stall_seconds() == 0.0


# ---------------------------------------------------------------------------
# the harness itself
# ---------------------------------------------------------------------------

def _contender(lk, log, tag):
    for _ in range(3):
        with lk:
            log.append(tag)


def _one_contended_schedule(seed):
    g = LockOrderGraph()
    lk = mx_lock("test.sched.contend", graph=g)
    log = []
    s = VirtualScheduler(seed=seed, name="det")
    s.spawn("a", _contender, lk, log, "a")
    s.spawn("b", _contender, lk, log, "b")
    s.run()
    return log, list(s.trace)


@pytest.mark.sched
def test_same_seed_replays_same_interleaving():
    assert _one_contended_schedule(7) == _one_contended_schedule(7)
    outcomes = {tuple(_one_contended_schedule(i)[0]) for i in range(16)}
    assert len(outcomes) > 1     # the sweep actually varies the order


@pytest.mark.sched
def test_planted_ab_ba_deadlock_caught_virtually():
    g = LockOrderGraph()
    wedged = 0
    for seed in range(16):
        a = mx_lock("test.dl.a", graph=g)
        b = mx_lock("test.dl.b", graph=g)

        def ab(a=a, b=b):
            with a:
                with b:
                    pass

        def ba(a=a, b=b):
            with b:
                with a:
                    pass

        s = VirtualScheduler(seed=seed, name="dl")
        s.spawn("ab", ab)
        s.spawn("ba", ba)
        try:
            s.run()
        except SchedDeadlock as e:
            wedged += 1
            assert "test.dl" in str(e) and f"seed={seed}" in str(e)
    # some schedules serialize cleanly; several must wedge — and they
    # wedge VIRTUALLY (this test finishes in milliseconds, no hang)
    assert wedged > 0
    # the static audit sees the same inversion as one cycle
    assert len(threads.cycle_findings(g)) == 1


@pytest.mark.sched
def test_sched_queue_fifo_across_schedules():
    def build(s):
        q = SchedQueue(maxsize=2)
        got = []

        def producer():
            for i in range(4):
                q.put(i)         # maxsize 2: put blocks virtually

        def consumer():
            for _ in range(4):
                got.append(q.get())

        s.spawn("producer", producer)
        s.spawn("consumer", consumer)

        def check(_s):
            assert got == [0, 1, 2, 3]
        return check

    assert explore(build, seeds=16, name="q") == 16


# ---------------------------------------------------------------------------
# product invariants under the harness
# ---------------------------------------------------------------------------

@pytest.mark.sched
def test_heartbeat_beat_vs_stop_never_flushes_after_stop(
        tmp_path, monkeypatch):
    """The telemetry double-flush regression: beat() racing stop()
    (the atexit-flush shape) is serialized — at most one beat lands,
    the Prometheus file exists iff a beat won, nothing writes after
    stop() returned, stop is idempotent, restart is a typed error."""
    path = str(tmp_path / "prom.txt")
    monkeypatch.setenv("MXNET_PROMETHEUS_FILE", path)

    def build(s):
        if os.path.exists(path):
            os.remove(path)
        hb = Heartbeat(interval=60.0)    # never started: no real daemon

        s.spawn("beat", hb.beat)
        s.spawn("stop", hb.stop)

        def check(_s):
            assert hb.beats in (0, 1)
            assert os.path.exists(path) == (hb.beats == 1)
            beats = hb.beats
            hb.beat()                    # no-op once stopped
            assert hb.beats == beats
            assert os.path.exists(path) == (beats == 1)
            hb.stop()                    # idempotent
            with pytest.raises(MXNetError):
                hb.start()               # threads cannot be restarted
        return check

    assert explore(build, seeds=SEEDS, name="hb") == SEEDS


@pytest.mark.sched
def test_future_rearm_exactly_once_under_replica_loss(monkeypatch):
    """Satellite invariant 1: a supervised future whose first batch is
    lost to a device failure is re-armed exactly once and every
    client observes ONLY the recovered result — never the poisoned
    buffers, never a hang, across the full schedule sweep."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    sync0 = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0

    def build(s):
        fut = ServingFuture()
        fut._supervised = True
        out = {}

        def bad_build():
            raise MXNetError("device lost: planted")

        def dispatcher():
            # the realistic ordering: resolve against the doomed
            # batch, then the supervisor's recovery re-arms and
            # re-resolves — all on the dispatcher thread, racing the
            # client's result() arbitrarily
            fut._resolve(bad_build)
            fut._rearm()
            fut._resolve(lambda: "recovered")

        def client():
            out["r"] = fut.result()

        s.spawn("dispatcher", dispatcher)
        s.spawn("client", client)

        def check(_s):
            assert out == {"r": "recovered"}
            assert fut._epoch == 1       # re-armed exactly once
            assert fut._err is None and fut.done()
        return check

    assert explore(build, seeds=SEEDS, name="rearm") == SEEDS
    assert (telemetry.value(telemetry.names.HOST_SYNCS, "wait_to_read")
            or 0) - sync0 == 0


class _FakePredictor:
    """Minimal predictor honoring the DynamicBatcher contract: shape
    buckets + an identity predict (no device work, no host sync)."""

    bucket_sizes = (1, 2, 4)
    n_traces = 0
    service_time_seed_s = None

    def bucket_for(self, rows):
        for b in self.bucket_sizes:
            if rows <= b:
                return b
        raise MXNetError(f"no bucket for {rows} rows")

    def predict(self, *args):
        return args[0]


@pytest.mark.sched
def test_fleet_submit_vs_drain_accepted_never_hangs(monkeypatch):
    """Satellite invariant 2: a router submit racing a fleet drain
    either lands on exactly one replica (and its future RESOLVES —
    the drain flushes accepted work) or fails typed
    (Overloaded/ServingShutdown). No schedule leaves an accepted
    future undone, and the serving path stays sync-free."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    sync0 = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0
    x = onp.zeros((1, 3), "float32")

    def build(s):
        clk = [0.0]
        fleet = serving.FleetController(
            _FakePredictor, example=None, replicas=2, max_batch=4,
            timeout_ms=5.0, clock=lambda: clk[0], start=False)
        out = {}

        def client():
            try:
                out["fut"] = fleet.router.submit(x)
            except (Overloaded, ServingShutdown) as e:
                out["err"] = e

        def drainer():
            fleet.drain()

        s.spawn("client", client)
        s.spawn("drainer", drainer)

        def check(_s):
            assert len(out) == 1         # exactly one terminal state
            if "err" in out:
                return                   # typed rejection: fine
            fut = out["fut"]
            assert fut.replica in ("replica-0", "replica-1")
            # the drain flushed it: done WITHOUT any further pumping
            assert fut.done()
            try:
                res = fut.result(timeout=0)
            except ServingShutdown:
                return                   # failed typed at the drain
            leaf = res if not isinstance(res, (tuple, list)) else res[0]
            assert leaf._data.shape[0] == 1
            for rep in fleet.replicas:
                assert len(rep.sup.batcher._window) == 0
        return check

    assert explore(build, seeds=SEEDS, name="fleet") == SEEDS
    assert (telemetry.value(telemetry.names.HOST_SYNCS, "wait_to_read")
            or 0) - sync0 == 0


@pytest.mark.sched
def test_window_retire_vs_abandon_each_step_exactly_once(monkeypatch):
    """Satellite invariant 3: a recovery abandon racing a drain — each
    in-flight entry is retired (synced) XOR abandoned, every one
    accounted for, none twice."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    sync0 = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0

    def build(s):
        synced = []
        w = DispatchWindow(max_inflight=8, sync_fn=synced.append,
                           what="sched probe")
        for i in range(3):
            w.push(i, tag=i)
        abandoned = []

        def drainer():
            w.drain()

        def abandoner():
            abandoned.extend(w.abandon())

        s.spawn("drainer", drainer)
        s.spawn("abandoner", abandoner)

        def check(_s):
            assert len(w) == 0
            assert w.stats["retires"] == len(synced)
            assert w.stats.get("abandoned", 0) == len(abandoned)
            assert sorted(synced + abandoned) == [0, 1, 2]
            assert w.stats["errors"] == 0
        return check

    assert explore(build, seeds=SEEDS, name="window") == SEEDS
    assert (telemetry.value(telemetry.names.HOST_SYNCS, "wait_to_read")
            or 0) - sync0 == 0


# ---------------------------------------------------------------------------
# the checked-in hierarchy (keep LAST: it audits the graph every test
# above — and, under tier-1, every test before this file — fed)
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_lock_hierarchy_cycle_free_and_within_baseline():
    """The process-global graph accumulated by the suite so far must be
    cycle-free and inside tests/fixtures/lock_hierarchy.json. A NEW
    legitimate edge (you added a nested acquisition): review it, then
    refresh the baseline by running tier-1 with
    ``MXNET_REFRESH_LOCK_BASELINE=1`` and committing the diff."""
    if os.environ.get("MXNET_REFRESH_LOCK_BASELINE"):
        threads.save_baseline(BASELINE)
        pytest.skip("lock_hierarchy.json refreshed from the observed "
                    "graph — review the diff and commit")
    cycles = threads.find_cycles()
    assert not cycles, f"lock-order cycles in the live graph: {cycles}"
    findings = threads.check_hierarchy(threads.load_baseline(BASELINE))
    assert not findings, "\n".join(str(f) for f in findings)
