"""gluon.contrib layer zoo (reference
python/mxnet/gluon/contrib/nn/basic_layers.py + contrib/rnn/): Concurrent,
PixelShuffle1/2/3D, the nine Conv RNN/LSTM/GRU cells, VariationalDropoutCell,
LSTMPCell — shape and gradient checks per class."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn


# ---------------------------------------------------------------------------
# contrib.nn
# ---------------------------------------------------------------------------

def test_concurrent_concats_branch_outputs():
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3), nn.Dense(4), cnn.Identity())
    net.initialize()
    x = nd.array(onp.ones((2, 5), "float32"))
    out = net(x)
    assert out.shape == (2, 3 + 4 + 5)
    # Identity branch passes the raw input through
    onp.testing.assert_allclose(out.asnumpy()[:, 7:], onp.ones((2, 5)))


def test_sparse_embedding_contrib_alias():
    emb = cnn.SparseEmbedding(20, 4)
    emb.initialize()
    with autograd.record():
        out = emb(nd.array(onp.array([1, 3], "int32")))
        loss = (out * out).sum()
    loss.backward()
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    assert isinstance(emb.weight.grad(), RowSparseNDArray)


def _pixel_shuffle_ref(x, factors):
    """Independent numpy model of the reference semantics: channel group
    c*prod(f)+block-index maps onto the upsampled spatial grid."""
    n, c_in, *sp = x.shape
    f = list(factors)
    c = c_in // int(onp.prod(f))
    y = x.reshape([n, c] + f + sp)
    # interleave: (N, C, f1..fk, s1..sk) -> (N, C, s1, f1, ..., sk, fk)
    k = len(f)
    perm = [0, 1]
    for i in range(k):
        perm.extend([2 + k + i, 2 + i])
    y = y.transpose(perm)
    return y.reshape([n, c] + [s * ff for s, ff in zip(sp, f)])


@pytest.mark.parametrize("cls,factors,shape", [
    (cnn.PixelShuffle1D, (2,), (1, 8, 3)),
    (cnn.PixelShuffle2D, (2, 3), (1, 12, 3, 5)),
    (cnn.PixelShuffle3D, (2, 3, 4), (1, 48, 3, 5, 7)),
])
def test_pixel_shuffle_matches_reference_semantics(cls, factors, shape):
    arg = factors[0] if len(factors) == 1 else factors
    ps = cls(arg)
    x = onp.arange(onp.prod(shape), dtype="float32").reshape(shape)
    got = ps(nd.array(x)).asnumpy()
    onp.testing.assert_array_equal(got, _pixel_shuffle_ref(x, list(factors)))


def test_pixel_shuffle_differentiable():
    ps = cnn.PixelShuffle2D(2)
    x = nd.array(onp.random.RandomState(0)
                 .randn(1, 8, 2, 2).astype("float32"))
    x.attach_grad()
    with autograd.record():
        loss = (ps(x) ** 2).sum()
    loss.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_sync_batch_norm_block_exported():
    bn = cnn.SyncBatchNorm(in_channels=4, num_devices=2)
    bn.initialize()
    x = nd.array(onp.random.RandomState(1)
                 .randn(3, 4, 5, 5).astype("float32"))
    with autograd.record():
        out = bn(x)
    assert out.shape == x.shape
    m = out.asnumpy().mean(axis=(0, 2, 3))
    onp.testing.assert_allclose(m, onp.zeros(4), atol=1e-4)


# ---------------------------------------------------------------------------
# contrib.rnn — conv cells
# ---------------------------------------------------------------------------

_CONV_CASES = [
    (crnn.Conv1DRNNCell, (2, 10), (4, 2, 10), 1),
    (crnn.Conv2DRNNCell, (2, 6, 7), (4, 2, 6, 7), 1),
    (crnn.Conv3DRNNCell, (1, 4, 4, 4), (2, 1, 4, 4, 4), 1),
    (crnn.Conv1DLSTMCell, (2, 10), (4, 2, 10), 2),
    (crnn.Conv2DLSTMCell, (2, 6, 7), (4, 2, 6, 7), 2),
    (crnn.Conv3DLSTMCell, (1, 4, 4, 4), (2, 1, 4, 4, 4), 2),
    (crnn.Conv1DGRUCell, (2, 10), (4, 2, 10), 1),
    (crnn.Conv2DGRUCell, (2, 6, 7), (4, 2, 6, 7), 1),
    (crnn.Conv3DGRUCell, (1, 4, 4, 4), (2, 1, 4, 4, 4), 1),
]


@pytest.mark.parametrize("cls,ishape,xshape,nstates", _CONV_CASES)
def test_conv_cell_shapes_and_grads(cls, ishape, xshape, nstates):
    hidden = 3
    cell = cls(input_shape=ishape, hidden_channels=hidden,
               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    batch = xshape[0]
    x = nd.array(onp.random.RandomState(0).randn(*xshape)
                 .astype("float32") * 0.3)
    states = cell.begin_state(batch)
    assert len(states) == nstates
    with autograd.record():
        out, next_states = cell(x, states)
        loss = (out ** 2).sum()
    loss.backward()
    # SAME-padded convs: state keeps the spatial shape, channels -> hidden
    assert out.shape == (batch, hidden) + xshape[2:]
    assert len(next_states) == nstates
    for s in next_states:
        assert s.shape == (batch, hidden) + xshape[2:]
    for name, p in cell.collect_params().items():
        g = p.grad().asnumpy()
        assert onp.isfinite(g).all(), name
        if "i2h" in name:  # input path must carry signal
            assert onp.abs(g).max() > 0, name


def test_conv_cell_unroll_three_steps():
    cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=4,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    seq = nd.array(onp.random.RandomState(2)
                   .randn(2, 3, 3, 8, 8).astype("float32"))
    outs, states = cell.unroll(3, seq, layout="NTC")
    assert len(outs) == 3 and outs[0].shape == (2, 4, 8, 8)
    assert len(states) == 2


def test_conv_cell_rejects_even_h2h_kernel_and_bad_layout():
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        crnn.Conv2DRNNCell(input_shape=(2, 6, 6), hidden_channels=2,
                           i2h_kernel=3, h2h_kernel=2)
    with pytest.raises(MXNetError):
        crnn.Conv2DRNNCell(input_shape=(2, 6, 6), hidden_channels=2,
                           i2h_kernel=3, h2h_kernel=3, conv_layout="NHWC")


# ---------------------------------------------------------------------------
# contrib.rnn — VariationalDropoutCell / LSTMPCell
# ---------------------------------------------------------------------------

def test_variational_dropout_mask_locked_until_reset():
    from mxnet_tpu.gluon import rnn as grnn
    base = grnn.RNNCell(6, input_size=6)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5,
                                       drop_states=0.4, drop_outputs=0.3)
    cell.initialize()
    x = nd.array(onp.ones((2, 6), "float32"))
    with autograd.record():
        st = cell.begin_state(2)
        _, st = cell(x, st)
        masks1 = [m.asnumpy() for m in (cell.drop_inputs_mask,
                                        cell.drop_states_mask,
                                        cell.drop_outputs_mask)]
        _, st = cell(x, st)
        masks2 = [m.asnumpy() for m in (cell.drop_inputs_mask,
                                        cell.drop_states_mask,
                                        cell.drop_outputs_mask)]
    for m1, m2 in zip(masks1, masks2):
        onp.testing.assert_array_equal(m1, m2)  # time-locked
    cell.reset()
    assert cell.drop_inputs_mask is None
    assert cell.drop_states_mask is None
    assert cell.drop_outputs_mask is None


def test_variational_dropout_identity_outside_training():
    from mxnet_tpu.gluon import rnn as grnn
    base = grnn.RNNCell(4, input_size=4)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.9)
    cell.initialize()
    x = nd.array(onp.ones((2, 4), "float32"))
    out_plain, _ = base(x, base.begin_state(2))
    out_wrapped, _ = cell(x, cell.begin_state(2))
    # inference mode: Dropout is identity, wrapper output == base output
    onp.testing.assert_allclose(out_wrapped.asnumpy(), out_plain.asnumpy(),
                                rtol=1e-6)


def test_lstmp_projection_shapes_grads_and_unroll():
    cell = crnn.LSTMPCell(hidden_size=16, projection_size=5)
    cell.initialize()
    x = nd.array(onp.random.RandomState(3).randn(4, 8).astype("float32"))
    with autograd.record():
        out, states = cell(x, cell.begin_state(4))
        loss = (out ** 2).sum()
    loss.backward()
    assert out.shape == (4, 5)            # projected
    assert states[0].shape == (4, 5)      # r
    assert states[1].shape == (4, 16)     # c
    assert cell.h2r_weight.shape == (5, 16)
    for name, p in cell.collect_params().items():
        assert onp.isfinite(p.grad().asnumpy()).all(), name
    seq = nd.array(onp.random.RandomState(4)
                   .randn(4, 3, 8).astype("float32"))
    outs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (4, 3, 5)
