"""Serving fleet controller (docs/SERVING.md "Serving fleet").

Pins the fleet contracts on top of the single-replica resilience stack:

- least-projected-wait routing with ``fut.replica``/``fut.version``
  breadcrumbs; open breakers / draining / retired replicas get ZERO new
  requests; all replicas unavailable is a typed
  ``Overloaded(reason="fleet")``, never a hang;
- replica-loss failover: a dead replica's in-flight + queued requests
  re-enqueue EXACTLY once onto the survivors, the replica restarts on a
  spare device (one ``mx_fleet_replica_restarts_total`` increment), a
  request lost twice fails typed;
- scoped preemption notices drain exactly the named replica; the
  process-global notice drains every replica (all on a fake clock);
- zero-downtime rolling weight swap: validated-first checkpoints, one
  replica draining at a time (<= 1 version of skew), zero dropped
  accepted requests, post-swap outputs bit-exact vs a fresh predictor,
  corrupt checkpoints abort typed with the OLD weights serving;
- autoscaling up/down against the queue-wait EWMA watermarks;
- the satellites: warmup-seeded admission EWMA, per-token deadline
  re-projection in the decode engine (pages returned), loadgen
  per-replica census, and the ``mx_fleet_*`` catalog entries;
- the chaos acceptance: 3 replicas, a replica-targeted device
  revocation mid-burst under MXNET_TRANSFER_GUARD=raise — zero lost
  accepted requests, zero hangs, exactly one restart, zero unblessed
  syncs.
"""
import os
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import CheckpointCorruptError, atomic
from mxnet_tpu.checkpoint.state import capture_train_state
from mxnet_tpu.elastic import detect
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import loadgen
from mxnet_tpu.serving.fleet import _Replica
from mxnet_tpu.testing import faults

IN, HIDDEN, CLASSES = 16, 32, 4
BUCKETS = (1, 2, 4)


@pytest.fixture(autouse=True, scope="module")
def _shared_compile_cache(tmp_path_factory):
    """One MXNET_COMPILE_CACHE for the whole module: the first
    predictor compiles each bucket once, every later build (and every
    fleet replica — warm spawn is the product behavior) AOT-warm-starts
    from it. Fresh dir per interpreter run (reuse across runs is the
    known segfault trap)."""
    path = str(tmp_path_factory.mktemp("fleet-compile-cache"))
    old = os.environ.get("MXNET_COMPILE_CACHE")
    os.environ["MXNET_COMPILE_CACHE"] = path
    yield
    if old is None:
        os.environ.pop("MXNET_COMPILE_CACHE", None)
    else:
        os.environ["MXNET_COMPILE_CACHE"] = old


@pytest.fixture(autouse=True)
def _clean_harness():
    """Every test leaves the chaos harness disarmed, devices restored,
    and every (scoped) preemption notice cleared. The gc.collect keeps
    fleet garbage (threads, device buffers) from billing a GC pause to
    a later test's step-time watchdog."""
    yield
    faults.reset()
    detect.notice().clear()
    detect.clear_scoped_notices()
    import gc
    gc.collect()


def make_net(seed=7):
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(HIDDEN, activation="relu", in_units=IN),
            nn.Dense(CLASSES, in_units=HIDDEN))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, IN), "float32")))
    return net


def build_pred(seed=7):
    # deterministic, per the build() contract: every (re)build must
    # produce the same params, so failover/restart is bit-exact
    return serving.CompiledPredictor(make_net(seed), bucket_sizes=BUCKETS)


def rows(n, seed=0):
    return onp.random.RandomState(seed).randn(n, IN).astype("float32")


def make_fleet(clk, n=3, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("timeout_ms", 5.0)
    return serving.FleetController(
        build_pred, example=(mx.nd.array(rows(1)),), replicas=n,
        clock=lambda: clk[0], start=False, **kw)


def seed_waits(fleet, waits):
    """Pin each replica's admission EWMA so routing is deterministic."""
    for rep, w in zip(fleet.replicas, waits):
        rep.sup.batcher._ewma_service = w


def pump_until_done(fleet, futs, rounds=50):
    for _ in range(rounds):
        if all(f.done() for f in futs):
            return
        fleet.pump(force=True)
    raise AssertionError("futures did not resolve under pump()")


# ---------------------------------------------------------------------------
# env accessors
# ---------------------------------------------------------------------------

def test_fleet_env_parsing(monkeypatch):
    for var in ("MXNET_FLEET_REPLICAS", "MXNET_FLEET_MIN_REPLICAS",
                "MXNET_FLEET_MAX_REPLICAS", "MXNET_FLEET_SCALE_UP_WAIT_MS",
                "MXNET_FLEET_SCALE_DOWN_WAIT_MS",
                "MXNET_FLEET_RESTART_RETRIES"):
        monkeypatch.delenv(var, raising=False)
    assert serving.fleet_replicas() == 1
    assert serving.fleet_min_replicas() == 1
    assert serving.fleet_max_replicas() == 0
    assert serving.fleet_scale_up_wait_s() == pytest.approx(0.2)
    assert serving.fleet_scale_down_wait_s() == pytest.approx(0.005)
    assert serving.fleet_restart_retries() == 2
    monkeypatch.setenv("MXNET_FLEET_REPLICAS", "3")
    monkeypatch.setenv("MXNET_FLEET_SCALE_UP_WAIT_MS", "50")
    monkeypatch.setenv("MXNET_FLEET_SCALE_DOWN_WAIT_MS", "-1")
    monkeypatch.setenv("MXNET_FLEET_RESTART_RETRIES", "0")
    assert serving.fleet_replicas() == 3
    assert serving.fleet_scale_up_wait_s() == pytest.approx(0.05)
    assert serving.fleet_scale_down_wait_s() < 0      # disables
    assert serving.fleet_restart_retries() == 0
    monkeypatch.setenv("MXNET_FLEET_REPLICAS", "junk")
    assert serving.fleet_replicas() == 1


def test_fleet_rejects_more_replicas_than_devices():
    import jax
    too_many = len(jax.devices()) + 1
    with pytest.raises(MXNetError, match="device"):
        serving.FleetController(build_pred, replicas=too_many,
                                start=False)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_router_picks_lowest_projected_wait():
    clk = [0.0]
    fleet = make_fleet(clk, 3)
    try:
        seed_waits(fleet, [0.5, 0.001, 0.5])
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        assert fut.replica == "replica-1"
        assert fut.version == 0
        assert fleet.stats["routed"] == 1
        # the emptiest changes as queues build: replica-1 now holds a
        # request, so a far-cheaper peer wins the next decision
        seed_waits(fleet, [0.5, 0.5, 0.0001])
        fut2 = fleet.router.submit(mx.nd.array(rows(1, seed=1)))
        assert fut2.replica == "replica-2"
        pump_until_done(fleet, [fut, fut2])
        assert fut.result(10).shape == (1, CLASSES)
        assert fut2.result(10).shape == (1, CLASSES)
    finally:
        fleet.close()


def test_router_skips_open_breaker_zero_new_requests():
    """An open breaker gets ZERO new routed requests — the router
    filters it out entirely (no admission attempt, no queue entry)."""
    clk = [0.0]
    fleet = make_fleet(clk, 3)
    try:
        seed_waits(fleet, [0.001, 0.5, 0.5])   # victim would win
        victim = fleet.replicas[0]
        victim.sup.breaker.trip("test")
        assert not victim.routable()
        for i in range(4):
            fut = fleet.router.submit(mx.nd.array(rows(1, seed=i)))
            assert fut.replica != victim.name
        assert victim.sup.batcher._queue.qsize() == 0
        assert len(victim.sup.batcher._forming) == 0
        assert (telemetry.value(telemetry.names.FLEET_ROUTED,
                                victim.name) or 0) == 0
        victim.sup.breaker.close()
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        assert fut.replica == victim.name      # back in rotation
    finally:
        fleet.close()


def test_router_all_unavailable_is_typed_overloaded():
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        rej0 = fleet.stats["rejected_fleet"]
        for rep in fleet.replicas:
            rep.sup.breaker.trip("test")
        with pytest.raises(serving.Overloaded, match="no replica") as ei:
            fleet.router.submit(mx.nd.array(rows(1)))
        assert ei.value.reason == "fleet"
        assert isinstance(ei.value, MXNetError)
        assert fleet.stats["rejected_fleet"] == rej0 + 1
    finally:
        fleet.close()


def test_router_falls_through_replica_rejection():
    """A replica that sheds at admission is skipped; the next candidate
    serves. Every replica rejecting surfaces as reason='fleet'."""
    clk = [0.0]
    fleet = make_fleet(clk, 2, depth=1)
    try:
        a, b = fleet.replicas
        seed_waits(fleet, [0.001, 0.5])
        # saturate a's queue so its admission rejects (shed=queue style:
        # depth 1, one rider waiting, submit with timeout=0)
        a.sup.batcher._queue.put_nowait(
            object.__new__(type("X", (), {})))  # placeholder occupies depth
        fut = fleet.router.submit(mx.nd.array(rows(1)), timeout=0.01)
        assert fut.replica == b.name
    finally:
        a.sup.batcher._drain_queue()
        fleet.close()


def test_route_fault_point_targets_one_replica():
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        seed_waits(fleet, [0.001, 0.5])
        faults.configure("serving.route@replica-0:before=1:error")
        with pytest.raises(faults.FaultInjectedError):
            fleet.router.submit(mx.nd.array(rows(1)))
        faults.configure(None)
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        assert fut.replica == "replica-0"      # untargeted peer unharmed
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# replica-loss failover (manual drive, fake clock)
# ---------------------------------------------------------------------------

def test_failover_moves_riders_exactly_once_and_restarts():
    N = 6
    X = rows(N, seed=3)
    singles = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    clk = [0.0]
    restarts0 = telemetry.value(telemetry.names.FLEET_RESTARTS) or 0
    fleet = make_fleet(clk, 3)
    try:
        victim = fleet.replicas[2]
        old_device = victim.device
        seed_waits(fleet, [0.5, 0.5, 0.001])   # all traffic -> victim
        futs = [fleet.router.submit(mx.nd.array(X[i:i + 1]))
                for i in range(N)]
        assert all(f.replica == victim.name for f in futs)
        faults.configure(f"serving.dispatch@{victim.name}:before=1"
                         f":revoke:d{victim.device.id}")
        pump_until_done(fleet, futs)
        outs = [f.result(10).asnumpy() for f in futs]
        for i in range(N):                     # failover preserves answers
            assert (outs[i] == singles[i]).all()
        assert fleet.stats["failovers"] == 1
        assert fleet.stats["requeued"] >= 1
        assert fleet.stats["failed_requeues"] == 0
        assert fleet.stats["restarts"] == 1
        assert (telemetry.value(telemetry.names.FLEET_RESTARTS) or 0) \
            - restarts0 == 1
        # restarted on a spare device, serving again, fresh breaker
        assert victim.state == _Replica.SERVING
        assert victim.device != old_device
        assert victim.sup.breaker.state == "closed"
        kinds = [e.kind for e in fleet.events if e.replica == victim.name]
        assert kinds[-3:] == ["replica_lost", "failover", "restart"]
        # riders carry the survivor breadcrumb after the re-arm
        assert all(f.replica != victim.name or f.done() for f in futs)
        # post-restart traffic flows through the revived replica
        seed_waits(fleet, [0.5, 0.5, 0.001])
        late = fleet.router.submit(mx.nd.array(X[:1]))
        assert late.replica == victim.name
        pump_until_done(fleet, [late])
        assert (late.result(10).asnumpy() == singles[0]).all()
    finally:
        fleet.close()


def test_request_lost_twice_fails_typed():
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        a, b = fleet.replicas
        seed_waits(fleet, [0.001, 0.5])
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        assert fut.replica == a.name
        faults.configure(
            f"serving.dispatch@{a.name}:before=1:revoke:d{a.device.id};"
            f"serving.dispatch@{b.name}:before=1:revoke:d{b.device.id}")
        for _ in range(20):
            if fut.done():
                break
            fleet.pump(force=True)
        with pytest.raises(MXNetError, match="repeated device"):
            fut.result(5)
        assert fleet.stats["failed_requeues"] == 1
        assert fleet.stats["failovers"] == 2
    finally:
        fleet.close()


def test_restart_exhaustion_retires_replica(monkeypatch):
    """Every restart attempt failing (world shrank to nothing spare)
    retires the replica with the error recorded — no infinite loop."""
    monkeypatch.setenv("MXNET_FLEET_RESTART_RETRIES", "1")
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        a = fleet.replicas[0]
        monkeypatch.setattr(fleet, "_pick_device",
                            lambda exclude=None: None)
        seed_waits(fleet, [0.001, 0.5])
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        faults.configure(f"serving.dispatch@{a.name}:before=1"
                         f":revoke:d{a.device.id}")
        pump_until_done(fleet, [fut])          # rider lands on survivor
        assert fut.result(10).shape == (1, CLASSES)
        assert a.state == _Replica.RETIRED
        assert isinstance(a.error, MXNetError)
        assert any(e.kind == "restart_failed" for e in fleet.events)
        assert fleet.stats["restarts"] == 0
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# scoped preemption drain (fake clock)
# ---------------------------------------------------------------------------

def test_scoped_notice_drains_only_named_replica():
    clk = [0.0]
    fleet = make_fleet(clk, 3)
    try:
        target = fleet.replicas[1]
        seed_waits(fleet, [0.5, 0.001, 0.5])
        futs = [fleet.router.submit(mx.nd.array(rows(1, seed=i)))
                for i in range(3)]
        assert all(f.replica == target.name for f in futs)
        detect.notice(target.scope).trigger()
        fleet.poll()                           # manual-mode drain
        assert target.state == _Replica.RETIRED
        for f in futs:                         # accepted requests land
            assert f.result(10).shape == (1, CLASSES)
        others = [r for r in fleet.replicas if r is not target]
        assert all(r.state == _Replica.SERVING for r in others)
        # the survivors still serve routed traffic
        fut = fleet.router.submit(mx.nd.array(rows(1)))
        assert fut.replica != target.name
        pump_until_done(fleet, [fut])
        assert fut.result(10).shape == (1, CLASSES)
        kinds = [(e.kind, e.replica) for e in fleet.events
                 if e.kind in ("drain", "retire")]
        assert kinds == [("drain", target.name), ("retire", target.name)]
    finally:
        fleet.close()


def test_global_notice_drains_every_replica():
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        detect.notice().trigger()
        fleet.poll()
        assert all(r.state == _Replica.RETIRED for r in fleet.replicas)
        with pytest.raises(serving.Overloaded) as ei:
            fleet.router.submit(mx.nd.array(rows(1)))
        assert ei.value.reason == "fleet"
    finally:
        detect.notice().clear()
        fleet.close()


def test_training_supervisor_ignores_scoped_notices():
    """A replica-scoped notice must never pause training: the elastic
    supervisor polls only the process-global notice."""
    detect.notice("fleet/replica-0").trigger()
    assert detect.notice("fleet/replica-0").requested()
    assert not detect.notice().requested()
    detect.clear_scoped_notices()
    assert not detect.notice("fleet/replica-0").requested()
    # and the global notice reaches scoped listeners (drain everything)
    detect.notice().trigger()
    assert detect.notice("fleet/replica-0").requested()


# ---------------------------------------------------------------------------
# autoscaling (fake clock)
# ---------------------------------------------------------------------------

def test_autoscale_up_and_down(monkeypatch):
    monkeypatch.setenv("MXNET_FLEET_SCALE_UP_WAIT_MS", "100")
    monkeypatch.setenv("MXNET_FLEET_SCALE_DOWN_WAIT_MS", "5")
    clk = [0.0]
    fleet = make_fleet(clk, 2, min_replicas=1, max_replicas=3)
    try:
        fleet.queue_wait_ewma = 0.5            # way past the high water
        assert fleet.maybe_scale() == "up"
        assert len([r for r in fleet.replicas
                    if r.state == _Replica.SERVING]) == 3
        assert fleet.stats["scale_ups"] == 1
        assert fleet.maybe_scale() is None     # at max_replicas
        fleet.queue_wait_ewma = 0.001          # idle below the low water
        assert fleet.maybe_scale() == "down"
        assert fleet.stats["scale_downs"] == 1
        serving_now = [r for r in fleet.replicas
                       if r.state == _Replica.SERVING]
        assert len(serving_now) == 2
        fleet.queue_wait_ewma = 0.001
        fleet.maybe_scale()
        fleet.queue_wait_ewma = 0.001
        assert fleet.maybe_scale() is None     # floor: min_replicas=1
        assert len([r for r in fleet.replicas
                    if r.state == _Replica.SERVING]) == 1
    finally:
        fleet.close()


def test_autoscale_down_disabled(monkeypatch):
    monkeypatch.setenv("MXNET_FLEET_SCALE_DOWN_WAIT_MS", "0")
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        fleet.queue_wait_ewma = 0.0
        assert fleet.maybe_scale() is None
        assert fleet.stats["scale_downs"] == 0
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# zero-downtime rolling weight swap
# ---------------------------------------------------------------------------

def write_ckpt(tmp_path, seed=23, step=1):
    """A committed checkpoint holding a DIFFERENT deterministic net's
    params (what a training run would have produced)."""
    st = capture_train_state(net=make_net(seed), step=step)
    root = os.path.join(str(tmp_path), "ckpt")
    return atomic.write_checkpoint(root, step, st.arrays,
                                   array_meta=st.array_meta,
                                   meta=st.meta), root


def test_rolling_swap_zero_drop_bit_exact(tmp_path):
    N = 4
    X = rows(N, seed=5)
    old_out = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    new_out = [build_pred(23).predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    path, root = write_ckpt(tmp_path)
    swaps0 = telemetry.value(telemetry.names.FLEET_SWAPS) or 0
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        seed_waits(fleet, [0.001, 0.001])
        # accepted-but-unserved traffic rides through the rollout
        inflight = [fleet.router.submit(mx.nd.array(X[i:i + 1]))
                    for i in range(N)]
        res = fleet.swap_weights(root)         # resolves newest valid
        assert res["version"] == 1 and res["replicas"] == 2
        assert res["path"] == path
        assert fleet.version == 1
        assert all(r.version == 1 for r in fleet.replicas)
        assert (telemetry.value(telemetry.names.FLEET_SWAPS) or 0) \
            - swaps0 == 1
        # zero dropped: the in-flight requests flushed during the
        # drain, ON THE OLD WEIGHTS
        for i, f in enumerate(inflight):
            assert (f.result(10).asnumpy() == old_out[i]).all()
        # <= 1 version of skew: replicas drained strictly one at a time
        order = [(e.kind, e.replica) for e in fleet.events
                 if e.kind in ("swap_drain", "swap_done")]
        assert order == [("swap_drain", "replica-0"),
                         ("swap_done", "replica-0"),
                         ("swap_drain", "replica-1"),
                         ("swap_done", "replica-1")]
        # post-swap traffic is bit-exact vs a fresh predictor built
        # from the new weights
        for i in range(N):
            fut = fleet.router.submit(mx.nd.array(X[i:i + 1]))
            assert fut.version == 1
            pump_until_done(fleet, [fut])
            assert (fut.result(10).asnumpy() == new_out[i]).all()
    finally:
        fleet.close()


def test_corrupt_checkpoint_aborts_typed_old_weights_serve(tmp_path):
    X = rows(2, seed=5)
    old_out = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(2)]
    path, _root = write_ckpt(tmp_path)
    # flip bytes in one committed array file: CRC must catch it
    arrays_dir = os.path.join(path, "arrays")
    victim_file = os.path.join(arrays_dir,
                               sorted(os.listdir(arrays_dir))[0])
    with open(victim_file, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")
    swaps0 = telemetry.value(telemetry.names.FLEET_SWAPS) or 0
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        with pytest.raises(CheckpointCorruptError):
            fleet.swap_weights(path)
        # typed abort BEFORE any replica drained: everything serving
        # the OLD weights at the OLD version, no swap recorded
        assert fleet.version == 0
        assert all(r.state == _Replica.SERVING for r in fleet.replicas)
        assert all(r.version == 0 for r in fleet.replicas)
        assert (telemetry.value(telemetry.names.FLEET_SWAPS) or 0) \
            == swaps0
        assert not any(e.kind.startswith("swap_drain")
                       for e in fleet.events)
        seed_waits(fleet, [0.001, 0.5])
        fut = fleet.router.submit(mx.nd.array(X[:1]))
        pump_until_done(fleet, [fut])
        assert (fut.result(10).asnumpy() == old_out[0]).all()
    finally:
        fleet.close()


def test_swap_missing_checkpoint_is_typed(tmp_path):
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        with pytest.raises(MXNetError, match="no valid checkpoint"):
            fleet.swap_weights(str(tmp_path / "empty"))
    finally:
        fleet.close()


def test_manager_latest_path_feeds_swap(tmp_path):
    """TrainCheckpointManager.latest_path() is the training→serving
    rollout handle."""
    from mxnet_tpu.checkpoint import TrainCheckpointManager
    root = str(tmp_path / "mgr")
    mgr = TrainCheckpointManager(root, keep_last=2)
    assert mgr.latest_path() is None
    st = capture_train_state(net=make_net(23), step=5)
    mgr.save_state(st)
    p = mgr.latest_path()
    assert p is not None and os.path.isdir(p)
    atomic.validate_checkpoint(p)              # swap-ready


# ---------------------------------------------------------------------------
# satellites: warmup-seeded EWMA, decode mid-stream shed, loadgen census
# ---------------------------------------------------------------------------

def test_warmup_seeds_admission_ewma():
    """Cold-start admission blindness fix: a warmed predictor hands its
    AOT execution timing to the batcher, so deadline shedding projects
    from request 1 instead of admitting blindly until the first
    retire."""
    pred = build_pred()
    assert pred.service_time_seed_s is None
    cold = serving.DynamicBatcher(pred, start=False, max_batch=4)
    assert cold._ewma_service is None
    assert cold.estimated_wait_s(1) is None    # blind before warmup
    cold.close()
    pred.warmup(mx.nd.array(rows(1)))
    assert pred.service_time_seed_s is not None
    assert pred.service_time_seed_s > 0
    warm = serving.DynamicBatcher(pred, start=False, max_batch=4)
    assert warm._ewma_service == pytest.approx(pred.service_time_seed_s)
    assert warm.estimated_wait_s(1) is not None
    warm.close()


def test_warm_seed_sheds_from_first_request(monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_SHED", "deadline")
    pred = build_pred()
    pred.warmup(mx.nd.array(rows(1)))
    pred.service_time_seed_s = 0.050           # pin a slow seed
    clk = [0.0]
    b = serving.DynamicBatcher(pred, start=False, max_batch=4,
                               clock=lambda: clk[0])
    with pytest.raises(serving.Overloaded) as ei:
        b.submit(mx.nd.array(rows(1)), deadline_ms=20.0)
    assert ei.value.reason == "deadline"       # shed on request ONE
    b.close()


def test_decode_midstream_deadline_shed_returns_pages():
    """Per-token deadline re-projection: a stream whose TPOT EWMA says
    the remaining tokens cannot finish in budget is shed MID-stream
    with a typed DeadlineExceeded, and its KV pages return to the
    pool."""
    clk = [0.0]
    model = serving.TinyDecoder(vocab=32, d_model=16, num_heads=2,
                                seed=0)
    eng = serving.DecodeEngine(model, ladder=(1, 2), max_context=64,
                               page_size=8, start=False,
                               clock=lambda: clk[0])
    eng.warmup()
    free0 = eng.kv.free_pages()
    stream = eng.submit(onp.array([3, 1], onp.int32), max_new=24,
                        deadline_ms=200.0)
    # each retire lands 60 fake-clock ms after the last: TPOT EWMA ~=
    # 60 ms, so after a couple of tokens the remaining ~22 x 60 ms
    # projection blows the 200 ms budget mid-stream
    for _ in range(30):
        if stream.done:
            break
        clk[0] += 0.060
        eng.step_once()
        eng.sync()
    with pytest.raises(serving.DeadlineExceeded, match="mid-flight"):
        stream.result(5)
    rec = stream.record()
    assert 0 < rec["tokens"] < 24              # shed MID-stream
    assert eng.stats["shed_midstream"] == 1
    assert eng.stats["deadline_missed"] >= 1
    assert eng.kv.free_pages() == free0        # pages back in the pool
    assert all(r is None for r in eng._occupant)
    eng.close()


def test_decode_stream_without_deadline_never_shed_midstream():
    clk = [0.0]
    model = serving.TinyDecoder(vocab=32, d_model=16, num_heads=2,
                                seed=0)
    eng = serving.DecodeEngine(model, ladder=(1, 2), max_context=64,
                               page_size=8, start=False,
                               clock=lambda: clk[0])
    eng.warmup()
    stream = eng.submit(onp.array([3, 1], onp.int32), max_new=4)
    for _ in range(30):
        if stream.done:
            break
        clk[0] += 60.0                         # hopeless pace, no budget
        eng.step_once()
        eng.sync()
    assert len(stream.result(5)) == 4          # runs to completion
    assert eng.stats["shed_midstream"] == 0
    eng.close()


class _FakeFut:
    def __init__(self, replica, exc=None):
        self.replica = replica
        self._exc = exc

    def result(self, timeout=None):
        if self._exc is not None:
            raise self._exc


def test_loadgen_fleet_census_round_robin():
    subs = [lambda *a, **kw: _FakeFut("r0"),
            lambda *a, **kw: _FakeFut("r1")]
    rep = loadgen.run_closed_loop(
        loadgen.fleet_issue(subs, lambda i: (i,)),
        concurrency=2, requests=10)
    assert rep["outcomes"]["ok"] == 10
    census = rep["replicas"]
    assert census["r0"]["outcomes"]["ok"] == 5
    assert census["r1"]["outcomes"]["ok"] == 5
    assert census["r0"]["qps"] > 0
    assert "p99_ms" in census["r0"]


def test_loadgen_fleet_census_attributes_failures():
    def sub(i, *a, **kw):
        if i % 2:
            return _FakeFut("r1", serving.DeadlineExceeded("late"))
        return _FakeFut("r0")

    rep = loadgen.run_closed_loop(
        loadgen.fleet_issue([sub], lambda i: (i,)),
        concurrency=1, requests=8)
    census = rep["replicas"]
    assert census["r0"]["outcomes"]["ok"] == 4
    assert census["r1"]["outcomes"]["deadline_missed"] == 4
    assert rep["outcomes"] == {"ok": 4, "rejected": 0,
                               "deadline_missed": 4, "error": 0}


def test_fleet_metric_names_cataloged():
    from mxnet_tpu.telemetry import names
    for const, kind in (("FLEET_REPLICAS", "gauge"),
                        ("FLEET_ROUTED", "counter"),
                        ("FLEET_RESTARTS", "counter"),
                        ("FLEET_SWAPS", "counter"),
                        ("FLEET_SCALE_EVENTS", "counter"),
                        ("FLEET_QUEUE_WAIT", "histogram")):
        name = getattr(names, const)
        assert name.startswith("mx_fleet_")
        assert name in names.CATALOG
        assert names.CATALOG[name]["kind"] == kind


def test_replica_gauge_tracks_states():
    clk = [0.0]
    fleet = make_fleet(clk, 2)
    try:
        assert telemetry.value(telemetry.names.FLEET_REPLICAS,
                               "serving") == 2
        fleet.drain_then_retire(fleet.replicas[0])
        assert telemetry.value(telemetry.names.FLEET_REPLICAS,
                               "serving") == 1
        assert telemetry.value(telemetry.names.FLEET_REPLICAS,
                               "retired") == 1
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# chaos acceptance: replica-targeted revoke mid-burst, threaded fleet
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_fleet_kill_one_replica_mid_burst(monkeypatch):
    """3 threaded replicas, a 28-request concurrent burst, one
    replica-targeted device revocation mid-traffic under
    MXNET_TRANSFER_GUARD=raise: zero lost accepted requests, zero
    hangs, exactly one mx_fleet_replica_restarts_total increment, the
    victim back in rotation on a spare device, bit-exact results, and
    zero unblessed host syncs in the serving hot loops."""
    N = 28
    X = rows(N, seed=13)
    singles = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    monkeypatch.setenv("MXNET_SERVING_SHED", "off")
    restarts0 = telemetry.value(telemetry.names.FLEET_RESTARTS) or 0
    sync0 = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0
    results = [None] * N
    errors = [None] * N
    fleet = serving.FleetController(
        build_pred, example=(mx.nd.array(rows(1)),), replicas=3,
        max_batch=4, timeout_ms=2.0)
    try:
        victim = fleet.replicas[-1]
        # steer the burst's head deterministically at the victim (a
        # near-zero service EWMA makes its projected wait the floor),
        # so the targeted dispatch fault is guaranteed to fire; real
        # retire timings take the EWMAs over once traffic flows
        victim.sup.batcher._ewma_service = 1e-6
        faults.configure(f"serving.dispatch@{victim.name}:before=2"
                         f":revoke:d{victim.device.id}")

        def client(i):
            deadline = time.time() + 60
            while True:
                try:
                    results[i] = fleet.router.submit(
                        mx.nd.array(X[i:i + 1])).result(60)
                    return
                except (serving.Overloaded, serving.ServingShutdown):
                    # typed retryable signals: breaker fast-fail, fleet
                    # saturation, or "arrived during fleet failover"
                    if time.time() >= deadline:
                        raise
                    time.sleep(0.01)
                except MXNetError as e:
                    errors[i] = e
                    return

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        hung = [i for i, t in enumerate(threads) if t.is_alive()]
        assert not hung, f"clients hung: {hung}"
        # the background restart may still be in flight: wait for it
        deadline = time.time() + 30
        while time.time() < deadline and not any(
                e.kind in ("restart", "restart_failed")
                for e in fleet.events):
            time.sleep(0.02)
        assert any(e.kind == "restart" for e in fleet.events), \
            "victim replica never restarted"
        assert fleet.stats["failovers"] == 1
        assert fleet.stats["restarts"] == 1
        assert (telemetry.value(telemetry.names.FLEET_RESTARTS) or 0) \
            - restarts0 == 1
        assert victim.state == _Replica.SERVING
        faults.restore_devices()
        late = fleet.router.submit(mx.nd.array(X[:1]))
        assert late.result(30) is not None
    finally:
        fleet.close()
    # zero unblessed syncs in the fleet's serving hot loops (results
    # are still async handles at this point — checked BEFORE asnumpy)
    assert (telemetry.value(telemetry.names.HOST_SYNCS, "wait_to_read")
            or 0) - sync0 == 0
    # zero lost accepted: every request has exactly one terminal state
    # and (clients retry typed rejections) every one SERVED
    for i in range(N):
        assert (results[i] is None) != (errors[i] is None), \
            f"request {i} has no terminal state"
        assert errors[i] is None, \
            f"request {i}: terminal failure {errors[i]!r}"
    for i in range(N):
        assert (results[i].asnumpy() == singles[i]).all(), \
            f"request {i} differs from single dispatch"


@pytest.mark.chaos
def test_chaos_rolling_swap_under_traffic(tmp_path, monkeypatch):
    """Rolling swap while threaded traffic flows, under
    MXNET_TRANSFER_GUARD=raise: zero dropped accepted requests and
    every result bit-exact against the OLD or the NEW weights (never a
    torn mix), with the fleet at the new version afterwards."""
    N = 24
    X = rows(N, seed=19)
    old_out = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    new_out = [build_pred(23).predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    monkeypatch.setenv("MXNET_SERVING_SHED", "off")
    _path, root = write_ckpt(tmp_path)
    results = [None] * N
    errors = [None] * N
    fleet = serving.FleetController(
        build_pred, example=(mx.nd.array(rows(1)),), replicas=3,
        max_batch=4, timeout_ms=2.0)
    try:
        def client(i):
            deadline = time.time() + 60
            while True:
                try:
                    results[i] = fleet.router.submit(
                        mx.nd.array(X[i:i + 1])).result(60)
                    return
                except (serving.Overloaded, serving.ServingShutdown):
                    if time.time() >= deadline:
                        raise
                    time.sleep(0.005)
                except MXNetError as e:
                    errors[i] = e
                    return

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(N)]
        for t in threads:
            t.start()
        time.sleep(0.05)                       # traffic in flight
        res = fleet.swap_weights(root)
        assert res["replicas"] == 3
        for t in threads:
            t.join(90)
        hung = [i for i, t in enumerate(threads) if t.is_alive()]
        assert not hung, f"clients hung: {hung}"
        for i in range(N):
            assert errors[i] is None and results[i] is not None, \
                f"request {i}: {errors[i]!r}"
            got = results[i].asnumpy()
            assert (got == old_out[i]).all() or \
                (got == new_out[i]).all(), \
                f"request {i} matches neither weight version"
        assert fleet.version == 1
        assert all(r.version == 1 for r in fleet.replicas
                   if r.state == _Replica.SERVING)
        # post-swap: the whole fleet answers with the NEW weights
        fut = fleet.router.submit(mx.nd.array(X[:1]))
        assert (fut.result(30).asnumpy() == new_out[0]).all()
    finally:
        fleet.close()
