"""Grid-sampling / deformable / proposal / correlation op tests.

Methodology per SURVEY §4: numpy golden forward + finite-difference
gradients (reference tests/python/unittest/test_operator.py
test_bilinear_sampler / test_spatial_transformer / test_correlation /
test_deformable_convolution analogs).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _np_bilinear_sample(data, ys, xs):
    """Zero-padded bilinear sampling golden, (B,C,H,W) at pixel coords."""
    B, C, H, W = data.shape
    out = onp.zeros((B, C) + ys.shape[1:], dtype=data.dtype)
    for b in range(B):
        for idx in onp.ndindex(ys.shape[1:]):
            y, x = ys[(b,) + idx], xs[(b,) + idx]
            y0, x0 = int(onp.floor(y)), int(onp.floor(x))
            for (yy, xx, w) in ((y0, x0, (1 - (y - y0)) * (1 - (x - x0))),
                                (y0, x0 + 1, (1 - (y - y0)) * (x - x0)),
                                (y0 + 1, x0, (y - y0) * (1 - (x - x0))),
                                (y0 + 1, x0 + 1, (y - y0) * (x - x0))):
                if 0 <= yy < H and 0 <= xx < W:
                    out[(b, slice(None)) + idx] += w * data[b, :, yy, xx]
    return out


def test_bilinear_sampler_golden():
    rng = onp.random.RandomState(0)
    data = rng.randn(2, 3, 5, 6).astype("float32")
    grid = rng.uniform(-1.2, 1.2, size=(2, 2, 4, 4)).astype("float32")
    out = nd.BilinearSampler(nd.array(data), nd.array(grid)).asnumpy()
    xs = (grid[:, 0] + 1) * (6 - 1) / 2.0
    ys = (grid[:, 1] + 1) * (5 - 1) / 2.0
    golden = _np_bilinear_sample(data, ys, xs)
    onp.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-5)


def test_bilinear_sampler_identity_grid():
    rng = onp.random.RandomState(1)
    data = rng.randn(1, 2, 4, 4).astype("float32")
    ys, xs = onp.meshgrid(onp.linspace(-1, 1, 4), onp.linspace(-1, 1, 4),
                          indexing="ij")
    grid = onp.stack([xs, ys], 0)[None].astype("float32")
    out = nd.BilinearSampler(nd.array(data), nd.array(grid)).asnumpy()
    onp.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-6)


def test_bilinear_sampler_grad():
    rng = onp.random.RandomState(2)
    data = rng.randn(1, 2, 5, 5).astype("float32")
    grid = rng.uniform(-0.9, 0.9, size=(1, 2, 3, 3)).astype("float32")
    check_numeric_gradient(
        lambda d, g: nd.BilinearSampler(d, g), [data, grid],
        rtol=2e-2, atol=2e-2)


def test_grid_generator_affine_identity():
    theta = onp.array([[1, 0, 0, 0, 1, 0]], dtype="float32")
    grid = nd.GridGenerator(nd.array(theta), "affine",
                            target_shape=(3, 5)).asnumpy()
    ys, xs = onp.meshgrid(onp.linspace(-1, 1, 3), onp.linspace(-1, 1, 5),
                          indexing="ij")
    onp.testing.assert_allclose(grid[0, 0], xs, rtol=1e-6, atol=1e-6)
    onp.testing.assert_allclose(grid[0, 1], ys, rtol=1e-6, atol=1e-6)


def test_grid_generator_warp_zero_flow():
    flow = onp.zeros((1, 2, 3, 4), dtype="float32")
    grid = nd.GridGenerator(nd.array(flow), "warp").asnumpy()
    ys, xs = onp.meshgrid(onp.linspace(-1, 1, 3), onp.linspace(-1, 1, 4),
                          indexing="ij")
    onp.testing.assert_allclose(grid[0, 0], xs, rtol=1e-6, atol=1e-6)
    onp.testing.assert_allclose(grid[0, 1], ys, rtol=1e-6, atol=1e-6)


def test_spatial_transformer_identity():
    rng = onp.random.RandomState(3)
    data = rng.randn(2, 3, 6, 6).astype("float32")
    theta = onp.tile(onp.array([[1, 0, 0, 0, 1, 0]], "float32"), (2, 1))
    out = nd.SpatialTransformer(nd.array(data), nd.array(theta),
                                target_shape=(6, 6)).asnumpy()
    onp.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_scale_and_grad():
    rng = onp.random.RandomState(4)
    data = rng.randn(1, 1, 8, 8).astype("float32")
    theta = onp.array([[0.5, 0, 0.1, 0, 0.5, -0.1]], dtype="float32")
    out = nd.SpatialTransformer(nd.array(data), nd.array(theta),
                                target_shape=(4, 4))
    assert out.shape == (1, 1, 4, 4)
    check_numeric_gradient(
        lambda d, t: nd.SpatialTransformer(d, t, target_shape=(4, 4)),
        [data, theta], rtol=2e-2, atol=2e-2)


def _np_deform_conv(data, offset, weight, stride, pad, dilate, dg):
    B, C, H, W = data.shape
    O, Cg, kh, kw = weight.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    out = onp.zeros((B, O, Ho, Wo), "float32")
    cpg = C // dg
    for b in range(B):
        for ho in range(Ho):
            for wo in range(Wo):
                col = onp.zeros((C, kh * kw), "float32")
                for k in range(kh * kw):
                    i, j = divmod(k, kw)
                    for g in range(dg):
                        oy = offset[b, (g * kh * kw + k) * 2, ho, wo]
                        ox = offset[b, (g * kh * kw + k) * 2 + 1, ho, wo]
                        y = ho * sh - ph + i * dh + oy
                        x = wo * sw - pw + j * dw + ox
                        sl = data[b:b + 1, g * cpg:(g + 1) * cpg]
                        col[g * cpg:(g + 1) * cpg, k] = _np_bilinear_sample(
                            sl, onp.array([[[y]]]), onp.array([[[x]]])
                        )[0, :, 0, 0]
                out[b, :, ho, wo] = (weight.reshape(O, -1)
                                     @ col.reshape(-1))
    return out


def test_deformable_conv_zero_offset_equals_conv():
    rng = onp.random.RandomState(5)
    data = rng.randn(2, 4, 7, 7).astype("float32")
    weight = rng.randn(6, 4, 3, 3).astype("float32")
    offset = onp.zeros((2, 2 * 9, 5, 5), dtype="float32")
    out = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=(3, 3), num_filter=6).asnumpy()
    ref = nd.Convolution(nd.array(data), nd.array(weight),
                         kernel=(3, 3), num_filter=6,
                         no_bias=True).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_conv_golden():
    rng = onp.random.RandomState(6)
    data = rng.randn(1, 2, 5, 5).astype("float32")
    weight = rng.randn(3, 2, 3, 3).astype("float32")
    offset = (rng.randn(1, 18, 3, 3) * 0.5).astype("float32")
    out = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=(3, 3), num_filter=3).asnumpy()
    golden = _np_deform_conv(data, offset, weight, (1, 1), (0, 0), (1, 1), 1)
    onp.testing.assert_allclose(out, golden, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_deformable_conv_numeric_grad():
    # ~540 eager finite-difference evaluations (~35s) — slow tier; the
    # quick gate keeps the forward golden above
    rng = onp.random.RandomState(6)
    data = rng.randn(1, 2, 5, 5).astype("float32")
    weight = rng.randn(3, 2, 3, 3).astype("float32")
    offset = (rng.randn(1, 18, 3, 3) * 0.5).astype("float32")
    check_numeric_gradient(
        lambda d, o, w: nd.contrib.DeformableConvolution(
            d, o, w, kernel=(3, 3), num_filter=3),
        [data, offset, weight], rtol=3e-2, atol=3e-2)


def test_deformable_conv_groups():
    rng = onp.random.RandomState(7)
    data = rng.randn(1, 4, 6, 6).astype("float32")
    weight = rng.randn(4, 2, 3, 3).astype("float32")   # num_group=2
    offset = onp.zeros((1, 2 * 2 * 9, 4, 4), "float32")  # dg=2
    out = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=(3, 3), num_filter=4, num_group=2,
        num_deformable_group=2).asnumpy()
    ref = nd.Convolution(nd.array(data), nd.array(weight), kernel=(3, 3),
                         num_filter=4, num_group=2, no_bias=True).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_psroi_pooling_uniform():
    """On a channelwise-constant map every pooled bin returns that
    channel-group's constant, regardless of trans offsets."""
    P, G, out_dim = 3, 3, 2
    C = out_dim * G * G
    data = onp.zeros((1, C, 9, 9), "float32")
    for c in range(C):
        data[0, c] = c
    rois = onp.array([[0, 1, 1, 7, 7]], dtype="float32")
    trans = (onp.random.RandomState(8).randn(1, 2, P, P) * 0.1) \
        .astype("float32")
    out = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans),
        spatial_scale=1.0, output_dim=out_dim, group_size=G, pooled_size=P,
        trans_std=0.1).asnumpy()
    assert out.shape == (1, out_dim, P, P)
    for d in range(out_dim):
        for ph in range(P):
            for pw in range(P):
                gh = min((ph * G) // P, G - 1)
                gw = min((pw * G) // P, G - 1)
                expect = d * G * G + gh * G + gw
                onp.testing.assert_allclose(out[0, d, ph, pw], expect,
                                            rtol=1e-5)


def test_proposal_shapes_and_ordering():
    rng = onp.random.RandomState(9)
    B, A, H, W = 1, 6, 4, 4  # scales x ratios = 2*3
    cls_prob = rng.uniform(0, 1, size=(B, 2 * A, H, W)).astype("float32")
    bbox_pred = (rng.randn(B, 4 * A, H, W) * 0.1).astype("float32")
    im_info = onp.array([[64, 64, 1.0]], dtype="float32")
    out = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10, threshold=0.7,
        rpn_min_size=4, scales=(4, 8), ratios=(0.5, 1, 2),
        feature_stride=16).asnumpy()
    assert out.shape == (10, 5)
    # boxes are clipped to the image (suppressed slots are zero padding)
    assert (out[:, 1:] >= -1e-4).all()
    assert (out[:, [1, 3]] <= 64).all() and (out[:, [2, 4]] <= 64).all()
    ws = out[:, 3] - out[:, 1]
    hs = out[:, 4] - out[:, 2]
    valid = ws > 0
    assert valid.any()
    assert (ws[valid] + 1 >= 4 - 1e-4).all() and \
        (hs[valid] + 1 >= 4 - 1e-4).all()
    out2 = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10, scales=(4, 8),
        ratios=(0.5, 1, 2)).asnumpy()
    assert out2.shape == (10, 5)


def test_proposal_backfills_survivors_from_pre_nms_pool():
    """NMS must run over the whole pre-NMS pool so survivors ranked beyond
    post_nms_top_n backfill suppressed slots (reference proposal.cc keeps
    the top post_n SURVIVORS of the pool, not survivors among the top
    post_n). With many overlapping top anchors plus distinct lower-scored
    ones, all post_n slots should hold real (nonzero-width) boxes."""
    rng = onp.random.RandomState(3)
    B, A, H, W = 1, 6, 8, 8
    # strongly peaked scores so the top anchors heavily overlap at one cell
    cls_prob = rng.uniform(0.4, 0.6, size=(B, 2 * A, H, W)).astype("float32")
    cls_prob[0, A:, 4, 4] = 0.99  # all 6 anchors at one location dominate
    bbox_pred = onp.zeros((B, 4 * A, H, W), dtype="float32")
    im_info = onp.array([[128, 128, 1.0]], dtype="float32")
    post_n = 8
    out = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=200, rpn_post_nms_top_n=post_n, threshold=0.5,
        rpn_min_size=1, scales=(4, 8), ratios=(0.5, 1, 2),
        feature_stride=8).asnumpy()
    assert out.shape == (post_n, 5)
    widths = out[:, 3] - out[:, 1]
    # every slot backfilled with a real proposal from the pool
    assert (widths > 0).all(), out


def _np_correlation(a, b, K, md, s1, s2, pad, multiply):
    B, C, H, W = a.shape
    kr = (K - 1) // 2
    border = md + kr
    ap = onp.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    bp = onp.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    Ho = int(onp.ceil((Hp - border * 2) / s1))
    Wo = int(onp.ceil((Wp - border * 2) / s1))
    ngr = md // s2
    ngw = 2 * ngr + 1
    out = onp.zeros((B, ngw * ngw, Ho, Wo), "float32")
    sumelems = K * K * C
    for bi in range(B):
        for ci, (dy, dx) in enumerate(
                (dy, dx) for dy in range(-ngr, ngr + 1)
                for dx in range(-ngr, ngr + 1)):
            for ho in range(Ho):
                for wo in range(Wo):
                    y1 = border + ho * s1
                    x1 = border + wo * s1
                    y2, x2 = y1 + dy * s2, x1 + dx * s2
                    acc = 0.0
                    for ky in range(-kr, K - kr):
                        for kx in range(-kr, K - kr):
                            pa = ap[bi, :, y1 + ky, x1 + kx]
                            pb = bp[bi, :, y2 + ky, x2 + kx]
                            acc += (pa * pb).sum() if multiply else \
                                onp.abs(pa - pb).sum()
                    out[bi, ci, ho, wo] = acc / sumelems
    return out


@pytest.mark.parametrize("multiply", [True, False])
def test_correlation_golden(multiply):
    rng = onp.random.RandomState(10)
    a = rng.randn(1, 3, 6, 6).astype("float32")
    b = rng.randn(1, 3, 6, 6).astype("float32")
    out = nd.Correlation(nd.array(a), nd.array(b), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1, is_multiply=multiply).asnumpy()
    golden = _np_correlation(a, b, 1, 1, 1, 1, 1, multiply)
    onp.testing.assert_allclose(out, golden, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_correlation_grad():
    # pure finite-difference sweep (~9s) — slow tier; the forward
    # goldens above stay in the quick gate
    rng = onp.random.RandomState(11)
    a = rng.randn(1, 2, 5, 5).astype("float32")
    b = rng.randn(1, 2, 5, 5).astype("float32")
    check_numeric_gradient(
        lambda x, y: nd.Correlation(x, y, kernel_size=1, max_displacement=1,
                                    pad_size=1),
        [a, b], rtol=2e-2, atol=2e-2)


def test_count_sketch_golden_and_grad():
    rng = onp.random.RandomState(12)
    B, D, O = 3, 10, 6
    data = rng.randn(B, D).astype("float32")
    h = rng.randint(0, O, size=(D,)).astype("float32")
    s = rng.choice([-1.0, 1.0], size=(D,)).astype("float32")
    out = nd.contrib.count_sketch(nd.array(data), nd.array(h), nd.array(s),
                                  out_dim=O).asnumpy()
    golden = onp.zeros((B, O), "float32")
    for i in range(D):
        golden[:, int(h[i])] += s[i] * data[:, i]
    onp.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-6)
    check_numeric_gradient(
        lambda d: nd.contrib.count_sketch(d, nd.array(h), nd.array(s),
                                          out_dim=O),
        [data], rtol=2e-2, atol=2e-2)


def test_sync_batch_norm_matches_batch_norm():
    rng = onp.random.RandomState(13)
    x = rng.randn(4, 3, 5, 5).astype("float32")
    gamma = onp.ones(3, "float32")
    beta = onp.zeros(3, "float32")
    mm = onp.zeros(3, "float32")
    mv = onp.ones(3, "float32")
    out = nd.contrib.SyncBatchNorm(
        nd.array(x), nd.array(gamma), nd.array(beta), nd.array(mm),
        nd.array(mv), fix_gamma=False).asnumpy()
    ref = nd.BatchNorm(
        nd.array(x), nd.array(gamma), nd.array(beta), nd.array(mm),
        nd.array(mv), eps=1e-3).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_sync_batch_norm_axis_name_updates_moving_stats():
    """Training under axis_name must update moving_mean/moving_var with the
    momentum rule, and inference (training flag off) must normalize by those
    running stats (reference contrib/sync_batch_norm.cc)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as onp2
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    from mxnet_tpu.ndarray.vision_ops import SyncBatchNorm as SBN
    from mxnet_tpu import _tape
    rng = onp.random.RandomState(7)
    x = rng.randn(4, 3, 2, 2).astype("float32") * 2 + 1.5
    gamma = onp.ones(3, "float32")
    beta = onp.zeros(3, "float32")
    mesh = Mesh(onp2.array(jax.devices()[:2]), ("dp",))

    def per_shard(xs):
        m = nd.array(onp.zeros(3, "float32"))
        v = nd.array(onp.ones(3, "float32"))
        out = SBN(mx.nd.from_jax(xs), nd.array(gamma), nd.array(beta),
                  m, v, fix_gamma=False, momentum=0.9,
                  axis_name="dp")._data
        # the op REBINDS m._data/v._data to the updated stats during the
        # trace (the protocol HybridBlock's state capture detects); a raw
        # jax caller returns them as outputs
        return out, m._data, v._data

    prev = _tape.set_training(True)
    try:
        from mxnet_tpu.parallel import shard_map as _shard_map
        out, new_mm, new_mv = jax.jit(_shard_map(
            per_shard, mesh, P("dp"),
            (P("dp"), P(), P())))(jnp.asarray(x))
    finally:
        _tape.set_training(prev)
    bm = x.mean(axis=(0, 2, 3))
    bv = x.var(axis=(0, 2, 3))
    # stats advanced one momentum step toward the GLOBAL batch moments
    onp.testing.assert_allclose(onp.asarray(new_mm), 0.1 * bm,
                                rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(new_mv), 0.9 + 0.1 * bv,
                                rtol=1e-3, atol=1e-4)
    # inference path (training flag off): normalize by running stats
    mm2 = nd.array(onp.asarray(new_mm))
    mv2 = nd.array(onp.asarray(new_mv))
    y = SBN(nd.array(x), nd.array(gamma), nd.array(beta), mm2, mv2,
            fix_gamma=False, axis_name="dp", eps=1e-3).asnumpy()
    ref = (x - onp.asarray(new_mm)[None, :, None, None]) / onp.sqrt(
        onp.asarray(new_mv)[None, :, None, None] + 1e-3)
    onp.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_sync_batch_norm_axis_name_psum():
    """Explicit shard_map path: per-shard moments psum'ed over the axis
    equal whole-batch normalization."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as onp2
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    rng = onp.random.RandomState(14)
    x = rng.randn(8, 3, 4, 4).astype("float32")
    gamma = onp.ones(3, "float32")
    beta = onp.zeros(3, "float32")
    mesh = Mesh(onp2.array(jax.devices()[:4]), ("dp",))

    from mxnet_tpu.ndarray.vision_ops import SyncBatchNorm as SBN

    def per_shard(xs):
        out = SBN(mx.nd.from_jax(xs), nd.array(gamma), nd.array(beta),
                  nd.array(onp.zeros(3, "float32")),
                  nd.array(onp.ones(3, "float32")),
                  fix_gamma=False, axis_name="dp")
        return out._data

    from mxnet_tpu.parallel import shard_map as _shard_map
    f = jax.jit(_shard_map(per_shard, mesh, P("dp"), P("dp")))
    # batch-moment normalization is the TRAINING path (inference uses the
    # moving averages, reference sync_batch_norm.cc)
    from mxnet_tpu import _tape
    prev = _tape.set_training(True)
    try:
        got = onp.asarray(f(jnp.asarray(x)))
    finally:
        _tape.set_training(prev)
    mean = x.mean(axis=(0, 2, 3), keepdims=True)
    var = x.var(axis=(0, 2, 3), keepdims=True)
    ref = (x - mean) / onp.sqrt(var + 1e-3)
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
