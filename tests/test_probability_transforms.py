"""Probability: TransformedDistribution + transformations + constraints +
the round-3 distributions (Binomial, NegativeBinomial, Multinomial,
FisherSnedecor, Independent, RelaxedBernoulli, RelaxedOneHotCategorical)
— log_prob/moments checked against scipy golden values (reference
python/mxnet/gluon/probability/distributions/*, transformation/*)."""
import numpy as onp
import pytest
import scipy.stats as sps

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import probability as P


# ---------------------------------------------------------------------------
# new distributions vs scipy
# ---------------------------------------------------------------------------

def test_binomial_log_prob_and_moments_vs_scipy():
    d = P.Binomial(n=10, prob=0.3)
    ks = onp.array([0.0, 3.0, 7.0, 10.0], "float32")
    got = d.log_prob(nd.array(ks)).asnumpy()
    want = sps.binom.logpmf(ks, 10, 0.3)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(float(d.mean.asnumpy()), 3.0, rtol=1e-6)
    onp.testing.assert_allclose(float(d.variance.asnumpy()), 2.1, rtol=1e-6)
    s = d.sample(4000).asnumpy()
    assert s.min() >= 0 and s.max() <= 10
    onp.testing.assert_allclose(s.mean(), 3.0, atol=0.2)


def test_binomial_logit_parameterization():
    p = 0.3
    logit = onp.log(p / (1 - p))
    d = P.Binomial(n=5, logit=onp.float32(logit))
    want = sps.binom.logpmf([2.0], 5, p)
    onp.testing.assert_allclose(d.log_prob(nd.array([2.0])).asnumpy(),
                                want, rtol=1e-4)


def test_negative_binomial_vs_scipy():
    n, p = 4.0, 0.4  # reference convention: mean = n*p/(1-p)
    d = P.NegativeBinomial(n=n, prob=p)
    ks = onp.array([0.0, 2.0, 5.0, 11.0], "float32")
    got = d.log_prob(nd.array(ks)).asnumpy()
    # scipy nbinom(n, p_success) counts failures at prob 1-p_success
    want = sps.nbinom.logpmf(ks, n, 1 - p)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(float(d.mean.asnumpy()), n * p / (1 - p),
                                rtol=1e-6)
    s = d.sample(6000).asnumpy()
    onp.testing.assert_allclose(s.mean(), n * p / (1 - p), rtol=0.1)


def test_multinomial_vs_scipy():
    probs = onp.array([0.2, 0.3, 0.5], "float32")
    d = P.Multinomial(3, prob=probs, total_count=8)
    x = onp.array([2.0, 3.0, 3.0], "float32")
    got = float(d.log_prob(nd.array(x)).asnumpy())
    want = sps.multinomial.logpmf(x, 8, probs)
    onp.testing.assert_allclose(got, want, rtol=1e-5)
    s = d.sample(2000).asnumpy()
    assert s.shape == (2000, 3)
    onp.testing.assert_array_equal(s.sum(-1), onp.full(2000, 8.0))
    onp.testing.assert_allclose(s.mean(0), 8 * probs, atol=0.25)


def test_fishersnedecor_vs_scipy():
    d1, d2 = 5.0, 12.0
    d = P.FisherSnedecor(d1, d2)
    xs = onp.array([0.3, 1.0, 2.5], "float32")
    got = d.log_prob(nd.array(xs)).asnumpy()
    want = sps.f.logpdf(xs, d1, d2)
    onp.testing.assert_allclose(got, want, rtol=1e-4)
    onp.testing.assert_allclose(float(d.mean.asnumpy()), d2 / (d2 - 2),
                                rtol=1e-6)
    s = d.sample(8000).asnumpy()
    assert (s > 0).all()
    onp.testing.assert_allclose(s.mean(), d2 / (d2 - 2), rtol=0.15)


def test_independent_sums_event_dims():
    base = P.Normal(loc=nd.array(onp.zeros((4, 3), "float32")),
                    scale=nd.array(onp.ones((4, 3), "float32")))
    ind = P.Independent(base, 1)
    v = onp.random.RandomState(0).randn(4, 3).astype("float32")
    got = ind.log_prob(nd.array(v)).asnumpy()
    want = sps.norm.logpdf(v).sum(-1)
    assert got.shape == (4,)
    onp.testing.assert_allclose(got, want, rtol=1e-5)
    ent = ind.entropy().asnumpy()
    onp.testing.assert_allclose(ent, onp.full(4, 3 * sps.norm.entropy()),
                                rtol=1e-6)


def test_relaxed_bernoulli_density_and_grad():
    T, p = 0.5, 0.3
    d = P.RelaxedBernoulli(T, prob=p)
    s = d.sample(1000).asnumpy()
    assert ((s > 0) & (s < 1)).all()
    # golden value: binary Concrete density (Maddison et al. 2017, eq. 24)
    # p(x) = T a x^{-T-1} (1-x)^{-T-1} / (a x^{-T} + (1-x)^{-T})^2
    x = onp.array([0.2, 0.5, 0.8], "float32")
    a = p / (1 - p)
    dens = (T * a * x ** (-T - 1) * (1 - x) ** (-T - 1)
            / (a * x ** (-T) + (1 - x) ** (-T)) ** 2)
    got = d.log_prob(nd.array(x)).asnumpy()
    onp.testing.assert_allclose(got, onp.log(dens), rtol=1e-4)
    # reparameterized: gradients flow to the logit parameter
    logit = nd.array(onp.zeros((), "float32"))
    logit.attach_grad()
    with autograd.record():
        dd = P.RelaxedBernoulli(T, logit=logit)
        out = (dd.sample(16) ** 2).sum()
    out.backward()
    assert float(onp.abs(logit.grad.asnumpy())) > 0


def test_relaxed_one_hot_categorical_simplex_and_density():
    T = 0.7
    probs = onp.array([0.2, 0.5, 0.3], "float32")
    d = P.RelaxedOneHotCategorical(T, prob=probs)
    s = d.sample(500).asnumpy()
    assert s.shape == (500, 3)
    onp.testing.assert_allclose(s.sum(-1), onp.ones(500), rtol=1e-4)
    assert (s > 0).all()
    # density integrates sensibly: compare against itself under the
    # ExpConcrete change of variables at a fixed point
    x = onp.array([0.2, 0.5, 0.3], "float32")
    lp = float(d.log_prob(nd.array(x)).asnumpy())
    assert onp.isfinite(lp)
    # golden: Concrete density on the simplex (Maddison et al. eq. 23)
    n = 3
    import math
    import scipy.special as spe
    logits = onp.log(probs)
    num = spe.gammaln(n) + (n - 1) * onp.log(T) \
        + (logits - (T + 1) * onp.log(x)).sum() \
        - n * spe.logsumexp(logits - T * onp.log(x))
    onp.testing.assert_allclose(lp, num, rtol=1e-4)


# ---------------------------------------------------------------------------
# transformations
# ---------------------------------------------------------------------------

def test_lognormal_via_transformed_distribution_matches_closed_form():
    mu, sigma = 0.4, 0.8
    td = P.TransformedDistribution(P.Normal(mu, sigma), P.ExpTransform())
    xs = onp.array([0.5, 1.0, 2.3], "float32")
    got = td.log_prob(nd.array(xs)).asnumpy()
    want = sps.lognorm.logpdf(xs, sigma, scale=onp.exp(mu))
    onp.testing.assert_allclose(got, want, rtol=1e-5)
    direct = P.LogNormal(mu, sigma).log_prob(nd.array(xs)).asnumpy()
    onp.testing.assert_allclose(got, direct, rtol=1e-5)


def test_affine_compose_and_inverse_round_trip():
    t = P.ComposeTransform([P.AffineTransform(1.0, 2.0),
                            P.ExpTransform()])
    x = nd.array(onp.array([0.1, -0.3, 0.7], "float32"))
    y = t(x)
    back = t.inv(y)
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy(), rtol=1e-5)
    # y = exp(1 + 2x): log_det = log(2) + (1 + 2x)
    ld = t.log_det_jacobian(x, y).asnumpy()
    want = onp.log(2.0) + (1 + 2 * x.asnumpy())
    onp.testing.assert_allclose(ld, want, rtol=1e-5)


def test_transformed_cdf_icdf_with_sign():
    # y = -x for x ~ Uniform(0,1): cdf_y(v) = 1 - cdf_x(-v)
    td = P.TransformedDistribution(P.Uniform(0.0, 1.0),
                                   P.AffineTransform(0.0, -1.0))
    v = nd.array(onp.array([-0.25], "float32"))
    onp.testing.assert_allclose(td.cdf(v).asnumpy(), [0.75], rtol=1e-6)
    q = td.icdf(nd.array(onp.array([0.75], "float32"))).asnumpy()
    onp.testing.assert_allclose(q, [-0.25], rtol=1e-6)


def test_sigmoid_transform_density_matches_logistic():
    td = P.TransformedDistribution(P.Normal(0.0, 1.0),
                                   P.SigmoidTransform())
    xs = onp.array([0.2, 0.5, 0.9], "float32")
    got = td.log_prob(nd.array(xs)).asnumpy()
    # manual change of variables
    logit = onp.log(xs) - onp.log1p(-xs)
    want = sps.norm.logpdf(logit) - onp.log(xs * (1 - xs))
    onp.testing.assert_allclose(got, want, rtol=1e-4)


def test_non_bijective_transform_rejected():
    with pytest.raises(MXNetError):
        P.TransformedDistribution(P.Normal(0.0, 1.0), P.AbsTransform())
    with pytest.raises(MXNetError):
        P.AbsTransform().log_det_jacobian(nd.array([1.0]), nd.array([1.0]))


def test_power_and_softmax_transforms():
    t = P.PowerTransform(2.0)
    x = nd.array(onp.array([1.5, 2.0], "float32"))
    onp.testing.assert_allclose(t(x).asnumpy(), [2.25, 4.0], rtol=1e-6)
    onp.testing.assert_allclose(t.inv(t(x)).asnumpy(), x.asnumpy(),
                                rtol=1e-6)
    sm = P.SoftmaxTransform()
    y = sm(nd.array(onp.array([1.0, 2.0, 3.0], "float32"))).asnumpy()
    onp.testing.assert_allclose(y.sum(), 1.0, rtol=1e-6)
    assert not sm.bijective


# ---------------------------------------------------------------------------
# constraints
# ---------------------------------------------------------------------------

def test_constraints_accept_and_reject():
    C = P.constraint
    assert C.Positive().check(nd.array([1.0, 2.0])) is not None
    with pytest.raises(MXNetError):
        C.Positive().check(nd.array([0.0]))
    C.Interval(0, 1).check(nd.array([0.0, 0.5, 1.0]))
    with pytest.raises(MXNetError):
        C.OpenInterval(0, 1).check(nd.array([0.0]))
    C.IntegerInterval(0, 5).check(nd.array([0.0, 3.0, 5.0]))
    with pytest.raises(MXNetError):
        C.IntegerInterval(0, 5).check(nd.array([2.5]))
    C.Boolean().check(nd.array([0.0, 1.0]))
    with pytest.raises(MXNetError):
        C.Boolean().check(nd.array([2.0]))
    C.Simplex().check(nd.array([[0.2, 0.8], [0.5, 0.5]]))
    with pytest.raises(MXNetError):
        C.Simplex().check(nd.array([[0.2, 0.9]]))
    tril = onp.array([[1.0, 0.0], [0.5, 2.0]], "float32")
    C.LowerCholesky().check(nd.array(tril))
    with pytest.raises(MXNetError):
        C.LowerCholesky().check(nd.array(-tril))
    C.PositiveDefinite().check(nd.array(tril @ tril.T))
    with pytest.raises(MXNetError):
        C.PositiveDefinite().check(nd.array(onp.array([[1.0, 3.0],
                                                       [3.0, 1.0]])))
    with pytest.raises(MXNetError):
        C.dependent.check(nd.array([1.0]))
    assert C.is_dependent(C.dependent)


def test_discrete_distributions_grad_flows_to_params():
    for mk in (lambda p: P.Binomial(n=5, prob=p),
               lambda p: P.NegativeBinomial(n=3.0, prob=p),
               lambda p: P.Multinomial(2, prob=nd.stack(p, 1 - p, axis=-1),
                                       total_count=4)):
        p = nd.array(onp.array(0.3, "float32"))
        p.attach_grad()
        with autograd.record():
            d = mk(p)
            v = nd.array([2.0, 2.0]) if isinstance(d, P.Multinomial) \
                else nd.array([2.0])
            lp = d.log_prob(v).sum()
        lp.backward()
        assert float(onp.abs(p.grad.asnumpy())) > 0, type(d).__name__
    # logit parameterization too
    lg = nd.array(onp.array(0.0, "float32"))
    lg.attach_grad()
    with autograd.record():
        lp = P.Binomial(n=5, logit=lg).log_prob(nd.array([2.0])).sum()
    lp.backward()
    assert float(onp.abs(lg.grad.asnumpy())) > 0


def test_transform_event_dim_above_base_sums_base_log_prob():
    td = P.TransformedDistribution(
        P.Normal(nd.array(onp.zeros(3, "f")), nd.array(onp.ones(3, "f"))),
        P.AffineTransform(0.0, 2.0, event_dim=1))
    lp = td.log_prob(nd.array(onp.full(3, 2.0, "f"))).asnumpy()
    assert lp.shape == ()
    want = sps.norm.logpdf([1.0] * 3).sum() - 3 * onp.log(2.0)
    onp.testing.assert_allclose(lp, want, rtol=1e-5)


def test_independent_under_transform_scalar_density():
    base = P.Independent(
        P.Normal(nd.array(onp.zeros(3, "f")), nd.array(onp.ones(3, "f"))),
        1)
    assert base.event_dim == 1
    td = P.TransformedDistribution(base, P.ExpTransform())
    lp = td.log_prob(nd.array(onp.ones(3, "f"))).asnumpy()
    assert lp.shape == ()
    want = sps.lognorm.logpdf(onp.ones(3), 1.0).sum()
    onp.testing.assert_allclose(lp, want, rtol=1e-5)


def test_power_transform_negative_exponent_cdf():
    td = P.TransformedDistribution(P.Exponential(1.0),
                                   P.PowerTransform(-1.0))
    got = float(td.cdf(nd.array([2.0])).asnumpy())
    # P(1/X <= 2) = P(X >= 0.5) = exp(-0.5)
    onp.testing.assert_allclose(got, onp.exp(-0.5), rtol=1e-5)
    q = float(td.icdf(nd.array([onp.float32(onp.exp(-0.5))])).asnumpy())
    onp.testing.assert_allclose(q, 2.0, rtol=1e-4)


def test_relaxed_one_hot_requires_param():
    with pytest.raises(MXNetError):
        P.RelaxedOneHotCategorical(0.5)


def test_affine_transform_params_receive_gradients():
    """Learned affine flow: loc/scale ride the op funnel as inputs, so
    max-likelihood training moves them (normalizing-flow regression)."""
    loc = nd.array(onp.array(0.0, "float32"))
    scale = nd.array(onp.array(1.0, "float32"))
    loc.attach_grad()
    scale.attach_grad()
    data = nd.array(onp.random.RandomState(0)
                    .normal(2.0, 0.5, 512).astype("float32"))
    with autograd.record():
        td = P.TransformedDistribution(P.Normal(0.0, 1.0),
                                       P.AffineTransform(loc, scale))
        nll = -(td.log_prob(data)).mean()
    nll.backward()
    g_loc = float(loc.grad.asnumpy())
    g_scale = float(scale.grad.asnumpy())
    assert abs(g_loc) > 0 and abs(g_scale) > 0, (g_loc, g_scale)
    # and a few SGD steps actually fit the target
    for _ in range(200):
        with autograd.record():
            td = P.TransformedDistribution(P.Normal(0.0, 1.0),
                                           P.AffineTransform(loc, scale))
            nll = -(td.log_prob(data)).mean()
        nll.backward()
        loc -= 0.1 * loc.grad
        scale -= 0.1 * scale.grad
    assert abs(float(loc.asnumpy()) - 2.0) < 0.1
    assert abs(abs(float(scale.asnumpy())) - 0.5) < 0.1
