"""Fused whole-train-step compilation (Trainer.compile_step / TrainLoop).

Covers the PR-1 acceptance bar: numerics parity with the eager
record/backward/step loop for SGD-momentum and Adam over >=3 steps,
exactly one compile per input-shape bucket across repeated steps and lr
changes, donation writeback keeping Parameter handles stable, the
transparent eager fallback, and the split (host-allreduce) mode for dist
stores.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss


def _build(seed=3, with_bn=True):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    if with_bn:
        # bias-free: a bias feeding BN has a ~0 gradient (mean
        # subtraction cancels shift), and Adam's sign-normalizing update
        # amplifies sub-1e-8 autodiff reduction-order noise to ~lr —
        # that would test float noise, not the fused step
        net.add(nn.Dense(8, in_units=4, activation="relu",
                         use_bias=False))
        net.add(nn.BatchNorm(in_channels=8))
    else:
        net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(3, in_units=8))
    net.initialize()
    return net


def _batch(bs=6, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return x, y


def _assert_params_close(net_a, net_b, rtol=1e-5, atol=1e-6):
    for (k, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_b.collect_params().items())):
        onp.testing.assert_allclose(pa.data().asnumpy(),
                                    pb.data().asnumpy(),
                                    rtol=rtol, atol=atol, err_msg=k)


def _run_eager(net, opt, opt_kwargs, x, y, steps, lr_change=None):
    trainer = Trainer(net.collect_params(), opt, dict(opt_kwargs))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    for i in range(steps):
        if lr_change and i == lr_change[0]:
            trainer.learning_rate = lr_change[1]
        with autograd.record():
            l = loss_blk(net(x), y)
        l.backward()
        trainer.step(x.shape[0])
    return trainer


def _run_fused(net, opt, opt_kwargs, x, y, steps, lr_change=None,
               kvstore="device"):
    trainer = Trainer(net.collect_params(), opt, dict(opt_kwargs),
                      kvstore=kvstore)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    for i in range(steps):
        if lr_change and i == lr_change[0]:
            trainer.learning_rate = lr_change[1]
        step(x, y)
    return trainer, step


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_compile_step_parity_vs_eager(opt, kwargs):
    """Weights (incl. BatchNorm running stats) after >=3 fused steps —
    with an lr change mid-run — match the eager tape loop."""
    x, y = _batch()
    net_e = _build()
    _run_eager(net_e, opt, kwargs, x, y, steps=4, lr_change=(2, 0.02))
    net_f = _build()
    _, step = _run_fused(net_f, opt, kwargs, x, y, steps=4,
                         lr_change=(2, 0.02))
    assert step.mode == "fused"
    _assert_params_close(net_e, net_f)


def test_compile_step_parity_with_clip_and_wd():
    x, y = _batch()
    kwargs = {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-3,
              "clip_gradient": 0.5}
    net_e = _build(with_bn=False)
    _run_eager(net_e, "sgd", kwargs, x, y, steps=3)
    net_f = _build(with_bn=False)
    _, step = _run_fused(net_f, "sgd", kwargs, x, y, steps=3)
    assert step.mode == "fused"
    _assert_params_close(net_e, net_f)


def test_compile_step_retrace_policy():
    """Exactly ONE compile per input-shape bucket: repeated steps, lr
    mutation, and per-call batch_size changes reuse the program; only a
    genuinely new shape bucket compiles a second one."""
    net = _build(with_bn=False)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    x, y = _batch(6)
    for lr in (0.1, 0.05, 0.2):
        trainer.learning_rate = lr
        step(x, y)
    assert step.n_traces == 1, "lr changes must not retrace"
    step(x, y, batch_size=12)   # rescale is traced, not static
    assert step.n_traces == 1
    x2, y2 = _batch(3)
    step(x2, y2)                # new shape bucket
    assert step.n_traces == 2
    step(x, y)                  # back to the first bucket: cached
    assert step.n_traces == 2
    assert len(step._trace_signatures) == 2


def test_compile_step_writeback_keeps_handles():
    """Donation contract: results are written back INTO the same
    Parameter NDArray handles — references users hold from .data() see
    the updated weights."""
    net = _build(with_bn=False)
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    x, y = _batch()
    first = list(net.collect_params().values())[0]
    handle = first.data()
    before = handle.asnumpy().copy()
    step(x, y)
    assert first.data() is handle, "handle must stay stable"
    assert not onp.allclose(handle.asnumpy(), before), \
        "held handle must observe the update"


def test_compile_step_eager_fallback_transparent():
    """A loss_fn that concretizes on host (asnumpy inside) cannot trace;
    the step must fall back to the eager tape path with the same
    numerics, not raise."""
    x, y = _batch()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()

    net_f = _build(with_bn=False)
    trainer = Trainer(net_f.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})

    def hostile(a, b):
        out = net_f(a)
        _ = float(out.asnumpy().sum())   # breaks the trace
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    for _ in range(2):
        step(x, y)
    assert step.mode == "eager"

    net_e = _build(with_bn=False)
    _run_eager(net_e, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
               x, y, steps=2)
    _assert_params_close(net_e, net_f)


def test_compile_step_fallback_rolls_back_update_counts():
    """A failed first trace must not leave the optimizer's update counts
    advanced — Adam's bias correction in the eager fallback has to see
    t=1 on the first real step."""
    x, y = _batch()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    net_f = _build(with_bn=False)
    trainer = Trainer(net_f.collect_params(), "adam",
                      {"learning_rate": 1e-2})

    def hostile(a, b):
        out = net_f(a)
        _ = float(out.asnumpy().sum())
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    for _ in range(3):
        step(x, y)
    assert step.mode == "eager"
    assert trainer._optimizer.num_update == 3

    net_e = _build(with_bn=False)
    _run_eager(net_e, "adam", {"learning_rate": 1e-2}, x, y, steps=3)
    _assert_params_close(net_e, net_f)


def test_compile_step_sparse_grad_falls_back():
    """Embedding with sparse_grad takes the lazy row path — compile_step
    must route to the eager loop, and training must still work."""
    mx.random.seed(5)
    net = nn.Sequential()
    net.add(nn.Embedding(16, 4, sparse_grad=True))
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})

    def loss_fn(tok):
        return (net(tok) ** 2).mean()

    step = trainer.compile_step(loss_fn)
    tok = nd.array(onp.array([1, 3, 1], "int32"))
    before = net._children["0"].weight.data().asnumpy().copy()
    step(tok, batch_size=3)
    assert step.mode == "eager"
    after = net._children["0"].weight.data().asnumpy()
    assert not onp.allclose(after[1], before[1])
    onp.testing.assert_allclose(after[2], before[2])  # untouched row


def test_compile_step_split_mode_host_allreduce():
    """Dist stores (num_workers>1; forced here via _force_fuse) cannot
    reduce inside the program: grads route through the kvstore's
    bucketed pushpull_list between the gradient and update programs —
    numerics must still match the plain fused/eager path."""
    from mxnet_tpu.kvstore.kvstore import KVStoreDist
    x, y = _batch()
    kwargs = {"learning_rate": 0.1, "momentum": 0.9}

    kv = KVStoreDist("dist_sync")
    kv._force_fuse = True
    assert not kv.in_program_reduce
    net_s = _build()
    trainer, step = None, None
    trainer = Trainer(net_s.collect_params(), "sgd", dict(kwargs),
                      kvstore=kv)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net_s(a), b))
    for _ in range(3):
        step(x, y)
    assert step.mode == "fused"
    assert kv.stats["collectives"] == 0  # single process: identity reduce

    net_e = _build()
    _run_eager(net_e, "sgd", kwargs, x, y, steps=3)
    _assert_params_close(net_e, net_s)


def test_compile_step_save_load_states_interop():
    """The fused step drives the SAME Updater state dict the eager path
    uses: save_states after fused steps restores into an eager trainer."""
    x, y = _batch()
    net = _build(with_bn=False)
    trainer, step = _run_fused(net, "adam", {"learning_rate": 1e-2},
                               x, y, steps=3)
    assert step.mode == "fused"
    assert len(trainer._updater.states) == len(trainer._params)
    import tempfile
    import os as _os
    fd, fname = tempfile.mkstemp()
    _os.close(fd)
    try:
        trainer.save_states(fname)
        trainer2 = Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2})
        trainer2.load_states(fname)
        assert len(trainer2._updater.states) == len(trainer._updater.states)
        assert trainer2._optimizer.num_update == \
            trainer._optimizer.num_update
    finally:
        _os.unlink(fname)


def test_train_loop_convergence_and_aot():
    """TrainLoop end-to-end: AOT compile reports the program, repeated
    steps reuse ONE compiled program, and the loss actually goes down."""
    rng = onp.random.RandomState(0)
    w_true = rng.randn(4, 3).astype("float32")
    xs = rng.randn(64, 4).astype("float32")
    ys = (xs @ w_true).argmax(axis=1).astype("int32")
    x, y = nd.array(xs), nd.array(ys)

    net = _build(with_bn=False)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.5, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss())
    loop.compiled_step.aot_compile(x, y)
    l0 = float(loop.step(x, y).asnumpy().mean())
    for _ in range(30):
        l = loop.step(x, y)
    l1 = float(l.asnumpy().mean())
    assert loop.compiled_step.n_traces == 1
    assert l1 < l0 * 0.7, f"loss did not drop: {l0} -> {l1}"


def test_suspend_taping_guard():
    """Inside the functionalized region, user record() must be inert:
    is_recording stays False under suspension and restores after."""
    from mxnet_tpu import _tape
    with _tape.suspend_taping():
        with autograd.record():
            assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()


def test_compile_step_hybridized_net_inlines():
    """A hybridized (CachedOp) block must inline into the ONE fused step
    program rather than nesting cached dispatch — parity holds and only
    one step program compiles."""
    x, y = _batch()
    net_e = _build(with_bn=False)
    _run_eager(net_e, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
               x, y, steps=3)

    net_f = _build(with_bn=False)
    net_f.hybridize()
    trainer = Trainer(net_f.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net_f(a), b))
    for _ in range(3):
        step(x, y)
    assert step.mode == "fused" and step.n_traces == 1
    _assert_params_close(net_e, net_f, rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# compiled-program structure (mx.analysis — ISSUE 4 acceptance)
# ---------------------------------------------------------------------------

def test_program_report_plain_fused_donates_everything(program_report):
    """dp=1 plain-fused mode, machine-checked: EVERY param/state buffer
    donated and actually aliased by XLA (no copy fallback), zero
    collectives, zero host transfers, zero dtype drift — the structural
    contract behind the writeback test above (which can't see a silent
    donation->copy regression: numerics stay right, HBM pays double)."""
    net = _build(with_bn=True)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    x, y = _batch()
    step(x, y)
    assert step.mode == "fused"
    rep = program_report(step, x, y)
    assert rep.mode == "fused"
    d = rep.donation
    # every param (incl. BN running stats) + every optimizer-state leaf
    assert d.expected == rep.meta["n_params"] + rep.meta["n_state_leaves"]
    assert d.aliased == d.expected, rep.summary()
    assert d.copied == [] and d.donated_bytes > 0
    assert rep.collectives.ops == []
    assert rep.host_transfers == [] and rep.dtype_drift == []
    assert rep.ok, rep.summary()


def test_program_report_fused_step_zero_stranded_ops(program_report):
    """ISSUE 9 structural acceptance: the plain fused MLP step's
    OPTIMIZED program carries a populated fusion census with ZERO
    fusable ops stranded between two fusions above the size floor —
    XLA fused everything it could, and the ideal-fusion diff
    (arXiv:2301.13062) stays silent.  A future change that fragments
    the step program (an op XLA stops fusing, a layout transpose
    between kernels) fails HERE, not as an MFU drop later."""
    net = _build(with_bn=True)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    x, y = _batch()
    step(x, y)
    rep = program_report(step, x, y)
    fr = rep.fusion
    assert fr is not None and fr.n_fusions > 0, rep.summary()
    assert fr.stranded == [], rep.summary()
    assert fr.boundary_bytes > 0          # kernels do exchange data
    assert all(k.kind in ("loop", "input", "output", "custom")
               for k in fr.fusions)
    assert rep.ok, rep.summary()


def test_program_report_donate_false_expects_nothing(program_report):
    """donate=False: the audit must not demand aliasing that was never
    requested."""
    net = _build(with_bn=False)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                donate=False)
    x, y = _batch()
    step(x, y)
    rep = program_report(step, x, y)
    assert rep.donation.expected is None
    assert rep.ok, rep.summary()
