"""mx.visualization: print_summary + plot_network.

Reference analog: python/mxnet/visualization.py (:46 print_summary,
:210 plot_network) — exercised the way the reference's users do
(mx.viz.* over a Symbol graph), with the summary's parameter math
cross-checked against the Gluon model zoo's real parameter count for
the same ResNet-18 architecture.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.symbol as sym


def _conv_bn_fc():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, sym.Variable("conv1_weight"), kernel=(3, 3),
                         num_filter=16, pad=(1, 1), no_bias=True,
                         name="conv1")
    bn = sym.BatchNorm(c1, sym.Variable("bn1_gamma"),
                       sym.Variable("bn1_beta"),
                       sym.Variable("bn1_moving_mean"),
                       sym.Variable("bn1_moving_var"), name="bn1")
    act = sym.Activation(bn, act_type="relu", name="relu1")
    fl = sym.Flatten(act, name="flat1")
    fc = sym.FullyConnected(fl, sym.Variable("fc1_weight"),
                            sym.Variable("fc1_bias"), num_hidden=10,
                            name="fc1")
    shapes = {"data": (1, 3, 8, 8), "conv1_weight": (16, 3, 3, 3),
              "bn1_gamma": (16,), "bn1_beta": (16,),
              "bn1_moving_mean": (16,), "bn1_moving_var": (16,),
              "fc1_weight": (10, 16 * 8 * 8), "fc1_bias": (10,)}
    return fc, shapes


def test_print_summary_table_and_params(capsys):
    fc, shapes = _conv_bn_fc()
    total = mx.viz.print_summary(fc, shape=shapes, line_length=90)
    out = capsys.readouterr().out
    # conv 3*16*3*3=432; bn gamma+beta=32; fc (1024+1)*10=10250
    assert total == 432 + 32 + 10250
    assert "Total params: 10714" in out
    assert "Layer (type)" in out and "Output Shape" in out
    assert "conv1(Convolution)" in out
    assert "16x8x8" in out          # batch axis stripped
    assert "fc1(FullyConnected)" in out and "10250" in out


def test_print_summary_requires_symbol_and_complete_shape():
    with pytest.raises(TypeError):
        mx.viz.print_summary("not a symbol")
    fc, shapes = _conv_bn_fc()
    del shapes["data"]
    with pytest.raises(mx.MXNetError, match="incomplete"):
        mx.viz.print_summary(fc, shape=shapes)


def test_print_summary_infers_param_shapes_from_data_alone():
    """Reference-style call: only the data shape supplied; parameter
    shapes (conv weight, BN stats, FC weight/bias) are inferred from op
    attrs like the reference's nnvm infer-shape pass does."""
    fc, _ = _conv_bn_fc()
    total = mx.viz.print_summary(fc, shape={"data": (1, 3, 8, 8)})
    assert total == 432 + 32 + 10250


def test_node_shapes_is_abstract_no_device_arrays(monkeypatch):
    """The shape walk must never materialize arrays: creating a concrete
    jnp array during it would defeat eval_shape (advisor round-4 low)."""
    import jax.numpy as jnp
    fc, shapes = _conv_bn_fc()
    real_zeros = jnp.zeros

    def boom(*a, **k):
        raise AssertionError("concrete array materialized during shape walk")

    monkeypatch.setattr(jnp, "zeros", boom)
    try:
        from mxnet_tpu.visualization import _node_shapes
        out = _node_shapes(fc, shapes)
    finally:
        monkeypatch.setattr(jnp, "zeros", real_zeros)
    assert out[id(fc)] == (1, 10)


def test_plot_network_source_and_hide_weights():
    fc, shapes = _conv_bn_fc()
    dot = mx.viz.plot_network(fc, shape=shapes)
    src = dot.source
    for want in ("conv1", "bn1", "relu1", "fc1", "digraph"):
        assert want in src
    assert "conv1_weight" not in src and "fc1_bias" not in src
    # edges carry the producer's (batch-stripped) shape
    assert "16x8x8" in src

    dot2 = mx.viz.plot_network(fc, shape=shapes, hide_weights=False)
    assert "conv1_weight" in dot2.source


def test_plot_network_fallback_digraph_without_graphviz(monkeypatch):
    import builtins
    real_import = builtins.__import__

    def no_graphviz(name, *a, **k):
        if name == "graphviz":
            raise ImportError("simulated absence")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_graphviz)
    fc, shapes = _conv_bn_fc()
    dot = mx.viz.plot_network(fc, shape=shapes)
    src = dot.source
    assert src.startswith("digraph") and "conv1" in src
    with pytest.raises(mx.MXNetError):
        dot.render()


# ---------------------------------------------------------------------------
# ResNet-18: symbolic graph whose summary total must equal the Gluon
# model zoo's trainable-parameter count for the same architecture
# ---------------------------------------------------------------------------

def _sym_resnet18(classes=1000):
    """Symbolic ResNet-18 v1 mirroring gluon.model_zoo.vision.resnet18_v1
    (BasicBlockV1: conv3x3-bn-relu-conv3x3-bn + identity/1x1-downsample)."""
    names = iter(range(10000))

    def v(prefix, shape=None):
        return sym.Variable(f"{prefix}")

    def conv(x, ci, co, k, s, p, name):
        return sym.Convolution(x, v(f"{name}_weight"), kernel=(k, k),
                               stride=(s, s), pad=(p, p), num_filter=co,
                               no_bias=True, name=name)

    def bn(x, name):
        return sym.BatchNorm(x, v(f"{name}_gamma"), v(f"{name}_beta"),
                             v(f"{name}_moving_mean"),
                             v(f"{name}_moving_var"), name=name)

    shapes = {"data": (1, 3, 224, 224)}

    def reg_conv(name, ci, co, k):
        shapes[f"{name}_weight"] = (co, ci, k, k)

    def reg_bn(name, c):
        for s in ("gamma", "beta", "moving_mean", "moving_var"):
            shapes[f"{name}_{s}"] = (c,)

    data = sym.Variable("data")
    x = conv(data, 3, 64, 7, 2, 3, "conv0")
    reg_conv("conv0", 3, 64, 7)
    x = bn(x, "bn0")
    reg_bn("bn0", 64)
    x = sym.Activation(x, act_type="relu", name="relu0")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                    pool_type="max", name="pool0")

    ci = 64
    bi = 0
    for stage, (co, s0) in enumerate([(64, 1), (128, 2), (256, 2),
                                      (512, 2)]):
        for blk in range(2):
            s = s0 if blk == 0 else 1
            n = f"s{stage}b{blk}"
            y = conv(x, ci, co, 3, s, 1, f"{n}_conv1")
            reg_conv(f"{n}_conv1", ci, co, 3)
            y = bn(y, f"{n}_bn1")
            reg_bn(f"{n}_bn1", co)
            y = sym.Activation(y, act_type="relu", name=f"{n}_relu1")
            y = conv(y, co, co, 3, 1, 1, f"{n}_conv2")
            reg_conv(f"{n}_conv2", co, co, 3)
            y = bn(y, f"{n}_bn2")
            reg_bn(f"{n}_bn2", co)
            if s != 1 or ci != co:
                sc = conv(x, ci, co, 1, s, 0, f"{n}_down")
                reg_conv(f"{n}_down", ci, co, 1)
                sc = bn(sc, f"{n}_downbn")
                reg_bn(f"{n}_downbn", co)
            else:
                sc = x
            x = sym.Activation(y + sc, act_type="relu", name=f"{n}_out")
            ci = co
            bi += 1

    x = sym.Pooling(x, global_pool=True, pool_type="avg", name="gap")
    x = sym.Flatten(x, name="flat")
    fc = sym.FullyConnected(x, sym.Variable("fc_weight"),
                            sym.Variable("fc_bias"), num_hidden=classes,
                            name="fc")
    shapes["fc_weight"] = (classes, 512)
    shapes["fc_bias"] = (classes,)
    return fc, shapes


def test_resnet18_summary_matches_gluon_param_count(capsys):
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(classes=1000)
    net.initialize()
    net(mx.nd.array(onp.zeros((1, 3, 32, 32), "float32")))
    gluon_trainable = sum(
        int(onp.prod(p.shape)) for p in net.collect_params().values()
        if p._data is not None and p.grad_req != "null")

    fc, shapes = _sym_resnet18()
    total = mx.viz.print_summary(fc, shape=shapes)
    out = capsys.readouterr().out
    assert total == gluon_trainable == 11689512
    assert "conv0(Convolution)" in out
    assert "64x112x112" in out      # stride-2 stem at 224 input
    assert "fc(FullyConnected)" in out

    dot = mx.viz.plot_network(fc, shape=shapes)
    assert "s3b1_conv2" in dot.source


def test_node_shapes_scalar_interior_output_not_missing():
    """A 0-d interior output (shape ()) is falsy: `or`-chained lookups
    misreported it as a missing input shape. Explicit `is None` checks
    must resolve it (ISSUE 1 satellite)."""
    data = sym.Variable("data")
    total = sym.sum(data)          # interior node, output shape ()
    out = data * total
    from mxnet_tpu.visualization import _node_shapes
    shp = _node_shapes(out, {"data": (2, 3)})
    assert sorted(shp.values()) == [(), (2, 3), (2, 3)]
    # and the user-facing surface runs end to end over it
    assert mx.viz.print_summary(out, shape={"data": (2, 3)}) == 0
