"""Subprocess worker for the kill-9 crash-consistency tests
(tests/test_checkpoint.py). Runs a deterministic TrainLoop with
checkpointing; the parent arms MXNET_FAULT_INJECT so this process gets
SIGKILLed mid-checkpoint, then re-runs it clean and asserts bit-exact
loss parity with an uninterrupted run.

Usage::

    python checkpoint_crash_worker.py <ckpt_dir> <out_file> \
        --mode fused|zero --opt sgd|adam --steps N [--every K]

Writes one loss per line to <out_file> as ``<step_index> <loss>`` —
appended AFTER the step completes, so a killed run leaves a truncated
but parseable log.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import numpy as onp  # noqa: E402


def batch(i, bs=8):
    rng = onp.random.RandomState(1000 + i)
    return (rng.randn(bs, 4).astype("float32"),
            rng.randint(0, 3, size=(bs,)).astype("int32"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("ckpt_dir")
    ap.add_argument("out_file")
    ap.add_argument("--mode", choices=["fused", "zero"], default="fused")
    ap.add_argument("--opt", choices=["sgd", "adam"], default="sgd")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--every", type=int, default=2)
    ap.add_argument("--sync", action="store_true")
    args = ap.parse_args()

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import TrainLoop, Trainer, nn
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.parallel import make_mesh

    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    opt_params = {"learning_rate": 0.05}
    if args.opt == "sgd":
        opt_params["momentum"] = 0.9
    trainer = Trainer(net.collect_params(), args.opt, opt_params)
    loss = gloss.SoftmaxCrossEntropyLoss()

    mesh = make_mesh({"dp": 4}, jax.devices()[:4]) \
        if args.mode == "zero" else None

    def run():
        loop = TrainLoop(net, trainer, loss,
                         checkpoint_dir=args.ckpt_dir,
                         checkpoint_every=args.every,
                         async_checkpoint=not args.sync)
        if args.mode == "zero":
            # TrainLoop compiles via Trainer.compile_step with auto
            # zero detection: the active mesh turns it on
            assert mesh is not None
        for i in range(loop.global_step, args.steps):
            x, y = batch(i)
            l = loop.step(nd.array(x), nd.array(y))
            val = float(onp.asarray(l.asnumpy()).sum())
            with open(args.out_file, "a") as f:
                f.write(f"{i} {val:.9e}\n")
                f.flush()
                os.fsync(f.fileno())
        loop.wait()
        if args.mode == "zero":
            assert loop.compiled_step.zero_sharded, "zero mode inactive"

    if mesh is not None:
        with mesh:
            run()
    else:
        run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
