"""Subprocess worker for the elastic chaos tests (tests/test_elastic.py,
markers ``chaos`` + ``slow``).

Two modes:

``chaos <ckpt_dir>``
    Runs a dp=8 supervised TrainLoop under ``MXNET_TELEMETRY=1`` +
    ``MXNET_TRANSFER_GUARD=raise`` with an in-process fault timeline —
    revoke 4 devices before dispatch hit 6, restore them before hit 10
    — so the run shrinks 8→4 and grows back 4→8. Then, in the same
    process, SELF-VERIFIES loss-curve continuity: for each re-formation
    it replays an uninterrupted reference run at the new layout,
    restored from the exact checkpoint the supervisor restored
    (``TrainCheckpointManager.restore_step``), and asserts the loss
    trajectories are bit-exact. Prints one JSON verdict line prefixed
    ``RESULT ``.

``sigterm <ckpt_dir>``
    Runs a long supervised loop, prints ``READY`` once steps are
    flowing, and waits for the parent's SIGTERM. The supervisor's
    preemption notice must drain the window and commit the grace-window
    final checkpoint; the worker prints the JSON verdict and exits 0.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ["MXNET_TELEMETRY"] = "1"
os.environ["MXNET_TRANSFER_GUARD"] = "raise"

import numpy as onp  # noqa: E402


def _build_fn(seed=3):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.gluon import loss as gloss

    def build():
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4, activation="relu"))
        net.add(nn.Dense(3, in_units=8))
        net.initialize()
        trainer = Trainer(net.collect_params(), "adam",
                          {"learning_rate": 0.05})
        return net, trainer, gloss.SoftmaxCrossEntropyLoss()

    return build


def _batch_fn(i, bs=8):
    import mxnet_tpu as mx
    rng = onp.random.RandomState(1000 + i)
    return (mx.nd.array(rng.randn(bs, 4).astype("float32")),
            mx.nd.array(rng.randint(0, 3, size=(bs,)).astype("int32")))


def _reference_segment(ckpt_dir, restored_step, until_step, dp):
    """Uninterrupted run at dp devices restored from the EXACT
    checkpoint the supervisor restored; returns {i: summed loss}."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.checkpoint import TrainCheckpointManager
    from mxnet_tpu.gluon import TrainLoop
    from mxnet_tpu.parallel import make_mesh

    build = _build_fn()
    net, trainer, loss_blk = build()
    with make_mesh({"dp": dp}, jax.devices()[:dp]):
        mgr = TrainCheckpointManager(ckpt_dir)
        mgr.restore_step(restored_step, trainer=trainer, net=net)
        loop = TrainLoop(net, trainer, loss_blk)
        handles = {}
        for i in range(restored_step, until_step):
            handles[i] = loop.step(*_batch_fn(i))
        loop.synchronize()
    return {i: float(h.asnumpy().sum()) for i, h in handles.items()}


def run_chaos(ckpt_dir):
    import mxnet_tpu as mx
    from mxnet_tpu.testing import faults

    total = 14
    faults.configure("step.dispatch:before=6:revoke:4;"
                     "step.dispatch:before=10:restore")
    sup = mx.elastic.ElasticSupervisor(
        _build_fn(), ckpt_dir, mesh_axes={"dp": -1},
        checkpoint_every=2, keep_last=99, backoff_base=0.0,
        log=mx.elastic.RecoveryLog())
    try:
        res = sup.run(_batch_fn, total)
    finally:
        faults.reset()

    wd = mx.telemetry.watchdog()
    verdict = {
        "ok": True, "detail": [],
        "final_step": res.final_step,
        "world_size": res.world_size,
        "preempted": res.preempted,
        "events": res.events,
        "device_lost_anomalies": len(wd.anomalies("device_lost")),
        "recoveries_by_cause": {
            c: len([e for e in res.events if e["cause"] == c])
            for c in ("device_lost", "grow")},
    }

    def fail(msg):
        verdict["ok"] = False
        verdict["detail"].append(msg)

    if res.final_step != total:
        fail(f"final_step {res.final_step} != {total}")
    if res.world_size != 8:
        fail(f"did not grow back: world {res.world_size}")
    if len(wd.anomalies("device_lost")) != 1:
        fail(f"{len(wd.anomalies('device_lost'))} device_lost "
             "anomalies, want exactly 1")
    shrink = [e for e in res.events if e["cause"] == "device_lost"]
    grow = [e for e in res.events if e["cause"] == "grow"]
    if len(shrink) != 1 or len(grow) != 1:
        fail(f"events: {len(shrink)} device_lost + {len(grow)} grow, "
             "want exactly 1 + 1")
    if verdict["ok"]:
        s, g = shrink[0], grow[0]
        if not (s["old_dp"] == 8 and s["new_dp"] == 4):
            fail(f"shrink dp {s['old_dp']}->{s['new_dp']}, want 8->4")
        if not (g["old_dp"] == 4 and g["new_dp"] == 8):
            fail(f"grow dp {g['old_dp']}->{g['new_dp']}, want 4->8")
        # loss-curve continuity: bit-exact from the restored step at
        # the new layout, vs an uninterrupted run restored from the
        # SAME checkpoint
        r1, r2 = s["restored_step"], g["restored_step"]
        ref4 = _reference_segment(ckpt_dir, r1, r2, dp=4)
        for i, want in ref4.items():
            if res.losses.get(i) != want:
                fail(f"dp=4 segment step {i}: supervised "
                     f"{res.losses.get(i)} != reference {want}")
        ref8 = _reference_segment(ckpt_dir, r2, 14, dp=8)
        for i, want in ref8.items():
            if res.losses.get(i) != want:
                fail(f"dp=8 segment step {i}: supervised "
                     f"{res.losses.get(i)} != reference {want}")
        verdict["dp4_segment"] = [r1, r2]
        verdict["dp8_segment"] = [r2, 14]
    print("RESULT " + json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


def run_sigterm(ckpt_dir):
    import mxnet_tpu as mx

    def batch_fn(i):
        if i == 5:
            print("READY", flush=True)
        time.sleep(0.02)      # keep the process alive for the signal
        return _batch_fn(i)

    sup = mx.elastic.ElasticSupervisor(
        _build_fn(), ckpt_dir, mesh_axes={"dp": -1},
        checkpoint_every=2, backoff_base=0.0,
        log=mx.elastic.RecoveryLog())
    res = sup.run(batch_fn, 100_000)
    mgr = sup.loop.checkpoint_manager
    verdict = {
        "preempted": res.preempted,
        "final_step": res.final_step,
        "latest_checkpoint": mgr.latest_step(),
        "preemption_events": len(res.events),
        "causes": [e["cause"] for e in res.events],
    }
    print("RESULT " + json.dumps(verdict), flush=True)
    return 0 if res.preempted else 1


def main():
    mode, ckpt_dir = sys.argv[1], sys.argv[2]
    if mode == "chaos":
        return run_chaos(ckpt_dir)
    if mode == "sigterm":
        return run_sigterm(ckpt_dir)
    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    sys.exit(main())
