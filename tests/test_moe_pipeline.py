"""Expert parallelism (MoE all-to-all) and pipeline parallelism (GPipe
microbatch schedule) — the EP/PP legs of the parallelism matrix (SURVEY
§2.3: absent in the reference; TPU-native extensions like ring attention).
Runs on the 8-virtual-device CPU mesh (conftest)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.ops import moe as moe_ops


def _weights(rng, e, d, h):
    gate = rng.randn(d, e).astype("float32") * 0.5
    w1 = rng.randn(e, d, h).astype("float32") * 0.2
    w2 = rng.randn(e, h, d).astype("float32") * 0.2
    return jnp.asarray(gate), jnp.asarray(w1), jnp.asarray(w2)


def _moe_numpy_reference(x, gate, w1, w2, top_k):
    """Per-token loop, unlimited capacity: ground truth when nothing is
    dropped."""
    probs = onp.exp(x @ gate)
    probs /= probs.sum(-1, keepdims=True)
    out = onp.zeros_like(x)
    for i in range(x.shape[0]):
        order = onp.argsort(-probs[i])[:top_k]
        for e in order:
            hdn = onp.maximum(x[i] @ w1[e], 0)
            out[i] += probs[i, e] * (hdn @ w2[e])
    return out


def test_moe_dense_matches_per_token_reference():
    rng = onp.random.RandomState(0)
    n, d, h, e, k = 16, 8, 12, 4, 2
    x = rng.randn(n, d).astype("float32")
    gate, w1, w2 = _weights(rng, e, d, h)
    out, aux = moe_ops.moe_ffn(jnp.asarray(x), gate, w1, w2, top_k=k,
                               capacity_factor=8.0)  # no drops
    ref = _moe_numpy_reference(x, onp.asarray(gate), onp.asarray(w1),
                               onp.asarray(w2), k)
    onp.testing.assert_allclose(onp.asarray(out), ref, rtol=2e-4, atol=2e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_tokens_statically():
    """Overflowing tokens are dropped (combine weight 0), shapes static —
    the Switch/GShard contract."""
    rng = onp.random.RandomState(1)
    n, d, h, e = 8, 4, 6, 2
    x = rng.randn(n, d).astype("float32")
    gate, w1, w2 = _weights(rng, e, d, h)
    # capacity 1 per expert with top_k=1: at most e tokens contribute
    out, _ = moe_ops.moe_ffn(jnp.asarray(x), gate, w1, w2, top_k=1,
                             capacity_factor=e / n)
    nonzero_rows = int((onp.abs(onp.asarray(out)).sum(-1) > 1e-7).sum())
    assert nonzero_rows <= e


def test_moe_expert_parallel_matches_dense():
    """EP path (experts sharded over 'ep', two all-to-alls) must equal the
    dense path when capacity is generous (no drops)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    ep = 4
    rng = onp.random.RandomState(2)
    n, d, h, e, k = 32, 8, 16, 8, 2
    x = rng.randn(n, d).astype("float32")
    gate, w1, w2 = _weights(rng, e, d, h)
    dense_out, dense_aux = moe_ops.moe_ffn(
        jnp.asarray(x), gate, w1, w2, top_k=k, capacity_factor=8.0)

    mesh = Mesh(onp.array(jax.devices()[:ep]), ("ep",))
    e_local = e // ep

    def shard_fn(xs, gw, w1s, w2s):
        out, aux = moe_ops.moe_ffn(xs, gw, w1s, w2s, top_k=k,
                                   capacity_factor=8.0, axis_name="ep")
        # tokens replicated across ep: every shard computes the full n
        return out, aux

    # every shard computes identical token outputs, but the all-to-alls
    # make that unprovable statically -> check_vma off
    from mxnet_tpu.parallel import shard_map as _shard_map
    f = jax.jit(_shard_map(
        shard_fn, mesh,
        (P(), P(), P("ep"), P("ep")),
        (P(), P())))
    ep_out, ep_aux = f(jnp.asarray(x), gate, w1, w2)
    onp.testing.assert_allclose(onp.asarray(ep_out),
                                onp.asarray(dense_out),
                                rtol=2e-4, atol=2e-5)


def test_moe_expert_parallel_gradients_flow():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    ep = 4
    rng = onp.random.RandomState(3)
    n, d, h, e = 16, 4, 8, 4
    x = jnp.asarray(rng.randn(n, d).astype("float32"))
    gate, w1, w2 = _weights(rng, e, d, h)
    mesh = Mesh(onp.array(jax.devices()[:ep]), ("ep",))

    def loss_fn(params, xs):
        gw, w1s, w2s = params

        def shard(xs_, gw_, w1_, w2_):
            out, aux = moe_ops.moe_ffn(xs_, gw_, w1_, w2_, top_k=1,
                                       capacity_factor=4.0, axis_name="ep")
            return jnp.sum(out ** 2) + 0.01 * aux

        from mxnet_tpu.parallel import shard_map as _shard_map
        return _shard_map(shard, mesh,
                          (P(), P(), P("ep"), P("ep")),
                          P())(xs, gw, w1s, w2s)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))((gate, w1, w2), x)
    assert onp.isfinite(float(loss))
    for g in grads:
        s = float(jnp.abs(g).sum())
        assert onp.isfinite(s) and s > 0


def test_moe_gluon_layer_trains():
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn
    rng = onp.random.RandomState(4)
    layer = nn.MoE(units=8, hidden=16, num_experts=4, top_k=2,
                   capacity_factor=4.0)
    layer.initialize()
    trainer = gluon.Trainer(layer.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = nd.array(rng.randn(16, 8).astype("float32"))
    target = nd.array(rng.randn(16, 8).astype("float32"))
    losses = []
    for _ in range(12):
        with autograd.record():
            out, aux = layer(x)
            loss = ((out - target) ** 2).mean() + 0.01 * aux
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

def _stage(p, x):
    return jnp.tanh(x @ p)


def test_pipeline_matches_sequential():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from mxnet_tpu.parallel.pipeline import run_pipeline
    pp, d, b, m = 4, 6, 16, 8
    rng = onp.random.RandomState(5)
    stages = jnp.asarray(rng.randn(pp, d, d).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, d).astype("float32"))
    mesh = Mesh(onp.array(jax.devices()[:pp]), ("pp",))
    out = run_pipeline(_stage, stages, x, num_microbatches=m, mesh=mesh)
    seq = onp.asarray(x)
    for s in range(pp):
        seq = onp.tanh(seq @ onp.asarray(stages[s]))
    onp.testing.assert_allclose(onp.asarray(out), seq, rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from mxnet_tpu.parallel.pipeline import run_pipeline
    pp, d, b, m = 4, 4, 8, 4
    rng = onp.random.RandomState(6)
    stages = jnp.asarray(rng.randn(pp, d, d).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, d).astype("float32"))
    mesh = Mesh(onp.array(jax.devices()[:pp]), ("pp",))

    def pipe_loss(ws):
        return jnp.mean(run_pipeline(_stage, ws, x, m, mesh) ** 2)

    def seq_loss(ws):
        h = x
        for s in range(pp):
            h = jnp.tanh(h @ ws[s])
        return jnp.mean(h ** 2)

    lp, gp = jax.value_and_grad(pipe_loss)(stages)
    ls, gs = jax.value_and_grad(seq_loss)(stages)
    onp.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    onp.testing.assert_allclose(onp.asarray(gp), onp.asarray(gs),
                                rtol=2e-4, atol=1e-5)


def test_pipeline_validates_shapes():
    from mxnet_tpu.parallel.pipeline import run_pipeline
    from mxnet_tpu.base import MXNetError
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    mesh = Mesh(onp.array(jax.devices()[:4]), ("pp",))
    stages = jnp.zeros((3, 4, 4))  # wrong stage count
    with pytest.raises(MXNetError, match="stacked_params"):
        run_pipeline(_stage, stages, jnp.zeros((8, 4)), 4, mesh)
    with pytest.raises(MXNetError, match="microbatch"):
        run_pipeline(_stage, jnp.zeros((4, 4, 4)), jnp.zeros((7, 4)), 4,
                     mesh)


# ---------------------------------------------------------------------------
# expect_spec structural coverage (PR 13): the EP and PP paths stop
# being dryrun-only — their compiled programs are pinned to the spec
# packs registered next to the implementations (ops/moe.py,
# parallel/pipeline.py): collective signature, zero implicit reshards
# above the floor, sharded-state byte budget, and the checked-in
# reshard baseline.
# ---------------------------------------------------------------------------

def _baseline_check(report, leg):
    import os
    from mxnet_tpu.analysis import sharding as asharding
    baselines = asharding.load_baselines(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures",
        "sharding_baselines.json"))
    return asharding.check_baseline(report.sharding, baselines, leg)


def test_moe_ep_spec_pack():
    """The EP program's structural contract: exactly the
    dispatch/combine all-to-all pair on 'ep', no implicit reshards,
    expert weights at ~1/ep per device."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from mxnet_tpu.analysis import sharding as asharding
    from mxnet_tpu.analysis.program import analyze_lowered
    from mxnet_tpu.parallel import shard_map as _shard_map
    ep = 4
    rng = onp.random.RandomState(2)
    n, d, h, e, k = 32, 8, 16, 8, 2
    x = jnp.asarray(rng.randn(n, d).astype("float32"))
    gate, w1, w2 = _weights(rng, e, d, h)
    mesh = Mesh(onp.array(jax.devices()[:ep]), ("ep",))
    fn = _shard_map(
        lambda xs, gw, u, v: moe_ops.moe_ffn(
            xs, gw, u, v, top_k=k, capacity_factor=8.0,
            axis_name="ep")[0],
        mesh, (P("ep"), P(), P("ep"), P("ep")), P("ep"))
    report = analyze_lowered(jax.jit(fn).lower(x, gate, w1, w2),
                             mesh=mesh)
    findings = asharding.expect_spec(report, "ep-moe")
    assert findings == [], [str(f) for f in findings]
    assert report.collectives.count("all_to_all", axis="ep") == 2
    assert report.sharding.reshards == []
    loc, glob = report.sharding.table.sharded_bytes("ep")
    assert glob == loc * ep         # w1/w2 really live at 1/ep
    assert _baseline_check(report, "ep-moe") == []


def test_pipeline_pp_spec_pack():
    """The PP program's structural contract: the ppermute ring hop plus
    the one last-stage psum broadcast on 'pp', no implicit reshards,
    stage weights at ~1/pp per device."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from mxnet_tpu.analysis import sharding as asharding
    from mxnet_tpu.analysis.program import analyze_lowered
    from mxnet_tpu.parallel.pipeline import run_pipeline
    pp, d, b, m = 4, 6, 16, 8
    rng = onp.random.RandomState(5)
    stages = jnp.asarray(rng.randn(pp, d, d).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, d).astype("float32"))
    mesh = Mesh(onp.array(jax.devices()[:pp]), ("pp",))
    lowered = jax.jit(
        lambda ws, xb: run_pipeline(_stage, ws, xb, m, mesh)) \
        .lower(stages, x)
    report = analyze_lowered(lowered, mesh=mesh)
    findings = asharding.expect_spec(report, "pp-gpipe")
    assert findings == [], [str(f) for f in findings]
    assert report.collectives.count("collective_permute",
                                    axis="pp") >= 1
    assert report.collectives.count("all_reduce", axis="pp") >= 1
    assert report.sharding.reshards == []
    loc, glob = report.sharding.table.sharded_bytes("pp")
    assert glob == loc * pp         # stage weights really live at 1/pp
    assert _baseline_check(report, "pp-gpipe") == []
