"""ZeRO-1 sharded weight update inside the fused train step (PR 2).

Covers the acceptance bar of ISSUE 2: sharded and allreduce fused-step
modes agree on SGD-momentum and Adam losses over 4 steps on a >=2-device
dp mesh (virtual CPU), per-replica optimizer-state bytes drop ~N× for
Adam, no retrace when only lr/batch-size change — plus the padded
non-divisible shapes, the small-param bucket (MXNET_ZERO_SHARD_MIN_SIZE),
multi-precision fp32 masters living sharded, and the eligibility gates
(non-elementwise optimizers, explicit zero_shard=True/False).
"""
import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import make_mesh, shard_batch

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")

DP = 4


def _mesh():
    return make_mesh({"dp": DP}, jax.devices()[:DP])


def _build(seed=3):
    """Dense sizes chosen so some flat sizes are NOT divisible by DP=4
    (Dense(5, in_units=3): weight 15, bias 5) — exercising the padded
    shard layout."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    return net


def _batch(bs=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return x, y


def _assert_params_close(net_a, net_b, rtol=1e-4, atol=1e-5):
    for (k, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_b.collect_params().items())):
        onp.testing.assert_allclose(pa.data().asnumpy(),
                                    pb.data().asnumpy(),
                                    rtol=rtol, atol=atol, err_msg=k)


def _run_eager(net, opt, kwargs, x, y, steps, lr_change=None):
    trainer = Trainer(net.collect_params(), opt, dict(kwargs))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    losses = []
    for i in range(steps):
        if lr_change and i == lr_change[0]:
            trainer.learning_rate = lr_change[1]
        with autograd.record():
            l = loss_blk(net(x), y)
        l.backward()
        trainer.step(x.shape[0])
        losses.append(float(l.asnumpy().mean()))
    return losses


def _run_zero(net, opt, kwargs, x, y, steps, lr_change=None):
    trainer = Trainer(net.collect_params(), opt, dict(kwargs))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    losses = []
    with _mesh() as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        for i in range(steps):
            if lr_change and i == lr_change[0]:
                trainer.learning_rate = lr_change[1]
            losses.append(float(step(xs, ys).asnumpy().mean()))
    return losses, step


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_zero_parity_vs_eager(monkeypatch, opt, kwargs):
    """Weights and per-step losses after 4 zero-sharded steps — with an
    lr change mid-run and padded (non-divisible) parameter shapes —
    match the single-logical-device eager loop."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()
    net_e = _build()
    le = _run_eager(net_e, opt, kwargs, x, y, steps=4, lr_change=(2, 0.02))
    net_z = _build()
    lz, step = _run_zero(net_z, opt, kwargs, x, y, steps=4,
                         lr_change=(2, 0.02))
    assert step.mode == "fused" and step.zero_sharded
    assert step._zero is not None
    # every trainable param is its own unit at min_size=1
    assert all(len(u["members"]) == 1 for u in step._zero.units)
    onp.testing.assert_allclose(le, lz, atol=1e-5)
    _assert_params_close(net_e, net_z)


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_zero_parity_vs_allreduce_fused(monkeypatch, opt, kwargs):
    """ISSUE 2 acceptance: the sharded and plain-allreduce fused modes
    agree on per-step losses over 4 steps (atol 1e-5)."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()

    net_a = _build()
    tr_a = Trainer(net_a.collect_params(), opt, dict(kwargs))
    lba = gloss.SoftmaxCrossEntropyLoss()
    step_a = tr_a.compile_step(lambda a, b: lba(net_a(a), b))
    la = [float(step_a(x, y).asnumpy().mean()) for _ in range(4)]
    assert step_a.mode == "fused" and not step_a.zero_sharded

    net_z = _build()
    lz, step_z = _run_zero(net_z, opt, kwargs, x, y, steps=4)
    onp.testing.assert_allclose(la, lz, atol=1e-5)
    _assert_params_close(net_a, net_z)


def test_zero_bucket_small_params(monkeypatch):
    """Params below MXNET_ZERO_SHARD_MIN_SIZE concatenate into ONE fused
    flat shard (per dtype) — numerics unchanged vs eager."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "100000")
    x, y = _batch()
    net_e = _build()
    le = _run_eager(net_e, "adam", {"learning_rate": 1e-2}, x, y, steps=4)
    net_z = _build()
    lz, step = _run_zero(net_z, "adam", {"learning_rate": 1e-2}, x, y,
                         steps=4)
    plan = step._zero
    assert len(plan.units) == 1 and len(plan.units[0]["members"]) == 6
    assert plan.units[0]["padded"] % DP == 0
    onp.testing.assert_allclose(le, lz, atol=1e-5)
    _assert_params_close(net_e, net_z)


def test_zero_no_retrace_on_lr_and_batch_size(monkeypatch):
    """lr mutation and per-call batch_size stay traced arguments under
    the sharded mode: exactly ONE compile."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "16")
    x, y = _batch()
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    with _mesh() as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        for lr in (0.1, 0.05, 0.2):
            trainer.learning_rate = lr
            step(xs, ys)
        step(xs, ys, batch_size=32)
    assert step.zero_sharded
    assert step.n_traces == 1, "lr/batch-size changes must not retrace"


def test_zero_state_bytes_drop(monkeypatch):
    """Adam moments live sharded: per-replica state bytes ~N× below the
    replicated plain mode."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()

    net_a = _build()
    tr_a = Trainer(net_a.collect_params(), "adam", {"learning_rate": 1e-2})
    lba = gloss.SoftmaxCrossEntropyLoss()
    step_a = tr_a.compile_step(lambda a, b: lba(net_a(a), b))
    step_a(x, y)
    full = step_a.optimizer_state_bytes()

    net_z = _build()
    _, step_z = _run_zero(net_z, "adam", {"learning_rate": 1e-2}, x, y,
                          steps=1)
    shard = step_z.optimizer_state_bytes()
    n_elems = sum(int(onp.prod(p.shape))
                  for p in net_a.collect_params().values())
    assert full == n_elems * 2 * 4  # two f32 moments, replicated
    # padding of the non-divisible shapes costs a little; still ~1/DP
    assert shard <= full / DP * 1.5, (full, shard)
    # states are physically NamedSharding-partitioned over dp
    for st in step_z._zero.states:
        for s in st:
            assert "dp" in str(s._data.sharding.spec)


def test_zero_multi_precision_masters_sharded(monkeypatch):
    """bf16 params + multi_precision: the fused path now ENGAGES (no
    eager fallback) with flat fp32 masters living sharded."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    mx.random.seed(5)
    net = nn.HybridSequential()
    net.add(nn.Dense(6, in_units=4))
    net.initialize()
    net.cast("bfloat16")
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2, "multi_precision": True})
    step = trainer.compile_step(lambda a: (net(a) ** 2).mean())
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(8, 4).astype("float32")).astype("bfloat16")
    with _mesh() as mesh:
        xs = shard_batch(x, mesh)
        before = net._children["0"].weight.data().asnumpy().copy()
        for _ in range(3):
            step(xs, batch_size=8)
    assert step.mode == "fused" and step.zero_sharded
    after = net._children["0"].weight.data().asnumpy()
    assert not onp.allclose(after.astype("float32"),
                            before.astype("float32"))
    assert onp.isfinite(after.astype("float32")).all()
    assert len(step._zero.masters) == 2  # weight + bias masters
    for m in step._zero.masters:
        import jax.numpy as jnp
        assert m._data.dtype == jnp.float32
        assert "dp" in str(m._data.sharding.spec)


def test_zero_requires_mesh_when_forced():
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                zero_shard=True)
    x, y = _batch()
    with pytest.raises(MXNetError, match="zero_shard"):
        step(x, y)


def test_zero_opt_out_inside_mesh():
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                zero_shard=False)
    x, y = _batch()
    with _mesh() as mesh:
        step(shard_batch(x, mesh), shard_batch(y, mesh))
    assert step.mode == "fused" and not step.zero_sharded


def test_zero_non_elementwise_optimizer_keeps_psum():
    """LAMB's trust ratio needs full-layer norms — the sharded update
    must NOT engage; the plain fused mode still runs on the mesh."""
    net = _build()
    trainer = Trainer(net.collect_params(), "lamb", {"learning_rate": 1e-2})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    x, y = _batch()
    with _mesh() as mesh:
        l = step(shard_batch(x, mesh), shard_batch(y, mesh))
    assert step.mode == "fused" and not step.zero_sharded
    assert onp.isfinite(float(l.asnumpy().mean()))


# ---------------------------------------------------------------------------
# compiled-program structure (mx.analysis — ISSUE 4 acceptance)
# ---------------------------------------------------------------------------

def test_zero_program_structure(program_report):
    """The zero-sharded compiled program, machine-checked: >=1
    reduce-scatter and >=1 all-gather on the dp axis, ZERO all-reduces
    carrying a shard unit's gradient (the arXiv:2004.13336 contract —
    a unit-sized all-reduce means the sharded update regressed to
    replicated reductions), all donated buffers aliased, no host
    transfers.  This is the checker the seed's hand-rolled allreduce
    count could not express."""
    net = _build()
    x, y = _batch()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    with _mesh() as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        step(xs, ys)
        assert step.zero_sharded
        rep = program_report(step, xs, ys)
    assert rep.mode == "zero"
    c = rep.collectives
    assert c.count("reduce_scatter", axis="dp") >= 1, rep.summary()
    assert c.count("all_gather", axis="dp") >= 1, rep.summary()
    assert c.matching("all_reduce", rep.meta["unit_sizes"]) == [], \
        rep.summary()
    d = rep.donation
    assert d.expected and d.aliased == d.expected and d.copied == []
    assert rep.host_transfers == []
    assert rep.ok, rep.summary()


def test_plain_mesh_mode_keeps_gradient_reduction(program_report):
    """zero_shard=False inside a mesh (the mesh-aware PLAIN fused mode):
    the dp gradient psum must still exist in-program — a missing
    reduction means replicas silently diverge."""
    net = _build()
    x, y = _batch()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                zero_shard=False)
    with _mesh() as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        step(xs, ys)
        assert not step.zero_sharded
        rep = program_report(step, xs, ys)
    assert rep.mode == "fused-mesh"
    c = rep.collectives
    assert c.count("all_reduce", axis="dp") \
        + c.count("reduce_scatter", axis="dp") >= 1, rep.summary()
    assert rep.ok, rep.summary()
