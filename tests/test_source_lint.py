"""Tier-1 jit-safety sweep: mx.analysis source lint over the framework's
own forward code (mxnet_tpu/gluon/) and the shipped examples.

Any NEW ``.asnumpy()``, tracer-dependent ``if``, or host-RNG call inside
a forward/hybrid_forward fails here immediately — the regression class
where a silently-untraceable forward demotes the whole fused train step
to the eager tape path.  Intentional host-side code is blessed in
tests/fixtures/lint_allowlist.txt (with a reason) or inline with
``# mx-lint: allow=<rule>``; docs/ANALYSIS.md documents the workflow.
"""
import os

import pytest

from mxnet_tpu.analysis.lint import filter_allowed, lint_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.lint


def _sweep(rel, allowlist):
    findings = lint_path(os.path.join(REPO, rel))
    active = filter_allowed(findings, allowlist)
    assert not active, (
        f"jit-unsafe code in {rel} (bless intentional host-side code in "
        "tests/fixtures/lint_allowlist.txt or inline with "
        "`# mx-lint: allow=<rule>` — docs/ANALYSIS.md):\n"
        + "\n".join(f"  {f}" for f in active))
    return findings


def test_gluon_forwards_are_jit_safe(lint_allowlist):
    findings = _sweep(os.path.join("mxnet_tpu", "gluon"), lint_allowlist)
    # the sweep must actually be LOOKING at something: the blessed
    # vision-transform violations are known-present sentinels — if they
    # vanish, the allowlist entries are stale (or the linter broke)
    blessed = [f for f in findings
               if "transforms.py" in f.where and f.rule == "MXA001"]
    assert blessed, ("expected the documented host-side vision-transform "
                     "findings; linter or allowlist is stale")


def test_examples_are_jit_safe(lint_allowlist):
    _sweep("examples", lint_allowlist)


def test_ops_and_parallel_forwards_are_jit_safe(lint_allowlist):
    """The MXA006 surface: ops/ and parallel/ hold the framework's
    collective patterns — any NEW forward that calls raw lax
    collectives (instead of parallel/collectives.py) or places data
    without an explicit sharding fails here (parallel/collectives.py
    itself is exempt by rule)."""
    _sweep(os.path.join("mxnet_tpu", "ops"), lint_allowlist)
    _sweep(os.path.join("mxnet_tpu", "parallel"), lint_allowlist)


def test_allowlist_entries_all_still_hit(lint_allowlist):
    """Every allowlist entry must still match a real finding — dead
    entries hide future violations at the same path."""
    findings = lint_path(os.path.join(REPO, "mxnet_tpu", "gluon"))
    findings += lint_path(os.path.join(REPO, "examples"))
    for suffix, rule in lint_allowlist:
        hit = any(f.where.rsplit(":", 1)[0].replace(os.sep, "/")
                  .endswith(suffix) and rule in ("*", f.rule)
                  for f in findings)
        assert hit, (f"allowlist entry `{suffix}::{rule}` no longer "
                     "matches any finding — remove the stale entry")
