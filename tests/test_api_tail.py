"""API-surface tail: BatchNormReLU, ModifierCell hierarchy,
GroupAdaGrad, InitDesc.

Reference analogs: gluon/nn/basic_layers.py BatchNormReLU,
gluon/rnn/rnn_cell.py ModifierCell/HybridRecurrentCell,
optimizer/contrib.py GroupAdaGrad, initializer.py InitDesc.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn, rnn


def test_batchnorm_relu_equals_bn_then_relu():
    onp.random.seed(0)
    x = nd.array(onp.random.randn(4, 8, 5, 5).astype("float32"))
    a = nn.BatchNormReLU(in_channels=8)
    b = nn.BatchNorm(in_channels=8)
    a.initialize()
    b.initialize()
    got = a(x).asnumpy()
    want = nd.relu(b(x)).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert (got >= 0).all()


def test_modifier_cell_hierarchy_and_delegation():
    base = rnn.LSTMCell(8, input_size=4)
    z = rnn.ZoneoutCell(base, zoneout_outputs=0.1)
    r = rnn.ResidualCell(rnn.RNNCell(4, input_size=4))
    assert isinstance(z, rnn.ModifierCell)
    assert isinstance(r, rnn.ModifierCell)
    assert rnn.HybridRecurrentCell is rnn.RecurrentCell
    assert z.state_info(2) == base.state_info(2)
    base.initialize()
    states = z.begin_state(batch_size=2)
    assert len(states) == len(base.state_info())
    assert "ZoneoutCell" in repr(z) and "LSTMCell" in repr(z)


def test_residual_cell_runs():
    c = rnn.ResidualCell(rnn.RNNCell(4, input_size=4))
    c.base_cell.initialize()
    x = nd.array(onp.random.randn(2, 4).astype("float32"))
    out, states = c(x, c.begin_state(batch_size=2))
    assert out.shape == (2, 4)


def test_group_adagrad_row_wise_history():
    opt = mx.optimizer.create("groupadagrad", learning_rate=0.1)
    w = nd.array(onp.ones((3, 4), "float32"))
    g = nd.array(onp.arange(12, dtype="float32").reshape(3, 4))
    state = opt.create_state(0, w)
    assert state[0].shape == (3, 1)  # one history entry per row
    opt.update(0, w, g, state)
    wn = w.asnumpy()
    # every element in a row moved with the SAME effective lr
    per_row_scale = (1.0 - wn) / (g.asnumpy() + 1e-30)
    for r in range(3):
        row = per_row_scale[r][g.asnumpy()[r] != 0]
        assert onp.allclose(row, row[0], rtol=1e-5)
    with pytest.raises(mx.MXNetError):
        mx.optimizer.create("groupadagrad", wd=0.1)


def test_block_setattr_and_load_dict():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.setattr("grad_req", "null")
    assert all(p.grad_req == "null"
               for p in net.collect_params().values())
    w = nd.array(onp.ones((4, 3), "float32"))
    b = nd.array(onp.full((4,), 2.0, "float32"))
    net.load_dict({"arg:weight": w, "aux:bias": b})  # 1.x prefixes strip
    onp.testing.assert_allclose(net.weight.data().asnumpy(), 1.0)
    onp.testing.assert_allclose(net.bias.data().asnumpy(), 2.0)
    with pytest.raises(mx.MXNetError, match="missing"):
        net.load_dict({"weight": w})
    with pytest.raises(mx.MXNetError, match="extra"):
        net.load_dict({"weight": w, "bias": b, "nope": w})
    net.load_dict({"weight": w}, allow_missing=True)
    net.load_dict({"weight": w, "bias": b, "nope": w}, ignore_extra=True,
                  allow_missing=True)


def test_share_parameters_ties_objects():
    d0 = nn.Dense(8, in_units=4)
    d1 = nn.Dense(8, in_units=4)
    d0.initialize()
    d1.initialize()
    d1.share_parameters(d0.collect_params())
    assert d1.weight is d0.weight and d1.bias is d0.bias
    # a later load into d0 must reflect in d1 (object sharing, not copy)
    d0.load_dict({"weight": nd.array(onp.full((8, 4), 3.0, "float32")),
                  "bias": nd.array(onp.zeros((8,), "float32"))})
    onp.testing.assert_allclose(d1.weight.data().asnumpy(), 3.0)
    x = nd.array(onp.ones((2, 4), "float32"))
    onp.testing.assert_allclose(d0(x).asnumpy(), d1(x).asnumpy())
    with pytest.raises(ValueError):
        d1.share_parameters([1, 2])


def test_register_op_hook_monitors_ops():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Activation("relu"))
    net.initialize()
    seen = []
    handle = net.register_op_hook(lambda tname, opname, arr:
                                  seen.append((tname, opname, arr.shape)))
    x = nd.array(onp.ones((2, 3), "float32"))
    net(x)
    ops = [o for _, o, _ in seen]
    assert any("fully_connected" in o for o in ops), ops
    assert any("relu" in o or "activation" in o for o in ops), ops
    n = len(seen)
    nd.relu(x)  # ops OUTSIDE the block's forward are not monitored
    assert len(seen) == n
    handle.detach()
    net(x)
    assert len(seen) == n and not net._op_hooks  # detached cleanly


def test_register_op_hook_concrete_under_record():
    """Callbacks must receive CONCRETE values even inside
    autograd.record() (review finding round 4: the kernel runs in a vjp
    trace there, so delivery rides the tape's post-vjp output check)."""
    from mxnet_tpu import autograd
    net = nn.Dense(4, in_units=3)
    net.initialize()
    sums = []
    handle = net.register_op_hook(
        lambda tname, opname, arr: sums.append(
            float(arr.asnumpy().sum())))
    try:
        x = nd.array(onp.ones((2, 3), "float32"))
        with autograd.record():
            loss = net(x).sum()
        assert sums and all(onp.isfinite(s) for s in sums)
        # gradient path is unaffected by monitoring
        loss.backward()
        assert onp.isfinite(net.weight.grad().asnumpy()).all()
    finally:
        handle.detach()


def test_load_dict_cast_dtype_saved():
    import jax.numpy as jnp
    net = nn.Dense(4, in_units=3)
    net.initialize()
    wbf = nd.array(onp.ones((4, 3), "float32")).astype("bfloat16")
    bbf = nd.array(onp.zeros((4,), "float32")).astype("bfloat16")
    net.load_dict({"weight": wbf, "bias": bbf}, cast_dtype=True,
                  dtype_source="saved")
    assert net.weight.data().dtype == jnp.bfloat16  # re-typed to saved
    net.load_dict({"weight": wbf, "bias": bbf})  # default: keep current
    assert net.weight.data().dtype == jnp.bfloat16
    with pytest.raises(mx.MXNetError, match="dtype_source"):
        net.load_dict({"weight": wbf, "bias": bbf}, dtype_source="bogus")


def test_infer_type_casts_float_params():
    import jax.numpy as jnp
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.infer_type(nd.array(onp.ones((2, 3), "float32"))
                   .astype("bfloat16"))
    assert net.weight.data().dtype == jnp.bfloat16


def test_hybrid_forward_compat_subclass():
    from mxnet_tpu.gluon import HybridBlock, Parameter

    class OldStyle(HybridBlock):
        def __init__(self):
            super().__init__()
            self.weight = Parameter("weight", shape=(4, 3))

        def hybrid_forward(self, F, x, weight):
            return F.FullyConnected(x, weight, num_hidden=4,
                                    no_bias=True)

    net = OldStyle()
    net.initialize()
    x = nd.array(onp.ones((2, 3), "float32"))
    out = net(x)
    assert out.shape == (2, 4)
    want = nd.dot(x, net.weight.data(), transpose_b=True).asnumpy()
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)


def test_init_desc_carries_attrs():
    from mxnet_tpu.initializer import InitDesc
    d = InitDesc("fc1_weight", attrs={"lr_mult": "0.1"})
    assert d == "fc1_weight" and isinstance(d, str)
    assert d.attrs["lr_mult"] == "0.1" and d.global_init is None


def test_every_registered_optimizer_class_is_importable():
    """Every class in the optimizer registry must be reachable via
    ``from mxnet_tpu.optimizer import <Name>`` (reference exports all
    optimizer classes from optimizer/__init__.py; round-4 judge hit an
    ImportError on GroupAdaGrad)."""
    import mxnet_tpu.optimizer as opt_pkg
    from mxnet_tpu.optimizer.optimizer import _registry

    for name, cls in _registry.items():
        assert cls.__name__ in opt_pkg.__all__, cls.__name__
        assert getattr(opt_pkg, cls.__name__) is cls
