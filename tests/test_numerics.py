"""Training-numerics observability (ISSUE 8): in-program grad/param
health, divergence watchdog, NaN-origin forensics.

Acceptance bar:

- numerics=on is BIT-exact on params/loss vs numerics=off for
  sgd-mom/adam x fused/zero;
- under the dp=4 ZeRO sharded update the reported norms are the TRUE
  global norms (parity vs a host recomputation of the full-batch
  gradient);
- an injected non-finite gradient produces exactly ONE nonfinite_grad
  anomaly (episode semantics across the dispatch window) plus one
  atomic golden-schema post-mortem dump naming the planted op;
- a 12-step pipelined run with MXNET_NUMERICS=per_layer and
  MXNET_TRANSFER_GUARD=raise completes with zero unblessed host syncs
  while the mx_numerics_* series fill;
- the eager NaN guard (inspector) feeds the same anomaly channel, is
  idempotent, and restores cleanly; TensorInspector dumps are atomic.
"""
import json
import math
import os

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import _tape, autograd, engine, inspector, nd, telemetry
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.ops import registry as opreg
from mxnet_tpu.parallel import make_mesh, shard_batch
from mxnet_tpu.telemetry import names, numerics
from mxnet_tpu.testing import faults

DP = 4


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.enable(None)
    telemetry.reset()


def _mesh():
    return make_mesh({"dp": DP}, jax.devices()[:DP])


def _build(seed=3):
    """Includes a non-divisible flat size (Dense(5): weight 40, bias 5)
    so the ZeRO padded shard layout is exercised."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    return net


def _batch(bs=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return x, y


def _compiled(net, opt, kwargs, numerics_mode=None):
    trainer = Trainer(net.collect_params(), opt, dict(kwargs))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    return trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                numerics=numerics_mode)


def _assert_params_bitexact(net_a, net_b):
    for (k, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_b.collect_params().items())):
        onp.testing.assert_array_equal(pa.data().asnumpy(),
                                       pb.data().asnumpy(), err_msg=k)


# ---------------------------------------------------------------------------
# mode parsing / plumbing
# ---------------------------------------------------------------------------

def test_mode_parsing(monkeypatch):
    assert numerics.mode("off") is None
    assert numerics.mode("global") == "global"
    assert numerics.mode("per_layer") == "per_layer"
    assert numerics.mode("per-layer") == "per_layer"
    for v, want in (("", None), ("0", None), ("off", None),
                    ("1", "global"), ("global", "global"),
                    ("per_layer", "per_layer")):
        monkeypatch.setenv("MXNET_NUMERICS", v)
        assert numerics.mode() == want, (v, want)
    monkeypatch.delenv("MXNET_NUMERICS")
    assert numerics.mode() is None


def test_spike_factor_and_drift_tol_env(monkeypatch):
    monkeypatch.setenv("MXNET_GRADNORM_SPIKE_FACTOR", "25")
    assert numerics.spike_factor() == 25.0
    monkeypatch.setenv("MXNET_GRADNORM_SPIKE_FACTOR", "bogus")
    assert numerics.spike_factor() == 10.0
    monkeypatch.setenv("MXNET_MASTER_DRIFT_TOL", "0.5")
    assert numerics.master_drift_tol() == 0.5
    monkeypatch.delenv("MXNET_MASTER_DRIFT_TOL")
    assert numerics.master_drift_tol() == 1e-2


def test_numerics_off_no_aux():
    net = _build()
    step = _compiled(net, "sgd", {"learning_rate": 0.1})
    x, y = _batch()
    step(x, y)
    assert step.numerics is None
    assert step.take_numerics() is None
    assert step.numerics_values() is None


def test_set_numerics_rebuckets():
    """Switching the mode on a live step compiles a fresh instrumented
    bucket (the mode is part of the cache signature) and aux appears."""
    net = _build()
    step = _compiled(net, "sgd", {"learning_rate": 0.1})
    x, y = _batch()
    step(x, y)
    assert step.n_traces == 1 and step.take_numerics() is None
    step.set_numerics("global")
    step(x, y)
    assert step.n_traces == 2
    vals = step.numerics_values()
    assert vals is not None and vals["grad_norm"] > 0
    step.set_numerics(None)
    step(x, y)
    assert step.n_traces == 2          # original bucket still cached
    assert step.take_numerics() is None


# ---------------------------------------------------------------------------
# bit-exact on-vs-off parity (the acceptance contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_on_off_bitexact_fused(opt, kwargs):
    x, y = _batch()
    net_a = _build()
    step_a = _compiled(net_a, opt, kwargs)
    losses_a = [step_a(x, y).asnumpy().copy() for _ in range(4)]
    assert step_a.mode == "fused"

    net_b = _build()
    step_b = _compiled(net_b, opt, kwargs, numerics_mode="per_layer")
    losses_b = []
    for _ in range(4):
        losses_b.append(step_b(x, y).asnumpy().copy())
        assert step_b.take_numerics() is not None
    assert step_b.mode == "fused" and step_b.numerics == "per_layer"
    for la, lb in zip(losses_a, losses_b):
        onp.testing.assert_array_equal(la, lb)
    _assert_params_bitexact(net_a, net_b)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_on_off_bitexact_zero(monkeypatch, opt, kwargs):
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()
    with _mesh() as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        net_a = _build()
        step_a = _compiled(net_a, opt, kwargs)
        losses_a = [step_a(xs, ys).asnumpy().copy() for _ in range(4)]
        assert step_a.zero_sharded

        net_b = _build()
        step_b = _compiled(net_b, opt, kwargs, numerics_mode="global")
        losses_b = [step_b(xs, ys).asnumpy().copy() for _ in range(4)]
        assert step_b.zero_sharded and step_b.take_numerics() is not None
    for la, lb in zip(losses_a, losses_b):
        onp.testing.assert_array_equal(la, lb)
    _assert_params_bitexact(net_a, net_b)


# ---------------------------------------------------------------------------
# true-global-norm parity vs host recomputation at dp=4 ZeRO
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_zero_global_norm_parity_vs_host(monkeypatch):
    """The psum-composed in-program statistics of a dp=4 ZeRO step
    equal a host recomputation of the FULL-batch gradient norms — every
    replica reports the true global number, not its shard's."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()
    rescale = 1.0 / x.shape[0]

    net_h = _build()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        l = loss_blk(net_h(x), y)
    l.backward()
    host_layers, host_gsq, host_psq = {}, 0.0, 0.0
    for k, p in sorted(net_h.collect_params().items()):
        if p.grad_req == "null":
            continue
        g = p.grad().asnumpy().astype("float64") * rescale
        host_layers[k] = math.sqrt((g ** 2).sum())
        host_gsq += (g ** 2).sum()
        host_psq += (p.data().asnumpy().astype("float64") ** 2).sum()

    net_z = _build()
    step = _compiled(net_z, "adam", {"learning_rate": 1e-2},
                     numerics_mode="per_layer")
    with _mesh() as mesh:
        step(shard_batch(x, mesh), shard_batch(y, mesh))
        vals = step.numerics_values()
    assert step.zero_sharded
    assert vals["nonfinite_total"] == 0
    onp.testing.assert_allclose(vals["grad_norm"], math.sqrt(host_gsq),
                                rtol=1e-4)
    onp.testing.assert_allclose(vals["param_norm"], math.sqrt(host_psq),
                                rtol=1e-4)
    assert set(vals["layer_grad_norm"]) == set(host_layers)
    for k, v in vals["layer_grad_norm"].items():
        onp.testing.assert_allclose(v, host_layers[k], rtol=1e-3,
                                    err_msg=k)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_zero_multi_precision_master_drift(monkeypatch):
    """bf16 params + multi_precision on the mesh: the aux reports the
    fp32-master-vs-weight drift, tiny on a healthy step (bf16 rounding
    only) — no master_drift anomaly fires."""
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    net = _build()
    net.cast("bfloat16")
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1e-2, "multi_precision": True})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                numerics="global")
    x, y = _batch()
    with _mesh() as mesh:
        step(shard_batch(x.astype("bfloat16"), mesh),
             shard_batch(y, mesh))
        vals = step.numerics_values()
    assert step.zero_sharded
    assert "master_drift" in vals
    assert 0 <= vals["master_drift"] < numerics.master_drift_tol()
    assert "bfloat16" in vals["nonfinite"]
    assert telemetry.watchdog().anomalies("master_drift") == []


# ---------------------------------------------------------------------------
# divergence watchdog: episode semantics
# ---------------------------------------------------------------------------

def _feed(mon, step_no, gsq=1.0, psq=100.0, usq=1e-4, nonfinite=0,
          **extra):
    raw = {"grad_sq": onp.float32(gsq), "param_sq": onp.float32(psq),
           "upd_sq": onp.float32(usq),
           "nonfinite": {"float32": onp.int32(nonfinite)}}
    raw.update(extra)
    rec = telemetry.StepNumerics("global", raw, ["p0"], {})
    return mon.observe_retire(step_no, rec)


def test_grad_spike_episode_fires_once():
    mon = numerics.monitor()
    for i in range(8):
        _feed(mon, i, gsq=1.0)
    assert telemetry.watchdog().anomalies() == []
    _feed(mon, 42, gsq=1e6)             # norm 1000 >> 10x EWMA of 1
    events = telemetry.watchdog().anomalies("grad_spike")
    assert [e["step"] for e in events] == [42]
    _feed(mon, 43, gsq=1e6)             # same episode: no re-fire
    assert len(telemetry.watchdog().anomalies("grad_spike")) == 1
    # the spiking samples were NOT folded into the EWMA
    assert telemetry.value(names.NUMERICS_GRAD_NORM_EWMA) < 2.0
    for i in range(3):                  # recovery re-arms
        _feed(mon, 50 + i, gsq=1.0)
    _feed(mon, 60, gsq=1e6)
    assert len(telemetry.watchdog().anomalies("grad_spike")) == 2


def test_update_ratio_out_of_band_episode():
    mon = numerics.monitor()
    for i in range(8):
        _feed(mon, i, usq=1e-4)         # ratio 1e-3
    _feed(mon, 9, usq=400.0)            # ratio 2.0 >> 10x EWMA
    events = telemetry.watchdog().anomalies("update_ratio")
    assert [e["step"] for e in events] == [9]
    _feed(mon, 10, usq=400.0)
    assert len(telemetry.watchdog().anomalies("update_ratio")) == 1


def test_nonfinite_counter_and_master_drift_episode(monkeypatch):
    mon = numerics.monitor()
    _feed(mon, 1, master_drift=onp.float32(1e-4))
    assert telemetry.watchdog().anomalies("master_drift") == []
    _feed(mon, 2, master_drift=onp.float32(0.5))
    _feed(mon, 3, master_drift=onp.float32(0.5))
    events = telemetry.watchdog().anomalies("master_drift")
    assert [e["step"] for e in events] == [2]
    _feed(mon, 4, nonfinite=7)
    assert telemetry.value(names.NUMERICS_NONFINITE, "float32") == 7
    assert len(telemetry.watchdog().anomalies("nonfinite_grad")) == 1


# ---------------------------------------------------------------------------
# injected non-finite gradient: one anomaly + one golden-schema dump
# ---------------------------------------------------------------------------

def test_injected_inf_grad_one_anomaly_and_dump(tmp_path, monkeypatch):
    """An overflow batch at one known step, retired through a live
    dispatch window: exactly ONE nonfinite_grad anomaly attributed to
    that step (later poisoned steps stay in the episode), one atomic
    schema-v1 post-mortem dump whose NaN-origin forensics names the
    planted op (exp), with the per-layer table and lr/step context."""
    dump_dir = tmp_path / "dumps"
    monkeypatch.setenv("MXNET_NUMERICS_DUMP_DIR", str(dump_dir))
    net = _build()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    # the planted op: exp overflows to inf on the injected batch
    step = trainer.compile_step(
        lambda a, b: loss_blk(net(nd.exp(a)), b), numerics="global")
    x, y = _batch()
    xinf = nd.array(onp.full((8, 4), 120.0, "float32"))
    w = engine.DispatchWindow(max_inflight=2)
    for i in range(1, 9):
        l = step(xinf if i == 5 else x, y)
        w.push(l._data, tag=i, aux=step.take_numerics())
    w.drain()

    events = telemetry.watchdog().anomalies("nonfinite_grad")
    assert len(events) == 1
    assert events[0]["step"] == 5
    assert "exp" in events[0]["message"]
    assert telemetry.value(names.ANOMALIES, "nonfinite_grad") == 1
    assert telemetry.value(names.NUMERICS_DUMPS) == 1

    dumps = sorted(dump_dir.glob("mx_numerics_*.json"))
    assert len(dumps) == 1
    assert not list(dump_dir.glob("*.tmp*")), "non-atomic dump debris"
    d = json.load(open(dumps[0]))
    # golden schema (v1)
    assert d["schema_version"] == numerics.DUMP_SCHEMA_VERSION == 1
    for key in ("time_unix", "kind", "step", "offending_op", "grad_norm",
                "param_norm", "update_ratio", "nonfinite", "layers",
                "context", "hints"):
        assert key in d, key
    assert d["kind"] == "nonfinite_grad" and d["step"] == 5
    assert "exp" in d["offending_op"]
    assert d["nonfinite"]["float32"] > 0
    # ranked per-layer table from the forensic re-execution
    assert d["layers"] and d["layers"][0]["nonfinite"] > 0
    assert {"param", "shape", "dtype", "grad_norm", "param_norm",
            "nonfinite"} <= set(d["layers"][0])
    # lr / step context
    assert d["context"]["learning_rate"] == pytest.approx(0.1)
    assert d["context"]["optimizer"] == "SGD"
    assert d["context"]["batch_size"] == 8
    assert d["hints"]


def test_nonfinite_without_dump_dir_still_one_anomaly(monkeypatch):
    monkeypatch.delenv("MXNET_NUMERICS_DUMP_DIR", raising=False)
    net = _build()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    step = trainer.compile_step(
        lambda a, b: loss_blk(net(nd.exp(a)), b), numerics="global")
    x, y = _batch()
    step(nd.array(onp.full((8, 4), 120.0, "float32")), y)
    step.numerics_values()
    events = telemetry.watchdog().anomalies("nonfinite_grad")
    assert len(events) == 1
    assert "MXNET_NUMERICS_DUMP_DIR" in events[0]["message"]
    assert telemetry.value(names.NUMERICS_DUMPS) == 0


# ---------------------------------------------------------------------------
# the acceptance run: pipelined + guarded + per_layer, zero unblessed syncs
# ---------------------------------------------------------------------------

def test_guarded_12step_per_layer_zero_unblessed_syncs(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    monkeypatch.setenv("MXNET_NUMERICS", "per_layer")
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    assert loop.compiled_step.numerics == "per_layer"
    x, y = _batch()
    loop.step(x, y)                  # compile outside the counted region
    loop.synchronize()
    telemetry.reset()
    tguard.reset_sync_counts()
    for bx, by in loop.prefetch((x, y) for _ in range(12)):
        loop.step(bx, by)            # raises on any unblessed sync
    loop.synchronize()
    counts = tguard.sync_counts()
    assert counts.get("wait_to_read", 0) == 0
    assert counts.get("window_retire", 0) == 12
    # the numerics series filled from the blessed retires alone
    assert telemetry.value(names.NUMERICS_GRAD_NORM) > 0
    assert telemetry.value(names.NUMERICS_PARAM_NORM) > 0
    assert telemetry.value(names.NUMERICS_UPDATE_RATIO) == 12
    layer_vals = telemetry.registry().get(
        names.NUMERICS_LAYER_GRAD_NORM).values()
    assert layer_vals and all(v >= 0 for v in layer_vals.values())
    assert telemetry.watchdog().anomalies() == []
    last = numerics.monitor().last()
    assert last is not None and last["step"] == loop.global_step
    # the new series export cleanly
    text = telemetry.prometheus_text()
    assert "mx_numerics_grad_norm " in text
    assert "mx_numerics_update_ratio_count 12" in text


# ---------------------------------------------------------------------------
# inspector satellites: eager NaN guard + atomic dumps
# ---------------------------------------------------------------------------

def test_nan_guard_idempotent_install_remove():
    base = len(opreg._INVOKE_WRAPPERS)
    inspector.install_nan_guard()
    inspector.install_nan_guard()        # must not double-wrap
    assert len(opreg._INVOKE_WRAPPERS) == base + 1
    inspector.remove_nan_guard()
    inspector.remove_nan_guard()         # idempotent
    assert len(opreg._INVOKE_WRAPPERS) == base


def test_nan_guard_restores_previous_output_check():
    hits = []
    sentinel = lambda name, outs: hits.append(name)   # noqa: E731
    prev = _tape.set_output_check(sentinel)
    try:
        inspector.install_nan_guard()
        inspector.remove_nan_guard()
        assert _tape._output_check is sentinel, \
            "remove_nan_guard clobbered another subsystem's hook"
    finally:
        inspector.remove_nan_guard()
        _tape.set_output_check(prev)


def test_nan_guard_telemetry_episode_and_exception_safety():
    inspector.install_nan_guard()
    try:
        a = nd.array([1.0, 2.0])
        bad = nd.array([-1.0])
        nd.abs(a)
        for _ in range(2):               # consecutive violations: one event
            with pytest.raises(MXNetError, match="non-finite"):
                nd.log(bad)
        assert len(telemetry.watchdog().anomalies("nonfinite_eager")) == 1
        assert telemetry.value(names.ANOMALIES, "nonfinite_eager") == 1
        nd.abs(a)                        # clean checked op re-arms
        with pytest.raises(MXNetError, match="non-finite"):
            nd.sqrt(nd.array([-4.0]))
        assert len(telemetry.watchdog().anomalies("nonfinite_eager")) == 2
    finally:
        # the exceptions above must not have corrupted install state
        inspector.remove_nan_guard()
    assert not inspector._guard_installed
    nd.log(nd.array([-1.0]))             # guard really gone: no raise


def test_inspector_dump_atomic_under_fault(tmp_path):
    """A fault injected at the dump's commit point (the same
    tmp+fsync+os.replace helper nd.save uses) leaves NO partial file
    and no temp debris; a retry reuses the sequence number."""
    insp = inspector.TensorInspector(nd.array([[1.0, 2.0]]), tag="numdump")
    inspector._dump_counters.pop("numdump", None)   # tag counters are global
    p1 = insp.dump_to_file("numdump", str(tmp_path))
    assert p1.endswith("numdump_1.npy")
    onp.testing.assert_array_equal(onp.load(p1), [[1.0, 2.0]])
    faults.configure("inspector.dump:before=1:error")
    try:
        with pytest.raises(OSError):
            insp.dump_to_file("numdump", str(tmp_path))
    finally:
        faults.reset()
    assert sorted(os.listdir(tmp_path)) == ["numdump_1.npy"], \
        "fault-injected dump left partial/temp files"
    p2 = insp.dump_to_file("numdump", str(tmp_path))
    assert p2.endswith("numdump_2.npy") and os.path.exists(p2)
