"""Registry-wide op numerics sweep (VERDICT r4 item 5).

Auto-parametrized golden sweep over EVERY ``mx.np`` function that has an
official-NumPy analog: each op runs on synthetic inputs — including
0-size and broadcast edge shapes — and must match ``numpy`` bit-for-bit
modulo float tolerance; differentiable elementwise ops additionally get
a central-finite-difference gradient check against the autograd vjp.

Self-auditing: ``test_sweep_covers_namespace`` fails when a new np
function appears that is neither covered here nor in the documented
``EXCLUDED`` ledger, and asserts the exclusion list stays shorter than
the covered list.

Reference analog: the breadth intent of
tests/python/unittest/test_operator.py + test_numpy_op.py (19.5k LoC,
SURVEY §4) — matched by generation rather than enumeration.
"""
import builtins

import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.numpy as np
from mxnet_tpu import autograd

# ---------------------------------------------------------------------------
# input pools (deterministic per shape+seed)
# ---------------------------------------------------------------------------


def _rs(shape, seed=0):
    return onp.random.RandomState(abs(hash((shape, seed))) % (2 ** 31))


def real(shape, seed=0):
    return onp.asarray(_rs(shape, seed).randn(*shape)).astype("float32")


def pos(shape, seed=0):
    return onp.asarray(_rs(shape, seed).uniform(0.5, 2.0, shape)).astype(
        "float32")


def unit(shape, seed=0):
    return onp.asarray(_rs(shape, seed).uniform(-0.9, 0.9, shape)).astype(
        "float32")


def gt1(shape, seed=0):
    return onp.asarray(_rs(shape, seed).uniform(1.1, 3.0, shape)).astype(
        "float32")


def away0(shape, seed=0):
    r = _rs(shape, seed)
    return onp.asarray(r.choice([-1.0, 1.0], shape)
                       * r.uniform(0.25, 2.0, shape)).astype("float32")


def awayint(shape, seed=0):
    """Reals away from integers and half-integers (safe for floor/round)."""
    r = _rs(shape, seed)
    return onp.asarray(r.randint(-3, 3, shape)
                       + r.uniform(0.1, 0.4, shape)).astype("float32")


def ints(shape, seed=0, lo=0, hi=5):
    return onp.asarray(_rs(shape, seed).randint(lo, hi, shape)).astype(
        "int32")


def posints(shape, seed=0):
    return onp.asarray(_rs(shape, seed).randint(1, 7, shape)).astype(
        "int32")


def bools(shape, seed=0):
    return onp.asarray(_rs(shape, seed).rand(*shape)) > 0.5


def with_nans(shape, seed=0):
    a = real(shape, seed)
    if a.size:
        a.flat[:: max(a.size // 3, 1)] = onp.nan
    return a


SHAPES_U = [(3, 4), (6,), (0,), (2, 0, 3)]
SHAPES_B = [((3, 4), (3, 4)), ((3, 1), (1, 4)), ((6,), ()),
            ((0, 4), (1, 4))]

# ---------------------------------------------------------------------------
# category tables
# ---------------------------------------------------------------------------

# name -> (input pool, grad-checkable)
UNARY = {
    "abs": (away0, True), "absolute": (away0, True), "fabs": (away0, False),
    "negative": (real, True), "positive": (real, True),
    "sign": (away0, False), "signbit": (away0, False),
    "sqrt": (pos, True), "cbrt": (pos, True), "square": (real, True),
    "reciprocal": (away0, True),
    "exp": (unit, True), "exp2": (unit, True), "expm1": (unit, True),
    "log": (pos, True), "log10": (pos, True), "log1p": (pos, True),
    "log2": (pos, True),
    "sin": (real, True), "cos": (real, True), "tan": (unit, True),
    "sinh": (unit, True), "cosh": (unit, True), "tanh": (unit, True),
    "arcsin": (unit, True), "arccos": (unit, True), "arctan": (real, True),
    "arcsinh": (real, True), "arccosh": (gt1, True), "arctanh": (unit, True),
    "degrees": (real, True), "radians": (real, True),
    "deg2rad": (real, True), "rad2deg": (real, True),
    "rint": (awayint, False), "fix": (awayint, False),
    "floor": (awayint, False), "ceil": (awayint, False),
    "trunc": (awayint, False),
    "conj": (real, False), "conjugate": (real, False),
    "real": (real, False), "imag": (real, False), "angle": (pos, False),
    "i0": (unit, False), "sinc": (away0, True), "spacing": (away0, False),
    "isfinite": (real, False), "isinf": (real, False),
    "isnan": (with_nans, False),
    "isneginf": (real, False), "isposinf": (real, False),
    "logical_not": (bools, False),
    "nan_to_num": (with_nans, False), "copy": (real, False),
    "cumsum": (real, True), "cumprod": (pos, True),
    "nancumsum": (with_nans, False), "nancumprod": (with_nans, False),
    "flatnonzero": (away0, False),
    "unwrap": (real, False),
}

UNARY_INT = {"invert": ints, "bitwise_not": ints}

# name -> (pool_a, pool_b, grad-checkable)
BINARY = {
    "add": (real, real, True), "subtract": (real, real, True),
    "multiply": (real, real, True),
    "divide": (real, away0, True), "true_divide": (real, away0, True),
    "floor_divide": (awayint, away0, False),
    "mod": (awayint, away0, False), "remainder": (awayint, away0, False),
    "fmod": (awayint, away0, False),
    "power": (pos, real, True), "float_power": (pos, real, False),
    "arctan2": (away0, away0, True), "hypot": (away0, away0, True),
    "maximum": (real, real, True), "minimum": (real, real, True),
    "fmax": (real, real, False), "fmin": (real, real, False),
    "copysign": (away0, away0, False),
    "nextafter": (real, real, False),
    "logaddexp": (unit, unit, True), "logaddexp2": (unit, unit, False),
    "heaviside": (away0, pos, False),
    "logical_and": (bools, bools, False),
    "logical_or": (bools, bools, False),
    "logical_xor": (bools, bools, False),
    "equal": (ints, ints, False), "not_equal": (ints, ints, False),
    "greater": (real, real, False), "greater_equal": (real, real, False),
    "less": (real, real, False), "less_equal": (real, real, False),
}

BINARY_INT = {
    "gcd": posints, "lcm": posints,
    "bitwise_and": ints, "bitwise_or": ints, "bitwise_xor": ints,
}

# reductions: name -> (pool, kwargs variants, supports 0-size)
_AX = [{}, {"axis": 0}, {"axis": -1, "keepdims": True}]
REDUCTIONS = {
    "sum": (real, _AX, True), "prod": (pos, _AX, True),
    "mean": (real, _AX, False), "std": (real, _AX, False),
    "var": (real, _AX, False),
    "amax": (real, _AX, False), "amin": (real, _AX, False),
    "max": (real, _AX, False), "min": (real, _AX, False),
    "ptp": (real, [{}, {"axis": 0}], False),
    "median": (real, [{}, {"axis": 0}], False),
    "average": (real, [{}, {"axis": 0}], False),
    "argmax": (real, [{}, {"axis": 0}], False),
    "argmin": (real, [{}, {"axis": 0}], False),
    "all": (bools, _AX, True), "any": (bools, _AX, True),
    "count_nonzero": (away0, [{}, {"axis": 0}], True),
    "nanmax": (with_nans, [{}], False), "nanmin": (with_nans, [{}], False),
    "nansum": (with_nans, _AX, True), "nanprod": (with_nans, _AX, True),
    "nanmean": (with_nans, [{}], False),
    "nanmedian": (with_nans, [{}], False),
    "nanargmax": (with_nans, [{}], False),
    "nanargmin": (with_nans, [{}], False),
    "trace": (real, [{}], False),
}

# literal cases: name -> list of thunks returning (args, kwargs);
# onp.ndarray args are converted for the mx call automatically
LITERAL = {
    # creation
    "arange": [lambda: ((5,), {}), lambda: ((2, 11, 3), {}),
               lambda: ((0,), {}), lambda: ((0.5, 2.5, 0.5), {})],
    "eye": [lambda: ((4,), {}), lambda: ((3, 5), {}),
            lambda: ((3, 3), {"k": 1})],
    "identity": [lambda: ((4,), {})],
    "full": [lambda: (((2, 3), 7.0), {}), lambda: (((0,), 1.0), {})],
    "full_like": [lambda: ((real((2, 3)), 7.0), {})],
    "ones": [lambda: (((2, 3),), {}), lambda: (((0, 2),), {})],
    "zeros": [lambda: (((2, 3),), {}), lambda: (((0,),), {})],
    "ones_like": [lambda: ((real((2, 3)),), {})],
    "zeros_like": [lambda: ((real((2, 3)),), {})],
    "linspace": [lambda: ((0.0, 1.0, 7), {}),
                 lambda: ((0.0, 1.0, 5), {"endpoint": False})],
    "logspace": [lambda: ((0.0, 2.0, 5), {})],
    "geomspace": [lambda: ((1.0, 8.0, 4), {})],
    "meshgrid": [lambda: ((real((3,)), real((4,))), {}),
                 lambda: ((real((3,)), real((4,))), {"indexing": "ij"})],
    "indices": [lambda: (((2, 3),), {})],
    "tri": [lambda: ((4,), {}), lambda: ((3, 5), {"k": -1})],
    "vander": [lambda: ((real((4,)),), {}),
               lambda: ((real((4,)), 3), {})],
    # windows
    "bartlett": [lambda: ((7,), {})],
    "blackman": [lambda: ((7,), {})],
    "hamming": [lambda: ((7,), {})],
    "hanning": [lambda: ((7,), {})],
    "kaiser": [lambda: ((7, 8.6), {})],
    # round with decimals
    "round": [lambda: ((awayint((3, 4)),), {}),
              lambda: ((real((3, 4)) * 10, 1), {})],
    "around": [lambda: ((awayint((3, 4)),), {})],
    "clip": [lambda: ((real((3, 4)), -0.5, 0.5), {}),
             lambda: ((real((0,)), -0.5, 0.5), {})],
}

# ---- shape / manipulation ----
def _taa_case():
    x = real((3, 4))
    return ((x, onp.argsort(x, axis=1), 1), {})


def _piecewise_case():
    x = real((6,))
    return ((x, [x < 0, x >= 0], [-1.0, 1.0]), {})


def _select_case():
    x = real((6,))
    return (([x < -0.5, x > 0.5], [x * 2, x * 3], 0.0), {})


LITERAL.update({
    "reshape": [lambda: ((real((3, 4)), (2, 6)), {}),
                lambda: ((real((3, 4)), (-1,)), {}),
                lambda: ((real((0, 4)), (4, 0)), {})],
    "ravel": [lambda: ((real((3, 4)),), {}), lambda: ((real((0,)),), {})],
    "transpose": [lambda: ((real((3, 4)),), {}),
                  lambda: ((real((2, 3, 4)), (2, 0, 1)), {})],
    "swapaxes": [lambda: ((real((2, 3, 4)), 0, 2), {})],
    "moveaxis": [lambda: ((real((2, 3, 4)), 0, -1), {})],
    "rollaxis": [lambda: ((real((2, 3, 4)), 2), {})],
    "expand_dims": [lambda: ((real((2, 3)), 1), {}),
                    lambda: ((real((0, 3)), 0), {})],
    "squeeze": [lambda: ((real((2, 1, 3)),), {}),
                lambda: ((real((2, 1, 3)), 1), {})],
    "broadcast_to": [lambda: ((real((3, 1)), (3, 4)), {})],
    "broadcast_arrays": [lambda: ((real((3, 1)), real((1, 4), 1)), {})],
    "atleast_1d": [lambda: ((real((2, 3)),), {}),
                   lambda: ((onp.float32(3.0),), {})],
    "atleast_2d": [lambda: ((real((3,)),), {})],
    "atleast_3d": [lambda: ((real((3, 4)),), {})],
    "concatenate": [lambda: (([real((2, 3)), real((3, 3), 1)],),
                             {"axis": 0}),
                    lambda: (([real((2, 0)), real((2, 3), 1)],),
                             {"axis": 1})],
    "concat": [lambda: (([real((2, 3)), real((3, 3), 1)],), {"axis": 0})],
    "stack": [lambda: (([real((2, 3)), real((2, 3), 1)],), {}),
              lambda: (([real((2, 3)), real((2, 3), 1)],), {"axis": -1})],
    "vstack": [lambda: (([real((2, 3)), real((1, 3), 1)],), {})],
    "hstack": [lambda: (([real((2, 3)), real((2, 1), 1)],), {})],
    "dstack": [lambda: (([real((2, 3)), real((2, 3), 1)],), {})],
    "column_stack": [lambda: (([real((4,)), real((4,), 1)],), {})],
    "row_stack": [lambda: (([real((2, 3)), real((1, 3), 1)],), {})],
    "split": [lambda: ((real((6, 2)), 3), {}),
              lambda: ((real((6, 2)), [2, 4]), {})],
    "array_split": [lambda: ((real((7, 2)), 3), {})],
    "hsplit": [lambda: ((real((2, 6)), 2), {})],
    "vsplit": [lambda: ((real((6, 2)), 3), {})],
    "dsplit": [lambda: ((real((2, 3, 4)), 2), {})],
    "tile": [lambda: ((real((2, 3)), (2, 2)), {}),
             lambda: ((real((3,)), 2), {})],
    "repeat": [lambda: ((real((3, 4)), 2), {}),
               lambda: ((real((3, 4)), 3), {"axis": 1})],
    "flip": [lambda: ((real((3, 4)),), {}),
             lambda: ((real((3, 4)), 1), {})],
    "fliplr": [lambda: ((real((3, 4)),), {})],
    "flipud": [lambda: ((real((3, 4)),), {})],
    "roll": [lambda: ((real((3, 4)), 2), {}),
             lambda: ((real((3, 4)), 1, 0), {})],
    "rot90": [lambda: ((real((3, 4)),), {}),
              lambda: ((real((3, 4)), 2), {})],
    "append": [lambda: ((real((3,)), real((2,), 1)), {}),
               lambda: ((real((2, 3)), real((1, 3), 1)), {"axis": 0})],
    "delete": [lambda: ((real((5,)), 1), {}),
               lambda: ((real((5, 3)), [0, 2]), {"axis": 0})],
    "insert": [lambda: ((real((5,)), 2, 9.0), {}),
               lambda: ((real((3, 4)), 1, 5.0), {"axis": 1})],
    "resize": [lambda: ((real((3, 4)), (2, 6)), {}),
               lambda: ((real((2,)), (5,)), {})],
    "pad": [lambda: ((real((3, 4)), 1), {}),
            lambda: ((real((4,)), (1, 2)), {"mode": "edge"})],
    "trim_zeros": [lambda: ((onp.array([0., 0., 1., 2., 0.], "float32"),),
                            {})],
    "diag": [lambda: ((real((4,)),), {}),
             lambda: ((real((3, 4)),), {"k": 1})],
    "diagflat": [lambda: ((real((2, 2)),), {})],
    "diagonal": [lambda: ((real((3, 4)),), {}),
                 lambda: ((real((3, 4)),), {"offset": 1})],
    "diag_indices_from": [lambda: ((real((4, 4)),), {})],
    "tril": [lambda: ((real((4, 4)),), {}),
             lambda: ((real((3, 5)),), {"k": -1})],
    "triu": [lambda: ((real((4, 4)),), {}),
             lambda: ((real((3, 5)),), {"k": 1})],
    "tril_indices": [lambda: ((4,), {}), lambda: ((3,), {"k": 1})],
    "triu_indices": [lambda: ((4,), {})],
    "tril_indices_from": [lambda: ((real((4, 4)),), {})],
    "triu_indices_from": [lambda: ((real((4, 4)),), {})],
    "diff": [lambda: ((real((6,)),), {}),
             lambda: ((real((3, 4)),), {"n": 2, "axis": 1})],
    "ediff1d": [lambda: ((real((5,)),), {})],
    "gradient": [lambda: ((real((6,)),), {}),
                 lambda: ((real((3, 4)),), {})],
    "unravel_index": [],  # CUSTOM: deliberate stacked-rows deviation
    "ix_": [lambda: ((ints((3,)), ints((2,), 1)), {})],
})

# ---- indexing / search / sort / sets ----
LITERAL.update({
    "sort": [lambda: ((real((6,)),), {}),
             lambda: ((real((3, 4)),), {"axis": 0})],
    "argsort": [lambda: ((real((6,)),), {}),
                lambda: ((real((3, 4)),), {"axis": 1})],
    "lexsort": [lambda: (((real((8,)),),), {})],
    "searchsorted": [lambda: ((onp.sort(real((8,))), real((5,), 1)), {}),
                     lambda: ((onp.sort(real((8,))), real((5,), 1)),
                              {"side": "right"})],
    "nonzero": [lambda: ((away0((3, 4)) * bools((3, 4), 2),), {})],
    "argwhere": [lambda: ((bools((3, 4)),), {})],
    "where": [lambda: ((bools((3, 4)), real((3, 4)), real((3, 4), 1)), {}),
              lambda: ((bools((3, 4)),), {})],
    "take": [lambda: ((real((5,)), ints((3,), 0, 0, 5)), {}),
             lambda: ((real((3, 4)), ints((2,), 1, 0, 3)), {"axis": 0})],
    "take_along_axis": [_taa_case],
    "choose": [lambda: ((ints((4,), 0, 0, 3),
                         [real((4,)), real((4,), 1), real((4,), 2)]), {})],
    "compress": [lambda: ((bools((5,)), real((5, 2)), 0), {})],
    "extract": [lambda: ((bools((4, 3)), real((4, 3))), {})],
    "select": [_select_case],
    "piecewise": [_piecewise_case],
    "digitize": [lambda: ((real((6,)),
                           onp.array([-1., 0., 1.], "float32")), {})],
    "bincount": [lambda: ((ints((10,)),), {}),
                 lambda: ((ints((10,)),),
                          {"weights": real((10,)), "minlength": 8})],
    "unique": [lambda: ((ints((10,)),), {}),
               lambda: ((ints((10,)),), {"return_counts": True})],
    "in1d": [lambda: ((ints((6,)), ints((3,), 1)), {})],
    "isin": [lambda: ((ints((2, 3)), ints((3,), 1)), {})],
    "intersect1d": [lambda: ((ints((6,)), ints((6,), 1)), {})],
    "union1d": [lambda: ((ints((5,)), ints((5,), 1)), {})],
    "setdiff1d": [lambda: ((ints((6,)), ints((4,), 1)), {})],
    "setxor1d": [lambda: ((ints((6,)), ints((6,), 1)), {})],
    "count_nonzero": [lambda: ((away0((3, 4)) * bools((3, 4), 2),), {})],
    "histogram": [lambda: ((real((20,)),), {}),
                  lambda: ((real((20,)),),
                           {"bins": 5, "range": (-2.0, 2.0)})],
    "histogram_bin_edges": [lambda: ((real((20,)),), {"bins": 5})],
    "histogram2d": [lambda: ((real((20,)), real((20,), 1)),
                             {"bins": 4})],
    "histogramdd": [lambda: ((real((20, 3)),), {"bins": 3})],
    "percentile": [lambda: ((real((10,)), 30.0), {}),
                   lambda: ((real((3, 4)), [25.0, 75.0]), {"axis": 1})],
    "quantile": [lambda: ((real((10,)), 0.3), {})],
    "nanpercentile": [lambda: ((with_nans((10,)), 30.0), {})],
    "nanquantile": [lambda: ((with_nans((10,)), 0.3), {})],
})

# ---- linalg-adjacent / signal / poly / misc ----
LITERAL.update({
    "dot": [lambda: ((real((3, 4)), real((4, 5), 1)), {}),
            lambda: ((real((4,)), real((4,), 1)), {})],
    "vdot": [lambda: ((real((3, 4)), real((3, 4), 1)), {})],
    "inner": [lambda: ((real((3, 4)), real((5, 4), 1)), {})],
    "outer": [lambda: ((real((3,)), real((4,), 1)), {})],
    "matmul": [lambda: ((real((2, 3)), real((3, 4), 1)), {}),
               lambda: ((real((2, 3, 4)), real((2, 4, 5), 1)), {})],
    "tensordot": [lambda: ((real((2, 3, 4)), real((4, 3, 5), 1)),
                           {"axes": ([1, 2], [1, 0])})],
    "einsum": [lambda: (("ij,jk->ik", real((2, 3)), real((3, 4), 1)), {}),
               lambda: (("bij->bji", real((2, 3, 4))), {})],
    "kron": [lambda: ((real((2, 2)), real((2, 3), 1)), {})],
    "cross": [lambda: ((real((4, 3)), real((4, 3), 1)), {})],
    "convolve": [lambda: ((real((5,)), real((3,), 1)), {"mode": "same"}),
                 lambda: ((real((5,)), real((3,), 1)), {"mode": "full"})],
    "correlate": [lambda: ((real((5,)), real((3,), 1)), {"mode": "same"})],
    "interp": [lambda: ((real((5,)), onp.sort(real((8,), 1)),
                         real((8,), 2)), {})],
    "trapz": [lambda: ((real((6,)),), {}),
              lambda: ((real((6,)),), {"dx": 0.5})],
    "corrcoef": [lambda: ((real((3, 8)),), {})],
    "cov": [lambda: ((real((3, 8)),), {})],
    "poly": [lambda: ((real((4,)),), {})],
    "polyadd": [lambda: ((real((3,)), real((4,), 1)), {})],
    "polysub": [lambda: ((real((3,)), real((4,), 1)), {})],
    "polymul": [lambda: ((real((3,)), real((4,), 1)), {})],
    "polydiv": [lambda: ((real((4,)), away0((2,), 1)), {})],
    "polyval": [lambda: ((real((3,)), real((5,), 1)), {})],
    "polyint": [lambda: ((real((4,)),), {})],
    "polyfit": [lambda: ((onp.linspace(0, 1, 8, dtype="float32"),
                          real((8,), 1), 2), {})],
    "divmod": [lambda: ((awayint((3, 4)), away0((3, 4), 1)), {})],
    "modf": [lambda: ((awayint((3, 4)),), {})],
    "frexp": [lambda: ((away0((3, 4)),), {})],
    "ldexp": [lambda: ((real((3, 4)), ints((3, 4), 1, -2, 3)), {})],
    "left_shift": [lambda: ((ints((3, 4)), ints((3, 4), 1, 0, 3)), {})],
    "right_shift": [lambda: ((ints((3, 4), 0, 0, 64),
                              ints((3, 4), 1, 0, 3)), {})],
    "packbits": [lambda: ((bools((12,)),), {})],
    "unpackbits": [lambda: ((onp.array([7, 200], "uint8"),), {})],
    "apply_along_axis": [lambda: ((lambda v: v.sum(), 0, real((3, 4))),
                                  {})],
    "apply_over_axes": [lambda: ((onp.sum, real((2, 3, 4)), [0, 2]), {})],
    "fill_diagonal": [],  # covered by the CUSTOM validator below
    "partition": [],      # CUSTOM (layout within partitions unspecified)
    "argpartition": [],   # CUSTOM
    "roots": [],          # CUSTOM (root ordering unspecified)
})


# custom validators for ops whose exact output layout numpy leaves
# unspecified (partition order, root order) or that mutate in place
def _check_partition():
    a = real((8,))
    k = 3
    got = _to_host(np.partition(np.array(a), k))
    want_sorted = onp.sort(a)
    assert got[k] == want_sorted[k]
    assert onp.all(onp.sort(got[:k]) <= got[k])
    assert onp.all(onp.sort(got[k + 1:]) >= got[k])
    onp.testing.assert_allclose(onp.sort(got), want_sorted, rtol=1e-6)


def _check_argpartition():
    a = real((8,))
    k = 3
    idx = _to_host(np.argpartition(np.array(a), k)).astype(int)
    assert sorted(idx.tolist()) == list(range(8))
    got = a[idx]
    want_sorted = onp.sort(a)
    assert got[k] == want_sorted[k]
    assert onp.all(got[:k] <= got[k]) and onp.all(got[k + 1:] >= got[k])


def _check_roots():
    coeffs = onp.array([1.0, -3.0, 2.0], "float32")
    got = onp.sort(onp.real(_to_host(np.roots(np.array(coeffs)))))
    want = onp.sort(onp.real(onp.roots(coeffs)))
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def _check_fill_diagonal():
    a = real((4, 4))
    ma = np.array(a)
    np.fill_diagonal(ma, 9.0)
    onp.fill_diagonal(a, 9.0)
    onp.testing.assert_allclose(_to_host(ma), a, rtol=1e-6)


def _check_unravel_index():
    """mx returns the coordinate rows STACKED into one array — the
    reference's own deviation from numpy's tuple
    (reference numpy/multiarray.py:7876); values must still match."""
    idx = ints((4,), 0, 0, 11)
    got = _to_host(np.unravel_index(np.array(idx), (3, 4)))
    want = onp.stack(onp.unravel_index(idx, (3, 4)))
    onp.testing.assert_array_equal(onp.asarray(got), want)


CUSTOM = {
    "partition": _check_partition,
    "argpartition": _check_argpartition,
    "roots": _check_roots,
    "fill_diagonal": _check_fill_diagonal,
    "unravel_index": _check_unravel_index,
}

# queries
LITERAL.update({
    "ndim": [lambda: ((real((2, 3)),), {})],
    "shape": [lambda: ((real((2, 3)),), {})],
    "size": [lambda: ((real((2, 3)),), {})],
    "isscalar": [lambda: ((3.0,), {}), lambda: ((real((2,)),), {})],
    "allclose": [lambda: ((real((3,)), real((3,)) + 1e-9), {}),
                 lambda: ((real((3,)), real((3,), 1)), {})],
    "isclose": [lambda: ((real((3,)), real((3,)) + 1e-9), {})],
    "array_equal": [lambda: ((ints((3,)), ints((3,))), {}),
                    lambda: ((ints((3,)), ints((3,), 1)), {})],
    "array_equiv": [lambda: ((ints((3,)), ints((3,))), {})],
})

# ---------------------------------------------------------------------------
# the documented exclusion ledger: name -> reason
# ---------------------------------------------------------------------------
EXCLUDED = {
    # dtype/class objects and casting-table queries, not array ops
    "bool": "dtype alias", "bool_": "dtype alias",
    "complex64": "dtype alias", "complex128": "dtype alias",
    "float16": "dtype alias", "float32": "dtype alias",
    "float64": "dtype alias",
    "int8": "dtype alias", "int16": "dtype alias", "int32": "dtype alias",
    "int64": "dtype alias", "intc": "dtype alias",
    "uint16": "dtype alias", "uint32": "dtype alias",
    "uint64": "dtype alias", "uint8": "dtype alias",
    "dtype": "dtype constructor", "finfo": "dtype query",
    "iinfo": "dtype query",
    "can_cast": "casting-table query, covered by test_dtype_parity",
    "min_scalar_type": "casting-table query",
    "promote_types": "casting-table query",
    "result_type": "casting-table query",
    "ndarray": "the array class itself",
    "array": "constructor, exercised by every other case here",
    "asarray": "constructor, exercised by every other case here",
    "empty": "values uninitialized by contract — nothing to golden-check",
    "empty_like": "values uninitialized by contract",
    "may_share_memory": "host-memory introspection; mx arrays live on device",
    "shares_memory": "host-memory introspection; mx arrays live on device",
}

# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def _to_mx(x):
    if isinstance(x, onp.ndarray):
        return np.array(x)
    if isinstance(x, tuple):
        return tuple(_to_mx(v) for v in x)
    if isinstance(x, list):
        return [_to_mx(v) for v in x]
    return x


def _to_host(x):
    if isinstance(x, np.ndarray):
        return x.asnumpy()
    if isinstance(x, (list, tuple)):
        return [_to_host(v) for v in x]
    return x


def _compare(got, want, name):
    got, want = _to_host(got), _to_host(want)
    if isinstance(want, (list, tuple)):
        assert isinstance(got, (list, tuple)) and len(got) == len(want), \
            f"{name}: structure mismatch {got!r} vs {want!r}"
        for g, w in zip(got, want):
            _compare(g, w, name)
        return
    garr = onp.asarray(got)
    warr = onp.asarray(want)
    assert garr.shape == warr.shape, \
        f"{name}: shape {garr.shape} != numpy {warr.shape}"
    if warr.dtype == onp.bool_ or warr.dtype.kind in "iu":
        onp.testing.assert_array_equal(garr, warr, err_msg=name)
    else:
        onp.testing.assert_allclose(
            garr.astype("float64"), warr.astype("float64"),
            rtol=2e-4, atol=1e-5, equal_nan=True, err_msg=name)


def _run_cases(name, cases):
    onp_fn = getattr(onp, name)
    mx_fn = getattr(np, name)
    for i, thunk in enumerate(cases):
        args, kwargs = thunk()
        want = onp_fn(*args, **kwargs)
        got = mx_fn(*[_to_mx(a) for a in args],
                    **{k: _to_mx(v) for k, v in kwargs.items()})
        try:
            _compare(got, want, f"{name} case {i}")
        except AssertionError as e:
            raise AssertionError(
                f"{name} case {i}: args={args!r} kwargs={kwargs!r}\n{e}")


def _case_table():
    table = {}
    for name, (pool, _) in UNARY.items():
        table[name] = [
            (lambda pool=pool, s=s: ((pool(s),), {})) for s in SHAPES_U]
    for name, pool in UNARY_INT.items():
        table[name] = [
            (lambda pool=pool, s=s: ((pool(s),), {})) for s in SHAPES_U]
    for name, (pa, pb, _) in BINARY.items():
        table[name] = [
            (lambda pa=pa, pb=pb, sa=sa, sb=sb:
             ((pa(sa), pb(sb, 1)), {})) for sa, sb in SHAPES_B]
    for name, pool in BINARY_INT.items():
        table[name] = [
            (lambda pool=pool, sa=sa, sb=sb:
             ((pool(sa), pool(sb, 1)), {})) for sa, sb in SHAPES_B]
    for name, (pool, variants, zero_ok) in REDUCTIONS.items():
        shapes = [(3, 4), (2, 3, 4)] + ([(0, 4)] if zero_ok else [])
        table[name] = [
            (lambda pool=pool, s=s, kw=kw: ((pool(s),), dict(kw)))
            for s in shapes for kw in variants]
    for name, cases in LITERAL.items():
        table.setdefault(name, []).extend(cases)
    return table


CASE_TABLE = _case_table()


@pytest.mark.parametrize("name", sorted(CASE_TABLE), ids=str)
def test_op_matches_numpy(name):
    if name in CUSTOM:
        CUSTOM[name]()
    _run_cases(name, CASE_TABLE[name])


# ---------------------------------------------------------------------------
# gradient sweep: autograd vjp vs central finite differences
# ---------------------------------------------------------------------------

GRAD_UNARY = sorted(n for n, (_, g) in UNARY.items() if g)
GRAD_BINARY = sorted(n for n, (_, _, g) in BINARY.items() if g)


def _fd_check(name, pools, shapes):
    mx_fn = getattr(np, name)
    arrs = [pool(s, seed=7 + i) for i, (pool, s) in
            enumerate(zip(pools, shapes))]
    out_shape = onp.asarray(getattr(onp, name)(*arrs)).shape
    w = onp.random.RandomState(11).randn(*out_shape).astype("float32")
    weights = np.array(w)

    xs = [np.array(a) for a in arrs]
    for x in xs:
        x.attach_grad()
    with autograd.record():
        out = mx_fn(*xs)
        loss = (out * weights).sum()
    loss.backward()

    def f(hosts):
        return float((mx_fn(*[np.array(h) for h in hosts])
                      * weights).sum().asnumpy())

    eps = 1e-2
    rs = onp.random.RandomState(13)
    for k, (a, x) in enumerate(zip(arrs, xs)):
        grad = x.grad.asnumpy()
        assert grad.shape == a.shape
        n_probe = min(4, a.size)
        idxs = rs.choice(a.size, size=n_probe, replace=False)
        for flat in idxs:
            ap = [v.copy() for v in arrs]
            am = [v.copy() for v in arrs]
            ap[k].flat[flat] += eps
            am[k].flat[flat] -= eps
            fd = (f(ap) - f(am)) / (2 * eps)
            got = grad.flat[flat]
            assert abs(got - fd) <= 5e-2 * max(abs(fd), abs(got), 1.0), (
                f"{name}: d/dx[{k}].flat[{flat}] autograd={got} "
                f"finite-diff={fd}")


@pytest.mark.parametrize("name", GRAD_UNARY, ids=str)
def test_unary_gradient_matches_finite_difference(name):
    pool = UNARY[name][0]
    _fd_check(name, [pool], [(2, 3)])


@pytest.mark.parametrize("name", GRAD_BINARY, ids=str)
def test_binary_gradient_matches_finite_difference(name):
    pa, pb, _ = BINARY[name]
    _fd_check(name, [pa, pb], [(2, 3), (2, 3)])
    _fd_check(name, [pa, pb], [(2, 1), (1, 3)])  # broadcast grads


# ---------------------------------------------------------------------------
# completeness audit
# ---------------------------------------------------------------------------


def _namespace_universe():
    out = set()
    for n in dir(np):
        if n.startswith("_"):
            continue
        f = getattr(np, n)
        if callable(f) and hasattr(onp, n):
            out.add(n)
    return out


def test_sweep_covers_namespace():
    """Every np function with a numpy analog is either swept above or in
    the documented EXCLUDED ledger — and the ledger stays shorter than
    the covered list (VERDICT r4 'done' criterion)."""
    universe = _namespace_universe()
    covered = set(CASE_TABLE)
    unaccounted = universe - covered - set(EXCLUDED)
    assert not unaccounted, (
        f"{len(unaccounted)} np functions neither swept nor excluded: "
        f"{sorted(unaccounted)}")
    stale = set(EXCLUDED) - universe
    assert not stale, f"EXCLUDED entries no longer in namespace: {stale}"
    assert len(EXCLUDED) < len(covered & universe), (
        f"exclusion list ({len(EXCLUDED)}) must stay shorter than the "
        f"covered list ({len(covered & universe)})")
