"""Model zoo construction + forward smoke tests
(reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.vision import get_model


@pytest.mark.slow
@pytest.mark.parametrize("name,size", [
    ("resnet18_v1", 112), ("resnet18_v2", 112), ("resnet34_v1", 112),
    ("resnet50_v1", 112), ("resnet50_v2", 112),
    ("vgg11", 64), ("vgg11_bn", 64),
    ("alexnet", 224),
    ("squeezenet1.0", 224), ("squeezenet1.1", 224),
    ("densenet121", 64),
    ("mobilenet0.25", 64), ("mobilenetv2_0.25", 64),
    ("mobilenetv3_small", 64),
])
def test_model_forward(name, size):
    net = get_model(name, classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, size, size))
    y = net(x)
    assert y.shape == (1, 10)


@pytest.mark.slow
def test_inception_v3():
    net = get_model("inceptionv3", classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 299, 299))
    y = net(x)
    assert y.shape == (1, 10)


def test_resnet18_hybrid_matches_eager():
    net = get_model("resnet18_v1", classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(2, 3, 32, 32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_hyb, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_resnet_train_step():
    """One SGD step through hybridized resnet18 converges the loss."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    net = get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize()
    net.hybridize()
    loss_fn = SoftmaxCrossEntropyLoss()
    x = nd.random.uniform(shape=(8, 3, 16, 16))
    label = nd.array(onp.random.randint(0, 4, (8,)))
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.02})
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = loss_fn(net(x), label).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses
    # full-network grad flow: every trainable parameter receives a nonzero
    # gradient (the exact bug class the cached-op tape-chaining fix covers)
    for name, p in net.collect_params().items():
        if p.grad_req != "null":
            assert float(abs(p.grad().asnumpy()).max()) > 0, name



def test_get_model_unknown_raises():
    with pytest.raises(mx.MXNetError):
        get_model("resnet1000_v9")


def test_model_save_load_roundtrip(tmp_path):
    net = get_model("mobilenet0.25", classes=7)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "m.params")
    net.save_parameters(f)
    net2 = get_model("mobilenet0.25", classes=7)
    net2.load_parameters(f)
    y1 = net2(x).asnumpy()
    onp.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# hardened downloads (ISSUE 3 satellite): retry + sha1 verify + atomic commit
# ---------------------------------------------------------------------------

def _sha1_of(path):
    import hashlib
    with open(path, "rb") as f:
        return hashlib.sha1(f.read()).hexdigest()


def test_download_sha1_verified_atomic(tmp_path):
    from mxnet_tpu.gluon.utils import check_sha1, download
    src = tmp_path / "src.bin"
    src.write_bytes(b"payload-bytes")
    good = _sha1_of(str(src))
    dst = str(tmp_path / "out.bin")
    got = download(f"file://{src}", path=dst, sha1_hash=good, retries=2)
    assert got == dst and check_sha1(dst, good)
    assert not [n for n in os.listdir(str(tmp_path)) if ".tmp-" in n]


def test_download_deletes_corrupt_temp_and_raises(tmp_path, monkeypatch):
    import time as _time
    from mxnet_tpu.gluon import utils as gutils
    monkeypatch.setattr(_time, "sleep", lambda s: None)
    src = tmp_path / "src.bin"
    src.write_bytes(b"corrupted!!")
    dst = str(tmp_path / "out.bin")
    with pytest.raises(mx.MXNetError, match="attempts"):
        gutils.download(f"file://{src}", path=dst,
                        sha1_hash="0" * 40, retries=3)
    assert not os.path.exists(dst)
    assert not [n for n in os.listdir(str(tmp_path)) if ".tmp-" in n]


def test_download_retries_transient_then_succeeds(tmp_path, monkeypatch):
    from mxnet_tpu.gluon import utils as gutils
    attempts = []
    real = gutils._fetch_once

    def flaky(url, tmp):
        attempts.append(url)
        if len(attempts) < 3:
            raise OSError("connection reset")
        real(url, tmp)

    monkeypatch.setattr(gutils, "_fetch_once", flaky)
    import time as _time
    monkeypatch.setattr(_time, "sleep", lambda s: None)
    src = tmp_path / "w.params"
    src.write_bytes(b"weights")
    dst = str(tmp_path / "cache" / "w.params")
    os.makedirs(str(tmp_path / "cache"))
    got = gutils.download(f"file://{src}", path=dst,
                          sha1_hash=_sha1_of(str(src)))
    assert got == dst and len(attempts) == 3
    assert open(dst, "rb").read() == b"weights"


def test_get_model_file_refetches_bad_cache(tmp_path, monkeypatch):
    from mxnet_tpu.gluon.model_zoo import model_store
    mirror = tmp_path / "mirror" / "gluon" / "models"
    os.makedirs(str(mirror))
    (mirror / "tiny.params").write_bytes(b"good-weights")
    sha = _sha1_of(str(mirror / "tiny.params"))
    monkeypatch.setenv("MXNET_GLUON_REPO", f"file://{tmp_path}/mirror/")
    root = str(tmp_path / "cache")
    os.makedirs(root)
    # poison the cache, register the true sha1 -> re-fetch replaces it
    with open(os.path.join(root, "tiny.params"), "wb") as f:
        f.write(b"rotten")
    model_store.register_model_sha1("tiny", sha)
    try:
        path = model_store.get_model_file("tiny", root=root)
    finally:
        model_store._model_sha1.pop("tiny", None)
    assert open(path, "rb").read() == b"good-weights"
