"""Model zoo construction + forward smoke tests
(reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.vision import get_model


@pytest.mark.slow
@pytest.mark.parametrize("name,size", [
    ("resnet18_v1", 112), ("resnet18_v2", 112), ("resnet34_v1", 112),
    ("resnet50_v1", 112), ("resnet50_v2", 112),
    ("vgg11", 64), ("vgg11_bn", 64),
    ("alexnet", 224),
    ("squeezenet1.0", 224), ("squeezenet1.1", 224),
    ("densenet121", 64),
    ("mobilenet0.25", 64), ("mobilenetv2_0.25", 64),
    ("mobilenetv3_small", 64),
])
def test_model_forward(name, size):
    net = get_model(name, classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, size, size))
    y = net(x)
    assert y.shape == (1, 10)


@pytest.mark.slow
def test_inception_v3():
    net = get_model("inceptionv3", classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 299, 299))
    y = net(x)
    assert y.shape == (1, 10)


def test_resnet18_hybrid_matches_eager():
    net = get_model("resnet18_v1", classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(2, 3, 32, 32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_hyb, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_resnet_train_step():
    """One SGD step through hybridized resnet18 converges the loss."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    net = get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize()
    net.hybridize()
    loss_fn = SoftmaxCrossEntropyLoss()
    x = nd.random.uniform(shape=(8, 3, 16, 16))
    label = nd.array(onp.random.randint(0, 4, (8,)))
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.02})
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = loss_fn(net(x), label).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses
    # full-network grad flow: every trainable parameter receives a nonzero
    # gradient (the exact bug class the cached-op tape-chaining fix covers)
    for name, p in net.collect_params().items():
        if p.grad_req != "null":
            assert float(abs(p.grad().asnumpy()).max()) > 0, name



def test_get_model_unknown_raises():
    with pytest.raises(mx.MXNetError):
        get_model("resnet1000_v9")


def test_model_save_load_roundtrip(tmp_path):
    net = get_model("mobilenet0.25", classes=7)
    net.initialize()
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "m.params")
    net.save_parameters(f)
    net2 = get_model("mobilenet0.25", classes=7)
    net2.load_parameters(f)
    y1 = net2(x).asnumpy()
    onp.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)
