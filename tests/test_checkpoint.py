"""Preemption-safe checkpointing (ISSUE 3).

Covers: the atomic+checksummed on-disk format (staged temp dir, CRC
manifest, os.replace commit, `latest` pointer, fallback past corrupt
checkpoints), complete TrainState capture/restore across the eager,
plain-fused, and ZeRO-sharded optimizer paths (bit-exact loss parity
after resume, dp=N -> dp=M resharding), async snapshotting (same
results, error propagation), TrainLoop auto-save/auto-resume/prune,
the fault-injection harness, and — marked slow — subprocess kill-9
tests that SIGKILL a real training run mid-commit and prove it resumes
bit-exactly.
"""
import json
import os
import subprocess
import sys

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import (
    CheckpointCorruptError, TrainCheckpointManager, apply_train_state,
    assemble_segments, atomic_write_bytes, capture_train_state,
    latest_valid, list_checkpoints, load_latest, prune_checkpoints,
    read_checkpoint, write_checkpoint)
from mxnet_tpu.checkpoint.atomic import step_dir_name
from mxnet_tpu.gluon import TrainLoop, Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import make_mesh, shard_batch
from mxnet_tpu.testing import faults
from mxnet_tpu.testing.faults import FaultInjectedError


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------- helpers
def _build(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    return net


def _batch(i, bs=8):
    rng = onp.random.RandomState(1000 + i)
    return (nd.array(rng.randn(bs, 4).astype("float32")),
            nd.array(rng.randint(0, 3, size=(bs,)).astype("int32")))


def _loss_sum(l):
    return float(onp.asarray(l.asnumpy()).sum())


# ================================================================ atomic IO
def test_write_read_roundtrip(tmp_path):
    root = str(tmp_path / "ck")
    arrays = {"a": onp.arange(6, dtype=onp.float32).reshape(2, 3),
              "b/nested": onp.array([1, 2], dtype=onp.int64),
              "c": onp.asarray(jax.numpy.ones((4,),
                                              dtype=jax.numpy.bfloat16))}
    path = write_checkpoint(root, 7, arrays, meta={"note": "hi"})
    assert os.path.basename(path) == step_dir_name(7)
    got, manifest = read_checkpoint(path)
    assert manifest["step"] == 7 and manifest["meta"]["note"] == "hi"
    assert sorted(got) == sorted(arrays)
    assert got["a"].dtype == onp.float32
    assert (got["a"] == arrays["a"]).all()
    assert str(got["c"].dtype) == "bfloat16"
    step, arrays2, _ = load_latest(root)
    assert step == 7 and (arrays2["b/nested"] == arrays["b/nested"]).all()


def test_corrupt_manifest_falls_back_to_older(tmp_path, caplog):
    root = str(tmp_path / "ck")
    write_checkpoint(root, 1, {"a": onp.zeros(3)})
    write_checkpoint(root, 2, {"a": onp.ones(3)})
    # corrupt the NEWEST manifest post-commit (disk rot)
    with open(os.path.join(root, step_dir_name(2), "manifest.json"),
              "w") as f:
        f.write("{not json")
    import logging
    with caplog.at_level(logging.WARNING, "mxnet_tpu.checkpoint"):
        step, arrays, _ = load_latest(root)
    assert step == 1 and (arrays["a"] == 0).all()
    assert any("corrupt" in r.message for r in caplog.records)


def test_truncated_array_fails_crc_and_falls_back(tmp_path):
    root = str(tmp_path / "ck")
    write_checkpoint(root, 1, {"a": onp.zeros(64)})
    write_checkpoint(root, 2, {"a": onp.ones(64)})
    target = os.path.join(root, step_dir_name(2), "arrays", "0.npy")
    raw = open(target, "rb").read()
    with open(target, "wb") as f:
        f.write(raw[:len(raw) // 2])     # torn write post-commit
    with pytest.raises(CheckpointCorruptError, match="checksum|missing"):
        read_checkpoint(os.path.join(root, step_dir_name(2)))
    step, arrays, _ = load_latest(root)
    assert step == 1


def test_stale_latest_pointer_falls_back_to_scan(tmp_path):
    root = str(tmp_path / "ck")
    write_checkpoint(root, 3, {"a": onp.arange(4)})
    with open(os.path.join(root, "latest"), "w") as f:
        f.write(step_dir_name(9) + "\n")      # points at nothing
    assert latest_valid(root)[0] == 3


def test_prune_keeps_newest(tmp_path):
    root = str(tmp_path / "ck")
    for s in (1, 2, 3, 4):
        write_checkpoint(root, s, {"a": onp.full(2, s)})
    prune_checkpoints(root, keep_last=2)
    assert list_checkpoints(root) == [3, 4]
    # a leftover staging dir from a crashed writer is swept too
    os.makedirs(os.path.join(root, ".tmp-step-junk"))
    prune_checkpoints(root, keep_last=2)
    assert not any(n.startswith(".tmp-") for n in os.listdir(root))


def test_commit_crash_leaves_no_partial_visible(tmp_path):
    """An injected failure BEFORE the commit rename must leave the root
    exactly as it was: old checkpoint valid, no new step dir."""
    root = str(tmp_path / "ck")
    write_checkpoint(root, 1, {"a": onp.zeros(8)})
    faults.configure("checkpoint.commit:before=1:error")
    with pytest.raises(FaultInjectedError):
        write_checkpoint(root, 2, {"a": onp.ones(8)})
    faults.reset()
    assert list_checkpoints(root) == [1]
    assert latest_valid(root)[0] == 1


# ================================================================ nd.save
def test_nd_save_atomic_keeps_old_file_on_crash(tmp_path):
    """Regression (ISSUE 3 satellite): a simulated crash mid-save leaves
    the previous good file intact and loadable."""
    fname = str(tmp_path / "arrs")
    nd.save(fname, {"w": nd.array([1.0, 2.0])})
    faults.configure("ndarray.save:before=1:error")
    with pytest.raises(FaultInjectedError):
        nd.save(fname, {"w": nd.array([9.0, 9.0, 9.0])})
    faults.reset()
    got = nd.load(fname)
    assert got["w"].asnumpy().tolist() == [1.0, 2.0]
    assert not [n for n in os.listdir(str(tmp_path))
                if ".tmp-" in n], "temp staging file leaked"


def test_atomic_write_bytes_replaces_whole(tmp_path):
    f = str(tmp_path / "blob")
    atomic_write_bytes(f, b"one")
    atomic_write_bytes(f, b"two-longer")
    assert open(f, "rb").read() == b"two-longer"


# ================================================================ faults
def test_fault_spec_parsing_and_counts():
    rules = faults.configure(
        "checkpoint.commit:after=1;x.y:before=3:error;z:before=1:delay:5")
    assert [r.action for r in rules] == ["kill", "error", "delay"]
    assert rules[2].delay_ms == 5
    faults.fault_point("x.y", "before")     # 1st: no fire
    faults.fault_point("x.y", "before")     # 2nd
    with pytest.raises(FaultInjectedError):
        faults.fault_point("x.y", "before")  # 3rd fires
    faults.fault_point("x.y", "before")     # 4th: fired rules stay quiet
    assert faults.hit_counts()[("x.y", "before")] == 4


def test_fault_bad_spec_rejected():
    with pytest.raises(ValueError):
        faults.configure("nonsense")
    faults.reset()


def test_fault_delay_sleeps(monkeypatch):
    slept = []
    import time as _t
    monkeypatch.setattr(_t, "sleep", lambda s: slept.append(s))
    faults.configure("p:before=1:delay:250")
    faults.fault_point("p", "before")
    assert slept == [0.25]


def test_assemble_segments_roundtrip():
    full = onp.arange(12, dtype=onp.float32).reshape(6, 2)
    arrays = {"x#seg0": full[:3], "x#seg3": full[3:], "y": onp.ones(2)}
    meta = {"x#seg0": {"seg_of": "x", "dim0_start": 0,
                       "global_shape": [6, 2]},
            "x#seg3": {"seg_of": "x", "dim0_start": 3,
                       "global_shape": [6, 2]}}
    out = assemble_segments(arrays, meta)
    assert (out["x"] == full).all() and (out["y"] == 1).all()
    with pytest.raises(MXNetError, match="gap|incomplete"):
        assemble_segments({"x#seg3": full[3:]}, {"x#seg3": meta["x#seg3"]})


# ================================================================ TrainState
def _train_run(mode, opt, n_steps, ckpt_dir=None, save_at=None,
               resume=False, dp=4, lr=0.05, async_save=False):
    """One deterministic run; returns per-step summed losses keyed by
    absolute step index."""
    net = _build()
    opt_params = {"learning_rate": lr}
    if opt == "sgd":
        opt_params["momentum"] = 0.9
    trainer = Trainer(net.collect_params(), opt, opt_params)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": dp}, jax.devices()[:dp]) \
        if mode == "zero" else None

    def body():
        mgr = TrainCheckpointManager(ckpt_dir, keep_last=3,
                                     async_save=async_save) \
            if ckpt_dir else None
        start = 0
        if mgr and resume and mgr.has_checkpoint():
            meta = mgr.restore_latest(trainer=trainer, net=net)
            start = int(meta["step"])
        if mode == "eager":
            from mxnet_tpu import autograd
            losses = {}
            for i in range(start, n_steps):
                x, y = _batch(i)
                with autograd.record():
                    l = loss_blk(net(x), y)
                l.backward()
                trainer.step(8)
                losses[i] = _loss_sum(l)
                if mgr and save_at and (i + 1) in save_at:
                    mgr.save(i + 1, trainer=trainer, net=net)
            if mgr:
                mgr.wait()
            return losses
        step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
        losses = {}
        for i in range(start, n_steps):
            x, y = _batch(i)
            losses[i] = _loss_sum(step(x, y))
            if mgr and save_at and (i + 1) in save_at:
                mgr.save(i + 1, trainer=trainer, net=net)
        if mgr:
            mgr.wait()
        if mode == "zero":
            assert step.zero_sharded
        return losses

    if mesh is not None:
        with mesh:
            return body()
    return body()


@pytest.mark.parametrize("mode", ["eager", "fused", "zero"])
@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_resume_bit_exact(tmp_path, mode, opt):
    """Save at step 3 of 6, restore into a FRESH net/trainer/program,
    replay — losses must match the uninterrupted run bit-exactly
    (params, momenta/moments, Adam t counters, RNG all round-trip)."""
    if mode == "zero" and len(jax.devices()) < 4:
        pytest.skip("needs virtual mesh")
    base = _train_run(mode, opt, 6)
    d = str(tmp_path / "ck")
    first = _train_run(mode, opt, 3, ckpt_dir=d, save_at={3})
    assert first == {i: base[i] for i in range(3)}
    resumed = _train_run(mode, opt, 6, ckpt_dir=d, resume=True)
    assert resumed == {i: base[i] for i in range(3, 6)}


def test_zero_reshard_dp4_to_dp2(tmp_path):
    """dp=N checkpoint resumes on a dp=M mesh: the logical (unpadded,
    per-param) state format re-pads and re-shards on restore."""
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual mesh")
    d = str(tmp_path / "ck")
    base = _train_run("zero", "adam", 6, dp=4)
    _train_run("zero", "adam", 3, ckpt_dir=d, save_at={3}, dp=4)
    resumed = _train_run("zero", "adam", 6, ckpt_dir=d, resume=True, dp=2)
    for i in range(3, 6):
        assert resumed[i] == pytest.approx(base[i], rel=1e-5)


def test_capture_restore_mid_run_live_plan(tmp_path):
    """Restore INTO a live zero program (plan already materialized):
    state is rebuilt in place and training continues bit-exactly."""
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual mesh")
    net = _build()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.05})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
        for i in range(3):
            step(*_batch(i))
        state = capture_train_state(trainer=trainer, net=net, step=3)
        want = [_loss_sum(step(*_batch(i))) for i in range(3, 6)]
        # keep training past the snapshot, then rewind
        for i in range(6, 8):
            step(*_batch(i))
        apply_train_state(state, trainer=trainer, net=net)
        got = [_loss_sum(step(*_batch(i))) for i in range(3, 6)]
    assert got == want


def test_multi_precision_masters_roundtrip(tmp_path, monkeypatch):
    """bf16 + multi_precision zero mode: fp32 masters are captured and
    restored exactly (NOT recast from the bf16 weights)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual mesh")
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")

    def build_mp():
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(6, in_units=4))
        net.initialize()
        net.cast("bfloat16")
        trainer = Trainer(net.collect_params(), "adam",
                          {"learning_rate": 1e-2, "multi_precision": True})
        return net, trainer

    def run(n_pre, n_post, d=None):
        net, trainer = build_mp()
        with make_mesh({"dp": 4}, jax.devices()[:4]):
            step = trainer.compile_step(lambda a: (net(a) ** 2).mean())
            mgr = TrainCheckpointManager(d) if d else None
            start = 0
            if mgr and mgr.has_checkpoint():
                start = mgr.restore_latest(trainer=trainer,
                                           net=net)["step"]
            rng = onp.random.RandomState(0)
            x = nd.array(rng.randn(8, 4).astype("float32")) \
                .astype("bfloat16")
            out = []
            for i in range(start, n_pre + n_post):
                out.append(_loss_sum(step(x, batch_size=8)))
                if mgr and i + 1 == n_pre:
                    mgr.save(i + 1, trainer=trainer, net=net, block=True)
            assert step.zero_sharded and step._zero.masters
            return out

    base = run(3, 3)
    d = str(tmp_path / "ck")
    run(3, 0, d=d)
    resumed = run(3, 3, d=d)
    assert resumed == base[3:]


def test_state_includes_rng_and_scheduler(tmp_path):
    from mxnet_tpu import lr_scheduler
    net = _build()
    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=0.1)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "lr_scheduler": sched})
    state = capture_train_state(trainer=trainer, net=net, step=5)
    assert "rng/key" in state.arrays
    assert state.meta["lr_scheduler"] is not None
    # mutate, then restore
    sched.base_lr = 0.7
    mx.random.seed(123456)
    apply_train_state(state, trainer=trainer, net=net)
    assert sched.base_lr == pytest.approx(0.1)
    from mxnet_tpu.ndarray.random import get_key_state
    assert (get_key_state() == state.arrays["rng/key"]).all()


# ================================================================ TrainLoop
def _loop_run(tmp_dir, n_steps, every=2, async_ckpt=True, keep_last=2):
    net = _build()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.05})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     checkpoint_dir=tmp_dir, checkpoint_every=every,
                     keep_last=keep_last, async_checkpoint=async_ckpt)
    losses = {}
    for i in range(loop.global_step, n_steps):
        losses[i] = _loss_sum(loop.step(*_batch(i)))
    loop.wait()
    return loop, losses


def test_trainloop_autoresume_bit_exact(tmp_path):
    d = str(tmp_path / "ck")
    base = _train_run("fused", "adam", 6)
    loop1, first = _loop_run(d, 4)
    assert loop1.checkpoint_manager.latest_step() == 4
    loop2, resumed = _loop_run(d, 6)
    assert loop2.global_step == 6
    assert resumed == {i: base[i] for i in range(4, 6)}


def test_trainloop_prunes_to_keep_last(tmp_path):
    d = str(tmp_path / "ck")
    _loop_run(d, 8, every=2, keep_last=2)
    assert list_checkpoints(d) == [6, 8]


def test_async_checkpoint_does_not_change_results(tmp_path):
    da, ds = str(tmp_path / "a"), str(tmp_path / "s")
    _, la = _loop_run(da, 5, async_ckpt=True)
    _, ls = _loop_run(ds, 5, async_ckpt=False)
    assert la == ls
    sa = load_latest(da)
    ss = load_latest(ds)
    assert sa[0] == ss[0]
    for k in sa[1]:
        if k == "rng/key":
            continue
        assert (sa[1][k] == ss[1][k]).all(), k


def test_async_write_error_propagates(tmp_path):
    d = str(tmp_path / "ck")
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    mgr = TrainCheckpointManager(d, async_save=True)
    faults.configure("checkpoint.stage:before=1:error")
    mgr.save(1, trainer=trainer, net=net)       # fails on the worker
    with pytest.raises(MXNetError, match="background checkpoint"):
        mgr.wait()
    faults.reset()
    # the manager recovers: next save succeeds
    mgr.save(2, trainer=trainer, net=net, block=True)
    assert mgr.latest_step() == 2


def test_trainloop_without_dir_rejects_manual_save():
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss())
    with pytest.raises(MXNetError, match="checkpoint_dir"):
        loop.save_checkpoint()


# ================================================================ Trainer API
def test_save_states_raises_when_zero_owns_state(tmp_path):
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual mesh")
    net = _build()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.05})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
        step(*_batch(0))
        assert step.zero_sharded
        with pytest.raises(MXNetError, match="ZeRO-sharded"):
            trainer.save_states(str(tmp_path / "states"))


def test_save_states_atomic_and_load_states_dir_shim(tmp_path):
    """Plain trainers keep the reference single-file format (now written
    crash-safely); load_states also accepts a new-format checkpoint
    dir (deprecation shim)."""
    from mxnet_tpu import autograd
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    for i in range(2):
        x, y = _batch(i)
        with autograd.record():
            l = loss_blk(net(x), y)
        l.backward()
        trainer.step(8)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    assert os.path.exists(fname)

    # old single-file path round-trips
    net2 = _build()
    trainer2 = Trainer(net2.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    trainer2.load_states(fname)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update

    # dir shim: point load_states at an atomic checkpoint directory
    state = capture_train_state(trainer=trainer, net=net, step=2)
    root = str(tmp_path / "ck")
    path = write_checkpoint(root, 2, state.arrays,
                            array_meta=state.array_meta, meta=state.meta)
    net3 = _build()
    trainer3 = Trainer(net3.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    trainer3.load_states(path)
    assert trainer3._optimizer.num_update == trainer._optimizer.num_update
    st2 = capture_train_state(trainer=trainer3, step=2)
    for k, v in st2.arrays.items():
        if k.startswith("opt/"):
            assert (v == state.arrays[k]).all(), k


def test_trainer_train_state_convenience():
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    st = trainer.train_state(step=4, net=net)
    assert st.step == 4 and any(k.startswith("param/")
                                for k in st.arrays)
    meta = trainer.load_train_state(st, net=net)
    assert meta["step"] == 4


# ================================================================ estimator
def test_estimator_checkpoint_handler_atomic(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    rng = onp.random.RandomState(0)
    ds = ArrayDataset(nd.array(rng.randn(16, 4).astype("float32")),
                      nd.array(rng.randint(0, 3, (16,)).astype("int32")))
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(),
                    trainer=trainer)
    h = CheckpointHandler(str(tmp_path), model_prefix="m", keep_last=2)
    est.fit(DataLoader(ds, batch_size=8), epochs=2, event_handlers=[h])
    assert os.path.exists(str(tmp_path / "m-epoch2.params"))
    ckpt_root = str(tmp_path / "m-ckpt")
    assert list_checkpoints(ckpt_root) == [1, 2]
    # and the saved state restores into a fresh trainer
    mgr = TrainCheckpointManager(ckpt_root)
    net2 = _build()
    tr2 = Trainer(net2.collect_params(), "sgd", {"learning_rate": 0.1})
    meta = mgr.restore_latest(trainer=tr2, net=net2)
    assert meta["step"] == 2
    w1 = net._children["0"].weight.data().asnumpy()
    w2 = net2._children["0"].weight.data().asnumpy()
    assert (w1 == w2).all()


# ================================================================ kill -9
def _run_worker(ckpt_dir, out, mode, opt, steps=6, env_extra=None,
                sync=False):
    env = dict(os.environ)
    env.pop("MXNET_FAULT_INJECT", None)
    env.update(env_extra or {})
    cmd = [sys.executable,
           os.path.join(os.path.dirname(__file__),
                        "checkpoint_crash_worker.py"),
           ckpt_dir, out, "--mode", mode, "--opt", opt,
           "--steps", str(steps)]
    if sync:
        cmd.append("--sync")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(cmd, env=env, cwd=repo, capture_output=True,
                          text=True, timeout=300)


def _losses(path):
    out = {}
    if os.path.exists(path):
        for line in open(path):
            i, v = line.split()
            out[int(i)] = float(v)
    return out


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["fused", "zero"])
@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_kill9_mid_commit_resumes_bit_exact(tmp_path, mode, opt):
    """End-to-end crash consistency: a real training subprocess is
    SIGKILLed DURING the checkpoint commit rename; rerunning it
    auto-resumes from the newest valid checkpoint and reproduces the
    uninterrupted run's losses bit-exactly."""
    base_out = str(tmp_path / "base.log")
    r = _run_worker(str(tmp_path / "nock"), base_out, mode, opt)
    assert r.returncode == 0, r.stderr[-2000:]
    base = _losses(base_out)
    assert sorted(base) == list(range(6))

    d = str(tmp_path / "ck")
    killed_out = str(tmp_path / "killed.log")
    r = _run_worker(
        d, killed_out, mode, opt, sync=True,
        env_extra={"MXNET_FAULT_INJECT": "checkpoint.commit:before=2"})
    assert r.returncode == -9, (r.returncode, r.stderr[-2000:])
    # the interrupted prefix matched while it lasted
    for i, v in _losses(killed_out).items():
        assert v == base[i]
    # first commit survived; the torn second one is invisible
    assert latest_valid(d)[0] == 2

    resumed_out = str(tmp_path / "resumed.log")
    r = _run_worker(d, resumed_out, mode, opt)
    assert r.returncode == 0, r.stderr[-2000:]
    resumed = _losses(resumed_out)
    assert sorted(resumed) == [2, 3, 4, 5], "did not resume from step 2"
    for i, v in resumed.items():
        assert v == base[i], f"loss diverged at step {i}"


@pytest.mark.slow
def test_kill9_after_publish_keeps_latest_valid(tmp_path):
    """Killed right AFTER publish: directory still has a valid latest;
    resume starts from the just-published step."""
    d = str(tmp_path / "ck")
    out = str(tmp_path / "o.log")
    r = _run_worker(
        d, out, "fused", "sgd", sync=True,
        env_extra={"MXNET_FAULT_INJECT": "checkpoint.publish:after=2"})
    assert r.returncode == -9
    assert latest_valid(d)[0] == 4
    r = _run_worker(d, str(tmp_path / "o2.log"), "fused", "sgd")
    assert r.returncode == 0
    assert sorted(_losses(str(tmp_path / "o2.log"))) == [4, 5]


@pytest.mark.slow
def test_kill9_first_commit_means_fresh_start(tmp_path):
    """Killed during the very FIRST commit: no valid checkpoint may be
    visible — the rerun starts from scratch rather than loading trash."""
    d = str(tmp_path / "ck")
    r = _run_worker(
        d, str(tmp_path / "o.log"), "fused", "sgd", sync=True,
        env_extra={"MXNET_FAULT_INJECT": "checkpoint.commit:before=1"})
    assert r.returncode == -9
    assert latest_valid(d) is None
    r = _run_worker(d, str(tmp_path / "o2.log"), "fused", "sgd")
    assert r.returncode == 0
    assert sorted(_losses(str(tmp_path / "o2.log"))) == list(range(6))
