"""Trainer/KVStore/optimizer integration + the MNIST E2E slice
(reference: tests/python/unittest/test_gluon_trainer.py, tests/python/train/)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize()
    w0 = net.weight.data().asnumpy().copy()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = nd.ones((4, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    w1 = net.weight.data().asnumpy()
    # dL/dw = sum over batch of x = [4,4]; rescaled by 1/4 -> [1,1]
    onp.testing.assert_allclose(w0 - 0.1 * onp.ones((1, 2)), w1, rtol=1e-5)


def test_trainer_stale_grad_raises():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd")
    with pytest.raises(mx.MXNetError):
        trainer.step(1)  # no backward ran
    # with ignore_stale_grad it proceeds
    trainer.step(1, ignore_stale_grad=True)


def test_trainer_lr_scheduler():
    from mxnet_tpu import lr_scheduler
    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=1.0)
    net = nn.Dense(1, in_units=1)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1.0, "lr_scheduler": sched})
    x = nd.ones((1, 1))
    lrs = []
    for _ in range(5):
        with autograd.record():
            l = net(x).sum()
        l.backward()
        trainer.step(1)
        lrs.append(trainer.learning_rate)
    assert lrs[0] == 1.0 and lrs[-1] < 1.0


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = Trainer(net.collect_params(), "adam")
    x = nd.ones((2, 2))
    for _ in range(3):
        with autograd.record():
            l = (net(x) ** 2).sum()
        l.backward()
        trainer.step(2)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    trainer2 = Trainer(net.collect_params(), "adam")
    trainer2.load_states(f)
    assert len(trainer2._updater.states) == len(trainer._updater.states)


def test_kvstore_push_pull():
    kv = mx.kvstore.create("tpu")
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.ones((2, 3)))
    # push replica list: sums
    kv.push(3, [nd.ones((2, 3)), nd.ones((2, 3))])
    kv.pull(3, out=out)
    onp.testing.assert_allclose(out.asnumpy(), 2 * onp.ones((2, 3)))


def test_kvstore_pushpull_fused():
    kv = mx.kvstore.create("tpu")
    a = nd.full((2,), 1.0)
    b = nd.full((2,), 3.0)
    kv.pushpull(0, [a, b])
    onp.testing.assert_allclose(a.asnumpy(), [4.0, 4.0])
    onp.testing.assert_allclose(b.asnumpy(), [4.0, 4.0])


def test_kvstore_broadcast():
    kv = mx.kvstore.create("tpu")
    src = nd.full((3,), 5.0)
    dst = nd.zeros((3,))
    kv.broadcast("w", src, out=dst)
    onp.testing.assert_allclose(dst.asnumpy(), [5, 5, 5])


def test_kvstore_update_on_store():
    from mxnet_tpu import optimizer as opt
    kv = mx.kvstore.create("tpu")
    kv.set_optimizer(opt.SGD(learning_rate=0.5))
    kv.init(0, nd.ones((2,)))
    kv.push(0, nd.ones((2,)))  # grad = 1 -> w = 1 - 0.5
    out = nd.zeros((2,))
    kv.pull(0, out=out)
    onp.testing.assert_allclose(out.asnumpy(), [0.5, 0.5])


def test_kvstore_types():
    for name in ("local", "device", "tpu", "nccl"):
        kv = mx.kvstore.create(name)
        assert kv.num_workers == 1 and kv.rank == 0


def _train_mnist(hybridize: bool, epochs=3):
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision import MNIST, transforms

    mx.random.seed(0)
    train_set = MNIST(root="/nonexistent", train=True)  # synthetic fallback
    to_tensor = transforms.ToTensor()
    train_set = train_set.transform_first(lambda x: to_tensor(x))
    loader = DataLoader(train_set, batch_size=256, shuffle=True)

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    if hybridize:
        net.hybridize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-3}, kvstore="tpu")
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for _ in range(epochs):
        metric.reset()
        for data, label in loader:
            data = data.reshape(data.shape[0], -1)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update(label, out)
    return metric.get()[1]


def test_mnist_mlp_convergence():
    """SURVEY §7 stage 5: the minimum end-to-end slice."""
    acc = _train_mnist(hybridize=False, epochs=2)
    assert acc > 0.85, f"imperative MLP failed to converge: acc={acc}"


def test_mnist_mlp_convergence_hybrid():
    acc = _train_mnist(hybridize=True, epochs=2)
    assert acc > 0.85, f"hybrid MLP failed to converge: acc={acc}"


def test_dataloader_basics():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = onp.random.rand(50, 4).astype("float32")
    Y = onp.arange(50).astype("float32")
    ds = ArrayDataset(X, Y)
    assert len(ds) == 50
    loader = DataLoader(ds, batch_size=16, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4
    xb, yb = batches[0]
    assert xb.shape == (16, 4) and yb.shape == (16,)
    onp.testing.assert_allclose(yb.asnumpy(), onp.arange(16))
    # last_batch discard
    loader2 = DataLoader(ds, batch_size=16, last_batch="discard")
    assert len(list(loader2)) == 3
    # threaded workers
    loader3 = DataLoader(ds, batch_size=10, num_workers=2)
    assert sum(b[1].shape[0] for b in loader3) == 50


def test_dataloader_failing_dataset_cancels_inflight():
    """ISSUE 2 satellite regression: when a worker raises, the threaded
    __iter__ must surface the error WITHOUT draining the remaining
    in-flight futures. Item 0 raises immediately; every other item
    blocks on a gate the test only opens AFTER the error arrives — the
    old implementation's pool shutdown waited on the blocked future and
    deadlocked here."""
    import threading
    import time
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import Dataset

    gate = threading.Event()

    class Failing(Dataset):
        def __len__(self):
            return 40

        def __getitem__(self, i):
            if i == 0:
                raise ValueError("poisoned sample")
            gate.wait(timeout=30)
            return onp.float32(i)

    loader = DataLoader(Failing(), batch_size=4, num_workers=1)
    t0 = time.monotonic()
    try:
        with pytest.raises(ValueError, match="poisoned"):
            for _ in loader:
                pass
        elapsed = time.monotonic() - t0
        # the error must not wait behind the gated in-flight batch
        assert elapsed < 10, f"error was blocked for {elapsed:.1f}s"
    finally:
        gate.set()   # release any worker thread still in __getitem__


def test_dataloader_timeout_raises():
    """timeout is honored per batch with a clear framework error."""
    import time
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import Dataset

    class Slow(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            time.sleep(2.0)
            return onp.float32(i)

    loader = DataLoader(Slow(), batch_size=2, num_workers=1, timeout=0.2)
    with pytest.raises(MXNetError, match="timeout"):
        next(iter(loader))


def test_dataloader_early_break_no_leak():
    """Abandoning the iterator (break) shuts the pool down without
    waiting on queued work; a fresh iteration still works."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(onp.arange(64).astype("float32"))
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    it = iter(loader)
    next(it)
    it.close()   # GeneratorExit path: finally must cancel + shutdown
    total = sum(b.shape[0] for b in loader)
    assert total == 64


def test_dataloader_sampler_api():
    from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                      RandomSampler, SequentialSampler)
    ds = ArrayDataset(onp.arange(10).astype("float32"))
    bs = BatchSampler(SequentialSampler(10), 3, "keep")
    loader = DataLoader(ds, batch_sampler=bs)
    sizes = [b.shape[0] for b in loader]
    assert sizes == [3, 3, 3, 1]
    rs = RandomSampler(10)
    assert sorted(list(rs)) == list(range(10))


def test_orbax_checkpoint_roundtrip(tmp_path):
    """TPU-native sharded-capable checkpointing (mx.checkpoint over orbax);
    reference parity baseline is single-file save_parameters/save_states."""
    from mxnet_tpu import checkpoint as ckpt
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(16, 4).astype("float32"))
    y = mx.nd.array(rng.randn(16, 1).astype("float32"))

    def build():
        net = nn.Dense(1, in_units=4)
        net.initialize()
        tr = mx.gluon.Trainer(net.collect_params(), "adam",
                              {"learning_rate": 0.05})
        return net, tr

    mx.random.seed(11)
    net, tr = build()
    for _ in range(3):
        with mx.autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        tr.step(16)
    ckpt.save_checkpoint(str(tmp_path / "ck"), net, tr, step=3)

    mx.random.seed(999)  # different init
    net2, tr2 = build()
    # run one step so the updater allocates its states
    with mx.autograd.record():
        loss = ((net2(x) - y) ** 2).mean()
    loss.backward()
    tr2.step(16)
    tree = ckpt.load_checkpoint(str(tmp_path / "ck"), net2, tr2)
    assert int(tree["step"]) == 3
    onp.testing.assert_allclose(net2.weight.data().asnumpy(),
                                net.weight.data().asnumpy(), rtol=1e-6)
    # training continues identically from the restored state
    for n_, t_ in ((net, tr), (net2, tr2)):
        with mx.autograd.record():
            l = ((n_(x) - y) ** 2).mean()
        l.backward()
        t_.step(16)
    onp.testing.assert_allclose(net2.weight.data().asnumpy(),
                                net.weight.data().asnumpy(), rtol=1e-5)


def test_checkpoint_manager_retention(tmp_path):
    from mxnet_tpu.checkpoint import CheckpointManager
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net(mx.nd.ones((1, 3)))
    mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
    for s in (1, 2, 3):
        mgr.save(s, net)
    assert mgr.latest_step() == 3
    tree = mgr.restore_latest(net)
    assert int(tree["step"]) == 3


def test_checkpoint_restore_into_fresh_trainer(tmp_path):
    # natural resume: load BEFORE any step — optimizer moments must be
    # allocated and applied, not silently dropped
    from mxnet_tpu import checkpoint as ckpt
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(16, 4).astype("float32"))
    y = mx.nd.array(rng.randn(16, 1).astype("float32"))

    mx.random.seed(21)
    net, tr = nn.Dense(1, in_units=4), None
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "adam",
                          {"learning_rate": 0.05})
    for _ in range(3):
        with mx.autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        tr.step(16)
    ckpt.save_checkpoint(str(tmp_path / "ck"), net, tr, step=3)

    mx.random.seed(77)
    net2 = nn.Dense(1, in_units=4)
    net2.initialize()
    tr2 = mx.gluon.Trainer(net2.collect_params(), "adam",
                           {"learning_rate": 0.05})
    ckpt.load_checkpoint(str(tmp_path / "ck"), net2, tr2)  # no prior step
    assert tr2._updater.states, "optimizer states must be restored"
    for n_, t_ in ((net, tr), (net2, tr2)):
        with mx.autograd.record():
            l = ((n_(x) - y) ** 2).mean()
        l.backward()
        t_.step(16)
    onp.testing.assert_allclose(net2.weight.data().asnumpy(),
                                net.weight.data().asnumpy(), rtol=1e-5)
