"""Fused multi-key pushpull (KVStoreDist.pushpull_list).

Reference analog: ps-lite message batching + big-array slicing in
src/kvstore/kvstore_dist.h (MXNET_KVSTORE_SLICE_THRESHOLD) and the
engine-ordering contract include/mxnet/kvstore.h:129-141. Cross-process
behavior is covered by tests/test_dist_kvstore.py; here the packing,
bucketing, write-back, and stats accounting run single-process with the
fuse path forced."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore.kvstore import KVStoreDist


def _mk_store(**kwargs):
    kv = mx.kvstore.create("dist_sync")
    kv._force_fuse = True  # exercise the fused path without 2 processes
    return kv


def test_fused_matches_per_key_results():
    rng = onp.random.RandomState(0)
    shapes = [(4, 3), (7,), (2, 2, 2), (5, 1)]
    vals = [rng.randn(*s).astype("float32") for s in shapes]

    kv_f = _mk_store()
    arrs_f = [nd.array(v) for v in vals]
    kv_f.pushpull_list(list(range(len(shapes))), arrs_f)

    kv_s = mx.kvstore.create("dist_sync")
    arrs_s = [nd.array(v) for v in vals]
    for i, a in enumerate(arrs_s):
        kv_s.pushpull(i, a)

    for f, s in zip(arrs_f, arrs_s):
        onp.testing.assert_allclose(f.asnumpy(), s.asnumpy(), rtol=1e-6)


def test_fused_mixed_dtypes_bucket_separately():
    # int32 vs float32: genuinely distinct dtypes under x64-disabled JAX
    # (float64 would silently downcast to float32 and share a bucket)
    kv = _mk_store()
    a = nd.array(onp.ones((3,), "float32"))
    b = nd.array(onp.full((3,), 4, "int32"))
    c = nd.array(onp.full((2,), 2.0, "float32"))
    kv.pushpull_list([0, 1, 2], [a, b, c])
    onp.testing.assert_allclose(a.asnumpy(), onp.ones(3))
    assert str(b.dtype).endswith("int32")
    onp.testing.assert_array_equal(b.asnumpy(), onp.full((3,), 4))
    onp.testing.assert_allclose(c.asnumpy(), 2 * onp.ones(2))


def test_fused_slice_threshold_splits_buckets(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_SLICE_THRESHOLD", "8")
    kv = _mk_store()
    arrs = [nd.array(onp.full((6,), float(i + 1), "float32"))
            for i in range(4)]
    kv.pushpull_list(list(range(4)), arrs)
    for i, a in enumerate(arrs):
        onp.testing.assert_allclose(a.asnumpy(), (i + 1) * onp.ones(6))


def test_fused_with_updater_runs_store_optimizer():
    from mxnet_tpu import optimizer as opt
    kv = _mk_store()
    kv.set_optimizer(opt.SGD(learning_rate=0.5))
    w0 = nd.array(onp.zeros((3,), "float32"))
    w1 = nd.array(onp.zeros((2, 2), "float32"))
    kv.init(0, w0)
    kv.init(1, w1)
    g0 = nd.array(onp.ones((3,), "float32"))
    g1 = nd.array(onp.full((2, 2), 2.0, "float32"))
    o0 = nd.zeros((3,))
    o1 = nd.zeros((2, 2))
    kv.pushpull_list([0, 1], [g0, g1], outs=[o0, o1])
    onp.testing.assert_allclose(o0.asnumpy(), -0.5 * onp.ones(3))
    onp.testing.assert_allclose(o1.asnumpy(), -1.0 * onp.ones((2, 2)))


def test_fused_sparse_values_fall_back_per_key():
    kv = _mk_store()
    dense = nd.array(onp.ones((3,), "float32"))
    sp = nd.sparse.row_sparse_array(
        (onp.ones((1, 2), "float32"), onp.array([1], "int32")),
        shape=(4, 2))
    kv.pushpull_list([0, 1], [dense, sp])
    onp.testing.assert_allclose(dense.asnumpy(), onp.ones(3))
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    assert isinstance(sp, RowSparseNDArray)
    assert sp.indices.asnumpy().tolist() == [1]


def test_trainer_uses_fused_path_and_stats_shrink():
    """Trainer._allreduce_grads makes ONE pushpull_list call; on a forced
    dist store the per-step host-sync count is 1 and collectives = number
    of dtype buckets, not number of parameters."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    calls = {"list": 0, "single": 0}
    orig_list = KVStoreDist.pushpull_list
    orig_single = KVStoreDist.pushpull

    net = nn.Sequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(4, in_units=8),
            nn.Dense(2, in_units=4))
    net.initialize()
    kv = _mk_store()

    def counting_list(self, *a, **k):
        calls["list"] += 1
        return orig_list(self, *a, **k)

    def counting_single(self, *a, **k):
        calls["single"] += 1
        return orig_single(self, *a, **k)

    KVStoreDist.pushpull_list = counting_list
    KVStoreDist.pushpull = counting_single
    try:
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=kv,
                           update_on_kvstore=False)
        x = nd.array(onp.random.RandomState(0)
                     .randn(4, 4).astype("float32"))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(4)
    finally:
        KVStoreDist.pushpull_list = orig_list
        KVStoreDist.pushpull = orig_single
    nparams = 6  # 3 layers x (weight, bias)
    assert calls["list"] == 1
    assert calls["single"] == 0  # all keys dense: nothing fell back
    # all six f32 params packed into ONE bucket -> one collective dispatch
    # accounted; zero blocking (single process never waits)
    assert kv.stats["collectives"] <= 1, kv.stats
    assert kv.stats["blocks"] <= 1, kv.stats
    assert nparams == len(tr._params)
