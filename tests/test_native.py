"""Native runtime tests: C++ dependency engine, RecordIO, prefetcher.

Reference analog: tests/cpp/engine/threaded_engine_test.cc (ordering,
exception semantics) and python recordio round-trip tests. The engine
orders *host* tasks here (device work is XLA's job on TPU).
"""
import os
import struct
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _native, recordio
from mxnet_tpu.base import MXNetError

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native lib unavailable (no g++?)")


def test_engine_write_ordering():
    # Ops writing the same var must run exclusively and in push order.
    eng = _native.NativeEngine(num_threads=4)
    var = eng.new_var()
    log = []
    for i in range(50):
        eng.push(lambda i=i: log.append(i), mutable_vars=[var])
    eng.wait_for_var(var)
    assert log == list(range(50))
    assert eng.var_version(var) == 50
    eng.close()


def test_engine_reads_parallel_writes_exclusive():
    eng = _native.NativeEngine(num_threads=4)
    var = eng.new_var()
    state = {"active": 0, "max_active": 0}
    lock = threading.Lock()

    def reader():
        with lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"], state["active"])
        time.sleep(0.01)
        with lock:
            state["active"] -= 1

    for _ in range(8):
        eng.push(reader, const_vars=[var])
    eng.wait_for_all()
    assert state["max_active"] > 1  # reads overlapped
    # now interleave a write: everything pushed after must see it done
    order = []
    eng.push(lambda: (time.sleep(0.02), order.append("w")), mutable_vars=[var])
    eng.push(lambda: order.append("r"), const_vars=[var])
    eng.wait_for_all()
    assert order == ["w", "r"]
    eng.close()


def test_engine_dependency_chain():
    # writer(a) -> reader(a) writer(b) -> reader(b); cross-var ordering
    eng = _native.NativeEngine(num_threads=4)
    a, b = eng.new_var(), eng.new_var()
    out = []
    eng.push(lambda: (time.sleep(0.02), out.append("wa")), mutable_vars=[a])
    eng.push(lambda: out.append("ra_wb"), const_vars=[a], mutable_vars=[b])
    eng.push(lambda: out.append("rb"), const_vars=[b])
    eng.wait_for_all()
    assert out == ["wa", "ra_wb", "rb"]
    eng.close()


def test_engine_exception_at_sync_point():
    # Async failures surface at wait_for_* (reference
    # threaded_engine.cc:422-436 exception propagation).
    eng = _native.NativeEngine(num_threads=2)
    var = eng.new_var()

    def boom():
        raise ValueError("kaboom from worker")

    eng.push(boom, mutable_vars=[var])
    with pytest.raises(MXNetError, match="kaboom"):
        eng.wait_for_var(var)
    # error is consumed; engine remains usable
    eng.push(lambda: None, mutable_vars=[var])
    eng.wait_for_var(var)
    eng.close()


@pytest.mark.parametrize("native_write,native_read",
                         [(True, True), (True, False), (False, True)])
def test_recordio_cross_compat(tmp_path, native_write, native_read,
                               monkeypatch):
    # native and pure-Python impls must interoperate byte-for-byte
    path = str(tmp_path / "data.rec")
    records = [b"hello", b"x" * 1021, b"", os.urandom(4096),
               struct.pack("<I", 0xced7230a)]  # payload containing magic
    w = (_native.NativeRecordIOWriter(path) if native_write
         else recordio._PyWriter(path))
    for r in records:
        w.write(r)
    w.close()
    r_ = (_native.NativeRecordIOReader(path) if native_read
          else recordio._PyReader(path))
    got = []
    while True:
        rec = r_.read()
        if rec is None:
            break
        got.append(rec)
    r_.close()
    assert got == records


def test_mxrecordio_api(tmp_path):
    path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(path, "w")
    for i in range(10):
        rec.write(f"record{i}".encode())
    rec.close()
    rec = recordio.MXRecordIO(path, "r")
    for i in range(10):
        assert rec.read() == f"record{i}".encode()
    assert rec.read() is None
    rec.reset()
    assert rec.read() == b"record0"
    rec.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    rec = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(20):
        rec.write_idx(i, f"rec{i}".encode())
    rec.close()
    rec = recordio.MXIndexedRecordIO(idx, path, "r")
    assert rec.keys == list(range(20))
    assert rec.read_idx(13) == b"rec13"
    assert rec.read_idx(4) == b"rec4"
    rec.close()


def test_indexed_writer_tell(tmp_path):
    # tell() in write mode must advance identically native vs pure-Python
    # (reference index-building pattern: pos = tell(); write_idx(...)).
    paths = [(str(tmp_path / "n.rec"), _native.NativeRecordIOWriter),
             (str(tmp_path / "p.rec"), recordio._PyWriter)]
    tells = []
    for path, cls in paths:
        w = cls(path)
        t = [w.tell()]
        for i in range(5):
            w.write(b"x" * (i * 3 + 1))
            t.append(w.tell())
        w.close()
        tells.append(t)
    assert tells[0] == tells[1]
    assert tells[0][0] == 0 and sorted(tells[0]) == tells[0]


def test_pyreader_truncated_header(tmp_path):
    path = str(tmp_path / "trunc.rec")
    w = recordio._PyWriter(path)
    w.write(b"full record")
    w.close()
    with open(path, "ab") as f:
        f.write(struct.pack("<I", 0xced7230a) + b"\x01\x02")  # cut mid-header
    r = recordio._PyReader(path)
    assert r.read() == b"full record"
    with pytest.raises(MXNetError, match="truncated header"):
        r.read()
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(flag=0, label=3.5, id=42, id2=0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload" and h2.label == 3.5 and h2.id == 42
    # multi-label
    h = recordio.IRHeader(flag=0, label=onp.array([1.0, 2.0, 3.0]), id=7, id2=0)
    s = recordio.pack(h, b"xyz")
    h2, payload = recordio.unpack(s)
    assert payload == b"xyz"
    onp.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])


def test_prefetcher(tmp_path):
    path = str(tmp_path / "big.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [os.urandom(onp.random.randint(1, 2000)) for _ in range(200)]
    for p in payloads:
        w.write(p)
    w.close()
    pf = _native.NativePrefetchReader(path, capacity=16)
    got = list(pf)
    pf.close()
    assert got == payloads


def test_native_batchify_stack_matches_numpy():
    """src/native/batchify.cc MXTBatchifyStack: GIL-free parallel collation
    must be byte-identical to numpy stack (reference StackBatchify,
    src/io/batchify.cc)."""
    from mxnet_tpu import _native
    from mxnet_tpu.gluon.data.batchify import Stack, _native_stack
    if not _native.available():
        pytest.skip("native library unavailable")
    rng = onp.random.RandomState(3)
    # large batch (>1MB) rides the native parallel copy
    arrs = [rng.randn(64, 512).astype("float32") for _ in range(16)]
    assert _native_stack(arrs) is not None
    onp.testing.assert_array_equal(Stack()(arrs).asnumpy(),
                                   onp.stack(arrs))
    # int dtype too
    iarrs = [rng.randint(0, 9, (256, 512)).astype("int32")
             for _ in range(16)]
    onp.testing.assert_array_equal(Stack()(iarrs).asnumpy(),
                                   onp.stack(iarrs))
    # small batches skip the thread spawn (numpy memcpy wins there)
    assert _native_stack([onp.zeros((4,), "float32")] * 8) is None
    # non-uniform shapes and object dtype refuse the raw-memcpy path
    assert _native_stack([onp.zeros((2,)), onp.zeros((3,))]) is None
    objs = [onp.array([{"x": 1}, [2]], dtype=object)] * 4
    assert _native_stack(objs) is None


def test_native_image_normalize_fused():
    """MXTBatchifyImageNormalize: HWC uint8 -> normalized NCHW float32,
    fused (reference image pipeline normalize+transpose on worker
    threads)."""
    from mxnet_tpu import _native
    from mxnet_tpu.gluon.data.batchify import ImageNormalize
    if not _native.available():
        pytest.skip("native library unavailable")
    rng = onp.random.RandomState(4)
    imgs = [rng.randint(0, 255, (16, 20, 3)).astype("uint8")
            for _ in range(6)]
    norm = ImageNormalize(mean=(0.5, 0.4, 0.3), std=(0.2, 0.25, 0.3))
    out = norm(imgs).asnumpy()
    ref = (onp.stack(imgs).astype("float32") / 255.0
           - onp.array([0.5, 0.4, 0.3], "float32")) \
        / onp.array([0.2, 0.25, 0.3], "float32")
    onp.testing.assert_allclose(out, ref.transpose(0, 3, 1, 2),
                                rtol=1e-5, atol=1e-6)
    # a non-uint8 sample anywhere in the batch must raise, not be
    # reinterpreted byte-wise
    with pytest.raises(ValueError, match="uint8"):
        norm([imgs[0], imgs[1].astype("float32")])


def test_dataloader_uses_native_batchify_end_to_end():
    from mxnet_tpu import _native
    from mxnet_tpu.gluon.data import DataLoader, ArrayDataset
    from mxnet_tpu.gluon.data.batchify import _native_stack
    import mxnet_tpu as mx
    if not _native.available():
        pytest.skip("native library unavailable")
    rng = onp.random.RandomState(5)
    # samples big enough that a 16-batch crosses the native threshold
    X = rng.randn(64, 128, 256).astype("float32")
    Y = rng.randint(0, 3, (64,)).astype("int32")
    assert _native_stack([X[i] for i in range(16)]) is not None  # precond
    ds = ArrayDataset(mx.nd.array(X), mx.nd.array(Y))
    dl = DataLoader(ds, batch_size=16, num_workers=2)
    seen = 0
    for xb, yb in dl:
        assert xb.shape == (16, 128, 256)
        idx = seen
        onp.testing.assert_array_equal(xb.asnumpy(), X[idx:idx + 16])
        seen += xb.shape[0]
    assert seen == 64


def test_native_jpeg_decode_matches_pil():
    """src/native/image.cc libjpeg decode (the OpenCV-decode-thread analog,
    iter_image_recordio_2.cc): RGB and grayscale paths match PIL."""
    import io
    from mxnet_tpu import _native
    from mxnet_tpu.image.image import imdecode, _native_jpeg_decode
    if not _native.available():
        pytest.skip("native library unavailable")
    try:
        from PIL import Image
    except ImportError:
        pytest.skip("PIL unavailable")
    rng = onp.random.RandomState(7)
    img = rng.randint(0, 255, (32, 40, 3)).astype("uint8")
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=95)
    payload = buf.getvalue()

    native = _native_jpeg_decode(payload, 1)
    assert native is not None
    pil = onp.asarray(Image.open(io.BytesIO(payload)).convert("RGB"))
    assert int(onp.abs(native.astype(int) - pil.astype(int)).max()) <= 2
    gray = _native_jpeg_decode(payload, 0)
    assert gray.shape == (32, 40, 1)
    # public imdecode rides the native path; BGR flip still applies
    rgb = imdecode(payload).asnumpy()
    bgr = imdecode(payload, to_rgb=False).asnumpy()
    onp.testing.assert_array_equal(rgb[..., ::-1], bgr)
    # non-JPEG bytes fall back cleanly (PNG through PIL)
    pbuf = io.BytesIO()
    Image.fromarray(img).save(pbuf, format="PNG")
    png = imdecode(pbuf.getvalue()).asnumpy()
    onp.testing.assert_array_equal(png, img)
    # corrupt JPEG raises through the fallback, not a crash
    with pytest.raises(Exception):
        imdecode(b"\xff\xd8corrupt")


def test_native_png_decode_lossless():
    """src/native/image_png.cc: PNG decodes bit-exact (lossless format),
    RGB and grayscale, dispatched by magic bytes through the same decode
    entry as JPEG."""
    import io
    from mxnet_tpu import _native
    from mxnet_tpu.image.image import imdecode, _native_jpeg_decode
    if not _native.available():
        pytest.skip("native library unavailable")
    lib = _native.get_lib()
    if not hasattr(lib, "MXTImagePNGDecode"):
        pytest.skip("built without libpng")
    try:
        from PIL import Image
    except ImportError:
        pytest.skip("PIL unavailable")
    rng = onp.random.RandomState(9)
    img = rng.randint(0, 255, (24, 30, 3)).astype("uint8")
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    payload = buf.getvalue()
    native = _native_jpeg_decode(payload, 1)
    assert native is not None
    onp.testing.assert_array_equal(native, img)
    # grayscale conversion parity with the PIL fallback: bit-exact (the
    # native path uses Pillow's own fixed-point luma, coefficients AND the
    # +0x8000 rounding term ImagingConvert's L24 path has carried since
    # 2013 — if a Pillow build without it ever appears, this drops to ±1)
    g = _native_jpeg_decode(payload, 0)[..., 0]
    pil_g = onp.asarray(Image.open(io.BytesIO(payload)).convert("L"))
    onp.testing.assert_array_equal(g, pil_g)
    onp.testing.assert_array_equal(imdecode(payload).asnumpy(), img)
    # RGBA: deterministic and PIL-parity (alpha DROPPED, not composited)
    rgba = rng.randint(0, 255, (12, 12, 4)).astype("uint8")
    abuf = io.BytesIO()
    Image.fromarray(rgba, "RGBA").save(abuf, format="PNG")
    ap = abuf.getvalue()
    d1 = _native_jpeg_decode(ap, 1)
    onp.testing.assert_array_equal(d1, _native_jpeg_decode(ap, 1))
    onp.testing.assert_array_equal(
        d1, onp.asarray(Image.open(io.BytesIO(ap)).convert("RGB")))
    # grayscale-source PNG expands to 3 channels on color decode
    gbuf = io.BytesIO()
    Image.fromarray(img[..., 0]).save(gbuf, format="PNG")
    g3 = _native_jpeg_decode(gbuf.getvalue(), 1)
    assert g3.shape == (24, 30, 3)
    onp.testing.assert_array_equal(g3[..., 0], img[..., 0])
    # corrupt PNG falls back (PIL raises) rather than crashing
    with pytest.raises(Exception):
        imdecode(b"\x89PNG\r\n\x1a\ncorrupt")


def test_png_colorspace_chunks_route_to_pil():
    """gAMA/iCCP/cHRM PNGs must decode through PIL (libpng's simplified
    API would sRGB-convert them, PIL ignores the tags) — identical pixels
    either way the library is built."""
    import io
    import struct as _s
    import zlib
    from mxnet_tpu.image.image import (_native_jpeg_decode, imdecode,
                                       _png_has_colorspace_chunk)
    try:
        from PIL import Image
    except ImportError:
        pytest.skip("PIL unavailable")
    rng = onp.random.RandomState(11)
    img = rng.randint(0, 255, (8, 8, 3)).astype("uint8")
    b = io.BytesIO()
    Image.fromarray(img).save(b, format="PNG")
    raw = b.getvalue()
    assert not _png_has_colorspace_chunk(raw)
    ihdr_end = raw.index(b"IHDR") + 4 + 13 + 4
    gama = _s.pack(">I", 100000)
    chunk = _s.pack(">I", 4) + b"gAMA" + gama + \
        _s.pack(">I", zlib.crc32(b"gAMA" + gama) & 0xffffffff)
    tagged = raw[:ihdr_end] + chunk + raw[ihdr_end:]
    assert _png_has_colorspace_chunk(tagged)
    assert _native_jpeg_decode(tagged, 1) is None
    pil = onp.asarray(Image.open(io.BytesIO(tagged)).convert("RGB"))
    onp.testing.assert_array_equal(imdecode(tagged).asnumpy(), pil)
