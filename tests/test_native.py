"""Native runtime tests: C++ dependency engine, RecordIO, prefetcher.

Reference analog: tests/cpp/engine/threaded_engine_test.cc (ordering,
exception semantics) and python recordio round-trip tests. The engine
orders *host* tasks here (device work is XLA's job on TPU).
"""
import os
import struct
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _native, recordio
from mxnet_tpu.base import MXNetError

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native lib unavailable (no g++?)")


def test_engine_write_ordering():
    # Ops writing the same var must run exclusively and in push order.
    eng = _native.NativeEngine(num_threads=4)
    var = eng.new_var()
    log = []
    for i in range(50):
        eng.push(lambda i=i: log.append(i), mutable_vars=[var])
    eng.wait_for_var(var)
    assert log == list(range(50))
    assert eng.var_version(var) == 50
    eng.close()


def test_engine_reads_parallel_writes_exclusive():
    eng = _native.NativeEngine(num_threads=4)
    var = eng.new_var()
    state = {"active": 0, "max_active": 0}
    lock = threading.Lock()

    def reader():
        with lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"], state["active"])
        time.sleep(0.01)
        with lock:
            state["active"] -= 1

    for _ in range(8):
        eng.push(reader, const_vars=[var])
    eng.wait_for_all()
    assert state["max_active"] > 1  # reads overlapped
    # now interleave a write: everything pushed after must see it done
    order = []
    eng.push(lambda: (time.sleep(0.02), order.append("w")), mutable_vars=[var])
    eng.push(lambda: order.append("r"), const_vars=[var])
    eng.wait_for_all()
    assert order == ["w", "r"]
    eng.close()


def test_engine_dependency_chain():
    # writer(a) -> reader(a) writer(b) -> reader(b); cross-var ordering
    eng = _native.NativeEngine(num_threads=4)
    a, b = eng.new_var(), eng.new_var()
    out = []
    eng.push(lambda: (time.sleep(0.02), out.append("wa")), mutable_vars=[a])
    eng.push(lambda: out.append("ra_wb"), const_vars=[a], mutable_vars=[b])
    eng.push(lambda: out.append("rb"), const_vars=[b])
    eng.wait_for_all()
    assert out == ["wa", "ra_wb", "rb"]
    eng.close()


def test_engine_exception_at_sync_point():
    # Async failures surface at wait_for_* (reference
    # threaded_engine.cc:422-436 exception propagation).
    eng = _native.NativeEngine(num_threads=2)
    var = eng.new_var()

    def boom():
        raise ValueError("kaboom from worker")

    eng.push(boom, mutable_vars=[var])
    with pytest.raises(MXNetError, match="kaboom"):
        eng.wait_for_var(var)
    # error is consumed; engine remains usable
    eng.push(lambda: None, mutable_vars=[var])
    eng.wait_for_var(var)
    eng.close()


@pytest.mark.parametrize("native_write,native_read",
                         [(True, True), (True, False), (False, True)])
def test_recordio_cross_compat(tmp_path, native_write, native_read,
                               monkeypatch):
    # native and pure-Python impls must interoperate byte-for-byte
    path = str(tmp_path / "data.rec")
    records = [b"hello", b"x" * 1021, b"", os.urandom(4096),
               struct.pack("<I", 0xced7230a)]  # payload containing magic
    w = (_native.NativeRecordIOWriter(path) if native_write
         else recordio._PyWriter(path))
    for r in records:
        w.write(r)
    w.close()
    r_ = (_native.NativeRecordIOReader(path) if native_read
          else recordio._PyReader(path))
    got = []
    while True:
        rec = r_.read()
        if rec is None:
            break
        got.append(rec)
    r_.close()
    assert got == records


def test_mxrecordio_api(tmp_path):
    path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(path, "w")
    for i in range(10):
        rec.write(f"record{i}".encode())
    rec.close()
    rec = recordio.MXRecordIO(path, "r")
    for i in range(10):
        assert rec.read() == f"record{i}".encode()
    assert rec.read() is None
    rec.reset()
    assert rec.read() == b"record0"
    rec.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    rec = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(20):
        rec.write_idx(i, f"rec{i}".encode())
    rec.close()
    rec = recordio.MXIndexedRecordIO(idx, path, "r")
    assert rec.keys == list(range(20))
    assert rec.read_idx(13) == b"rec13"
    assert rec.read_idx(4) == b"rec4"
    rec.close()


def test_indexed_writer_tell(tmp_path):
    # tell() in write mode must advance identically native vs pure-Python
    # (reference index-building pattern: pos = tell(); write_idx(...)).
    paths = [(str(tmp_path / "n.rec"), _native.NativeRecordIOWriter),
             (str(tmp_path / "p.rec"), recordio._PyWriter)]
    tells = []
    for path, cls in paths:
        w = cls(path)
        t = [w.tell()]
        for i in range(5):
            w.write(b"x" * (i * 3 + 1))
            t.append(w.tell())
        w.close()
        tells.append(t)
    assert tells[0] == tells[1]
    assert tells[0][0] == 0 and sorted(tells[0]) == tells[0]


def test_pyreader_truncated_header(tmp_path):
    path = str(tmp_path / "trunc.rec")
    w = recordio._PyWriter(path)
    w.write(b"full record")
    w.close()
    with open(path, "ab") as f:
        f.write(struct.pack("<I", 0xced7230a) + b"\x01\x02")  # cut mid-header
    r = recordio._PyReader(path)
    assert r.read() == b"full record"
    with pytest.raises(MXNetError, match="truncated header"):
        r.read()
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(flag=0, label=3.5, id=42, id2=0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload" and h2.label == 3.5 and h2.id == 42
    # multi-label
    h = recordio.IRHeader(flag=0, label=onp.array([1.0, 2.0, 3.0]), id=7, id2=0)
    s = recordio.pack(h, b"xyz")
    h2, payload = recordio.unpack(s)
    assert payload == b"xyz"
    onp.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])


def test_prefetcher(tmp_path):
    path = str(tmp_path / "big.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [os.urandom(onp.random.randint(1, 2000)) for _ in range(200)]
    for p in payloads:
        w.write(p)
    w.close()
    pf = _native.NativePrefetchReader(path, capacity=16)
    got = list(pf)
    pf.close()
    assert got == payloads
