"""Resilient serving (docs/SERVING.md "Resilient serving").

Pins the resilience contracts on top of PR 12's serving engine:

- typed failure taxonomy: DeadlineExceeded / Overloaded(reason) /
  ServingShutdown — an accepted request ends in exactly one of
  {result, typed failure}, NEVER a hang;
- per-request deadlines: expired requests are dropped at dequeue
  (never padded/dispatched); admission control sheds at submit when
  the EWMA-projected queue wait exceeds the deadline
  (MXNET_SERVING_SHED=off|deadline|queue), all on the injected fake
  clock;
- circuit breaker open/half-open/close transitions;
- graceful drain: reject new, flush forming + in-flight, close;
- dispatcher-death propagation into every pending future;
- ServingSupervisor auto-recovery: device loss rebuilds the predictor
  over available_devices() and re-enqueues in-flight requests exactly
  once; transient failures retry bounded; fatal propagates;
- the chaos acceptance: revoke mid-traffic under
  MXNET_TRANSFER_GUARD=raise — zero lost accepted requests, exactly
  one recovery, bit-exact results post-recovery, zero unblessed syncs.
"""
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.elastic import detect
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import loadgen
from mxnet_tpu.serving.resilience import CircuitBreaker
from mxnet_tpu.testing import faults

IN, HIDDEN, CLASSES = 16, 32, 4


@pytest.fixture(autouse=True)
def _clean_harness():
    """Every test leaves the chaos harness disarmed, devices restored,
    and the preemption notice cleared."""
    yield
    faults.reset()
    detect.notice().clear()


def make_net(in_units=IN, hidden=HIDDEN, classes=CLASSES):
    onp.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu", in_units=in_units),
            nn.Dense(classes, in_units=hidden))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, in_units), "float32")))
    return net


def rows(n, in_units=IN, seed=0):
    return onp.random.RandomState(seed).randn(n, in_units) \
        .astype("float32")


@pytest.fixture
def pred():
    return serving.CompiledPredictor(make_net(),
                                     bucket_sizes=(1, 2, 4, 8))


def manual_batcher(pred, clk, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("timeout_ms", 5.0)
    return serving.DynamicBatcher(pred, start=False,
                                  clock=lambda: clk[0], **kw)


def build_pred():
    # deterministic, per the ServingSupervisor build() contract: every
    # (re)build must produce the same params, so recovery is bit-exact
    mx.random.seed(7)
    return serving.CompiledPredictor(make_net(), bucket_sizes=(1, 2, 4, 8))


# ---------------------------------------------------------------------------
# env accessors
# ---------------------------------------------------------------------------

def test_shed_mode_parsing(monkeypatch):
    monkeypatch.delenv("MXNET_SERVING_SHED", raising=False)
    assert serving.shed_mode() == "deadline"          # the default
    for v in ("off", "deadline", "queue"):
        monkeypatch.setenv("MXNET_SERVING_SHED", v)
        assert serving.shed_mode() == v
    monkeypatch.setenv("MXNET_SERVING_SHED", "bogus")
    assert serving.shed_mode() == "deadline"


def test_default_deadline_parsing(monkeypatch):
    monkeypatch.delenv("MXNET_SERVING_DEADLINE_MS", raising=False)
    assert serving.default_deadline_ms() is None
    monkeypatch.setenv("MXNET_SERVING_DEADLINE_MS", "25")
    assert serving.default_deadline_ms() == 25.0
    monkeypatch.setenv("MXNET_SERVING_DEADLINE_MS", "0")
    assert serving.default_deadline_ms() is None
    monkeypatch.setenv("MXNET_SERVING_DEADLINE_MS", "junk")
    assert serving.default_deadline_ms() is None


def test_queue_timeout_parsing(monkeypatch):
    monkeypatch.delenv("MXNET_SERVING_QUEUE_TIMEOUT_MS", raising=False)
    assert serving.queue_timeout_s() == pytest.approx(120.0)
    monkeypatch.setenv("MXNET_SERVING_QUEUE_TIMEOUT_MS", "250")
    assert serving.queue_timeout_s() == pytest.approx(0.25)
    monkeypatch.setenv("MXNET_SERVING_QUEUE_TIMEOUT_MS", "-5")
    assert serving.queue_timeout_s() == 0.0


# ---------------------------------------------------------------------------
# deadlines: expiry at dequeue (fake clock)
# ---------------------------------------------------------------------------

def test_expired_request_dropped_at_dequeue(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    miss0 = telemetry.value(telemetry.names.SERVING_DEADLINE_MISSED) or 0
    fut = b.submit(mx.nd.array(rows(1)), deadline_ms=3.0)
    clk[0] = 0.004                        # past the 3 ms deadline
    assert b.process_once(force=True) is False   # nothing dispatched
    with pytest.raises(serving.DeadlineExceeded, match="never dispatched"):
        fut.result(5)
    assert b.stats["batches"] == 0        # never padded/dispatched
    assert b.stats["deadline_missed"] == 1
    assert (telemetry.value(telemetry.names.SERVING_DEADLINE_MISSED)
            or 0) - miss0 == 1
    b.close()


def test_unexpired_request_dispatches_normally(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(1)), deadline_ms=50.0)
    clk[0] = 0.006                        # past the batch timeout only
    assert b.process_once() is True
    assert fut.result(10).shape == (1, CLASSES)
    b.close()


def test_deadline_boundary_exact(pred):
    # a request AT its deadline is expired; one a tick under is served
    clk = [0.0]
    b = manual_batcher(pred, clk)
    f_dead = b.submit(mx.nd.array(rows(1)), deadline_ms=10.0)
    clk[0] = 0.010
    assert b.process_once(force=True) is False
    with pytest.raises(serving.DeadlineExceeded):
        f_dead.result(5)
    f_live = b.submit(mx.nd.array(rows(1)), deadline_ms=10.0)
    clk[0] = 0.010 + 0.0099
    assert b.process_once(force=True) is True
    assert f_live.result(10).shape == (1, CLASSES)
    b.close()


def test_env_default_deadline_applies(pred, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_DEADLINE_MS", "3")
    monkeypatch.setenv("MXNET_SERVING_SHED", "off")
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(1)))       # deadline from env
    clk[0] = 0.004
    assert b.process_once(force=True) is False
    with pytest.raises(serving.DeadlineExceeded):
        fut.result(5)
    # deadline_ms=0 opts a single request out of the env default
    f2 = b.submit(mx.nd.array(rows(1)), deadline_ms=0)
    clk[0] = 60.0
    assert b.process_once(force=True) is True
    assert f2.result(10).shape == (1, CLASSES)
    b.close()


# ---------------------------------------------------------------------------
# admission control / shedding (fake clock, seeded EWMA)
# ---------------------------------------------------------------------------

def test_shed_deadline_rejects_on_projected_wait(pred, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_SHED", "deadline")
    clk = [0.0]
    b = manual_batcher(pred, clk)
    b._ewma_service = 0.050               # 50 ms per micro-batch
    rej0 = telemetry.value(telemetry.names.SERVING_REJECTED,
                           "deadline") or 0
    # 1 waiting batch x 50 ms projected > 20 ms deadline: shed
    with pytest.raises(serving.Overloaded, match="projected queue wait") \
            as ei:
        b.submit(mx.nd.array(rows(1)), deadline_ms=20.0)
    assert ei.value.reason == "deadline"
    assert (telemetry.value(telemetry.names.SERVING_REJECTED, "deadline")
            or 0) - rej0 == 1
    # same request with budget for one batch: admitted
    fut = b.submit(mx.nd.array(rows(1)), deadline_ms=100.0)
    assert b.process_once(force=True) is True
    assert fut.result(10).shape == (1, CLASSES)
    # no deadline: never shed by projection
    assert b.submit(mx.nd.array(rows(1))) is not None
    b.flush()
    b.close()


def test_shed_off_admits_regardless_of_projection(pred, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_SHED", "off")
    clk = [0.0]
    b = manual_batcher(pred, clk)
    b._ewma_service = 10.0                # hopeless projection
    fut = b.submit(mx.nd.array(rows(1)), deadline_ms=5.0)
    assert fut is not None                # admitted anyway (off)
    b.flush()
    b.close()


def test_shed_queue_rejects_without_blocking(pred, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_SHED", "queue")
    clk = [0.0]
    b = manual_batcher(pred, clk, depth=1)
    b.submit(mx.nd.array(rows(1)))
    t0 = time.perf_counter()
    with pytest.raises(serving.Overloaded, match="saturated") as ei:
        b.submit(mx.nd.array(rows(1)), timeout=30.0)   # timeout ignored
    assert ei.value.reason == "queue"
    assert time.perf_counter() - t0 < 1.0              # no blocking
    b.flush()
    b.close()


def test_queue_full_is_typed_overloaded(pred):
    # the former raw 120 s queue.put: bound explicit, error typed
    clk = [0.0]
    b = manual_batcher(pred, clk, depth=1)
    rej0 = telemetry.value(telemetry.names.SERVING_REJECTED, "queue") or 0
    b.submit(mx.nd.array(rows(1)))
    with pytest.raises(serving.Overloaded, match="saturated") as ei:
        b.submit(mx.nd.array(rows(1)), timeout=0.02)
    assert ei.value.reason == "queue"
    assert isinstance(ei.value, MXNetError)            # still an MXNetError
    assert (telemetry.value(telemetry.names.SERVING_REJECTED, "queue")
            or 0) - rej0 == 1
    b.flush()
    b.close()


def test_estimated_wait_formula(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)                      # max_batch 4
    assert b.estimated_wait_s(1) is None               # no EWMA yet
    b._ewma_service = 0.010
    # 1 row waiting -> 1 batch, empty window
    assert b.estimated_wait_s(1) == pytest.approx(0.010)
    # 5 rows -> 2 batches
    assert b.estimated_wait_s(5) == pytest.approx(0.020)
    b.close()


def test_ewma_updates_at_retire(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    b.submit(mx.nd.array(rows(1)))
    assert b.process_once(force=True) is True
    clk[0] = 0.030                        # 30 ms of "device time"
    b.flush()                             # retire records service time
    assert b._ewma_service == pytest.approx(0.030)
    b.close()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_opens_at_threshold():
    clk = [0.0]
    br = CircuitBreaker(failure_threshold=3, clock=lambda: clk[0])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"           # under threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()


def test_breaker_cooldown_half_open_then_closes():
    clk = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        clock=lambda: clk[0])
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk[0] = 4.9
    assert not br.allow()                 # cooldown not elapsed
    clk[0] = 5.1
    assert br.allow()                     # the probe
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_reopens_on_half_open_failure():
    clk = [0.0]
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                        clock=lambda: clk[0])
    br.trip("recovery")
    clk[0] = 2.0
    assert br.allow() and br.state == "half_open"
    br.record_failure()                   # probe failed
    assert br.state == "open"
    states = [s for s, _t, _c in br.transitions]
    assert states == ["closed", "open", "half_open", "open"]


def test_breaker_explicit_transitions_and_gauge():
    br = CircuitBreaker()
    assert telemetry.value(telemetry.names.SERVING_BREAKER_STATE) == 0
    br.trip("recovery")
    assert telemetry.value(telemetry.names.SERVING_BREAKER_STATE) == 2
    br.half_open()
    assert telemetry.value(telemetry.names.SERVING_BREAKER_STATE) == 1
    br.close()
    assert telemetry.value(telemetry.names.SERVING_BREAKER_STATE) == 0


def test_open_breaker_fast_fails_submit(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    b.breaker = CircuitBreaker()
    b.breaker.trip("recovery")
    with pytest.raises(serving.Overloaded, match="circuit breaker") as ei:
        b.submit(mx.nd.array(rows(1)))
    assert ei.value.reason == "breaker"
    b.breaker.close()
    assert b.submit(mx.nd.array(rows(1))) is not None
    b.flush()
    b.close()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

def test_drain_flushes_accepted_then_rejects_new(pred):
    pred.warmup(mx.nd.array(rows(1)))
    hist = telemetry.registry().get(telemetry.names.SERVING_DRAIN_SECONDS)
    d0 = hist.count()
    b = serving.DynamicBatcher(pred, max_batch=8, timeout_ms=50.0)
    futs = [b.submit(mx.nd.array(rows(1, seed=i))) for i in range(5)]
    b.drain()
    for f in futs:                        # accepted requests all land
        assert f.result(30).shape == (1, CLASSES)
    with pytest.raises((serving.Overloaded, serving.ServingShutdown)):
        b.submit(mx.nd.array(rows(1)))
    assert hist.count() - d0 == 1         # drain duration recorded
    b.drain()                             # idempotent
    b.close()


def test_drain_manual_mode(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(1)))
    b.drain()
    assert fut.result(10).shape == (1, CLASSES)
    with pytest.raises(serving.ServingShutdown):
        b.submit(mx.nd.array(rows(1)))


def test_drain_check_preemption_bridge(pred):
    """The supervisor's SIGTERM path: the dispatch loop polls
    drain_check and drains itself."""
    pred.warmup(mx.nd.array(rows(1)), buckets=(1, 2, 4, 8))
    b = serving.DynamicBatcher(pred, max_batch=8, timeout_ms=1.0)
    want = threading.Event()
    b.drain_check = want.is_set
    futs = [b.submit(mx.nd.array(rows(1, seed=i))) for i in range(4)]
    want.set()
    deadline = time.time() + 15
    while not b._stop.is_set() and time.time() < deadline:
        time.sleep(0.005)
    assert b._stop.is_set(), "drain_check never initiated the drain"
    for f in futs:
        assert f.result(30).shape == (1, CLASSES)
    with pytest.raises((serving.Overloaded, serving.ServingShutdown)):
        b.submit(mx.nd.array(rows(1)))
    b.close()


# ---------------------------------------------------------------------------
# dispatcher death -> ServingShutdown (the anti-hang regression)
# ---------------------------------------------------------------------------

def test_dispatcher_death_fails_pending_futures(pred):
    b = serving.DynamicBatcher(pred, max_batch=4, timeout_ms=60000.0,
                               start=False)
    f1 = b.submit(mx.nd.array(rows(1)))
    f2 = b.submit(mx.nd.array(rows(1, seed=1)))

    def boom():
        raise RuntimeError("loop machinery bug")

    b._serve_loop_inner = boom
    t = threading.Thread(target=b._serve_loop, daemon=True)
    t.start()
    t.join(10)
    assert not t.is_alive()
    for f in (f1, f2):                    # typed, not a hang
        with pytest.raises(serving.ServingShutdown, match="died"):
            f.result(5)
    with pytest.raises(serving.ServingShutdown, match="died"):
        b.submit(mx.nd.array(rows(1)))
    assert b.stats["shutdown_failed"] == 2


def test_close_with_backlog_never_hangs(pred):
    # close() flushes the backlog; anything undispatchable fails typed
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(1)))
    b.close()                             # flush dispatches the backlog
    assert fut.result(10).shape == (1, CLASSES)


# ---------------------------------------------------------------------------
# ServingSupervisor: classified recovery
# ---------------------------------------------------------------------------

def make_supervisor(example=False, **kw):
    ex = (mx.nd.array(rows(1)),) if example else None
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_ms", 1.0)
    return serving.ServingSupervisor(build_pred, example=ex, **kw)


def test_supervisor_serves_plain_traffic():
    X = rows(8, seed=3)
    with make_supervisor() as sup:
        futs = [sup.submit(mx.nd.array(X[i:i + 1])) for i in range(8)]
        outs = [f.result(30) for f in futs]
    assert all(o.shape == (1, CLASSES) for o in outs)
    assert sup.stats["recoveries"] == 0
    assert sup.breaker.state == "closed"


def submit_with_retry(sup, x, budget_s=60.0):
    """A real client's posture: an Overloaded rejection (breaker open
    while recovery runs, queue full) is retryable — back off and
    resubmit. Bounded, so a broken service still fails the test."""
    deadline = time.time() + budget_s
    while True:
        try:
            return sup.submit(x)
        except serving.Overloaded:
            if time.time() >= deadline:
                raise
            time.sleep(0.01)


def test_supervisor_device_loss_recovery_requeues_once():
    X = rows(8, seed=3)
    singles = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(8)]
    rec0 = telemetry.value(telemetry.names.SERVING_RECOVERIES,
                           "device_lost") or 0
    with make_supervisor() as sup:
        faults.configure("serving.dispatch:before=1:revoke:1")
        futs = [submit_with_retry(sup, mx.nd.array(X[i:i + 1]))
                for i in range(8)]
        outs = [f.result(60).asnumpy() for f in futs]
        assert sup.stats["recoveries"] == 1
        assert sup.stats["requeued"] >= 1     # the revoked batch's riders
        assert sup.stats["failed_requeues"] == 0
        assert sup.last_recovery["cause"] == "device_lost"
        assert sup.last_recovery["downtime_s"] < 60
    # the half-open breaker closes at the first successful retire —
    # guaranteed by the close()-time window drain at the latest
    states = [s for s, _t, _c in sup.breaker.transitions]
    assert states == ["closed", "open", "half_open", "closed"]
    for i in range(8):                    # recovery preserves answers
        assert (outs[i] == singles[i]).all()
    assert (telemetry.value(telemetry.names.SERVING_RECOVERIES,
                            "device_lost") or 0) - rec0 == 1


def test_supervisor_second_loss_fails_typed():
    """Re-enqueue is EXACTLY once: a request lost twice fails with the
    device-loss error instead of looping forever."""
    X = rows(1, seed=5)
    with make_supervisor() as sup:
        faults.configure("serving.dispatch:before=1:revoke:1;"
                         "serving.dispatch:before=2:revoke:1")
        fut = sup.submit(mx.nd.array(X))
        with pytest.raises(MXNetError, match="repeated device"):
            fut.result(60)
        assert sup.stats["recoveries"] == 2
        assert sup.stats["failed_requeues"] == 1


def test_supervisor_transient_retry_succeeds():
    X = rows(4, seed=7)
    ret0 = telemetry.value(telemetry.names.SERVING_RETRIES,
                           "transient") or 0
    with make_supervisor(backoff_base=0.01) as sup:
        faults.configure("serving.dispatch:before=1:error")
        futs = [sup.submit(mx.nd.array(X[i:i + 1])) for i in range(4)]
        outs = [f.result(60) for f in futs]
        assert all(o.shape == (1, CLASSES) for o in outs)
        assert sup.stats["retried"] >= 1       # the faulted batch's riders
        assert sup.stats["failed_requeues"] == 0
        assert sup.stats["recoveries"] == 0    # no rebuild for transient
    assert (telemetry.value(telemetry.names.SERVING_RETRIES, "transient")
            or 0) - ret0 >= 1


def test_supervisor_transient_budget_exhausted():
    X = rows(1, seed=9)
    with make_supervisor(max_retries=0, backoff_base=0.01) as sup:
        faults.configure("serving.dispatch:before=1:error")
        fut = sup.submit(mx.nd.array(X))
        with pytest.raises(MXNetError, match="transient"):
            fut.result(60)
        assert sup.stats["failed_requeues"] == 1


def test_supervisor_fatal_propagates():
    # wrong feature width against a proven program: classified fatal —
    # no recovery, the future fails with the dispatch error
    with make_supervisor(example=True) as sup:
        good = sup.submit(mx.nd.array(rows(1)))
        assert good.result(30).shape == (1, CLASSES)
        bad = sup.submit(mx.nd.array(
            onp.zeros((1, IN + 3), "float32")))
        with pytest.raises(Exception):
            bad.result(30)
        assert sup.stats["recoveries"] == 0
        assert sup.stats["retried"] == 0


def test_supervisor_drain_on_preemption_notice():
    X = rows(4, seed=11)
    hist = telemetry.registry().get(telemetry.names.SERVING_DRAIN_SECONDS)
    d0 = hist.count()
    sup = make_supervisor()
    try:
        futs = [sup.submit(mx.nd.array(X[i:i + 1])) for i in range(4)]
        detect.notice().trigger()
        deadline = time.time() + 15
        while not sup.batcher._stop.is_set() and time.time() < deadline:
            time.sleep(0.005)
        assert sup.batcher._stop.is_set(), "preemption never drained"
        for f in futs:                    # accepted requests all land
            assert f.result(30).shape == (1, CLASSES)
        with pytest.raises((serving.Overloaded, serving.ServingShutdown)):
            sup.submit(mx.nd.array(X[:1]))
        assert hist.count() - d0 == 1
    finally:
        detect.notice().clear()
        sup.close()


def test_fault_point_serving_admit(pred):
    """The third chaos seam: faults injected at admission surface on
    the submitting client's thread."""
    clk = [0.0]
    b = manual_batcher(pred, clk)
    faults.configure("serving.admit:before=1:error")
    with pytest.raises(faults.FaultInjectedError):
        b.submit(mx.nd.array(rows(1)))
    faults.configure(None)
    assert b.submit(mx.nd.array(rows(1))) is not None
    b.flush()
    b.close()


# ---------------------------------------------------------------------------
# loadgen outcome census
# ---------------------------------------------------------------------------

def test_loadgen_outcome_census_closed():
    def issue(i):
        if i % 4 == 0:
            raise serving.Overloaded("shed", reason="queue")
        if i % 4 == 1:
            raise serving.DeadlineExceeded("late")
        if i % 4 == 2:
            raise RuntimeError("boom")

    rep = loadgen.run_closed_loop(issue, concurrency=2, requests=40)
    assert rep["outcomes"] == {"ok": 10, "rejected": 10,
                               "deadline_missed": 10, "error": 10}
    assert rep["issued"] == 40 and rep["requests"] == 10
    assert rep["reject_rate"] == pytest.approx(0.25)
    assert rep["deadline_miss_rate"] == pytest.approx(0.25)
    assert rep["goodput_qps"] is not None
    assert rep["goodput_qps"] <= rep["qps"]


def test_loadgen_slow_completion_counts_as_deadline_missed():
    def issue(i):
        if i % 2:
            time.sleep(0.03)

    rep = loadgen.run_closed_loop(issue, concurrency=1, requests=10,
                                  deadline_s=0.01)
    assert rep["outcomes"]["ok"] == 5
    assert rep["outcomes"]["deadline_missed"] == 5


def test_loadgen_open_loop_counts_submit_rejections():
    def submit(i):
        if i % 2:
            raise serving.Overloaded("shed at admission",
                                     reason="deadline")
        return lambda *_: None

    rep = loadgen.run_open_loop(submit, rate_qps=2000.0, requests=20)
    assert rep["outcomes"]["rejected"] == 10
    assert rep["outcomes"]["ok"] == 10
    assert rep["reject_rate"] == pytest.approx(0.5)


def test_classify_outcome_walks_cause_chain():
    try:
        try:
            raise serving.Overloaded("inner", reason="queue")
        except serving.Overloaded as inner:
            raise MXNetError("wrapped") from inner
    except MXNetError as e:
        assert loadgen.classify_outcome(e) == "rejected"
    assert loadgen.classify_outcome(RuntimeError("x")) == "error"
    assert loadgen.classify_outcome(
        serving.DeadlineExceeded("late")) == "deadline_missed"


# ---------------------------------------------------------------------------
# chaos acceptance: revoke mid-traffic, zero lost accepted requests
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_revoke_mid_traffic_zero_lost(monkeypatch):
    """Sustained concurrent traffic across a revoke -> recover ->
    restore cycle under MXNET_TRANSFER_GUARD=raise: every accepted
    request ends in exactly one of {result, typed failure} with zero
    hangs, exactly one recovery is recorded with bounded downtime,
    post-recovery results stay bit-exact vs single dispatch, and the
    serving hot loop performs zero unblessed host syncs."""
    N = 32
    X = rows(N, seed=13)
    singles = [build_pred().predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(N)]
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    monkeypatch.setenv("MXNET_SERVING_SHED", "off")
    rec0 = telemetry.value(telemetry.names.SERVING_RECOVERIES,
                           "device_lost") or 0
    sync0 = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0
    results = [None] * N
    errors = [None] * N
    with make_supervisor(example=True, timeout_ms=2.0) as sup:
        faults.configure("serving.dispatch:before=2:revoke:1")

        def client(i):
            try:
                results[i] = submit_with_retry(
                    sup, mx.nd.array(X[i:i + 1])).result(60)
            except MXNetError as e:
                errors[i] = e

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        hung = [i for i, t in enumerate(threads) if t.is_alive()]
        assert not hung, f"clients hung: {hung}"
        assert sup.stats["recoveries"] == 1
        assert sup.stats["recovery_downtime_s"] < 60
        faults.restore_devices()           # the world grows back
        # post-restore traffic flows on the recovered predictor
        late = sup.submit(mx.nd.array(X[:1]))
        assert late.result(30) is not None
    # zero unblessed syncs in the serving hot loop (results still async)
    assert (telemetry.value(telemetry.names.HOST_SYNCS, "wait_to_read")
            or 0) - sync0 == 0
    # every request: exactly one terminal state, and — with clients
    # retrying typed Overloaded rejections like real traffic — every
    # single one is eventually SERVED across the revocation
    for i in range(N):
        assert (results[i] is None) != (errors[i] is None), \
            f"request {i} has no terminal state"
        assert errors[i] is None, \
            f"request {i}: terminal failure {errors[i]!r}"
    for i in range(N):                     # bit-exact incl. post-recovery
        assert (results[i].asnumpy() == singles[i]).all(), \
            f"request {i} differs from single dispatch post-recovery"
    assert (telemetry.value(telemetry.names.SERVING_RECOVERIES,
                            "device_lost") or 0) - rec0 == 1


@pytest.mark.chaos
def test_chaos_revoke_at_retire_seam():
    """A deferred device loss surfacing at the window retire (not at
    dispatch) recovers identically: the in-flight riders re-enqueue
    and resolve."""
    N = 8
    X = rows(N, seed=17)
    with make_supervisor(timeout_ms=1.0, inflight=2) as sup:
        faults.configure("serving.retire:before=1:revoke:1")
        futs = []
        for i in range(N):
            try:
                futs.append(sup.submit(mx.nd.array(X[i:i + 1])))
            except serving.Overloaded:
                futs.append(None)          # shed while breaker open
        outs = []
        for f in futs:
            if f is None:
                continue
            try:
                outs.append(f.result(60))
            except serving.Overloaded:
                pass
        # the retire (and with it the injected loss + recovery) runs on
        # the dispatcher thread, concurrent with the clients' response
        # reads — wait for it rather than racing it
        deadline = time.time() + 30
        while sup.stats["recoveries"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert sup.stats["recoveries"] == 1
        assert outs, "no request survived the retire-seam revocation"
        assert all(o.shape == (1, CLASSES) for o in outs)
