"""Elastic training supervisor (ISSUE 11).

Covers: device-lost classification at the dispatch seams (patterns,
chained exceptions, exactly-one anomaly per episode), the chaos-harness
``revoke``/``restore`` fault actions and the surviving-world helpers
(``parallel.dist.available_devices``/``world_changed``), the watchdog
anomaly-channel subscription, DispatchWindow abandon/partial drain, the
TrainLoop interrupt path (drain + earliest faulted step's error + final
checkpoint), checkpoint restore metrics/provenance/``restore_step``,
preemption notices with grace-window saves, and the ElasticSupervisor
recovery state machine — parametrized sgd-mom/adam × fused/zero parity
proofs that post-recovery losses match an uninterrupted run restored at
the same step. Marked ``chaos``+``slow``: subprocess tests driving the
full dp=8→4→8 shrink/grow cycle (bit-exact continuity, zero unblessed
syncs under MXNET_TRANSFER_GUARD=raise) and a SIGTERM kill whose
grace-window checkpoint lands at the interrupted step.
"""
import json
import os
import signal
import subprocess
import sys
import time
import types

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import elastic
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import TrainCheckpointManager
from mxnet_tpu.checkpoint.atomic import (read_checkpoint, step_dir_name)
from mxnet_tpu.elastic import detect
from mxnet_tpu.engine import DispatchWindow
from mxnet_tpu.gluon import TrainLoop, Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import dist, make_mesh
from mxnet_tpu.testing import faults
from mxnet_tpu.testing.faults import (DeviceRevokedError,
                                      FaultInjectedError)

NDEV = len(jax.devices())


@pytest.fixture(autouse=True)
def _clean_elastic():
    faults.reset()
    detect.notice().clear()
    mx.telemetry.watchdog().reset()
    yield
    faults.reset()
    detect.notice().clear()
    mx.telemetry.watchdog().reset()


# ---------------------------------------------------------------- helpers
def _build_fn(seed=3):
    def build():
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4, activation="relu"))
        net.add(nn.Dense(3, in_units=8))
        net.initialize()
        trainer = Trainer(net.collect_params(), "adam",
                          {"learning_rate": 0.05})
        return net, trainer, gloss.SoftmaxCrossEntropyLoss()
    return build


def _build_opt(opt, seed=3):
    def build():
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4, activation="relu"))
        net.add(nn.Dense(3, in_units=8))
        net.initialize()
        params = {"learning_rate": 0.05}
        if opt == "sgd":
            params["momentum"] = 0.9
        trainer = Trainer(net.collect_params(), opt, params)
        return net, trainer, gloss.SoftmaxCrossEntropyLoss()
    return build


def _batch(i, bs=8):
    rng = onp.random.RandomState(1000 + i)
    return (mx.nd.array(rng.randn(bs, 4).astype("float32")),
            mx.nd.array(rng.randint(0, 3, size=(bs,)).astype("int32")))


def _fresh_log():
    return elastic.RecoveryLog()


# ================================================================ detection
def test_is_device_lost_patterns():
    assert detect.is_device_lost(
        RuntimeError("INTERNAL: device lost: TPU_3"))
    assert detect.is_device_lost(RuntimeError("TPU is unhealthy"))
    assert detect.is_device_lost(
        RuntimeError("chip has been removed from the system"))
    assert detect.is_device_lost(
        DeviceRevokedError("INTERNAL: device lost: x removed"))
    assert not detect.is_device_lost(ValueError("shape mismatch"))
    assert not detect.is_device_lost(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory"))


def test_is_device_lost_walks_the_chain():
    inner = DeviceRevokedError("INTERNAL: device lost: TFRT_CPU_7")
    outer = MXNetError("async train step 5 failed (deferred error)")
    outer.__cause__ = inner
    assert detect.is_device_lost(outer)
    assert detect.classify(outer) == "device_lost"


def test_classify_taxonomy():
    assert detect.classify(DeviceRevokedError("device lost: x")) \
        == "device_lost"
    assert detect.classify(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory")) == "oom"
    assert detect.classify(FaultInjectedError("disk blip")) \
        == "transient"
    assert detect.classify(OSError("connection reset")) == "transient"
    assert detect.classify(ValueError("bad shape")) == "fatal"
    from mxnet_tpu.elastic.supervisor import StallEscalation
    assert detect.classify(StallEscalation("3 stalls")) == "stall"


def test_device_lost_anomaly_exactly_once_across_seams():
    wd = mx.telemetry.watchdog()
    e = DeviceRevokedError("INTERNAL: device lost: TFRT_CPU_7 removed")
    assert detect.maybe_record_device_lost(e, "inner seam", step=4)
    wrapped = MXNetError("async step 4 failed")
    wrapped.__cause__ = e
    # the outer seam sees the SAME failure: chain-marked, no re-fire
    assert not detect.maybe_record_device_lost(wrapped, "outer seam")
    assert not detect.maybe_record_device_lost(e, "third seam")
    evs = wd.anomalies("device_lost")
    assert len(evs) == 1
    assert evs[0]["step"] == 4
    assert "inner seam" in evs[0]["message"]


def test_non_device_errors_not_recorded():
    wd = mx.telemetry.watchdog()
    assert not detect.maybe_record_device_lost(
        ValueError("nope"), "seam")
    assert wd.anomalies("device_lost") == []


def test_device_lost_guard_propagates_and_records():
    wd = mx.telemetry.watchdog()
    with pytest.raises(DeviceRevokedError):
        with detect.device_lost_guard("guarded seam", step=7):
            raise DeviceRevokedError("device lost: y")
    assert len(wd.anomalies("device_lost")) == 1


# ================================================================ faults
def test_revoke_grammar():
    rules = faults.configure("step.dispatch:before=6:revoke:4")
    assert rules[0].action == "revoke" and rules[0].count == 4
    rules = faults.configure("p:after=1:revoke")
    assert rules[0].count == 1
    rules = faults.configure("p:before=2:restore")
    assert rules[0].action == "restore"
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.configure("p:before=1:explode")


@pytest.mark.skipif(NDEV < 4, reason="needs virtual multi-device mesh")
def test_revoke_shrinks_world_and_restore_grows_it_back():
    n0 = len(dist.available_devices())
    faults.configure("p:before=1:revoke:2")
    with pytest.raises(DeviceRevokedError, match="device lost"):
        faults.fault_point("p")
    assert len(faults.revoked_device_ids()) == 2
    assert len(dist.available_devices()) == n0 - 2
    assert dist.world_changed(jax.devices())
    faults.restore_devices()
    assert len(dist.available_devices()) == n0
    assert not dist.world_changed(jax.devices())


def test_revoke_never_kills_the_last_device():
    faults.configure("p:before=1:revoke:9999")
    with pytest.raises(DeviceRevokedError):
        faults.fault_point("p")
    assert len(dist.available_devices()) >= 1


def test_reset_restores_revoked_devices():
    faults.configure("p:before=1:revoke:1")
    with pytest.raises(DeviceRevokedError):
        faults.fault_point("p")
    assert faults.revoked_device_ids()
    faults.reset()
    assert not faults.revoked_device_ids()


# ================================================================ dist
def test_available_devices_requeries_backend(monkeypatch):
    fake = [types.SimpleNamespace(id=0), types.SimpleNamespace(id=1),
            types.SimpleNamespace(id=2)]
    monkeypatch.setattr(jax, "devices",
                        lambda backend=None: list(fake))
    assert [d.id for d in dist.available_devices()] == [0, 1, 2]
    lost = fake.pop()          # the backend world shrank AFTER import
    assert [d.id for d in dist.available_devices()] == [0, 1]
    assert dist.world_changed([types.SimpleNamespace(id=0),
                               types.SimpleNamespace(id=1), lost])
    assert not dist.world_changed(list(fake))


@pytest.mark.skipif(NDEV < 2, reason="needs virtual multi-device mesh")
def test_world_changed_accepts_a_mesh():
    mesh = make_mesh({"dp": 2}, jax.devices()[:2])
    assert mesh.devices == jax.devices()[:2]
    if NDEV > 2:
        assert dist.world_changed(mesh)      # mesh < full world
    assert not dist.world_changed(dist.available_devices())


# ================================================================ watchdog
def test_watchdog_subscription():
    wd = mx.telemetry.watchdog()
    seen = []
    cb = wd.subscribe(seen.append)
    wd.report("stall", 3, "slow step")
    wd.report("device_lost", 4, "gone")
    assert [e["kind"] for e in seen] == ["stall", "device_lost"]
    wd.unsubscribe(cb)
    wd.report("stall", 5, "again")
    assert len(seen) == 2


def test_watchdog_subscriber_exception_swallowed():
    wd = mx.telemetry.watchdog()

    def bad(evt):
        raise RuntimeError("subscriber bug")

    wd.subscribe(bad)
    evt = wd.report("stall", 1, "x")     # must not raise
    assert evt["kind"] == "stall"
    assert len(wd.anomalies("stall")) == 1


# ================================================================ window
def test_window_abandon_discards_without_sync():
    synced = []
    w = DispatchWindow(max_inflight=5, sync_fn=synced.append)
    for i in range(3):
        w.push(onp.zeros(2), tag=i + 1)
    assert len(w) == 3
    tags = w.abandon()
    assert tags == [1, 2, 3]
    assert len(w) == 0 and synced == []
    assert w.stats["abandoned"] == 3


def test_window_drain_partial_discards_after_first_failure():
    def sync(p):
        if p == "bad":
            raise RuntimeError("device lost: gone mid-flight")

    w = DispatchWindow(max_inflight=5, sync_fn=sync)
    w.push("ok", tag=1)
    w.push("bad", tag=2)
    w.push("late", tag=3)
    retired, discarded = w.drain_partial()
    assert retired == 1
    assert discarded == [3]          # the faulted entry is consumed,
    assert len(w) == 0               # everything after it discarded


def test_window_drain_partial_clean():
    w = DispatchWindow(max_inflight=5, sync_fn=lambda p: p)
    w.push("a", tag=1)
    w.push("b", tag=2)
    assert w.drain_partial() == (2, [])


# ================================================================ interrupt
def test_interrupt_drains_window_and_writes_final_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    build = _build_fn()
    net, trainer, loss_blk = build()
    loop = TrainLoop(net, trainer, loss_blk, checkpoint_dir=d,
                     inflight=4)
    for i in range(3):
        loop.step(*_batch(i))
    assert loop.engine_stats()["pending"] == 3

    def boom(*a, **k):
        raise KeyboardInterrupt

    loop._step = boom
    with pytest.raises(KeyboardInterrupt):
        loop.step(*_batch(3))
    # the window was drained (not abandoned), and a final checkpoint
    # landed at the interrupted step
    assert loop.engine_stats()["pending"] == 0
    assert loop.engine_stats()["retires"] == 3
    mgr = TrainCheckpointManager(d)
    assert mgr.latest_step() == 3


def test_interrupt_propagates_earliest_faulted_step_error(tmp_path):
    d = str(tmp_path / "ck")
    net, trainer, loss_blk = _build_fn()()
    loop = TrainLoop(net, trainer, loss_blk, checkpoint_dir=d,
                     inflight=4)
    for i in range(3):
        loop.step(*_batch(i))
    # the first retire during the interrupt drain faults: its error is
    # the real story and must propagate instead of the bare interrupt
    faults.configure("window.retire:before=1:error")

    def boom(*a, **k):
        raise KeyboardInterrupt

    loop._step = boom
    with pytest.raises(FaultInjectedError):
        loop.step(*_batch(3))
    assert loop.engine_stats()["pending"] == 0   # rest abandoned
    # the final checkpoint still landed
    assert TrainCheckpointManager(d).latest_step() == 3


# ================================================================ manager
def test_restore_metrics_and_provenance(tmp_path):
    d = str(tmp_path / "ck")
    net, trainer, loss_blk = _build_fn()()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    mgr = TrainCheckpointManager(d, async_save=False)
    for i in range(3):
        step(*_batch(i))
    mgr.save(3, trainer=trainer, net=net)
    assert mgr.restore_provenance is None

    c0 = mx.telemetry.value(mx.telemetry.names.CHECKPOINT_RESTORES)
    net2, trainer2, _ = _build_fn()()
    mgr2 = TrainCheckpointManager(d)
    meta = mgr2.restore_latest(trainer=trainer2, net=net2)
    assert meta["step"] == 3
    c1 = mx.telemetry.value(mx.telemetry.names.CHECKPOINT_RESTORES)
    assert c1 == c0 + 1
    prov = mgr2.restore_provenance
    assert prov["step"] == 3
    assert prov["resumed_from"].endswith(step_dir_name(3))
    assert prov["dp_from"] == 1 and prov["dp_to"] == 1
    assert prov["reshard"] is None
    assert prov["duration_s"] > 0


def test_restore_step_targets_a_specific_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    net, trainer, loss_blk = _build_fn()()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    mgr = TrainCheckpointManager(d, keep_last=5, async_save=False)
    for i in range(4):
        step(*_batch(i))
        mgr.save(i + 1, trainer=trainer, net=net)
    net2, trainer2, _ = _build_fn()()
    mgr2 = TrainCheckpointManager(d, keep_last=5)
    meta = mgr2.restore_step(2, trainer=trainer2, net=net2)
    assert meta["step"] == 2
    assert mgr2.restore_provenance["step"] == 2
    with pytest.raises(Exception):      # missing step raises
        mgr2.restore_step(9, trainer=trainer2, net=net2)


def test_saves_after_restore_carry_provenance(tmp_path):
    d = str(tmp_path / "ck")
    net, trainer, loss_blk = _build_fn()()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    mgr = TrainCheckpointManager(d, keep_last=5, async_save=False)
    for i in range(2):
        step(*_batch(i))
    mgr.save(2, trainer=trainer, net=net)
    net2, trainer2, _ = _build_fn()()
    mgr2 = TrainCheckpointManager(d, keep_last=5, async_save=False)
    mgr2.restore_latest(trainer=trainer2, net=net2)
    mgr2.save(5, trainer=trainer2, net=net2)
    _, manifest = read_checkpoint(os.path.join(d, step_dir_name(5)))
    prov = manifest["meta"]["resumed_from"]
    assert prov["step"] == 2
    assert prov["resumed_from"].endswith(step_dir_name(2))


@pytest.mark.skipif(NDEV < 4, reason="needs virtual multi-device mesh")
def test_zero_restore_provenance_names_the_reshard(tmp_path):
    d = str(tmp_path / "ck")
    build = _build_fn()
    net, trainer, loss_blk = build()
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
        for i in range(3):
            step(*_batch(i))
        assert step.zero_sharded
        mgr = TrainCheckpointManager(d, async_save=False)
        mgr.save(3, trainer=trainer, net=net)
    net2, trainer2, _ = build()
    with make_mesh({"dp": 2}, jax.devices()[:2]):
        mgr2 = TrainCheckpointManager(d)
        mgr2.restore_latest(trainer=trainer2, net=net2)
    prov = mgr2.restore_provenance
    assert prov["dp_from"] == 4 and prov["dp_to"] == 2
    assert prov["reshard"] == "dp4->dp2"


# ================================================================ preemption
def test_preemption_notice_trigger_and_grace(monkeypatch):
    n = detect.notice()
    assert not n.requested()
    monkeypatch.setenv("MXNET_PREEMPTION_GRACE_SEC", "45")
    assert detect.preemption_grace_sec() == 45
    assert n.remaining_grace() == 45
    n.trigger()
    assert n.requested()
    assert n.remaining_grace() <= 45
    n.clear()
    assert not n.requested()


def test_supervisor_graceful_preemption(tmp_path):
    d = str(tmp_path / "ck")
    c0 = mx.telemetry.value(mx.telemetry.names.ELASTIC_PREEMPTIONS) or 0

    def batch_fn(i):
        if i == 3:
            detect.notice().trigger()
        return _batch(i)

    sup = elastic.ElasticSupervisor(
        _build_fn(), d, mesh_axes=None, checkpoint_every=None,
        backoff_base=0.0, log=_fresh_log())
    res = sup.run(batch_fn, 10)
    assert res.preempted
    # the notice lands DURING step 4's batch; the check at the next
    # iteration exits with the grace-window save at step 4
    assert res.final_step == 4
    assert TrainCheckpointManager(d).latest_step() == 4
    assert [e["cause"] for e in res.events] == ["preemption"]
    c1 = mx.telemetry.value(mx.telemetry.names.ELASTIC_PREEMPTIONS)
    assert c1 == c0 + 1


# ================================================================ supervisor
@pytest.mark.parametrize("opt", ["sgd", "adam"])
@pytest.mark.parametrize("mode", ["fused", "zero"])
def test_recovery_losses_match_uninterrupted_restore(tmp_path, mode,
                                                     opt):
    """Post-recovery losses are BIT-EXACT vs an uninterrupted run
    restored at the same step (at the new layout, for zero): the
    recovery state machine composes drain/re-form/recompile/restore
    without perturbing the training computation."""
    if mode == "zero" and NDEV < 8:
        pytest.skip("needs the 8-device virtual mesh")
    d = str(tmp_path / "ck")
    total = 8
    build = _build_opt(opt)
    if mode == "zero":
        # a genuine device revocation: dp=8 shrinks to dp=4
        faults.configure("step.dispatch:before=6:revoke:4")
        mesh_axes, ref_dp = {"dp": -1}, 4
    else:
        # a transient failure: same world, restart from the checkpoint
        faults.configure("step.dispatch:before=6:error")
        mesh_axes, ref_dp = None, None
    log = _fresh_log()
    sup = elastic.ElasticSupervisor(
        build, d, mesh_axes=mesh_axes, checkpoint_every=2,
        keep_last=99, backoff_base=0.0, log=log)
    res = sup.run(_batch, total)
    faults.reset()

    assert res.final_step == total
    assert len(res.events) == 1          # exactly one RecoveryLog event
    ev = res.events[0]
    restored = ev["restored_step"]
    assert restored == 4                 # newest checkpoint before the
    assert ev["step"] == 5               # failure at step 5's dispatch
    if mode == "zero":
        assert ev["cause"] == "device_lost"
        assert ev["old_dp"] == 8 and ev["new_dp"] == 4
        assert len(ev["lost_devices"]) == 4
        wd = mx.telemetry.watchdog()
        assert len(wd.anomalies("device_lost")) == 1   # exactly one
    else:
        assert ev["cause"] == "transient"

    # reference: fresh build, restore the SAME checkpoint at the new
    # layout, run the same steps uninterrupted
    net, trainer, loss_blk = build()
    if ref_dp:
        ctx = make_mesh({"dp": ref_dp}, jax.devices()[:ref_dp])
    else:
        import contextlib
        ctx = contextlib.nullcontext()
    with ctx:
        mgr = TrainCheckpointManager(d, keep_last=99)
        mgr.restore_step(restored, trainer=trainer, net=net)
        loop = TrainLoop(net, trainer, loss_blk)
        handles = {i: loop.step(*_batch(i))
                   for i in range(restored, total)}
        loop.synchronize()
    ref = {i: float(h.asnumpy().sum()) for i, h in handles.items()}
    for i in range(restored, total):
        assert res.losses[i] == ref[i], f"step {i} diverged"


def test_retry_budget_exhausted(tmp_path):
    d = str(tmp_path / "ck")
    faults.configure(";".join(
        f"step.dispatch:before={n}:error" for n in range(1, 6)))
    sup = elastic.ElasticSupervisor(
        _build_fn(), d, mesh_axes=None, max_retries=2,
        backoff_base=0.0, log=_fresh_log())
    with pytest.raises(MXNetError, match="recovery budget exhausted"):
        sup.run(_batch, 8)


def test_forward_progress_resets_retry_budget(tmp_path):
    d = str(tmp_path / "ck")
    # three failures, but each recovery REPLAYS successfully past the
    # restored step before the next one hits — the budget never trips
    faults.configure("step.dispatch:before=3:error;"
                     "step.dispatch:before=7:error;"
                     "step.dispatch:before=10:error")
    sup = elastic.ElasticSupervisor(
        _build_fn(), d, mesh_axes=None, checkpoint_every=1,
        max_retries=1, backoff_base=0.0, log=_fresh_log())
    res = sup.run(_batch, 8)
    assert res.final_step == 8
    assert res.recoveries == 3


def test_recovery_disabled_propagates(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_ELASTIC", "0")
    d = str(tmp_path / "ck")
    faults.configure("step.dispatch:before=3:error")
    log = _fresh_log()
    sup = elastic.ElasticSupervisor(_build_fn(), d, mesh_axes=None,
                                    backoff_base=0.0, log=log)
    with pytest.raises(FaultInjectedError):
        sup.run(_batch, 8)
    assert len(log) == 0


def test_fatal_errors_propagate(tmp_path):
    d = str(tmp_path / "ck")

    def batch_fn(i):
        if i == 2:
            raise ValueError("a real bug, not the hardware")
        return _batch(i)

    sup = elastic.ElasticSupervisor(_build_fn(), d, mesh_axes=None,
                                    backoff_base=0.0, log=_fresh_log())
    with pytest.raises(ValueError, match="real bug"):
        sup.run(batch_fn, 8)


def test_stall_escalation_recovers(tmp_path):
    d = str(tmp_path / "ck")
    wd = mx.telemetry.watchdog()

    def batch_fn(i):
        if i == 3:
            wd.report("stall", i, "synthetic stall episode")
        return _batch(i)

    sup = elastic.ElasticSupervisor(
        _build_fn(), d, mesh_axes=None, checkpoint_every=2,
        stall_escalation=1, backoff_base=0.0, log=_fresh_log())
    res = sup.run(batch_fn, 8)
    assert res.final_step == 8
    assert [e["cause"] for e in res.events] == ["stall"]
    assert res.events[0]["restored_step"] == 4


@pytest.mark.skipif(NDEV < 8, reason="needs the 8-device virtual mesh")
def test_window_retire_seam_recovers(tmp_path):
    """A device loss surfacing at the WINDOW RETIRE (the pipelined
    seam) recovers exactly like one at dispatch."""
    d = str(tmp_path / "ck")
    faults.configure("window.retire:before=5:revoke:4")
    log = _fresh_log()
    sup = elastic.ElasticSupervisor(
        _build_fn(), d, mesh_axes={"dp": -1}, checkpoint_every=2,
        backoff_base=0.0, log=log)
    res = sup.run(_batch, 8)
    faults.reset()
    assert res.final_step == 8
    assert len(res.events) == 1
    assert res.events[0]["cause"] == "device_lost"
    assert res.events[0]["new_dp"] == 4
    assert len(mx.telemetry.watchdog().anomalies("device_lost")) == 1


# ================================================================ log
def test_recovery_log_schema_and_metrics():
    log = _fresh_log()
    c0 = mx.telemetry.value(mx.telemetry.names.ELASTIC_RECOVERIES,
                            "device_lost") or 0
    evt = log.record(cause="device_lost", lost_devices=["TPU_3"],
                     old_dp=8, new_dp=4, restored_step=40,
                     downtime_s=1.25, discarded_steps=2, step=42)
    for k in ("cause", "lost_devices", "old_dp", "new_dp",
              "restored_step", "discarded_steps", "downtime_s", "step",
              "time_unix"):
        assert k in evt
    assert len(log) == 1
    assert log.events("device_lost") == [evt]
    assert log.events("grow") == []
    c1 = mx.telemetry.value(mx.telemetry.names.ELASTIC_RECOVERIES,
                            "device_lost")
    assert c1 == c0 + 1
    assert mx.telemetry.value(
        mx.telemetry.names.ELASTIC_WORLD_SIZE) == 4
    assert "device_lost" in log.table()
    assert "8->4" in log.table().replace(" ", "")


def test_env_gates(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC", raising=False)
    assert detect.elastic_enabled() and not detect.armed()
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    assert detect.elastic_enabled() and detect.armed()
    monkeypatch.setenv("MXNET_ELASTIC", "0")
    assert not detect.elastic_enabled() and not detect.armed()
    monkeypatch.setenv("MXNET_ELASTIC_MAX_RETRIES", "7")
    assert detect.max_retries() == 7
    monkeypatch.setenv("MXNET_ELASTIC_MAX_RETRIES", "bogus")
    assert detect.max_retries() == 3


# ================================================================ chaos
def _worker(mode, ckpt_dir, timeout=600):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable,
           os.path.join(repo, "tests", "elastic_chaos_worker.py"),
           mode, ckpt_dir]
    env = dict(os.environ)
    env.pop("MXNET_FAULT_INJECT", None)
    return cmd, env, repo


def _result_line(out):
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(f"no RESULT line in worker output:\n{out}")


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_shrink_grow_bit_exact(tmp_path):
    """THE chaos acceptance test (subprocess; MXNET_TELEMETRY=1 +
    MXNET_TRANSFER_GUARD=raise inside): a dp=8 supervised run survives
    a mid-run 4-device revocation, re-forms at dp=4, restores the
    newest atomic checkpoint, and its loss trajectory is bit-exact vs
    an uninterrupted dp=4 run restored from the same checkpoint; the
    world then grows back to dp=8 (also bit-exact from its re-form
    checkpoint); exactly one device_lost anomaly and one RecoveryLog
    event per episode; zero unblessed syncs (the guard would raise)."""
    cmd, env, repo = _worker("chaos", str(tmp_path / "ck"))
    r = subprocess.run(cmd, env=env, cwd=repo, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, f"worker failed:\n{r.stdout}\n{r.stderr}"
    v = _result_line(r.stdout)
    assert v["ok"], v["detail"]
    assert v["final_step"] == 14 and v["world_size"] == 8
    assert v["device_lost_anomalies"] == 1
    assert v["recoveries_by_cause"] == {"device_lost": 1, "grow": 1}


@pytest.mark.slow
@pytest.mark.chaos
def test_sigterm_grace_window_save(tmp_path):
    """Subprocess kill test: SIGTERM mid-run triggers the preemption
    notice; the supervisor drains its window, commits the grace-window
    final checkpoint at the interrupted step, and exits cleanly."""
    d = str(tmp_path / "ck")
    cmd, env, repo = _worker("sigterm", d)
    p = subprocess.Popen(cmd, env=env, cwd=repo,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    try:
        # wait for the worker to report steps flowing
        deadline = time.time() + 300
        ready = False
        lines = []
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            lines.append(line)
            if line.startswith("READY"):
                ready = True
                break
        assert ready, "worker never became READY:\n" + "".join(lines)
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=300)
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    assert p.returncode == 0, f"worker exit {p.returncode}:\n{out}\n{err}"
    v = _result_line("".join(lines) + out)
    assert v["preempted"]
    assert v["causes"] == ["preemption"]
    # the grace-window save landed AT the step the run stopped on
    assert v["latest_checkpoint"] == v["final_step"]
    mgr = TrainCheckpointManager(d)
    assert mgr.latest_step() == v["final_step"]
