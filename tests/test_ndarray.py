"""NDArray semantics tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_create_and_asnumpy():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    onp.testing.assert_allclose(a.asnumpy(), [[1, 2], [3, 4]])


def test_zeros_ones_full():
    assert nd.zeros((2, 3)).asnumpy().sum() == 0
    assert nd.ones((2, 3)).asnumpy().sum() == 6
    onp.testing.assert_allclose(nd.full((2,), 7).asnumpy(), [7, 7])
    a = nd.arange(0, 10, 2)
    onp.testing.assert_allclose(a.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    onp.testing.assert_allclose((a + b).asnumpy(), [5, 7, 9])
    onp.testing.assert_allclose((a - b).asnumpy(), [-3, -3, -3])
    onp.testing.assert_allclose((a * b).asnumpy(), [4, 10, 18])
    onp.testing.assert_allclose((b / a).asnumpy(), [4, 2.5, 2])
    onp.testing.assert_allclose((a + 1).asnumpy(), [2, 3, 4])
    onp.testing.assert_allclose((1 - a).asnumpy(), [0, -1, -2])
    onp.testing.assert_allclose((a ** 2).asnumpy(), [1, 4, 9])
    onp.testing.assert_allclose((-a).asnumpy(), [-1, -2, -3])


def test_inplace_ops():
    a = nd.array([1.0, 2.0])
    a += 1
    onp.testing.assert_allclose(a.asnumpy(), [2, 3])
    a *= 2
    onp.testing.assert_allclose(a.asnumpy(), [4, 6])
    a -= nd.array([1.0, 1.0])
    onp.testing.assert_allclose(a.asnumpy(), [3, 5])


def test_broadcasting():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.ones((3, 1))
    assert c.broadcast_to((3, 4)).shape == (3, 4)


def test_indexing_read():
    a = nd.array(onp.arange(24).reshape(2, 3, 4))
    onp.testing.assert_allclose(a[0].asnumpy(), onp.arange(12).reshape(3, 4))
    onp.testing.assert_allclose(a[1, 2].asnumpy(), [20, 21, 22, 23])
    onp.testing.assert_allclose(a[:, 1, :].asnumpy(),
                                onp.arange(24).reshape(2, 3, 4)[:, 1, :])


def test_indexing_write():
    a = nd.zeros((3, 3))
    a[1] = 5.0
    assert a.asnumpy()[1].sum() == 15
    a[0, 0] = 2.0
    assert a.asnumpy()[0, 0] == 2
    # augmented slice assignment mutates the base
    a[2] += 1.0
    onp.testing.assert_allclose(a.asnumpy()[2], [1, 1, 1])


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_transpose_and_dot():
    a = nd.array(onp.random.rand(3, 4).astype("float32"))
    b = nd.array(onp.random.rand(4, 5).astype("float32"))
    c = nd.dot(a, b)
    onp.testing.assert_allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(),
                                rtol=1e-5)
    assert a.T.shape == (4, 3)
    d = nd.dot(a, b, transpose_a=False, transpose_b=False)
    assert d.shape == (3, 5)


def test_reductions():
    a = nd.array(onp.arange(12, dtype="float32").reshape(3, 4))
    assert a.sum().asnumpy() == 66
    onp.testing.assert_allclose(a.sum(axis=0).asnumpy(), [12, 15, 18, 21])
    onp.testing.assert_allclose(a.mean(axis=1).asnumpy(), [1.5, 5.5, 9.5])
    assert a.max().asnumpy() == 11
    assert a.min().asnumpy() == 0
    onp.testing.assert_allclose(nd.sum(a, axis=1, keepdims=True).shape, (3, 1))
    # exclude semantics
    onp.testing.assert_allclose(nd.sum(a, axis=0, exclude=True).asnumpy(),
                                a.asnumpy().sum(axis=1))


def test_concat_split_stack():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_take_one_hot_pick():
    w = nd.array(onp.arange(12, dtype="float32").reshape(4, 3))
    idx = nd.array([0, 2])
    t = nd.take(w, idx)
    assert t.shape == (2, 3)
    onp.testing.assert_allclose(t.asnumpy(), w.asnumpy()[[0, 2]])
    oh = nd.one_hot(nd.array([1, 0]), 3)
    onp.testing.assert_allclose(oh.asnumpy(), [[0, 1, 0], [1, 0, 0]])
    p = nd.pick(w, nd.array([0, 1, 2, 0]), axis=1)
    onp.testing.assert_allclose(p.asnumpy(), [0, 4, 8, 9])


def test_comparison_ops():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    onp.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    onp.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    onp.testing.assert_allclose((a <= 2).asnumpy(), [1, 1, 0])


def test_sort_topk():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    onp.testing.assert_allclose(nd.sort(a).asnumpy(), [[1, 2, 3], [0, 4, 5]])
    idx = nd.topk(a, k=2)
    onp.testing.assert_allclose(idx.asnumpy(), [[0, 2], [1, 2]])
    both = nd.topk(a, k=1, ret_typ="both")
    onp.testing.assert_allclose(both[0].asnumpy(), [[3], [5]])


def test_astype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == onp.int32
    c = a.astype("float16")
    assert c.dtype == onp.float16


def test_wait_to_read_and_waitall():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.asnumpy()[0, 0] == 100


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    a, b = nd.ones((2, 2)), nd.zeros((3,))
    nd.save(fname, [a, b])
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2
    onp.testing.assert_allclose(loaded[0].asnumpy(), a.asnumpy())
    nd.save(fname, {"x": a, "y": b})
    d = nd.load(fname)
    assert set(d) == {"x", "y"}


def test_context_placement():
    a = nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0)


def test_sequence_mask():
    x = nd.ones((4, 2, 3))  # (T, B, ...)
    y = nd.SequenceMask(x, sequence_length=nd.array([2, 3]),
                        use_sequence_length=True, value=0)
    ynp = y.asnumpy()
    assert ynp[:2, 0].sum() == 6 and ynp[2:, 0].sum() == 0
    assert ynp[:3, 1].sum() == 9 and ynp[3:, 1].sum() == 0


def test_random_ops():
    mx.random.seed(42)
    a = mx.nd.random.uniform(0, 1, shape=(100,))
    assert 0 <= a.asnumpy().min() and a.asnumpy().max() <= 1
    mx.random.seed(42)
    b = mx.nd.random.uniform(0, 1, shape=(100,))
    onp.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    n = mx.nd.random.normal(0, 1, shape=(10000,))
    assert abs(n.asnumpy().mean()) < 0.1


def test_elemwise_unary_math():
    a = nd.array([0.5, 1.0, 2.0])
    onp.testing.assert_allclose(nd.exp(a).asnumpy(), onp.exp(a.asnumpy()),
                                rtol=1e-6)
    onp.testing.assert_allclose(nd.log(a).asnumpy(), onp.log(a.asnumpy()),
                                rtol=1e-6)
    onp.testing.assert_allclose(nd.sigmoid(a).asnumpy(),
                                1 / (1 + onp.exp(-a.asnumpy())), rtol=1e-6)
    onp.testing.assert_allclose(nd.relu(nd.array([-1.0, 1.0])).asnumpy(), [0, 1])
