"""Sub-minute 2-process dist smoke for the QUICK gate (VERDICT r2 weak #8):
if a jax/jaxlib bump breaks jax.distributed.initialize on CPU, this fails
in the fast suite instead of only in the slow nightly-style rig."""
import json
import os
import socket
import subprocess
import sys

import numpy as onp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_smoke(tmp_path):
    import pytest

    worker = os.path.join(REPO, "tests", "dist_smoke_worker.py")
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "-p", str(_free_port()),
           sys.executable, worker, str(tmp_path)]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=120,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode("utf-8", "replace")
    assert proc.returncode == 0, f"smoke launch failed:\n{out[-3000:]}"
    results = {}
    for r in (0, 1):
        p = tmp_path / f"smoke{r}.json"
        assert p.exists(), f"rank {r} missing:\n{out[-3000:]}"
        results[r] = json.loads(p.read_text())
    if any(res.get("capability") == "no-cpu-multiprocess"
           for res in results.values()):
        # This jaxlib's CPU backend has no multi-process collective
        # runtime ("Multiprocess computations aren't implemented on the
        # CPU backend") — an environment capability, not a framework
        # regression. Everything a jax/jaxlib bump CAN break in the
        # quick gate was still exercised and passed: tools/launch.py
        # spawned both ranks, jax.distributed.initialize joined the
        # coordinator on each, and the dist_sync store constructed its
        # worker mesh. The collective VALUES are covered on TPU/GPU
        # rigs and by the in-process virtual-mesh tests
        # (test_kvstore_batched, test_parallel_program).
        pytest.skip("jaxlib CPU backend cannot run multi-process "
                    "collectives (launch + dist-init + store "
                    "construction verified)")
    for r in (0, 1):
        res = results[r]
        onp.testing.assert_allclose(res["sum"], [3.0] * 3)
        onp.testing.assert_allclose(res["fused"][0], [3.0] * 2)
        onp.testing.assert_allclose(res["fused"][1], [6.0] * 5)
        # fused call: one collective dispatch, one host sync for 2 keys
        assert res["stats"]["collectives"] == 2  # 1 per-key + 1 fused
        assert res["stats"]["blocks"] == 2
