"""Base utility modules: name scopes, attribute scopes, error registry,
logging, class-factory registry.

Reference analogs: python/mxnet/{name,attribute,error,log,registry}.py
— exercised through the same surfaces reference users hit (mx.name.
Prefix around symbol construction, mx.AttrScope attaching string attrs,
registry-driven create from JSON configs).
"""
import logging

import pytest

import mxnet_tpu as mx
import mxnet_tpu.symbol as sym


# ---------------------------------------------------------------------------
# name scopes
# ---------------------------------------------------------------------------

def test_name_manager_counts_per_hint():
    nm = mx.name.NameManager()
    assert nm.get(None, "fc") == "fc0"
    assert nm.get(None, "fc") == "fc1"
    assert nm.get(None, "conv") == "conv0"
    assert nm.get("explicit", "fc") == "explicit"


def test_prefix_applies_to_symbol_construction():
    data = sym.Variable("data")
    with mx.name.Prefix("mynet_"):
        net = sym.FullyConnected(data, sym.Variable("w"), num_hidden=10,
                                 name="fc1")
        auto = sym.relu(net)
    assert net.name == "mynet_fc1"
    assert auto.name == "mynet_relu0"
    # outside the scope the default manager resumes, no prefix
    outside = sym.relu(net)
    assert outside.name.startswith("relu") and \
        not outside.name.startswith("mynet_")


def test_name_managers_nest():
    with mx.name.Prefix("a_"):
        with mx.name.Prefix("b_"):
            assert mx.name.current().get(None, "x") == "b_x0"
        assert mx.name.current().get(None, "x") == "a_x0"


# ---------------------------------------------------------------------------
# attribute scopes
# ---------------------------------------------------------------------------

def test_attr_scope_attaches_and_nests():
    data = sym.Variable("data")
    with mx.AttrScope(ctx_group="stage1"):
        a = sym.relu(data)
        with mx.AttrScope(ctx_group="stage2", lr_mult="0.1"):
            b = sym.relu(a)
    c = sym.relu(b)
    assert a.attr("ctx_group") == "stage1"
    assert b.attr("ctx_group") == "stage2" and b.attr("lr_mult") == "0.1"
    assert c.attr("ctx_group") is None
    d = sym.attr_dict(c) if hasattr(sym, "attr_dict") else c.attr_dict()
    assert d[b.name]["ctx_group"] == "stage2"
    with pytest.raises(ValueError):
        mx.AttrScope(bad=123)


def test_attr_scope_covers_variables_and_operators():
    """Reference contract: EVERY symbol created in the scope gets the
    attrs — including Variable and operator-overload nodes (review
    finding round 4)."""
    with mx.AttrScope(ctx_group="g1"):
        x = sym.Variable("x")
        y = sym.Variable("y")
        z = x + y
        n = -z
    assert x.attr("ctx_group") == "g1"
    assert z.attr("ctx_group") == "g1" and n.attr("ctx_group") == "g1"
    with mx.name.Prefix("p_"):
        w = sym.Variable("a") + sym.Variable("b")
    assert w.name.startswith("p_")


def test_attr_scope_instance_reuse_does_not_leak():
    s = mx.AttrScope(grp="a")
    with mx.AttrScope(extra="x"):
        with s:
            pass
    with s:
        node = sym.Variable("v")
    assert node.attr("grp") == "a"
    assert node.attr("extra") is None  # stale enclosing scope must not leak


def test_attr_kwarg_is_copied_and_validated():
    d = {"lr_mult": "0.1"}
    a = sym.relu(sym.Variable("x"), attr=d)
    d["lr_mult"] = "10"
    assert a.attr("lr_mult") == "0.1"  # no aliasing of caller state
    with pytest.raises(ValueError):
        sym.relu(sym.Variable("x"), attr={"lr_mult": 0.1})


def test_shared_input_graphs_traverse_linearly():
    """Diamond-heavy graphs (y = x*x chained) must not blow up
    exponentially in the graph walks (review finding round 4)."""
    y = sym.Variable("x")
    for _ in range(60):
        y = y * y
    assert y.attr_dict() == {}
    assert y.list_arguments() == ["x"]
    assert len(y.get_internals()) == 61


def test_attr_survives_json_roundtrip():
    data = sym.Variable("data")
    with mx.AttrScope(ctx_group="g0"):
        y = sym.exp(data, name="e0")
    y2 = sym.load_json(y.tojson())
    assert y2.attr("ctx_group") == "g0"


# ---------------------------------------------------------------------------
# error registry
# ---------------------------------------------------------------------------

def test_error_registry():
    from mxnet_tpu import error
    assert issubclass(error.InternalError, mx.MXNetError)
    with pytest.raises(error.InternalError, match="hint"):
        raise error.InternalError("boom")
    assert error.get_error_class("ValueError") is ValueError
    assert error.get_error_class("InternalError") is error.InternalError
    assert error.get_error_class("NoSuchThing") is mx.MXNetError


# ---------------------------------------------------------------------------
# log
# ---------------------------------------------------------------------------

def test_get_logger_format(tmp_path):
    logf = tmp_path / "t.log"
    logger = mx.log.get_logger("mxt_test_logger", filename=str(logf),
                               level=logging.INFO)
    logger.info("hello world")
    logger.debug("invisible")  # below level
    for h in logger.handlers:
        h.flush()
    text = logf.read_text()
    assert "hello world" in text and "invisible" not in text
    line = [l for l in text.splitlines() if "hello world" in l][0]
    assert line.startswith("I")          # level letter prefix
    assert "test_base_modules" in line   # pathname in the prefix
    # idempotent: second call must not duplicate handlers
    again = mx.log.get_logger("mxt_test_logger")
    assert again is logger and len(logger.handlers) == 1
    with pytest.warns(DeprecationWarning):
        mx.log.getLogger("mxt_test_logger")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class _Base:
    def __init__(self, v=0):
        self.v = v


def test_registry_register_alias_create():
    reg = mx.registry.get_register_func(_Base, "thing")
    alias = mx.registry.get_alias_func(_Base, "thing")
    create = mx.registry.get_create_func(_Base, "thing")

    @alias("myimpl", "impl2")
    class Impl(_Base):
        pass

    assert mx.registry.get_registry(_Base)["myimpl"] is Impl
    assert isinstance(create("MyImpl"), Impl)          # case-insensitive
    assert isinstance(create("impl2", 5), Impl)
    inst = Impl(3)
    assert create(inst) is inst                         # instance passthru
    assert create('["myimpl", {"v": 7}]').v == 7        # JSON list form
    assert create('{"thing": "myimpl", "v": 9}').v == 9  # JSON dict form
    with pytest.raises(KeyError):
        create("unregistered")
    with pytest.raises(TypeError):
        reg(int)  # not a subclass
    with pytest.warns(UserWarning):
        reg(Impl, "myimpl")  # override warns
