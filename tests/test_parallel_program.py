"""Program-level (HLO) regression guards for the parallel paths.

The round-3 AMP episode proved value-level tests cannot catch a silent
efficiency regression: numerics stay right while the compiled program
quietly does the wrong thing (f32-width activations then; per-parameter
collectives or unsharded matmuls next). These tests pin the COMPILED
PROGRAM structure the way tests/test_amp_program.py pins dtype flow:

1. the 8-device DP train step's gradient reduction stays exactly the
   gradient set, once, on the dp axis — checked through the
   mx.analysis collective census (which also enforces the combined
   tuple-all-reduce form on backends whose combiner pass runs; the
   contract the reference's kvstore comm layer exists for,
   include/mxnet/kvstore.h:129-141 ordering + ps-lite batching);
2. the TP leg actually shards the matmul: per-device dot shapes are the
   tp-fraction of the logical shapes and the backward contraction over
   the sharded axis emits a collective;
3. the dist-kvstore cross-worker reduction program is exactly ONE
   all-reduce over the bucketed 1-D buffer (the program
   KVStoreDist._dispatch_sum jits), and pushpull_list dispatches exactly
   one such buffer per dtype bucket.

All run on the conftest's virtual 8-device CPU mesh; GSPMD emits the
same collective structure XLA would emit on an ICI-connected TPU slice.
"""
import re

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _hlo_lines(txt, op):
    return [l for l in txt.splitlines() if f" {op}(" in l or f"{op}(" in l
            and "=" in l]


def _all_reduce_lines(txt):
    return [l for l in txt.splitlines() if re.search(r"all-reduce(\.\d+)?\(", l)
            and "=" in l]


def _compile_dp_step(net, in_shape, n_dp=8, bs=16, classes=8):
    from __graft_entry__ import make_train_step, _init_net

    onp.random.seed(0)
    params = _init_net(net, (1,) + in_shape)
    mesh = Mesh(onp.array(jax.devices()[:n_dp]), ("dp",))
    step_fn = make_train_step(net, params, lr=0.1)
    repl = NamedSharding(mesh, P())
    p_shard = tuple(repl for _ in params)
    step = jax.jit(step_fn,
                   in_shardings=(p_shard, p_shard,
                                 NamedSharding(mesh, P("dp")),
                                 NamedSharding(mesh, P("dp")), repl),
                   donate_argnums=(0, 1))
    pd = tuple(jax.device_put(p._data._data, s)
               for p, s in zip(params, p_shard))
    mom = tuple(jax.device_put(jnp.zeros_like(d), s)
                for d, s in zip(pd, p_shard))
    x = jax.device_put(
        jnp.asarray(onp.random.uniform(size=(bs,) + in_shape)
                    .astype("float32")), NamedSharding(mesh, P("dp")))
    y = jax.device_put(
        jnp.asarray(onp.random.randint(0, classes, size=(bs,))
                    .astype("int32")), NamedSharding(mesh, P("dp")))
    key = jax.random.PRNGKey(0)
    txt = step.lower(pd, mom, x, y, key).compile().as_text()
    return txt, params, mesh


def _grad_elems(params):
    return sum(int(p._data.size) for p in params)


def test_dp_gradient_allreduces_are_combined_mlp():
    """26-parameter MLP, dp=8: gradient-reduction structure via the
    mx.analysis collective census (the checker that replaced this test's
    seed-era regex hand-count).

    Backend caveat the hand-count missed: combining many small
    all-reduces into one tuple all-reduce is an XLA COMBINER-pass
    decision, and XLA:CPU does not schedule that pass — on the virtual
    CPU mesh one all-reduce per gradient is the backend's own canonical
    output, not a framework regression.  The backend-independent
    invariants that DO catch the historical bug class (per-parameter
    collective storms, duplicated reductions, replicated-compute
    fallbacks) are:

    1. every all-reduce runs on the dp axis (no stray mesh traffic);
    2. each gradient is reduced EXACTLY once — the total all-reduced
       payload stays within the gradient set + scalar loss slack, so a
       doubled reduction or an activation being reduced fails;
    3. the op count never exceeds one-per-parameter + loss slack;
    4. on backends whose combiner runs (TPU), the seed's strict
       contract holds: <= 4 ops, one tuple all-reduce carrying the
       whole gradient set.
    """
    from mxnet_tpu import analysis

    net = nn.HybridSequential()
    for _ in range(12):
        net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(8))
    txt, params, mesh = _compile_dp_step(net, (32,))
    n_params = len(params)
    assert n_params >= 20
    census = analysis.collective_census(txt, mesh=mesh)
    ars = [op for op in census.ops if op.kind == "all_reduce"]
    assert ars, "gradient reduction vanished from the program"
    assert all("dp" in op.axes for op in ars), (
        "all-reduce off the dp axis:\n" +
        "\n".join(f"{op.name}: axes={op.axes}" for op in ars))
    grad_elems = _grad_elems(params)
    reduced = census.total_elements("all_reduce")
    assert reduced <= grad_elems + 1024, (
        f"{reduced} elements all-reduced vs {grad_elems} gradient "
        "elements — something beyond the gradients (activations? a "
        "duplicated reduction?) is crossing the dp axis")
    assert len(ars) <= n_params + 2, (
        f"{len(ars)} all-reduces for {n_params} params — MORE than one "
        "collective per parameter")
    if jax.default_backend() != "cpu":   # combiner pass available
        assert len(ars) <= 4, (
            f"{len(ars)} all-reduces for {n_params} params — gradient "
            "bucketing regressed to (near-)per-parameter collectives")
        assert max(op.operand_count for op in ars) >= 20, \
            "no combined gradient all-reduce found"


@pytest.mark.slow
def test_dp_gradient_allreduces_are_combined_resnet18():
    """ResNet-18, dp=8 (the dryrun's DP leg at model scale), via the
    census: BatchNorm adds inherent per-layer statistics all-reduces, so
    the payload bound gets batch-stat slack, but the structural bounds
    of the MLP test still hold (see its docstring for the CPU-backend
    combiner caveat)."""
    from mxnet_tpu import analysis
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=16)
    txt, params, mesh = _compile_dp_step(net, (3, 32, 32), classes=16)
    n_params = len(params)
    assert n_params >= 100
    census = analysis.collective_census(txt, mesh=mesh)
    ars = [op for op in census.ops if op.kind == "all_reduce"]
    assert ars and all("dp" in op.axes for op in ars)
    # grads once + BN batch-stat reductions (statistics are
    # channel-sized: generous 2x slack still catches activation-sized
    # regressions)
    assert census.total_elements("all_reduce") <= \
        2 * _grad_elems(params) + 65536
    assert len(ars) <= 2 * n_params, (
        f"{len(ars)} all-reduces for {n_params} params: per-parameter "
        "collectives are back")
    if jax.default_backend() != "cpu":
        assert max(op.operand_count for op in ars) >= 15, \
            "combined weight-gradient all-reduce is gone"


def test_tp_dense_matmul_is_sharded():
    """Dense(1024) with weight P('tp', None) over tp=8: every dot in the
    compiled step must run on the 1/8 weight shard (f32[128,512]), the
    full-size dot must be absent, and the backward contraction over the
    sharded axis must emit a collective."""
    from __graft_entry__ import make_train_step, _init_net

    onp.random.seed(0)
    net = nn.Dense(1024, in_units=512)
    params = _init_net(net, (1, 512))
    mesh = Mesh(onp.array(jax.devices()), ("tp",))
    step_fn = make_train_step(net, params, lr=0.1)
    shards = tuple(
        NamedSharding(mesh, P("tp") if len(p._data.shape) == 1
                      else P("tp", None)) for p in params)
    repl = NamedSharding(mesh, P())
    step = jax.jit(step_fn, in_shardings=(shards, shards, repl, repl, repl),
                   donate_argnums=(0, 1))
    pd = tuple(jax.device_put(p._data._data, s)
               for p, s in zip(params, shards))
    mom = tuple(jax.device_put(jnp.zeros_like(d), s)
                for d, s in zip(pd, shards))
    x = jax.device_put(jnp.asarray(
        onp.random.uniform(size=(4, 512)).astype("float32")), repl)
    y = jax.device_put(jnp.zeros((4,), jnp.int32), repl)
    txt = step.lower(pd, mom, x, y, jax.random.PRNGKey(0)).compile().as_text()

    dots = [l for l in txt.splitlines() if re.search(r"=.* dot\(", l)]
    assert dots, "no dot ops in compiled TP step"
    assert not any("f32[1024,512]" in l for l in dots), (
        "full-size weight matmul present — TP sharding silently "
        "regressed to replicated compute")
    assert any("f32[4,128]" in l or "f32[128,512]" in l for l in dots), (
        "no tp-fraction dot shapes found:\n"
        + "\n".join(l[:120] for l in dots))
    n_coll = sum(len(_hlo_lines(txt, op)) for op in
                 ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute"))
    assert n_coll >= 1, "sharded-contraction collective missing"


def test_kvstore_dispatch_sum_program_is_one_allreduce():
    """The program KVStoreDist._dispatch_sum jits — sum over the worker
    axis of a (num_workers, N) bucketed buffer, replicated output — must
    compile to exactly ONE all-reduce (simulated here with 8 local
    devices standing in for 8 workers; same GSPMD partitioning)."""
    mesh = Mesh(onp.array(jax.devices()), ("worker",))
    fn = jax.jit(lambda a: jnp.sum(a, axis=0),
                 out_shardings=NamedSharding(mesh, P()))
    gshape = (8, 4096)
    arg = jax.ShapeDtypeStruct(
        gshape, jnp.float32,
        sharding=NamedSharding(mesh, P("worker")))
    txt = fn.lower(arg).compile().as_text()
    ars = _all_reduce_lines(txt)
    assert len(ars) == 1, (
        f"expected exactly 1 all-reduce, got {len(ars)}:\n"
        + "\n".join(l[:120] for l in ars))
    assert "4096" in ars[0], ars[0]


def test_pushpull_list_one_dispatch_per_dtype_bucket():
    """pushpull_list must hand _dispatch_sum exactly one flattened 1-D
    buffer per dtype bucket — the program-dispatch contract behind the
    wall-clock numbers test_dist_kvstore checks."""
    kv = mx.kvstore.create("dist_sync")
    kv._force_fuse = True
    seen = []
    orig = kv._dispatch_sum

    def spy(buf):
        seen.append((buf.ndim, str(buf.dtype), buf.size))
        return orig(buf)

    kv._dispatch_sum = spy
    vals = [mx.nd.array(onp.ones((4, 3), "float32")),
            mx.nd.array(onp.full((7,), 2, "int32")),
            mx.nd.array(onp.ones((2, 5), "float32")),
            mx.nd.array(onp.full((3,), 4, "int32"))]
    kv.pushpull_list([0, 1, 2, 3], vals)
    assert len(seen) == 2, seen  # one bucket per dtype
    by_dtype = {d: n for nd_, d, n in seen}
    assert all(nd_ == 1 for nd_, _, _ in seen), seen  # flattened buffers
    assert by_dtype["float32"] == 4 * 3 + 2 * 5
    assert by_dtype["int32"] == 7 + 3
    # values still correct through the spied path
    onp.testing.assert_allclose(vals[0].asnumpy(), onp.ones((4, 3)))
    onp.testing.assert_array_equal(vals[1].asnumpy(), onp.full((7,), 2))
