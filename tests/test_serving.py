"""mx.serving — AOT-compiled predictor + dynamic batcher (docs/SERVING.md).

Pins the serving-engine contracts:

- shape-bucket quantization and AOT warmup (one compiled program per
  bucket, zero retraces under live traffic);
- fake-clock DynamicBatcher semantics: timeout flush, max-batch flush,
  idle/force flush, pad-to-bucket with valid-row masking;
- BIT-EXACT batched-vs-single outputs (a row's result must not depend
  on its batch-mates or the padding);
- pipelined-vs-sync parity (in-flight window 2 vs 0);
- the guarded zero-sync hot loop: under MXNET_TRANSFER_GUARD=raise the
  dispatch path performs NO unblessed host sync, and a forward that
  hides a host sync is flushed out as an error;
- bf16/int8 predictor variants through the AMP/quantization paths.
"""
import threading

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn

IN, HIDDEN, CLASSES = 16, 32, 4


def make_net(in_units=IN, hidden=HIDDEN, classes=CLASSES):
    onp.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu", in_units=in_units),
            nn.Dense(classes, in_units=hidden))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, in_units), "float32")))
    return net


def rows(n, in_units=IN, seed=0):
    return onp.random.RandomState(seed).randn(n, in_units) \
        .astype("float32")


@pytest.fixture
def pred():
    return serving.CompiledPredictor(make_net(),
                                     bucket_sizes=(1, 2, 4, 8))


# ---------------------------------------------------------------------------
# CompiledPredictor: buckets, AOT, retraces
# ---------------------------------------------------------------------------

def test_bucket_for_rounds_up(pred):
    assert pred.bucket_for(1) == 1
    assert pred.bucket_for(3) == 4
    assert pred.bucket_for(8) == 8
    with pytest.raises(MXNetError, match="largest shape bucket"):
        pred.bucket_for(9)


def test_pad_to_bucket_returns_mask(pred):
    x = mx.nd.array(rows(3))
    (padded,), valid = pred.pad_to_bucket(x)
    assert padded.shape == (4, IN) and valid == 3
    assert onp.asarray(padded.asnumpy()[3]).sum() == 0.0   # zero rows


def test_predict_returns_async_ndarray(pred):
    out = pred.predict(mx.nd.array(rows(1)))
    assert isinstance(out, mx.nd.NDArray)
    assert out.shape == (1, CLASSES)


def test_warmup_compiles_every_bucket_once(pred):
    flops = pred.warmup(mx.nd.array(rows(1)))
    assert set(flops) == {1, 2, 4, 8}
    assert pred.n_traces == 4
    # live traffic at every bucket: ZERO further retraces (the AOT
    # executables serve it)
    for n in (1, 2, 3, 4, 7, 8):
        padded, valid = pred.pad_to_bucket(mx.nd.array(rows(n)))
        out = pred.predict(*padded)
        assert out.shape[0] == pred.bucket_for(n)
    assert pred.n_traces == 4


def test_bucket_retrace_count_without_warmup(pred):
    # unwarmed: one trace per DISTINCT bucket, repeats are cache hits
    for n in (1, 1, 2, 2, 4, 1):
        padded, _ = pred.pad_to_bucket(mx.nd.array(rows(n)))
        pred.predict(*padded)
    assert pred.n_traces == 3


def test_predictor_requires_materialized_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(4))      # no in_units, never forwarded: deferred
    net.initialize()
    with pytest.raises(MXNetError, match="materialized"):
        serving.CompiledPredictor(net)


# ---------------------------------------------------------------------------
# static-analysis gates on the serving program
# ---------------------------------------------------------------------------

def test_predict_program_analysis(pred):
    x = mx.nd.array(rows(4))
    report = pred.analyze(x)
    assert report.mode == "predict"
    assert report.ok, report.summary()
    assert not report.collectives.ops          # single-device forward
    assert report.host_transfers == []


def test_predict_memory_report(pred):
    x = mx.nd.array(rows(4))
    r = pred.memory_report(x)
    assert r is not None and r.peak_bytes > 0
    # no-arg merge covers the analyzed bucket
    merged = pred.memory_report()
    assert merged.peak_bytes >= r.peak_bytes


# ---------------------------------------------------------------------------
# DynamicBatcher: fake-clock semantics
# ---------------------------------------------------------------------------

def manual_batcher(pred, clk, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("timeout_ms", 5.0)
    return serving.DynamicBatcher(pred, start=False,
                                  clock=lambda: clk[0], **kw)


def test_fake_clock_timeout_flush(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(1)))
    assert b.process_once() is False          # young and not full
    clk[0] = 0.0049
    assert b.process_once() is False          # still inside the window
    clk[0] = 0.0051
    assert b.process_once() is True           # oldest aged past 5 ms
    assert b.stats["flush_timeout"] == 1
    assert fut.result(10).shape == (1, CLASSES)
    b.close()


def test_fake_clock_max_batch_flush(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    futs = [b.submit(mx.nd.array(rows(1, seed=i))) for i in range(4)]
    # clock did NOT advance: the flush is size-triggered
    assert b.process_once() is True
    assert b.stats["flush_full"] == 1
    assert b.stats["rows"] == 4 and b.stats["padded_rows"] == 0
    for f in futs:
        assert f.result(10).shape == (1, CLASSES)
    b.close()


def test_fake_clock_force_flush_and_fill(pred):
    clk = [0.0]
    b = manual_batcher(pred, clk)
    fut = b.submit(mx.nd.array(rows(3)))
    assert b.process_once() is False
    assert b.process_once(force=True) is True
    assert b.stats["flush_force"] == 1
    # 3 valid rows dispatched in the 4-row bucket
    assert b.stats["rows"] == 3 and b.stats["padded_rows"] == 1
    assert b.batch_fill == pytest.approx(0.75)
    assert fut.result(10).shape == (3, CLASSES)
    b.close()


def test_process_once_empty_is_noop(pred):
    b = manual_batcher(pred, [0.0])
    assert b.process_once() is False
    assert b.process_once(force=True) is False
    b.close()


def test_oversized_request_rejected(pred):
    b = manual_batcher(pred, [0.0])
    with pytest.raises(MXNetError, match="max_batch"):
        b.submit(mx.nd.array(rows(5)))
    b.close()


def test_queue_backpressure(pred):
    b = manual_batcher(pred, [0.0], depth=1)
    b.submit(mx.nd.array(rows(1)))
    with pytest.raises(MXNetError, match="saturated"):
        b.submit(mx.nd.array(rows(1)), timeout=0.05)
    b.flush()
    b.close()


def test_future_timeout_message(pred):
    b = manual_batcher(pred, [0.0])
    fut = b.submit(mx.nd.array(rows(1)))
    with pytest.raises(MXNetError, match="not completed"):
        fut.result(0.01)
    b.flush()
    assert fut.result(10).shape == (1, CLASSES)
    b.close()


def test_dispatch_error_fails_futures(pred):
    pred.warmup(mx.nd.array(rows(1)), buckets=(1,))
    clk = [0.0]
    b = manual_batcher(pred, clk)
    # wrong feature width: the bucket trace fails at dispatch, and the
    # proven predictor must NOT silently demote to eager
    fut = b.submit(mx.nd.array(onp.zeros((1, IN + 3), "float32")))
    with pytest.raises(Exception):
        b.process_once(force=True)
    with pytest.raises(Exception):
        fut.result(10)
    b.close()


# ---------------------------------------------------------------------------
# batched-vs-single parity
# ---------------------------------------------------------------------------

def test_batched_bit_exact_vs_single(pred):
    pred.warmup(mx.nd.array(rows(1)))
    X = rows(8, seed=3)
    singles = [pred.predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(8)]
    with serving.DynamicBatcher(pred, max_batch=8,
                                timeout_ms=20.0) as b:
        futs = [b.submit(mx.nd.array(X[i:i + 1])) for i in range(8)]
        batched = [f.result(30).asnumpy() for f in futs]
    for i in range(8):
        assert (batched[i] == singles[i]).all(), \
            f"row {i} differs between batched and single dispatch"


def test_pad_mask_parity_multi_row_request(pred):
    # a 3-row request padded into the 4-bucket must return EXACTLY the
    # single-dispatch rows — padding never leaks into valid outputs
    pred.warmup(mx.nd.array(rows(1)))
    X = rows(3, seed=5)
    singles = [pred.predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(3)]
    with serving.DynamicBatcher(pred, max_batch=4,
                                timeout_ms=5.0) as b:
        out = b.submit(mx.nd.array(X)).result(30).asnumpy()
    assert out.shape == (3, CLASSES)
    for i in range(3):
        assert (out[i:i + 1] == singles[i]).all()


def test_pipelined_vs_sync_parity(pred):
    pred.warmup(mx.nd.array(rows(1)))
    X = rows(12, seed=9)

    def run(inflight):
        with serving.DynamicBatcher(pred, max_batch=4, timeout_ms=2.0,
                                    inflight=inflight) as b:
            futs = [b.submit(mx.nd.array(X[i:i + 1]))
                    for i in range(12)]
            return [f.result(30).asnumpy() for f in futs]

    sync = run(0)       # window 0: every micro-batch retires eagerly
    piped = run(2)      # pipelined: host runs ahead of the device
    for a, c in zip(sync, piped):
        assert (a == c).all()


# ---------------------------------------------------------------------------
# guarded zero-sync hot loop
# ---------------------------------------------------------------------------

def test_guarded_serving_run_zero_unblessed_syncs(pred, monkeypatch):
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    pred.warmup(mx.nd.array(rows(1)))
    X = rows(8, seed=11)
    before = telemetry.value(telemetry.names.HOST_SYNCS,
                             "wait_to_read") or 0
    with serving.DynamicBatcher(pred, max_batch=8, timeout_ms=1.0) as b:
        futs = [b.submit(mx.nd.array(X[i:i + 1])) for i in range(8)]
        outs = [f.result(30) for f in futs]
    assert len(outs) == 8
    after = telemetry.value(telemetry.names.HOST_SYNCS,
                            "wait_to_read") or 0
    assert after - before == 0, \
        "serving hot loop performed an unblessed NDArray host sync"


def test_guard_flushes_out_hidden_host_sync(monkeypatch):
    # a forward hiding a host materialization: the trace fails (tracer
    # has no concrete value), the eager fallback then trips the armed
    # transfer guard INSIDE the hot region instead of silently syncing
    # per request forever
    from mxnet_tpu.gluon import HybridBlock

    class Hostile(HybridBlock):
        def __init__(self):
            super().__init__()
            self.d = nn.Dense(4, in_units=IN)

        def forward(self, x):
            _ = x.asnumpy()            # the bug under test
            return self.d(x)

    net = Hostile()
    net.initialize()
    net(mx.nd.array(onp.zeros((1, IN), "float32")))
    p = serving.CompiledPredictor(net, bucket_sizes=(1,))
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    with pytest.raises(MXNetError, match="hot region"):
        p.predict(mx.nd.array(rows(1)))


# ---------------------------------------------------------------------------
# serving telemetry
# ---------------------------------------------------------------------------

def test_serving_metrics_flow(pred):
    reg = telemetry.registry()
    req0 = reg.value(telemetry.names.SERVING_REQUESTS) or 0
    bat0 = reg.value(telemetry.names.SERVING_BATCHES) or 0
    lat = reg.get(telemetry.names.SERVING_LATENCY)
    occ = reg.get(telemetry.names.SERVING_OCCUPANCY)
    lat0, occ0 = lat.count(), occ.count()
    with serving.DynamicBatcher(pred, max_batch=4, timeout_ms=1.0) as b:
        futs = [b.submit(mx.nd.array(rows(1, seed=i))) for i in range(6)]
        for f in futs:
            f.result(30)
    assert (reg.value(telemetry.names.SERVING_REQUESTS) or 0) - req0 == 6
    n_batches = (reg.value(telemetry.names.SERVING_BATCHES) or 0) - bat0
    assert n_batches >= 1
    assert lat.count() - lat0 == 6          # one latency per request
    assert occ.count() - occ0 == n_batches  # one occupancy per batch


# ---------------------------------------------------------------------------
# precision variants
# ---------------------------------------------------------------------------

def test_predictor_for_bf16_casts_params():
    net = make_net()
    p = serving.predictor_for(net, dtype="bf16", bucket_sizes=(1, 4))
    dtypes = {str(prm.data()._data.dtype)
              for prm in net.collect_params().values()}
    assert "bfloat16" in dtypes
    out = p.predict(mx.nd.array(rows(1)))
    assert out.shape == (1, CLASSES)


def test_predictor_for_int8_needs_calib():
    with pytest.raises(MXNetError, match="calib_data"):
        serving.predictor_for(make_net(), dtype="int8")


def test_predictor_for_int8_served_outputs_close():
    X = rows(32, seed=13)
    net = make_net()
    f32 = serving.CompiledPredictor(net, bucket_sizes=(1, 8))
    ref = f32.predict(mx.nd.array(X[:8])).asnumpy()
    # quantize the SAME net in place (the reference conversion
    # contract) and serve the int8 variant through the batcher
    calib = [mx.nd.array(X[i:i + 8]) for i in range(0, 32, 8)]
    p8 = serving.predictor_for(net, dtype="int8", calib_data=calib,
                               bucket_sizes=(1, 8))
    assert any(type(b).__name__ == "QuantizedDense" for b in net)
    with serving.DynamicBatcher(p8, max_batch=8, timeout_ms=5.0) as b:
        out = b.submit(mx.nd.array(X[:8])).result(30).asnumpy()
    # int8 quantization error is bounded, ranks mostly preserved
    assert out.shape == ref.shape
    assert onp.abs(out - ref).max() < 0.5
    agree = (out.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.75


def test_predictor_for_unknown_dtype():
    with pytest.raises(MXNetError, match="unknown serving dtype"):
        serving.predictor_for(make_net(), dtype="fp8")


# ---------------------------------------------------------------------------
# load generator
# ---------------------------------------------------------------------------

def test_loadgen_percentiles_exact():
    from mxnet_tpu.serving import loadgen
    lat = [0.001 * i for i in range(1, 101)]     # 1..100 ms
    p = loadgen.percentiles(lat)
    assert p["p50_ms"] == pytest.approx(50.5, abs=1.0)
    assert p["p99_ms"] == pytest.approx(99.01, abs=1.0)
    assert loadgen.percentiles([])["p50_ms"] is None


def test_loadgen_closed_loop_counts():
    from mxnet_tpu.serving import loadgen
    seen = []
    rep = loadgen.run_closed_loop(lambda i: seen.append(i),
                                  concurrency=4, requests=40)
    assert rep["requests"] == 40 and rep["errors"] == 0
    assert len(seen) == 40 and rep["qps"] > 0
    assert rep["p50_ms"] is not None


def test_loadgen_open_loop_completes():
    from mxnet_tpu.serving import loadgen
    done = []

    def submit(i):
        return lambda *_: done.append(i)

    rep = loadgen.run_open_loop(submit, rate_qps=2000.0, requests=32)
    assert rep["requests"] == 32 and rep["errors"] == 0
    assert len(done) == 32


def test_loadgen_counts_errors():
    from mxnet_tpu.serving import loadgen

    def issue(i):
        if i % 2:
            raise RuntimeError("boom")

    rep = loadgen.run_closed_loop(issue, concurrency=2, requests=10)
    assert rep["errors"] == 5 and rep["requests"] == 5


# ---------------------------------------------------------------------------
# end-to-end: concurrent clients through the threaded batcher
# ---------------------------------------------------------------------------

def test_concurrent_clients_all_served(pred):
    pred.warmup(mx.nd.array(rows(1)))
    X = rows(24, seed=17)
    singles = [pred.predict(mx.nd.array(X[i:i + 1])).asnumpy()
               for i in range(24)]
    results = [None] * 24
    with serving.DynamicBatcher(pred, max_batch=8, timeout_ms=2.0) as b:
        def client(i):
            results[i] = b.submit(
                mx.nd.array(X[i:i + 1])).result(30).asnumpy()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i in range(24):
        assert (results[i] == singles[i]).all()
    assert pred.n_traces == 4       # buckets only, never per-request
