"""ONNX export/import round trip (reference python/mxnet/contrib/onnx:
mx2onnx export_model + onnx2mx import_model). Serialization is the
hand-rolled protobuf wire format (contrib/onnx_proto.py); the round trip
proves both directions against each other, and the wire-level test checks
the format against protobuf rules directly."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import onnx as mxonnx
from mxnet_tpu.contrib import onnx_proto as P


def _mlp_symbol():
    x = sym.Variable("data")
    h = sym.FullyConnected(x, sym.Variable("w1"), sym.Variable("b1"),
                           name="fc1", flatten=False)
    h = sym.relu(h, name="act1")
    out = sym.FullyConnected(h, sym.Variable("w2"), sym.Variable("b2"),
                             name="fc2", flatten=False)
    return sym.softmax(out, axis=-1, name="prob")


def _mlp_params(rng):
    return {
        "w1": nd.array(rng.randn(16, 8).astype("float32")),
        "b1": nd.array(rng.randn(16).astype("float32")),
        "w2": nd.array(rng.randn(4, 16).astype("float32")),
        "b2": nd.array(rng.randn(4).astype("float32")),
    }


def test_mlp_roundtrip(tmp_path):
    rng = onp.random.RandomState(0)
    s = _mlp_symbol()
    params = _mlp_params(rng)
    path = str(tmp_path / "mlp.onnx")
    assert mxonnx.export_model(s, params, in_shapes=[(2, 8)],
                               onnx_file_path=path) == path

    sym2, args, aux = mxonnx.import_model(path)
    assert set(args) == {"w1", "b1", "w2", "b2"}
    assert not aux
    x = nd.array(rng.randn(2, 8).astype("float32"))
    want = s.eval(data=x, **params).asnumpy()
    got = sym2.eval(data=x, **args).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    meta = mxonnx.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (2, 8))]


def test_convnet_roundtrip(tmp_path):
    """Conv -> BN -> relu -> maxpool -> flatten -> FC with aux states."""
    rng = onp.random.RandomState(1)
    x = sym.Variable("data")
    c = sym.Convolution(x, sym.Variable("cw"), sym.Variable("cb"),
                        kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        num_filter=6, name="conv1")
    b = sym.BatchNorm(c, sym.Variable("g"), sym.Variable("be"),
                      sym.Variable("moving_mean"),
                      sym.Variable("moving_var"),
                      eps=1e-5, use_global_stats=True, name="bn1")
    r = sym.Activation(b, act_type="relu", name="relu1")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    f = sym.Flatten(p, name="flat")
    out = sym.FullyConnected(f, sym.Variable("fw"), sym.Variable("fb"),
                             name="fc", flatten=True)

    params = {
        "cw": nd.array(rng.randn(6, 3, 3, 3).astype("float32") * 0.1),
        "cb": nd.array(rng.randn(6).astype("float32") * 0.1),
        "g": nd.array(onp.abs(rng.randn(6)).astype("float32") + 0.5),
        "be": nd.array(rng.randn(6).astype("float32") * 0.1),
        "moving_mean": nd.array(rng.randn(6).astype("float32") * 0.1),
        "moving_var": nd.array(onp.abs(rng.randn(6)).astype("float32") + 1),
        "fw": nd.array(rng.randn(10, 6 * 4 * 4).astype("float32") * 0.05),
        "fb": nd.array(rng.randn(10).astype("float32") * 0.1),
    }
    path = str(tmp_path / "conv.onnx")
    mxonnx.export_model(out, params, in_shapes=[(2, 3, 8, 8)],
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    assert set(aux) == {"moving_mean", "moving_var"}
    xv = nd.array(rng.randn(2, 3, 8, 8).astype("float32"))
    want = out.eval(data=xv, **params).asnumpy()
    got = sym2.eval(data=xv, **args, **aux).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_elementwise_and_shape_ops_roundtrip(tmp_path):
    rng = onp.random.RandomState(2)
    a = sym.Variable("a")
    b = sym.Variable("b")
    s = sym.broadcast_add(a, b, name="s1")
    s = sym.transpose(s, axes=(1, 0), name="t1")
    s = sym.reshape(s, shape=(2, 6), name="r1")
    s = sym.concat(s, s, dim=1, name="c1")
    s = sym.tanh(s, name="tanh1")

    path = str(tmp_path / "ew.onnx")
    mxonnx.export_model(s, {}, in_shapes=[(3, 4), (3, 4)],
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    av = nd.array(rng.randn(3, 4).astype("float32"))
    bv = nd.array(rng.randn(3, 4).astype("float32"))
    want = s.eval(a=av, b=bv).asnumpy()
    got = sym2.eval(a=av, b=bv).asnumpy()
    assert got.shape == (2, 12)
    onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_wire_format_is_valid_protobuf(tmp_path):
    """Byte-level checks against protobuf rules: top-level fields parse
    with the declared wire types and the expected ONNX field numbers."""
    s = _mlp_symbol()
    params = _mlp_params(onp.random.RandomState(0))
    path = str(tmp_path / "m.onnx")
    mxonnx.export_model(s, params, in_shapes=[(2, 8)], onnx_file_path=path)
    with open(path, "rb") as f:
        blob = f.read()
    model = P.parse_message(blob)
    assert model[1][0][1] == P.ONNX_IR_VERSION       # ir_version varint
    assert model[2][0][1] == b"mxnet_tpu"            # producer_name
    opset = P.parse_message(model[8][0][1])
    assert opset[2][0][1] == P.ONNX_OPSET
    g = P.parse_message(model[7][0][1])
    op_types = [P.parse_message(n)[4][0][1].decode() for w, n in g[1]]
    # Flatten is injected before Gemm only when flatten=True; this MLP
    # used flatten=False
    assert op_types == ["Gemm", "Relu", "Gemm", "Softmax"]
    names = [P.parse_message(t)[8][0][1].decode() for w, t in g[5]]
    assert set(names) == {"w1", "b1", "w2", "b2"}
    # initializer raw bytes round-trip exactly
    for w, t in g[5]:
        nm, arr = mxonnx._parse_tensor(t)
        onp.testing.assert_array_equal(arr, params[nm].asnumpy())


def test_unsupported_op_raises_with_name(tmp_path):
    x = sym.Variable("x")
    s = mx.symbol.Symbol("arctanh", "odd1", [x], {})
    with pytest.raises(MXNetError, match="arctanh"):
        mxonnx.export_model(s, {}, onnx_file_path=str(tmp_path / "x.onnx"))


def test_export_uniquifies_colliding_names(tmp_path):
    """ONNX is SSA: default symbol-factory names collide (relu_1 twice);
    export must uniquify every value name."""
    x = sym.Variable("x")
    s = sym.relu(sym.relu(x))  # both auto-named relu_1
    path = str(tmp_path / "u.onnx")
    mxonnx.export_model(s, {}, onnx_file_path=path)
    with open(path, "rb") as f:
        g = P.parse_message(P.parse_message(f.read())[7][0][1])
    outs = [P.parse_message(n)[2][0][1].decode() for w, n in g[1]]
    assert len(set(outs)) == len(outs) == 2
    sym2, args, aux = mxonnx.import_model(path)
    xv = nd.array(onp.array([-1.0, 2.0], "float32"))
    onp.testing.assert_allclose(sym2.eval(x=xv).asnumpy(), [0.0, 2.0])


def test_import_typed_int32_data_and_unknown_encoding_raises(tmp_path):
    """Official onnx tooling writes typed repeated fields (int32_data)
    instead of raw_data; those parse, and a truly unknown encoding raises
    instead of fabricating zeros."""
    t = P.MessageWriter()
    t.write_int(1, 3)
    t.write_int(2, P.TensorDataType.INT32)
    t.write_string(8, "v")
    t.write_packed_ints(5, [1, -2, 3])
    name, arr = mxonnx._parse_tensor(t.tobytes())
    assert name == "v" and arr.dtype == onp.int32
    onp.testing.assert_array_equal(arr, [1, -2, 3])

    bad = P.MessageWriter()
    bad.write_int(1, 2)
    bad.write_int(2, P.TensorDataType.DOUBLE)
    bad.write_string(8, "w")  # no data fields at all, nonzero numel
    with pytest.raises(MXNetError, match="unsupported data"):
        mxonnx._parse_tensor(bad.tobytes())


def test_unknown_shape_value_info_omits_shape(tmp_path):
    """shape=None must omit the TensorShapeProto entirely — writing an
    empty one declares rank 0 and breaks shape inference downstream."""
    vi = mxonnx._value_info("o", None).tobytes()
    ty = P.parse_message(P.parse_message(vi)[2][0][1])
    tt = P.parse_message(ty[1][0][1])
    assert 2 not in tt  # no shape submessage at all
    vi2 = mxonnx._value_info("i", (2, 3)).tobytes()
    tt2 = P.parse_message(P.parse_message(
        P.parse_message(vi2)[2][0][1])[1][0][1])
    assert 2 in tt2


def test_transformer_block_ops_roundtrip(tmp_path):
    """The transformer-surface op set: Gather (embedding lookup),
    LayerNormalization, reductions, Pow/Erf (exact GELU), Squeeze/Slice —
    export then import reproduces the graph exactly."""
    rng = onp.random.RandomState(4)
    vocab, dim = 20, 8

    ids = sym.Variable("ids")
    emb = sym.embedding(ids, sym.Variable("emb_w"), name="emb")
    ln = sym.LayerNorm(emb, sym.Variable("g"), sym.Variable("b"),
                       axis=-1, eps=1e-5, name="ln")
    # exact GELU: 0.5 * x * (1 + erf(x / sqrt(2)))
    erf_in = sym.broadcast_mul(ln, sym.Variable("inv_sqrt2"), name="scl")
    gelu = sym.broadcast_mul(
        sym.broadcast_mul(ln, sym.Variable("half"), name="halfx"),
        sym.broadcast_add(sym.erf(erf_in, name="erf1"),
                          sym.Variable("one"), name="one_p"),
        name="gelu")
    pooled = sym.mean(gelu, axis=1, name="pool")        # (B, dim)
    powd = sym.broadcast_power(pooled, sym.Variable("two"), name="sq")
    sliced = sym.slice_axis(powd, axis=1, begin=0, end=4, name="sl")
    out = sym.expand_dims(sliced, axis=1, name="unsq")

    params = {
        "emb_w": nd.array(rng.randn(vocab, dim).astype("float32")),
        "g": nd.array(onp.abs(rng.randn(dim)).astype("float32") + 0.5),
        "b": nd.array(rng.randn(dim).astype("float32") * 0.1),
        "inv_sqrt2": nd.array(onp.array(1 / onp.sqrt(2), "float32")),
        "half": nd.array(onp.array(0.5, "float32")),
        "one": nd.array(onp.array(1.0, "float32")),
        "two": nd.array(onp.array(2.0, "float32")),
    }
    path = str(tmp_path / "block.onnx")
    mxonnx.export_model(out, params, in_shapes=[(2, 5)],
                        in_types=["int32"], onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    x = nd.array(rng.randint(0, vocab, (2, 5)).astype("int32"))
    want = out.eval(ids=x, **params).asnumpy()
    got = sym2.eval(ids=x, **args).asnumpy()
    assert want.shape == (2, 1, 4)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # the exported graph used the expected ONNX op set
    with open(path, "rb") as f:
        g = P.parse_message(P.parse_message(f.read())[7][0][1])
    op_types = {P.parse_message(n)[4][0][1].decode() for w, n in g[1]}
    assert {"Gather", "LayerNormalization", "Erf", "ReduceMean", "Pow",
            "Slice", "Unsqueeze"} <= op_types
    # LayerNormalization is opset-17: the declared opset must follow
    with open(path, "rb") as f:
        model = P.parse_message(f.read())
    assert P.parse_message(model[8][0][1])[2][0][1] == 17
    # unsupported semantics raise instead of exporting wrong graphs
    with pytest.raises(MXNetError, match="exclude"):
        mxonnx.export_model(
            sym.mean(sym.Variable("z"), axis=1, exclude=True, name="m1"),
            {}, onnx_file_path=str(tmp_path / "x.onnx"))
    with pytest.raises(MXNetError, match="wrap"):
        mxonnx.export_model(
            sym.take(sym.Variable("z"), sym.Variable("i"), mode="wrap",
                     name="t1"),
            {}, onnx_file_path=str(tmp_path / "x.onnx"))


def test_clip_minmax_leaky_roundtrip(tmp_path):
    rng = onp.random.RandomState(5)
    a = sym.Variable("a")
    b = sym.Variable("b")
    s = sym.clip(sym.broadcast_maximum(a, b, name="mx1"), a_min=-0.5,
                 a_max=0.8, name="cl")
    s = sym.LeakyReLU(s, act_type="leaky", slope=0.1, name="lr")
    s = sym.broadcast_minimum(s, b, name="mn1")
    path = str(tmp_path / "cm.onnx")
    mxonnx.export_model(s, {}, in_shapes=[(3, 4), (3, 4)],
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    av = nd.array(rng.randn(3, 4).astype("float32"))
    bv = nd.array(rng.randn(3, 4).astype("float32"))
    onp.testing.assert_allclose(sym2.eval(a=av, b=bv).asnumpy(),
                                s.eval(a=av, b=bv).asnumpy(),
                                rtol=1e-6, atol=1e-7)
    # one-sided clip (ReLU6 pattern): max-only bound round-trips
    r6 = sym.clip(sym.Variable("y"), a_min=None, a_max=6.0, name="r6")
    p6 = str(tmp_path / "r6.onnx")
    mxonnx.export_model(r6, {}, in_shapes=[(4,)], onnx_file_path=p6)
    s6, _, _ = mxonnx.import_model(p6)
    yv = nd.array(onp.array([-3.0, 2.0, 7.0, 6.0], "float32"))
    onp.testing.assert_allclose(s6.eval(y=yv).asnumpy(),
                                [-3.0, 2.0, 6.0, 6.0])
    # Elu round trip
    e = sym.LeakyReLU(sym.Variable("x"), act_type="elu", slope=0.3,
                      name="elu1")
    p2 = str(tmp_path / "elu.onnx")
    mxonnx.export_model(e, {}, in_shapes=[(5,)], onnx_file_path=p2)
    s3, a3, _ = mxonnx.import_model(p2)
    xv = nd.array(onp.array([-2.0, -0.5, 0.0, 0.5, 2.0], "float32"))
    onp.testing.assert_allclose(s3.eval(x=xv).asnumpy(),
                                e.eval(x=xv).asnumpy(), rtol=1e-6)


def test_split_import_multi_output(tmp_path):
    """External models use Split heavily; build a Split node by hand (our
    sym API has no multi-output surface to export it from) and import."""
    graph = P.MessageWriter()
    node = P.MessageWriter()
    node.write_string(1, "x")
    for o in ("s0", "s1", "s2"):
        node.write_string(2, o)
    node.write_string(3, "sp")
    node.write_string(4, "Split")
    attr = P.MessageWriter()
    attr.write_string(1, "axis")
    attr.write_int(3, 1)
    attr.write_int(20, P.AttrType.INT)
    node.write_message(5, attr)
    graph.write_message(1, node)
    # consumer: add s0 + s2
    add = P.MessageWriter()
    add.write_string(1, "s0")
    add.write_string(1, "s2")
    add.write_string(2, "out")
    add.write_string(3, "a1")
    add.write_string(4, "Add")
    graph.write_message(1, add)
    graph.write_string(2, "g")
    vi = mxonnx._value_info("x", (2, 6))
    graph.write_message(11, vi)
    graph.write_message(12, mxonnx._value_info("out", None))
    model = P.MessageWriter()
    model.write_int(1, P.ONNX_IR_VERSION)
    opset = P.MessageWriter()
    opset.write_string(1, "")
    opset.write_int(2, 13)
    model.write_message(8, opset)
    model.write_message(7, graph)
    path = str(tmp_path / "split.onnx")
    with open(path, "wb") as f:
        f.write(model.tobytes())

    s, args, aux = mxonnx.import_model(path)
    x = onp.arange(12.0, dtype="float32").reshape(2, 6)
    got = s.eval(x=nd.array(x)).asnumpy()
    onp.testing.assert_allclose(got, x[:, 0:2] + x[:, 4:6])


def test_varint_edge_cases():
    w = P.MessageWriter()
    w.write_int(1, 0)
    w.write_int(2, 300)
    w.write_int(3, 2 ** 40)
    w.write_int(4, -1)  # negative int64: 10-byte two's complement varint
    f = P.parse_message(w.tobytes())
    assert f[1][0][1] == 0
    assert f[2][0][1] == 300
    assert f[3][0][1] == 2 ** 40
    assert P.signed64(f[4][0][1]) == -1


def test_split_evaluates_once_per_forward(tmp_path, monkeypatch):
    """Sibling Split outputs share one evaluation (executor group cache).
    Reuses the hand-built model from the sibling test with nd.split
    instrumented to count dispatches."""
    import mxnet_tpu.ndarray as ndm
    calls = {"n": 0}
    orig = ndm.split

    def counting_split(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(ndm, "split", counting_split)
    test_split_import_multi_output(tmp_path)
    assert calls["n"] == 1, calls


def test_variadic_min_import(tmp_path):
    """ONNX Min/Max are variadic; 3+ inputs fold into pairwise chains."""
    graph = P.MessageWriter()
    node = P.MessageWriter()
    for i in ("a", "b", "c"):
        node.write_string(1, i)
    node.write_string(2, "out")
    node.write_string(3, "m1")
    node.write_string(4, "Min")
    graph.write_message(1, node)
    graph.write_string(2, "g")
    for nm in ("a", "b", "c"):
        graph.write_message(11, mxonnx._value_info(nm, (4,)))
    graph.write_message(12, mxonnx._value_info("out", None))
    model = P.MessageWriter()
    model.write_int(1, P.ONNX_IR_VERSION)
    opset = P.MessageWriter()
    opset.write_string(1, "")
    opset.write_int(2, 13)
    model.write_message(8, opset)
    model.write_message(7, graph)
    path = str(tmp_path / "min3.onnx")
    with open(path, "wb") as f:
        f.write(model.tobytes())
    s, args, aux = mxonnx.import_model(path)
    a = onp.array([1.0, 5.0, 3.0, 0.0], "float32")
    b = onp.array([2.0, 1.0, 9.0, -1.0], "float32")
    c = onp.array([0.5, 7.0, 2.0, 4.0], "float32")
    got = s.eval(a=nd.array(a), b=nd.array(b), c=nd.array(c)).asnumpy()
    onp.testing.assert_allclose(got, onp.minimum(onp.minimum(a, b), c))


def test_constant_node_folding_import(tmp_path):
    """Third-party exporters feed Reshape shapes / operand tensors via
    Constant nodes rather than initializers; both uses must import."""
    graph = P.MessageWriter()
    # Constant -> int64 shape tensor for Reshape
    cshape = P.MessageWriter()
    cshape.write_string(2, "shp")
    cshape.write_string(3, "c_shape")
    cshape.write_string(4, "Constant")
    attr = P.MessageWriter()
    attr.write_string(1, "value")
    attr.write_message(5, mxonnx._tensor("", onp.asarray([2, 6], "int64")))
    attr.write_int(20, P.AttrType.TENSOR)
    cshape.write_message(5, attr)
    graph.write_message(1, cshape)
    # Constant -> float tensor consumed as a DATA operand of Add
    cdata = P.MessageWriter()
    cdata.write_string(2, "bias")
    cdata.write_string(3, "c_bias")
    cdata.write_string(4, "Constant")
    attr2 = P.MessageWriter()
    attr2.write_string(1, "value")
    attr2.write_message(
        5, mxonnx._tensor("", onp.full((1, 6), 0.5, "float32")))
    attr2.write_int(20, P.AttrType.TENSOR)
    cdata.write_message(5, attr2)
    graph.write_message(1, cdata)
    # x (3,4) --Reshape(shp)--> (2,6) --Add(bias)--> out
    rsh = P.MessageWriter()
    rsh.write_string(1, "x")
    rsh.write_string(1, "shp")
    rsh.write_string(2, "r")
    rsh.write_string(3, "rshp")
    rsh.write_string(4, "Reshape")
    graph.write_message(1, rsh)
    add = P.MessageWriter()
    add.write_string(1, "r")
    add.write_string(1, "bias")
    add.write_string(2, "out")
    add.write_string(3, "a0")
    add.write_string(4, "Add")
    graph.write_message(1, add)
    graph.write_string(2, "g")
    graph.write_message(11, mxonnx._value_info("x", (3, 4)))
    graph.write_message(12, mxonnx._value_info("out", None))
    model = P.MessageWriter()
    model.write_int(1, P.ONNX_IR_VERSION)
    opset = P.MessageWriter()
    opset.write_string(1, "")
    opset.write_int(2, 13)
    model.write_message(8, opset)
    model.write_message(7, graph)
    path = str(tmp_path / "const.onnx")
    with open(path, "wb") as f:
        f.write(model.tobytes())

    s, args, aux = mxonnx.import_model(path)
    # shape constant folded away; data constant surfaced as a parameter
    assert "shp" not in args and "shp" not in aux
    assert "bias" in args
    x = onp.arange(12.0, dtype="float32").reshape(3, 4)
    got = s.eval(x=nd.array(x), bias=args["bias"]).asnumpy()
    onp.testing.assert_allclose(got, x.reshape(2, 6) + 0.5)


def test_scalar_arith_export_matches_param_dtype(tmp_path):
    """Add/Mul scalar constants must carry the graph element type T, not
    hardcoded float32 (ONNX same-type-T constraint)."""
    x = sym.Variable("x")
    w = sym.Variable("w")
    y = (x * w + 2.0) * 3.0
    params = {"w": nd.array(onp.ones((4,), "float16"))}
    path = str(tmp_path / "f16.onnx")
    mxonnx.export_model(y, params, in_shapes=[(4,)],
                        in_types=["float16"], onnx_file_path=path)
    with open(path, "rb") as f:
        m = P.parse_message(f.read())
    g = P.parse_message(m[7][0][1])
    dtypes = set()
    for wire, t in g.get(5, []):
        tf = P.parse_message(t)
        nm = mxonnx._get_str(tf, 8)
        if "const" in nm:
            dtypes.add(mxonnx._get_int(tf, 2, -1))
    assert dtypes == {P.TensorDataType.FLOAT16}, dtypes


def test_resize_export_import_roundtrip(tmp_path):
    """UpSampling exports as opset-13 Resize and round-trips; a
    foreign-style Resize with linear sizes imports via BilinearResize2D."""
    x = sym.Variable("x")
    y = sym.UpSampling(x, scale=2, sample_type="nearest")
    path = str(tmp_path / "resize.onnx")
    mxonnx.export_model(y, {}, in_shapes=[(1, 2, 3, 3)],
                        onnx_file_path=path)
    s, args, aux = mxonnx.import_model(path)
    xv = onp.arange(18.0, dtype="float32").reshape(1, 2, 3, 3)
    got = s.eval(x=nd.array(xv)).asnumpy()
    want = xv.repeat(2, axis=2).repeat(2, axis=3)
    onp.testing.assert_allclose(got, want, rtol=1e-6)

    # hand-built foreign Resize: linear mode with explicit sizes
    graph = P.MessageWriter()
    szs = mxonnx._tensor("szs", onp.asarray([1, 2, 6, 6], "int64"))
    graph.write_message(5, szs)
    node = P.MessageWriter()
    node.write_string(1, "x")
    node.write_string(1, "")
    node.write_string(1, "")
    node.write_string(1, "szs")
    node.write_string(2, "out")
    node.write_string(3, "r0")
    node.write_string(4, "Resize")
    attr = P.MessageWriter()
    attr.write_string(1, "mode")
    attr.write_bytes(4, b"linear")
    attr.write_int(20, P.AttrType.STRING)
    node.write_message(5, attr)
    graph.write_message(1, node)
    graph.write_string(2, "g")
    graph.write_message(11, mxonnx._value_info("x", (1, 2, 3, 3)))
    graph.write_message(12, mxonnx._value_info("out", None))
    model = P.MessageWriter()
    model.write_int(1, P.ONNX_IR_VERSION)
    opset = P.MessageWriter()
    opset.write_string(1, "")
    opset.write_int(2, 13)
    model.write_message(8, opset)
    model.write_message(7, graph)
    p2 = str(tmp_path / "resize_sizes.onnx")
    with open(p2, "wb") as f:
        f.write(model.tobytes())
    s2, args2, aux2 = mxonnx.import_model(p2)
    out = s2.eval(x=nd.array(xv)).asnumpy()
    assert out.shape == (1, 2, 6, 6)
    assert onp.isfinite(out).all()


def test_resize_import_rejects_unsupported_numerics(tmp_path):
    """Resize import must never silently substitute interpolation:
    nearest with fractional scales and linear with align_corners raise."""
    def build(mode, ctm, scales):
        graph = P.MessageWriter()
        sc = mxonnx._tensor("sc", onp.asarray(scales, "float32"))
        graph.write_message(5, sc)
        node = P.MessageWriter()
        node.write_string(1, "x")
        node.write_string(1, "")
        node.write_string(1, "sc")
        node.write_string(2, "out")
        node.write_string(3, "r0")
        node.write_string(4, "Resize")
        for k, v in (("mode", mode),
                     ("coordinate_transformation_mode", ctm)):
            a = P.MessageWriter()
            a.write_string(1, k)
            a.write_bytes(4, v.encode())
            a.write_int(20, P.AttrType.STRING)
            node.write_message(5, a)
        graph.write_message(1, node)
        graph.write_string(2, "g")
        graph.write_message(11, mxonnx._value_info("x", (1, 2, 4, 4)))
        graph.write_message(12, mxonnx._value_info("out", None))
        model = P.MessageWriter()
        model.write_int(1, P.ONNX_IR_VERSION)
        opset = P.MessageWriter()
        opset.write_string(1, "")
        opset.write_int(2, 13)
        model.write_message(8, opset)
        model.write_message(7, graph)
        path = str(tmp_path / f"{mode}_{ctm}.onnx")
        with open(path, "wb") as f:
            f.write(model.tobytes())
        return path

    with pytest.raises(MXNetError):
        mxonnx.import_model(build("nearest", "asymmetric",
                                  [1, 1, 1.5, 1.5]))
    with pytest.raises(MXNetError):
        mxonnx.import_model(build("linear", "align_corners",
                                  [1, 1, 2.0, 2.0]))
    # half-pixel linear fractional scales DO import (floor sizing)
    s, args, aux = mxonnx.import_model(
        build("linear", "half_pixel", [1, 1, 1.5, 1.5]))
    out = s.eval(x=nd.array(onp.ones((1, 2, 4, 4), "float32"))).asnumpy()
    assert out.shape == (1, 2, 6, 6)


@pytest.mark.parametrize("mode,bi", [
    ("lstm", False), ("gru", False), ("rnn_tanh", False),
    ("rnn_relu", False), ("lstm", True), ("gru", True),
])
def test_rnn_onnx_roundtrip(tmp_path, mode, bi):
    """Reference RNN op (packed cuDNN parameters) exports as ONNX
    LSTM/GRU/RNN with gate reorder + layout conversion and reimports to
    identical numerics."""
    from mxnet_tpu.ops.rnn import rnn_packed_param_size
    rng = onp.random.RandomState(0)
    T, N, C, H = 6, 3, 5, 7
    n = rnn_packed_param_size(mode, C, H, 1, bi)
    pv = rng.randn(n).astype("float32") * 0.2
    x = sym.Variable("x")
    p = sym.Variable("p")
    y = sym.RNN(x, p, state_size=H, mode=mode, bidirectional=bi)
    path = str(tmp_path / f"rnn_{mode}_{bi}.onnx")
    mxonnx.export_model(y, {"p": nd.array(pv)}, in_shapes=[(T, N, C)],
                        onnx_file_path=path)
    s, args, aux = mxonnx.import_model(path)
    # the packed vector was repacked into W/R/B: no raw initializer left
    assert "p" not in args
    xv = rng.randn(T, N, C).astype("float32")
    got = s.eval(x=nd.array(xv), **args).asnumpy()
    want = nd.RNN(nd.array(xv), nd.array(pv), state_size=H, mode=mode,
                  bidirectional=bi).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_rnn_import_rejects_foreign_semantics(tmp_path):
    """GRU linear_before_reset=0 and direction=reverse have different
    recurrences than this backend — import must refuse, not approximate."""
    def build(op, g, extra_attrs):
        H, C, D = 4, 3, 1
        graph = P.MessageWriter()
        for key, shape in (("W", (D, g * H, C)), ("R", (D, g * H, H))):
            graph.write_message(
                5, mxonnx._tensor(key, onp.zeros(shape, "float32")))
        node = P.MessageWriter()
        for i in ("x", "W", "R"):
            node.write_string(1, i)
        node.write_string(2, "out")
        node.write_string(3, "n0")
        node.write_string(4, op)
        for k, v in [("hidden_size", H)] + extra_attrs:
            a = P.MessageWriter()
            a.write_string(1, k)
            if isinstance(v, str):
                a.write_bytes(4, v.encode())
                a.write_int(20, P.AttrType.STRING)
            else:
                a.write_int(3, v)
                a.write_int(20, P.AttrType.INT)
            node.write_message(5, a)
        graph.write_message(1, node)
        graph.write_string(2, "g")
        graph.write_message(11, mxonnx._value_info("x", (5, 2, C)))
        graph.write_message(12, mxonnx._value_info("out", None))
        model = P.MessageWriter()
        model.write_int(1, P.ONNX_IR_VERSION)
        opset = P.MessageWriter()
        opset.write_string(1, "")
        opset.write_int(2, 13)
        model.write_message(8, opset)
        model.write_message(7, graph)
        path = str(tmp_path / f"{op}{len(extra_attrs)}.onnx")
        with open(path, "wb") as f:
            f.write(model.tobytes())
        return path

    with pytest.raises(MXNetError):
        mxonnx.import_model(build("GRU", 3, []))  # lbr defaults to 0
    with pytest.raises(MXNetError):
        mxonnx.import_model(build("LSTM", 4, [("direction", "reverse")]))
    # plain LSTM without B imports fine (zero biases)
    s, args, aux = mxonnx.import_model(
        build("LSTM", 4, [("direction", "forward")]))
    out = s.eval(x=nd.array(onp.ones((5, 2, 3), "float32")),
                 **args).asnumpy()
    assert out.shape == (5, 1, 2, 4)  # ONNX Y layout (T, D, N, H)


@pytest.mark.parametrize("mode,bi,layers", [
    ("lstm", False, 2), ("gru", True, 3), ("rnn_tanh", False, 3),
])
def test_rnn_onnx_multilayer_chain(tmp_path, mode, bi, layers):
    """Multi-layer RNN exports as a chain of single-layer ONNX nodes
    (each layer's Y reshaped to feed the next) and round-trips."""
    from mxnet_tpu.ops.rnn import rnn_packed_param_size
    rng = onp.random.RandomState(1)
    T, N, C, H = 5, 2, 4, 6
    n = rnn_packed_param_size(mode, C, H, layers, bi)
    pv = rng.randn(n).astype("float32") * 0.2
    x = sym.Variable("x")
    p = sym.Variable("p")
    y = sym.RNN(x, p, state_size=H, mode=mode, bidirectional=bi,
                num_layers=layers)
    path = str(tmp_path / "ml.onnx")
    mxonnx.export_model(y, {"p": nd.array(pv)}, in_shapes=[(T, N, C)],
                        onnx_file_path=path)
    s, args, aux = mxonnx.import_model(path)
    xv = rng.randn(T, N, C).astype("float32")
    got = s.eval(x=nd.array(xv), **args).asnumpy()
    want = nd.RNN(nd.array(xv), nd.array(pv), state_size=H, mode=mode,
                  bidirectional=bi, num_layers=layers).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_third_party_torch_fixture():
    """Import an ONNX file produced by an INDEPENDENT exporter
    (PyTorch's TorchScript ONNX exporter, opset 13 — committed fixture
    tests/fixtures/torch_convnet.onnx: conv+bn+relu+flatten+linear) and
    match PyTorch's own recorded output. Closes VERDICT r3 weak #5: all
    prior import coverage was self-produced or hand-synthesized."""
    import os
    fdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
    s, args, aux = mxonnx.import_model(
        os.path.join(fdir, "torch_convnet.onnx"))
    x = onp.load(os.path.join(fdir, "torch_convnet_input.npy"))
    want = onp.load(os.path.join(fdir, "torch_convnet_output.npy"))
    feeds = dict(args)
    feeds.update(aux)
    got = s.eval(data=nd.array(x), **feeds).asnumpy()
    assert got.shape == want.shape == (1, 10)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
