"""Autograd tests (reference: tests/python/unittest/test_autograd.py,
test_higher_order_grad.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain_rule():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * x  # x^3
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [12.0])  # 3x^2


def test_backward_with_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    onp.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_grad_req_write_overwrites():
    x = nd.array([1.0])
    x.attach_grad()
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = 2 * x
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [0.0])


def test_multiple_inputs():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), [4.0, 5.0])
    onp.testing.assert_allclose(b.grad.asnumpy(), [1.0, 2.0])


def test_reused_input():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x * 2
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [8.0])  # 2x + 2


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # only d(z)/dx = y


def test_stop_gradient_op():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.stop_gradient(x * x) * x
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_matmul_grad():
    a = nd.array(onp.random.rand(3, 4).astype("float32"))
    b = nd.array(onp.random.rand(4, 2).astype("float32"))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = nd.dot(a, b).sum()
    c.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                onp.ones((3, 2)) @ b.asnumpy().T, rtol=1e-5)
    onp.testing.assert_allclose(b.grad.asnumpy(),
                                a.asnumpy().T @ onp.ones((3, 2)), rtol=1e-5)


def test_is_recording_is_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        assert autograd.is_recording()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_pause_stops_recording():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 10  # not recorded
        w = y + 1
    w.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_autograd_grad_api():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    gx = autograd.grad(y, x)
    onp.testing.assert_allclose(gx.asnumpy(), [6.0])
    # .grad untouched by grad()
    onp.testing.assert_allclose(x.grad.asnumpy(), [0.0])


def test_higher_order_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x  # x^3
        gx = autograd.grad(y, x, create_graph=True, retain_graph=True)
    gx.backward()
    # d/dx (3x^2) = 6x = 12
    onp.testing.assert_allclose(x.grad.asnumpy(), [12.0])


def test_multiple_heads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y1 = x * 2
        y2 = x * 3
    autograd.backward([y1, y2])
    onp.testing.assert_allclose(x.grad.asnumpy(), [5.0, 5.0])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y = self.saved_tensors[0]
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_dropout_respects_mode():
    x = nd.ones((100,))
    out_predict = nd.Dropout(x, p=0.5)
    onp.testing.assert_allclose(out_predict.asnumpy(), x.asnumpy())
    with autograd.record(train_mode=True):
        out_train = nd.Dropout(x, p=0.5)
    zeros = (out_train.asnumpy() == 0).sum()
    assert 10 < zeros < 90  # roughly half dropped


def test_exception_in_graph_propagates():
    x = nd.array([1.0])
    with pytest.raises(Exception):
        x.backward()  # not recorded, no grad


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * x
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [10.0])
