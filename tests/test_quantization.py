"""INT8 quantization tests (reference: tests/python/quantization/
test_quantization.py — round-trip + quantized-net accuracy checks)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(16, 16).astype("float32") * 3)
    qd, mn, mxr = q.quantize_v2(x)
    assert str(qd.dtype) == "int8"
    back = q.dequantize(qd, mn, mxr)
    err = onp.abs(back.asnumpy() - x.asnumpy()).max()
    scale = max(abs(float(mn.asnumpy()[0])), abs(float(mxr.asnumpy()[0]))) / 127
    assert err <= scale * 0.51 + 1e-6  # within half a quantization step


def test_quantize_with_calib_range():
    x = mx.nd.array(onp.array([[-5.0, 0.0, 5.0, 100.0]], "float32"))
    qd, mn, mxr = q.quantize_v2(x, min_calib_range=-5.0, max_calib_range=5.0)
    # 100 saturates to 127
    assert qd.asnumpy()[0, 3] == 127


def test_quantized_dense_close_to_fp32():
    rng = onp.random.RandomState(1)
    dense = nn.Dense(8, in_units=16, use_bias=True)
    dense.initialize()
    x = mx.nd.array(rng.uniform(-1, 1, (4, 16)).astype("float32"))
    ref = dense(x).asnumpy()
    qd = q.QuantizedDense(dense, -1.0, 1.0)
    out = qd(x).asnumpy()
    # int8 symmetric: ~1% relative error budget for this scale
    assert onp.abs(out - ref).max() < 0.05, onp.abs(out - ref).max()


def test_quantize_net_swaps_and_stays_accurate():
    rng = onp.random.RandomState(2)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(10, in_units=32))
    net.initialize()
    calib = [mx.nd.array(rng.uniform(-1, 1, (8, 16)).astype("float32"))
             for _ in range(4)]
    x = calib[0]
    ref = net(x).asnumpy()
    q.quantize_net(net, calib, calib_mode="naive")
    swapped = [type(c).__name__ for c in net]
    assert swapped == ["QuantizedDense", "QuantizedDense"], swapped
    out = net(x).asnumpy()
    rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6)
    assert rel < 0.05, rel


def test_quantized_conv():
    rng = onp.random.RandomState(3)
    conv = nn.Conv2D(4, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    x = mx.nd.array(rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32"))
    ref = conv(x).asnumpy()
    qc = q.QuantizedConv(conv, -1.0, 1.0)
    out = qc(x).asnumpy()
    assert onp.abs(out - ref).max() < 0.1, onp.abs(out - ref).max()


def test_entropy_threshold_clips_long_tail():
    """KL-entropy calibration (reference calibrate.cc entropy mode): on a
    long-tailed activation the optimal threshold ignores outliers, giving
    lower int8 round-trip error than naive min/max."""
    from mxnet_tpu.contrib.quantization import _optimal_threshold
    rng = onp.random.RandomState(0)
    bulk = rng.randn(200000).astype("float32")
    outliers = rng.choice([-80.0, 80.0], size=40).astype("float32")
    arr = onp.concatenate([bulk, outliers])

    th = _optimal_threshold(arr)
    assert th < 20.0, th          # naive would use 80
    assert th > 1.0, th           # but must still cover the bulk

    def int8_mse(x, threshold):
        scale = threshold / 127.0
        q = onp.clip(onp.round(x / scale), -127, 127)
        return float(((q * scale - x) ** 2).mean())

    # the KL threshold trades the rare outliers for bulk fidelity: error
    # on the 99.98% bulk drops by >10x vs the naive full-range scale
    assert int8_mse(bulk, th) < int8_mse(bulk, float(onp.abs(arr).max())) / 10


def test_quantize_net_entropy_beats_naive_on_outlier_input():
    from mxnet_tpu.contrib.quantization import quantize_net
    rng = onp.random.RandomState(1)

    def make_net():
        onp.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu", in_units=16),
                nn.Dense(8, in_units=32))
        net.initialize()
        for p in net.collect_params().values():
            p.set_data(nd.array(onp.random.RandomState(
                p.shape[0]).uniform(-0.3, 0.3, p.shape).astype("float32")))
        return net

    # calibration data: gaussian bulk + rare extreme spikes
    batches = []
    for _ in range(6):
        x = rng.randn(32, 16).astype("float32")
        x[0, 0] = 300.0  # one extreme outlier element per batch
        batches.append(nd.array(x))
    x_eval = nd.array(rng.randn(64, 16).astype("float32"))

    ref = make_net()
    want = ref(x_eval).asnumpy()

    outs = {}
    for mode in ("naive", "entropy"):
        qnet = make_net()
        quantize_net(qnet, list(batches), calib_mode=mode,
                     num_calib_batches=6)
        outs[mode] = qnet(x_eval).asnumpy()
    err_naive = float(((outs["naive"] - want) ** 2).mean())
    err_entropy = float(((outs["entropy"] - want) ** 2).mean())
    assert err_entropy < err_naive, (err_entropy, err_naive)


def test_quantize_net_rejects_unknown_mode():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4))
    net.initialize()
    from mxnet_tpu.contrib.quantization import quantize_net
    with pytest.raises(MXNetError):
        quantize_net(net, [nd.ones((2, 4))], calib_mode="klentropy")
