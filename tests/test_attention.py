"""Flash/ring attention + transformer/BERT tests.

Numeric oracle: unfused softmax(QK^T)V in f32 (attention_reference), the
same check style the reference uses for fused vs unfused ops (SURVEY §4).
Ring attention runs on the virtual 8-device CPU mesh — the TPU-world analog
of the reference's multi-process localhost collectives tests.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops import attention as A


def _rand_qkv(b=2, h=4, s=64, d=32, seed=0):
    rng = onp.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_forward(causal):
    q, k, v = _rand_qkv()
    ref = A.attention_reference(q, k, v, causal=causal)
    out = A.flash_attention(q, k, v, causal=causal, use_pallas=False)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_pallas_interpret(causal):
    # The Pallas TPU kernel, run through the interpreter on CPU.
    q, k, v = _rand_qkv(s=96, d=24)  # odd sizes exercise padding
    ref = A.attention_reference(q, k, v, causal=causal)
    out = A._flash_fwd_pallas(q, k, v, causal, 24 ** -0.5, interpret=True)[0]
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_flash_attention_cross_length():
    q, k, v = _rand_qkv()
    q = q[:, :, :32]
    ref = A.attention_reference(q, k, v, causal=True)
    out = A.flash_attention(q, k, v, causal=True, use_pallas=False)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_flash_attention_grad():
    q, k, v = _rand_qkv(s=32, d=16)

    def loss_flash(q_, k_, v_):
        return jnp.sum(A.flash_attention(q_, k_, v_, causal=True,
                                         use_pallas=False) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(A.attention_reference(q_, k_, v_, causal=True) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_8dev(causal):
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(onp.array(devs[:8]), ("sp",))
    q, k, v = _rand_qkv(s=64)
    ref = A.attention_reference(q, k, v, causal=causal)
    out = A.ring_attention_sharded(q, k, v, mesh, axis="sp", causal=causal)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_flash_attention_valid_length():
    # Per-sample key padding via the fused blockwise path must match an
    # explicitly-masked unfused reference.
    q, k, v = _rand_qkv(b=3, s=16, d=8)
    vl = jnp.asarray([16, 9, 4], jnp.float32)
    out = A.flash_attention(q, k, v, valid_length=vl)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (8 ** -0.5)
    keep = jnp.arange(16)[None, None, None, :] < vl[:, None, None, None]
    p = jax.nn.softmax(jnp.where(keep, s, -1e30), axis=-1)
    ref = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)
    # and it is differentiable (vl gets a zero cotangent)
    g = jax.grad(lambda q_: jnp.sum(
        A.flash_attention(q_, k, v, valid_length=vl) ** 2))(q)
    assert onp.isfinite(onp.asarray(g)).all()


def test_masked_attention_respects_causal():
    # causal=True must still hold when an additive mask is supplied
    from mxnet_tpu.gluon.nn.transformer import _masked_attention
    q, k, v = _rand_qkv(s=12, d=8)
    zero_mask = jnp.zeros((1, 1, 1, 12), jnp.float32)
    out = _masked_attention(q, k, v, zero_mask, 8 ** -0.5, causal=True)
    ref = A.attention_reference(q, k, v, causal=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_multi_head_attention_layer():
    from mxnet_tpu.gluon import nn
    mha = nn.MultiHeadAttention(units=32, num_heads=4)
    mha.initialize()
    x = mx.nd.array(onp.random.randn(2, 10, 32).astype("float32"))
    out = mha(x)
    assert out.shape == (2, 10, 32)
    # padding mask changes masked positions' influence, not output shape
    mask = onp.zeros((2, 1, 1, 10), "float32")
    mask[:, :, :, 5:] = -1e30
    out_m = mha(x, mask=mx.nd.array(mask))
    assert out_m.shape == (2, 10, 32)
    assert not onp.allclose(out.asnumpy(), out_m.asnumpy())
    # valid_length (fused path) must agree with the equivalent additive mask
    out_vl = mha(x, valid_length=mx.nd.array(onp.array([5, 5], "float32")))
    onp.testing.assert_allclose(out_vl.asnumpy(), out_m.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_transformer_encoder_grad_flows():
    from mxnet_tpu.gluon import nn
    enc = nn.TransformerEncoder(num_layers=2, units=16, hidden_size=32,
                                num_heads=2)
    enc.initialize()
    x = mx.nd.array(onp.random.randn(2, 8, 16).astype("float32"))
    with mx.autograd.record():
        out = enc(x)
        loss = (out * out).sum()
    loss.backward()
    params = enc.collect_params()
    grads = [p.grad() for p in params.values() if p.grad_req != "null"]
    assert any(float(onp.abs(g.asnumpy()).sum()) > 0 for g in grads)


def test_bert_forward_and_mlm():
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.bert_small_test(use_decoder=True)
    net.initialize()
    tokens = mx.nd.array(onp.random.randint(0, 128, (2, 12)), dtype="int32")
    vlen = mx.nd.array(onp.array([12, 7]), dtype="int32")
    seq, pooled, scores = net(tokens, None, vlen)
    assert seq.shape == (2, 12, 32)
    assert pooled.shape == (2, 32)
    assert scores.shape == (2, 12, 128)


@pytest.mark.slow
def test_bert_classifier_train_step():
    from mxnet_tpu.gluon.model_zoo import bert
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    net = bert.BERTClassifier(bert.bert_small_test(), num_classes=3)
    net.initialize()
    tokens = mx.nd.array(onp.random.randint(0, 128, (4, 10)), dtype="int32")
    y = mx.nd.array(onp.array([0, 1, 2, 1]), dtype="int32")
    loss_fn = SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3})
    with mx.autograd.record():
        logits = net(tokens)
        loss = loss_fn(logits, y)
    loss.backward()
    trainer.step(4)
    assert onp.isfinite(float(loss.mean().asnumpy()))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_pallas_backward(causal):
    # FlashAttention-2-style Pallas backward (interpret mode) vs the
    # unfused reference VJP
    q, k, v = _rand_qkv(b=2, h=2, s=48, d=16, seed=3)

    def loss_pallas(q_, k_, v_):
        return jnp.sum(A._flash_tpu(q_, k_, v_, causal, 16 ** -0.5,
                                    True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(A.attention_reference(q_, k_, v_,
                                             causal=causal) ** 2)

    g = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_flash_attention_pallas_backward_cross_length():
    q, k, v = _rand_qkv(b=1, h=2, s=64, d=8, seed=4)
    q = q[:, :, :24]

    def loss_pallas(q_, k_, v_):
        return jnp.sum(A._flash_tpu(q_, k_, v_, True, 8 ** -0.5, True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(A.attention_reference(q_, k_, v_, causal=True) ** 2)

    g = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_pallas_backward_multiblock(causal):
    # Small explicit blocks force a 3x4 grid: exercises cross-block
    # accumulator init/+=/finalize and the causal block-skip predicate in
    # both backward kernels (not reachable with default 512 blocks on CI
    # sizes).
    q, k, v = _rand_qkv(b=1, h=2, s=48, d=8, seed=5)
    k = k[:, :, :64] if k.shape[2] >= 64 else k
    sm = 8 ** -0.5

    o, lse = A._flash_fwd_pallas(q, k, v, causal, sm, block_q=16,
                                 block_k=16, interpret=True)
    rng = onp.random.RandomState(9)
    do = jnp.asarray(rng.randn(*o.shape).astype("float32"))
    dq, dk, dv = A._flash_bwd_pallas(q, k, v, o, lse, do, causal, sm,
                                     block_q=16, block_k=16, interpret=True)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: A.attention_reference(q_, k_, v_, causal=causal,
                                                 sm_scale=sm), q, k, v)
    rq, rk, rv = vjp(do)
    for a, b in zip((dq, dk, dv), (rq, rk, rv)):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_flash_pallas_bf16_interpret():
    """bf16 flash attention (interpret mode): the dtype the AMP path now
    feeds the Pallas kernels on TPU — fwd matches the reference, bwd
    grads are finite and keep the activation dtype."""
    rng = onp.random.RandomState(0)
    B, H, S, D = 2, 2, 64, 32
    q, k, v, do = (jnp.asarray(rng.randn(B, H, S, D), jnp.bfloat16)
                   for _ in range(4))
    out, lse = A._flash_fwd_pallas(q, k, v, causal=True,
                                   sm_scale=D ** -0.5, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = A.attention_reference(q, k, v, causal=True, sm_scale=D ** -0.5)
    onp.testing.assert_allclose(onp.asarray(out, "float32"),
                                onp.asarray(ref, "float32"),
                                rtol=3e-2, atol=3e-2)
    dq, dk, dv = A._flash_bwd_pallas(q, k, v, out, lse, do, causal=True,
                                     sm_scale=D ** -0.5, interpret=True)
    for g in (dq, dk, dv):
        assert g.dtype == jnp.bfloat16
        assert onp.isfinite(onp.asarray(g, "float32")).all()
