"""Systematic mx.np dtype-promotion parity vs NumPy (the reference's
"_npi numpy semantics" contract: src/operator/numpy/ mirrors NumPy
broadcasting AND dtype rules). Covers the binary-op promotion lattice over
the dtypes both stacks support, array-array and array-scalar, plus the
known documented deviations (float64 default is narrowed to float32 on
TPU unless x64 is enabled). Also tests the Mixed/Load initializers and
HybridSequentialRNNCell added for reference-parity."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

# dtype pairs both numpy and the TPU build express natively (float64 is
# traded for float32 on TPU by design — PARITY.md documents the deviation,
# so it is excluded from the exact-promotion matrix)
_DTYPES = ["bool", "int8", "uint8", "int32", "float16", "float32"]
_OPS = [("add", onp.add), ("multiply", onp.multiply),
        ("subtract", onp.subtract)]


def _sample(dt):
    if dt == "bool":
        return onp.array([True, False, True])
    if "int" in dt:
        return onp.array([1, 2, 3], dtype=dt)
    return onp.array([0.5, 1.5, 2.5], dtype=dt)


@pytest.mark.parametrize("a_dt", _DTYPES)
@pytest.mark.parametrize("b_dt", _DTYPES)
def test_binary_promotion_matches_numpy(a_dt, b_dt):
    if a_dt == "bool" and b_dt == "bool":
        ref_dt = "bool"  # numpy subtract forbids bool-bool; check add only
        got = (mx.np.array(_sample(a_dt)) + mx.np.array(_sample(b_dt)))
        assert str(got.dtype) == "bool"
        return
    a, b = _sample(a_dt), _sample(b_dt)
    for name, np_op in _OPS:
        if "bool" in (a_dt, b_dt) and name == "subtract":
            continue
        want = np_op(a, b)
        got = getattr(mx.np, name)(mx.np.array(a), mx.np.array(b))
        want_dt = str(want.dtype)
        if want_dt == "float64":
            want_dt = "float32"  # documented TPU narrowing
        if want_dt == "int64":
            want_dt = "int32"    # x64 disabled
        if "float16" in (a_dt, b_dt) and a_dt != b_dt \
                and "float32" not in (a_dt, b_dt):
            # documented deviation (PARITY.md): int <op> float16 keeps
            # float16 on the XLA promotion lattice, where NumPy widens to
            # float64 because the int range exceeds f16
            want_dt = "float16"
        assert str(got.dtype) == want_dt, \
            f"{name}({a_dt},{b_dt}): {got.dtype} vs numpy {want.dtype}"
        onp.testing.assert_allclose(got.asnumpy().astype("float64"),
                                    want.astype("float64"), rtol=1e-3)


@pytest.mark.parametrize("scalar", [2, 2.5, True])
@pytest.mark.parametrize("a_dt", ["int32", "float32", "float16"])
def test_scalar_promotion_matches_numpy(a_dt, scalar):
    """Python scalars are weakly typed: int32 + 2 stays int32,
    int32 + 2.5 promotes to float (NumPy 2 / JAX semantics)."""
    a = _sample(a_dt)
    want = a + scalar
    got = mx.np.array(a) + scalar
    want_dt = {"float64": "float32", "int64": "int32"}.get(
        str(want.dtype), str(want.dtype))
    assert str(got.dtype) == want_dt, (a_dt, scalar, got.dtype, want.dtype)
    onp.testing.assert_allclose(got.asnumpy().astype("float64"),
                                want.astype("float64"), rtol=1e-3)


def test_comparison_and_division_dtypes():
    i = mx.np.array(onp.array([1, 2], "int32"))
    assert str((i > 1).dtype) == "bool"
    assert "float" in str((i / 2).dtype)  # true division promotes ints
    f16 = mx.np.array(onp.array([1.0], "float16"))
    f32 = mx.np.array(onp.array([1.0], "float32"))
    assert str((f16 + f32).dtype) == "float32"


def test_mixed_initializer_dispatch():
    from mxnet_tpu import initializer as init
    from mxnet_tpu.gluon import nn
    net = nn.Dense(8, in_units=4)
    net.initialize(init=init.Mixed(
        [".*weight", ".*"], [init.Constant(2.0), init.Zero()]))
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((8, 4), 2.0))
    onp.testing.assert_allclose(net.bias.data().asnumpy(), onp.zeros(8))
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="pattern"):
        nn.Dense(2, in_units=2).initialize(
            init=init.Mixed(["nomatch.*"], [init.Zero()]))


def test_load_initializer_roundtrip(tmp_path):
    from mxnet_tpu import initializer as init
    from mxnet_tpu.gluon import nn
    src = nn.Dense(4, in_units=3)
    src.initialize()
    params = {"weight": src.weight.data(), "bias": src.bias.data()}
    dst = nn.Dense(4, in_units=3)
    dst.initialize(init=init.Load(params))
    onp.testing.assert_allclose(dst.weight.data().asnumpy(),
                                src.weight.data().asnumpy())
    # shape mismatch raises with the parameter name
    from mxnet_tpu.base import MXNetError
    bad = nn.Dense(5, in_units=3)
    with pytest.raises(MXNetError, match="weight"):
        bad.initialize(init=init.Load(params))
    # missing name falls to default_init
    extra = nn.Dense(4, in_units=3)
    extra.initialize(init=init.Load({"weight": params["weight"]},
                                    default_init=init.Zero()))
    onp.testing.assert_allclose(extra.bias.data().asnumpy(), onp.zeros(4))


def test_mixed_and_load_override_suffix_rules():
    """Reference Mixed/Load override __call__ so pattern / saved-array
    dispatch beats the base bias/gamma suffix zeros-ones rules — a
    restored bias must not be silently re-zeroed."""
    from mxnet_tpu import initializer as init
    saved_bias = nd.array(onp.array([1.5, -2.5], "float32"))
    ld = init.Load({"fc0_bias": saved_bias})
    arr = nd.zeros((2,))
    ld("fc0_bias", arr)
    onp.testing.assert_allclose(arr.asnumpy(), [1.5, -2.5])

    # Mixed dispatches by pattern, then the MATCHED initializer applies its
    # own rules (reference Mixed.__call__ -> inner __call__): a plain
    # Constant still suffix-zeros a bias, while Load restores it
    mix = init.Mixed([".*bias", ".*"],
                     [init.Load({"net_bias": saved_bias}), init.Zero()])
    arr2 = nd.zeros((2,))
    mix("net_bias", arr2)
    onp.testing.assert_allclose(arr2.asnumpy(), [1.5, -2.5])
    const_mix = init.Mixed([".*bias"], [init.Constant(3.0)])
    arr3 = nd.zeros((2,))
    const_mix("net_bias", arr3)
    onp.testing.assert_allclose(arr3.asnumpy(), [0.0, 0.0])  # ref semantics


def test_hybrid_sequential_rnn_cell():
    from mxnet_tpu.gluon import rnn
    cell = rnn.HybridSequentialRNNCell()
    cell.add(rnn.LSTMCell(8, input_size=4))
    cell.add(rnn.GRUCell(6, input_size=8))
    cell.initialize()
    x = nd.array(onp.random.RandomState(0).randn(2, 4).astype("float32"))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 6)
    assert len(new_states) == 3  # LSTM (h, c) + GRU (h)
