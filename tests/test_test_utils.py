"""test_utils reference-tail helpers.

Reference analog: the helpers of python/mxnet/test_utils.py that the
reference's own unit tests consume (tolerances, random builders,
assertion variants, statistical generator checks, optimizer
comparison, data fixtures).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, test_utils as tu


def test_tolerance_helpers():
    assert tu.get_rtol(None, onp.float32) == tu.default_rtols()[
        onp.dtype(onp.float32)]
    assert tu.get_rtol(0.5) == 0.5
    assert tu.get_atol(None, onp.float16) == 1e-1
    x16 = onp.ones(3, onp.float16)
    x64 = onp.ones(3, onp.float64)
    rtol, atol = tu.get_tols(x16, x64, None, None)
    assert rtol == 1e-2 and atol == 1e-1  # the looser of the two
    assert tu.get_etol(None) == 0 and tu.get_etol(0.1) == 0.1


def test_random_builders():
    a = tu.random_arrays((3, 4))
    assert a.shape == (3, 4) and a.dtype == onp.float32
    l = tu.random_arrays((2,), (3,))
    assert len(l) == 2
    s = tu.random_sample(list(range(10)), 4)
    assert len(s) == 4 and len(set(s)) == 4
    assert tu.create_2d_tensor(3, 4).shape == (3, 4)
    assert tu.create_vector(5).tolist() == [0, 1, 2, 3, 4]
    x, y = tu.rand_coord_2d(0, 5, 10, 15)
    assert 0 <= x < 5 and 10 <= y < 15


def test_sparse_builders():
    arr, (data, indices) = tu.rand_sparse_ndarray((8, 4), "row_sparse",
                                                  density=0.5)
    assert arr.shape == (8, 4)
    arr2 = tu.create_sparse_array((6, 3), "row_sparse",
                                  rsp_indices=[1, 4], data_init=2.0)
    d = arr2.asnumpy()
    assert (d[1] == 2.0).all() and (d[0] == 0).all()
    z = tu.create_sparse_array_zd((4, 2), "row_sparse", density=0)
    assert (z.asnumpy() == 0).all()


def test_assertion_variants():
    a = onp.array([1.0, 2.0, 3.0, 4.0])
    b = a.copy()
    b[0] = 99.0
    with pytest.raises(AssertionError):
        tu.assert_almost_equal_with_err(a, b, etol=0.1)
    tu.assert_almost_equal_with_err(a, b, etol=0.3)  # 25% mismatch ok
    an = a.copy()
    bn = a.copy()
    an[1] = onp.nan
    bn[1] = onp.nan
    tu.assert_almost_equal_ignore_nan(an, bn)
    tu.assert_exception(lambda: 1 / 0, ZeroDivisionError)
    with pytest.raises(AssertionError):
        tu.assert_exception(lambda: None, ValueError)
    tu.assert_allclose(nd.array(a), a)


def test_np_reduce_and_collapse():
    d = onp.arange(24.0).reshape(2, 3, 4)
    r = tu.np_reduce(d, axis=(0, 2), keepdims=True,
                     numpy_reduce_func=onp.sum)
    onp.testing.assert_allclose(r, d.sum(axis=(0, 2), keepdims=True))
    c = tu.collapse_sum_like(onp.ones((2, 3, 4)), (3, 1))
    assert c.shape == (3, 1)
    onp.testing.assert_allclose(c, 8.0)


def test_statistical_checks():
    onp.random.seed(0)
    gen = lambda n: onp.random.normal(0, 1.0, size=n)
    assert tu.mean_check(gen, 0, 1.0, nsamples=200000)
    assert tu.var_check(gen, 1.0, nsamples=200000)
    import scipy.stats as ss
    buckets, probs = tu.gen_buckets_probs_with_ppf(
        lambda x: ss.norm.ppf(x, 0, 1), 5)
    assert len(buckets) == 5 and abs(sum(probs) - 1.0) < 1e-9
    tu.verify_generator(gen, buckets, probs, nsamples=50000, nrepeat=3)
    bad = lambda n: onp.random.normal(3.0, 1.0, size=n)  # wrong mean
    with pytest.raises(AssertionError):
        tu.verify_generator(bad, buckets, probs, nsamples=50000,
                            nrepeat=3)
    # discrete buckets
    dgen = lambda n: onp.random.randint(0, 4, size=n)
    p, obs, exp = tu.chi_square_check(dgen, [0, 1, 2, 3], [0.25] * 4,
                                      nsamples=50000)
    assert p > 0.01


def test_compare_optimizer():
    onp.random.seed(0)
    o1 = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    o2 = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    tu.compare_optimizer(o1, o2, [(4, 3), (5,)], "float32")
    o3 = mx.optimizer.create("sgd", learning_rate=0.2)
    with pytest.raises(AssertionError):
        tu.compare_optimizer(o1, o3, [(4, 3)], "float32")


def test_check_gluon_hybridize_consistency():
    from mxnet_tpu.gluon import nn

    def builder():
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(2))
        return net

    tu.check_gluon_hybridize_consistency(
        builder, [nd.array(onp.random.rand(3, 4).astype("float32"))])


def test_matrix_generators():
    q = tu.new_orthonormal_matrix_2d(4, 4)
    onp.testing.assert_allclose(q @ q.T, onp.eye(4), atol=1e-8)
    m = tu.new_matrix_with_real_eigvals_2d(5)
    assert onp.abs(onp.linalg.eigvals(m).imag).max() < 1e-9
    mn = tu.new_matrix_with_real_eigvals_nd((2, 3, 3))
    assert mn.shape == (2, 3, 3)
    s = tu.new_sym_matrix_with_real_eigvals_2d(4)
    onp.testing.assert_allclose(s, s.T)


def test_mnist_fixtures(tmp_path):
    m = tu.get_mnist(path=str(tmp_path))  # no files -> synthetic
    assert m["train_data"].shape[1:] == (1, 28, 28)
    assert m["train_data"].dtype == onp.float32
    assert set(onp.unique(m["train_label"])) <= set(range(10))
    # ubyte writer round-trips through the real IDX reader
    tu.get_mnist_ubyte(path=str(tmp_path))
    m2 = tu.get_mnist(path=str(tmp_path))
    assert m2["train_data"].shape == m["train_data"].shape
    tr, val = tu.get_mnist_iterator(batch_size=32, input_shape=(784,),
                                    path=str(tmp_path))
    batch = next(iter(tr))
    assert batch.data[0].shape == (32, 784)
    # sharded parts are disjoint and cover the whole train set
    n_total = len(tu.get_mnist(path=str(tmp_path))["train_label"])
    sizes = []
    for i in range(3):
        tri, _ = tu.get_mnist_iterator(batch_size=1, input_shape=(784,),
                                       num_parts=3, part_index=i,
                                       path=str(tmp_path))
        sizes.append(sum(1 for _ in tri))
    assert sum(sizes) == n_total and max(sizes) - min(sizes) <= 1
    with pytest.raises(mx.MXNetError):
        tu.get_mnist_iterator(1, (784,), num_parts=3, part_index=5,
                              path=str(tmp_path))
    with pytest.raises(mx.MXNetError, match="cifar"):
        tu.get_cifar10(path=str(tmp_path))
    assert tu.get_im2rec_path().endswith("im2rec.py")


def test_shuffle_csr_column_indices():
    arr, _ = tu.rand_sparse_ndarray((6, 4), "csr", density=0.7)
    indptr = arr.indptr.asnumpy()
    before = arr.indices.asnumpy().copy()
    out = tu.shuffle_csr_column_indices(arr)
    after = out.indices.asnumpy()
    assert after.shape == before.shape
    # per-row membership preserved even though order may change
    for i in range(len(indptr) - 1):
        onp.testing.assert_array_equal(
            onp.sort(after[indptr[i]:indptr[i + 1]]),
            onp.sort(before[indptr[i]:indptr[i + 1]]))


def test_misc_helpers(tmp_path):
    assert tu.list_gpus() == []
    assert tu.has_tvm_ops() is False and tu.is_op_runnable() is True
    a = nd.array(onp.ones(3, "float32"))
    assert tu.same_array(a, a)
    assert not tu.same_array(a, nd.array(onp.ones(3, "float32")))
    out = tu.assign_each(onp.array([1.0, -2.0]), lambda x: x * 2)
    onp.testing.assert_allclose(out, [2.0, -4.0])
    out2 = tu.assign_each2(onp.array([1.0]), onp.array([3.0]),
                           lambda x, y: x + y)
    onp.testing.assert_allclose(out2, [4.0])
    import sys
    with tu.discard_stderr():
        print("hidden", file=sys.stderr)
    sec = tu.check_speed(lambda: nd.array(onp.ones(4)), n=3, warmup=1)
    assert sec > 0
    assert tu.check_speed(lambda: 1, n=2, warmup=0) >= 0  # warmup=0 ok
    it = tu.DummyIter(tu.get_mnist_iterator(
        8, (784,), path=str(tmp_path))[0])
    it.reset()  # epoch-loop compatible no-op
    assert next(it) is next(it)


def test_symbolic_helpers():
    import mxnet_tpu.symbol as sym
    x1 = sym.Variable("a")
    y1 = sym.relu(sym.exp(x1))
    x2 = sym.Variable("b")
    y2 = sym.relu(sym.exp(x2))
    y3 = sym.exp(sym.relu(x2))
    assert tu.same_symbol_structure(y1, y2)
    assert not tu.same_symbol_structure(y1, y3)

    tu.check_symbolic_backward(
        lambda a: (a * a).sum(),
        [onp.array([1.0, 2.0], "float32")],
        [onp.array(1.0, "float32")],
        [onp.array([2.0, 4.0], "float32")])
