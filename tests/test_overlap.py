"""Communication overlap & latency hiding (PR 17).

Covers the exposed-communication analysis pass (analysis/overlap.py)
on canned schedules — sync dependency-slack windows, async start/done
spans, movement transparency, root-escape deadlines, taint exclusion —
the baseline regression gate (unit bands + the tier-1 ``lint``-marked
sweep against tests/fixtures/overlap_baselines.json), and the bucketed
ZeRO gradient path it measures: reverse-topological bucket schedules,
the bucketed reduce-scatter/all-gather routing with non-divisible
tails, bit-exact loss/param parity of bucketed vs monolithic updates,
the per-payload-byte comm-cost invariant (N buckets of B bytes cost
one collective of N*B bytes), the double-buffered pipeline permute,
the transfer-guard-armed pipelined run, and the autotuner's
exposed-comm scoring term.

Acceptance bar of ISSUE 17: the bucketed zero program on the virtual
dp=8 mesh measures overlap_fraction > 0 where the serial monolithic
baseline measures ~0 (zero at metric resolution: the only residual
hider is the nanoseconds-scale loss tail the scheduler may park after
the weight all-gather).
"""
import json
import math
import os
import textwrap

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.analysis import overlap as aoverlap
from mxnet_tpu.analysis import sharding as asharding
from mxnet_tpu.analysis.report import CollectiveOp, CollectiveStats
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.fused_step import zero_bucket_schedule
from mxnet_tpu.parallel import make_mesh, shard_batch
from mxnet_tpu.parallel.collectives import (allgather_bucketed,
                                            reduce_scatter_bucketed)
from mxnet_tpu.telemetry import names as tn
from mxnet_tpu.tuning import space as tspace

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
BASELINES = os.path.join(FIXTURES, "overlap_baselines.json")

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")

DP = 4


# ---------------------------------------------------------------------------
# canned-schedule censuses: window grammar, hider accounting
# ---------------------------------------------------------------------------

# a collective whose value reaches the ROOT tuple through plumbing
# only (bitcast): its deadline is program completion, so the trailing
# independent dot hides it.  Hiders must be flops-bearing kernels —
# the fusion census prices standalone dots, not standalone plumbing.
_CANNED_ROOT_ESCAPE = textwrap.dedent("""\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[128,128]{1,0}, f32[128,128]{1,0})->(f32[16,128]{1,0}, f32[128,128]{1,0})}

ENTRY %main (p0: f32[128,128], p1: f32[128,128]) -> (f32[16,128], f32[128,128]) {
  %p0 = f32[128,128]{1,0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  %reduce-scatter.1 = f32[16,128]{1,0} reduce-scatter(f32[128,128]{1,0} %p0), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, dimensions={0}, to_apply=%add
  %dot.1 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %p1, f32[128,128]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %bitcast.1 = f32[16,128]{1,0} bitcast(f32[16,128]{1,0} %reduce-scatter.1)
  ROOT %tuple.1 = (f32[16,128]{1,0}, f32[128,128]{1,0}) tuple(f32[16,128]{1,0} %bitcast.1, f32[128,128]{1,0} %dot.1)
}
""")

# the dot CONSUMES the reduce-scatter: the window closes at the
# consumer and the tainted dot cannot hide its own producer
_CANNED_DEPENDENT = textwrap.dedent("""\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[128,128]{1,0}, f32[128,128]{1,0})->f32[16,128]{1,0}}

ENTRY %main (p0: f32[128,128], p1: f32[128,128]) -> f32[16,128] {
  %p0 = f32[128,128]{1,0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  %reduce-scatter.1 = f32[16,128]{1,0} reduce-scatter(f32[128,128]{1,0} %p0), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, dimensions={0}, to_apply=%add
  ROOT %dot.1 = f32[16,128]{1,0} dot(f32[16,128]{1,0} %reduce-scatter.1, f32[128,128]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
""")

# async start/done pair: the window is the scheduler's explicit span,
# and the dot placed inside it hides the wire time
_CANNED_ASYNC = textwrap.dedent("""\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[256]{0}, f32[128,128]{1,0})->(f32[256]{0}, f32[128,128]{1,0})}

ENTRY %main (p0: f32[256], p1: f32[128,128]) -> (f32[256], f32[128,128]) {
  %p0 = f32[256]{0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  %all-reduce-start.1 = f32[256]{0} all-reduce-start(f32[256]{0} %p0), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add
  %dot.1 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %p1, f32[128,128]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %all-reduce-done.1 = f32[256]{0} all-reduce-done(f32[256]{0} %all-reduce-start.1)
  ROOT %tuple.1 = (f32[256]{0}, f32[128,128]{1,0}) tuple(f32[256]{0} %all-reduce-done.1, f32[128,128]{1,0} %dot.1)
}
""")

# a movement-only fusion (slice writeback) consuming the collective is
# followed TRANSPARENTLY: it neither closes the window nor counts as a
# hider, so the trailing independent dot still hides the wire time
_CANNED_MOVEMENT = textwrap.dedent("""\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[128,128]{1,0}, f32[128,128]{1,0})->(f32[8,128]{1,0}, f32[128,128]{1,0})}

%fused_movement (param_0.1: f32[16,128]) -> f32[8,128] {
  %param_0.1 = f32[16,128]{1,0} parameter(0)
  ROOT %slice.1 = f32[8,128]{1,0} slice(f32[16,128]{1,0} %param_0.1), slice={[0:8], [0:128]}
}

ENTRY %main (p0: f32[128,128], p1: f32[128,128]) -> (f32[8,128], f32[128,128]) {
  %p0 = f32[128,128]{1,0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  %reduce-scatter.1 = f32[16,128]{1,0} reduce-scatter(f32[128,128]{1,0} %p0), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, dimensions={0}, to_apply=%add
  %fusion.1 = f32[8,128]{1,0} fusion(f32[16,128]{1,0} %reduce-scatter.1), kind=kLoop, calls=%fused_movement
  %dot.1 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %p1, f32[128,128]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %tuple.1 = (f32[8,128]{1,0}, f32[128,128]{1,0}) tuple(f32[8,128]{1,0} %fusion.1, f32[128,128]{1,0} %dot.1)
}
""")


def test_root_escape_window_extends_to_schedule_end():
    rep = aoverlap.overlap_census(_CANNED_ROOT_ESCAPE, num_devices=8)
    assert rep.scheduled and rep.n_collectives == 1
    [w] = rep.windows
    assert w.kind == "reduce_scatter" and not w.is_async
    # value escapes through bitcast into the root tuple: deadline is
    # program completion (end == schedule length, 6 entry ops)
    assert w.window == (0, 6)
    assert w.n_hiders == 1                  # the independent dot
    assert w.comm_s > 0 and w.hide_s > 0
    assert w.exposed_s == pytest.approx(max(0.0, w.comm_s - w.hide_s))
    assert rep.overlap_fraction > 0.0


def test_dependent_consumer_closes_window_and_cannot_hide():
    rep = aoverlap.overlap_census(_CANNED_DEPENDENT, num_devices=8)
    [w] = rep.windows
    # the dot NEEDS the bytes: window closes there, and the tainted
    # consumer is never credited as a hider
    assert w.window[1] == 3 and w.n_hiders == 0
    assert w.hide_s == 0.0
    assert w.exposed_s == pytest.approx(w.comm_s)
    assert rep.overlap_fraction == pytest.approx(0.0)


def test_async_pair_window_is_start_done_span():
    rep = aoverlap.overlap_census(_CANNED_ASYNC, num_devices=8)
    assert rep.n_collectives == 1 and rep.n_async == 1
    [w] = rep.windows
    assert w.is_async
    # schedule: p0 p1 start dot done tuple -> span (2, 4)
    assert w.window == (2, 4)
    assert w.n_hiders == 1 and w.hide_s > 0


def test_movement_fusion_is_transparent_and_unpriced():
    secs, movement = aoverlap._kernel_tables(_CANNED_MOVEMENT)
    assert "fusion.1" in movement and "fusion.1" not in secs
    assert "dot.1" in secs
    rep = aoverlap.overlap_census(_CANNED_MOVEMENT, num_devices=8)
    [w] = rep.windows
    # slice writeback carries no deadline: window runs to the end and
    # the dot AFTER the movement fusion still hides the collective
    assert w.window == (0, 6)
    assert w.n_hiders == 1 and w.hide_s > 0


def test_report_brief_and_table():
    rep = aoverlap.overlap_census(_CANNED_ROOT_ESCAPE, num_devices=8)
    b = rep.brief()
    for k in ("exposed_comm_s", "total_comm_s", "overlap_fraction",
              "n_collectives", "n_async", "zero_bucket_bytes"):
        assert k in b
    d = rep.to_dict()
    assert d["scheduled"] is True and d["windows"]
    assert "exposed=" in rep.summary_line()
    assert "reduce-scatter.1" in rep.table_str()


def test_unparseable_hlo_degrades_to_empty_report():
    rep = aoverlap.overlap_census("not hlo at all", num_devices=8)
    assert rep.n_collectives == 0 and rep.total_comm_s == 0.0
    assert rep.overlap_fraction == 0.0


# ---------------------------------------------------------------------------
# bucket schedule (gluon/fused_step.py)
# ---------------------------------------------------------------------------

def _unit(padded, upd="float32", fwd="float32"):
    return {"padded": padded, "upd_dtype": upd, "dtypes": [fwd]}


def test_bucket_schedule_serial_is_single_bucket_in_order():
    units = [_unit(256), _unit(256), _unit(256)]     # 1 KiB each
    assert zero_bucket_schedule(units, 0) == [[0, 1, 2]]
    assert zero_bucket_schedule(units, None) == [[0, 1, 2]]
    assert zero_bucket_schedule(units, -1) == [[0, 1, 2]]


def test_bucket_schedule_reverse_topological_and_size_bounded():
    units = [_unit(256), _unit(256), _unit(256)]
    # backward produces the LAST unit's gradient first
    assert zero_bucket_schedule(units, 1024) == [[2], [1], [0]]
    assert zero_bucket_schedule(units, 2048) == [[2, 1], [0]]
    assert zero_bucket_schedule(units, 1 << 30) == [[2, 1, 0]]
    # bucket smaller than every unit: units still ship, one per bucket
    assert zero_bucket_schedule(units, 1) == [[2], [1], [0]]


def test_bucket_schedule_never_mixes_dtypes():
    units = [_unit(256), _unit(256, upd="float16"), _unit(256)]
    for bb in (0, 1 << 30):
        sched = zero_bucket_schedule(units, bb)
        covered = sorted(k for b in sched for k in b)
        assert covered == [0, 1, 2]
        for b in sched:
            assert len({str(units[k]["upd_dtype"]) for k in b}) == 1


# ---------------------------------------------------------------------------
# bucketed collective routing (parallel/collectives.py)
# ---------------------------------------------------------------------------

def _segs(lens, seed=0):
    rng = onp.random.RandomState(seed)
    return [jnp.asarray(rng.randn(n).astype("float32")) for n in lens]


def test_reduce_scatter_bucketed_non_divisible_tails():
    segs = _segs((5, 7, 4))
    calls = []

    def constrain(buf):
        calls.append(tuple(buf.shape))
        return buf

    outs = reduce_scatter_bucketed(segs, 4, constrain=constrain)
    # ONE (num_shards, S) buffer: ceil(5/4) + ceil(7/4) + ceil(4/4)
    assert calls == [(4, 2 + 2 + 1)]
    for seg, out in zip(segs, outs):
        n = seg.shape[0]
        pad = (-n) % 4
        onp.testing.assert_array_equal(
            onp.asarray(out),
            onp.pad(onp.asarray(seg), (0, pad)))


def test_allgather_bucketed_round_trips_with_orig_lens():
    lens = (5, 7, 4)
    segs = _segs(lens, seed=1)
    shards = reduce_scatter_bucketed(segs, 4)
    back = allgather_bucketed(shards, 4, orig_lens=lens)
    for seg, full in zip(segs, back):
        onp.testing.assert_array_equal(onp.asarray(full),
                                       onp.asarray(seg))
    # without orig_lens the scatter padding stays on
    padded = allgather_bucketed(shards, 4)
    assert [int(p.shape[0]) for p in padded] == [8, 8, 4]


def test_allgather_bucketed_rejects_non_divisible_segment():
    with pytest.raises(MXNetError, match="not divisible"):
        allgather_bucketed([jnp.arange(5.0)], 4)


# ---------------------------------------------------------------------------
# per-payload-byte comm cost: bucketing leaves the modeled budget alone
# ---------------------------------------------------------------------------

def test_comm_cost_invariant_under_bucketing():
    """N bucketed collectives of B bytes each must cost what ONE
    collective of N*B bytes costs — otherwise the cost model would
    punish the overlap-motivated bucket split."""
    profile = asharding.bandwidth_profile()

    def _op(kind, elements, name, decomposed=False):
        return CollectiveOp(kind=kind, name=name, elements=elements,
                            dtype="f32", axes=("dp",), group_size=8,
                            decomposed=decomposed)

    for kind in ("all_gather", "reduce_scatter", "all_reduce"):
        many = asharding.comm_cost(CollectiveStats(ops=[
            _op(kind, 1024, f"{kind}.{i}") for i in range(8)]), profile)
        one = asharding.comm_cost(CollectiveStats(ops=[
            _op(kind, 8 * 1024, kind)]), profile)
        assert many.total_s > 0
        assert math.isclose(many.total_s, one.total_s, rel_tol=1e-9), \
            (kind, many.total_s, one.total_s)


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def _rep(exposed, total):
    r = aoverlap.OverlapReport()
    r.exposed_comm_s = float(exposed)
    r.total_comm_s = float(total)
    return r


def test_check_baseline_one_sided_bands():
    base = {"leg": {"exposed_comm_s": 1e-5, "overlap_fraction": 0.5,
                    "tol_pct": 25}}
    # within both bands
    assert aoverlap.check_baseline(_rep(1.1e-5, 2e-5), base, "leg") == []
    # improvement is never a finding
    assert aoverlap.check_baseline(_rep(1e-7, 2e-5), base, "leg") == []
    # exposure regressed AND fraction collapsed: both bands fire
    worse = aoverlap.check_baseline(_rep(2e-5, 2.01e-5), base, "leg")
    assert len(worse) == 2
    assert all(f.rule == "overlap-regression" and f.checker == "overlap"
               for f in worse)


def test_check_baseline_absolute_floors():
    base = {"leg": {"exposed_comm_s": 0.0, "overlap_fraction": 0.02,
                    "tol_pct": 10}}
    # 1 us absolute band on exposed seconds near zero
    assert aoverlap.check_baseline(_rep(5e-7, 1e-4), base, "leg") == []
    bad = aoverlap.check_baseline(_rep(2e-6, 1e-4), base, "leg")
    assert len(bad) == 1 and "exposed comm" in bad[0].message
    # 0.05 absolute fraction floor: a 0.02 baseline fraction cannot
    # fire the fraction band even when the measured fraction is 0
    frac_only = [f for f in aoverlap.check_baseline(
        _rep(1e-7, 1e-7), base, "leg") if "fraction" in f.message]
    assert frac_only == []


def test_check_baseline_missing_leg_warns():
    out = aoverlap.check_baseline(_rep(0, 0), {}, "nope")
    assert len(out) == 1
    assert out[0].severity == "warn"
    assert "no overlap baseline" in out[0].message


def test_baseline_from_env_parses_path_and_leg(monkeypatch, tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"_comment": "x",
                             "legA": {"exposed_comm_s": 1e-6}}))
    monkeypatch.setenv("MXNET_OVERLAP_BASELINE", str(p))
    bl, leg = aoverlap.baseline_from_env()
    assert leg is None and set(bl) == {"legA"}
    monkeypatch.setenv("MXNET_OVERLAP_BASELINE", f"{p}:legA")
    bl, leg = aoverlap.baseline_from_env()
    assert leg == "legA" and "legA" in bl
    monkeypatch.delenv("MXNET_OVERLAP_BASELINE")
    assert aoverlap.baseline_from_env() is None
    monkeypatch.setenv("MXNET_OVERLAP_BASELINE",
                       str(tmp_path / "missing.json"))
    assert aoverlap.baseline_from_env() is None


def test_checked_in_fixture_has_both_legs():
    bl = aoverlap.load_baselines(BASELINES)
    assert set(bl) == {"zero-serial", "zero-bucketed"}
    for leg in bl.values():
        assert leg["exposed_comm_s"] > 0 and "tol_pct" in leg


# ---------------------------------------------------------------------------
# the acceptance programs: serial vs bucketed zero step on dp=8
# ---------------------------------------------------------------------------

def _acceptance_census(bucket_bytes):
    """The canonical overlap-analysis program of tools/diagnose.py
    --overlap and docs/PERF_NOTES.md \"Communication overlap\"."""
    onp.random.seed(3)
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, in_units=32, activation="relu"),
            nn.Dense(48, activation="relu"), nn.Dense(10))
    net.initialize()
    loss = gloss.SoftmaxCrossEntropyLoss()
    x = nd.array(onp.random.randn(64, 32).astype("float32"))
    y = nd.array(onp.random.randint(0, 10, size=(64,))
                 .astype("float32"))
    net(x)   # materialize deferred-init params off-mesh
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.01})
    step = trainer.compile_step(lambda a, b: loss(net(a), b))
    with tspace.trial({"zero.shard_min_size": 1,
                       "zero.bucket_bytes": bucket_bytes}):
        with make_mesh({"dp": 8}, jax.devices()[:8]) as m:
            xs, ys = shard_batch(x, m), shard_batch(y, m)
            step(xs, ys)
            hlo = step.lower_entry(xs, ys)["lowered"].compile().as_text()
            return aoverlap.overlap_census(hlo, mesh=m)


@pytest.fixture(scope="module")
def serial_census():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return _acceptance_census(0)


@pytest.fixture(scope="module")
def bucketed_census():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return _acceptance_census(16384)


@needs_mesh
def test_serial_baseline_measures_zero_overlap(serial_census):
    """The monolithic step (one packed collective over every unit)
    leaves nothing independent to hide behind: fraction ~0 at metric
    resolution (the lone residual hider is the nanoseconds-scale loss
    tail the scheduler may park after the weight all-gather)."""
    rep = serial_census
    assert rep.scheduled and rep.n_collectives >= 2
    assert rep.total_comm_s > 0
    assert rep.overlap_fraction < 1e-3, rep.summary_line()
    assert rep.exposed_comm_s >= 0.99 * rep.total_comm_s
    assert rep.zero_bucket_bytes == 0
    assert "dp" in rep.per_axis_total_s


@needs_mesh
def test_bucketed_step_overlaps_collectives(bucketed_census,
                                            serial_census):
    """The ISSUE 17 acceptance bar: bucket k's all-gather is free to
    run during bucket k+1's optimizer update, and the XLA scheduler
    demonstrably interleaves them — positive measured fraction."""
    rep = bucketed_census
    assert rep.overlap_fraction > 5e-3, rep.summary_line()
    assert rep.overlap_fraction > serial_census.overlap_fraction
    assert rep.n_collectives >= serial_census.n_collectives
    assert rep.zero_bucket_bytes == 16384
    hidden = [w for w in rep.windows
              if w.kind == "all_gather" and w.n_hiders > 0]
    assert hidden, rep.table_str()
    assert all(w.hide_s > 0 for w in hidden)


@pytest.mark.lint
@needs_mesh
def test_overlap_baseline_sweep(serial_census, bucketed_census):
    """The checked-in overlap posture of both legs, enforced against
    tests/fixtures/overlap_baselines.json on every tier-1 run (the
    sharding-baseline sweep's shape, one gate per leg)."""
    baselines = aoverlap.load_baselines(BASELINES)
    for leg, rep in (("zero-serial", serial_census),
                     ("zero-bucketed", bucketed_census)):
        findings = aoverlap.check_baseline(rep, baselines, leg)
        assert findings == [], [str(f) for f in findings]


@needs_mesh
def test_publish_refreshes_exposed_comm_gauges(bucketed_census):
    aoverlap.publish(bucketed_census)
    assert telemetry.value(tn.OVERLAP_FRACTION) == pytest.approx(
        bucketed_census.overlap_fraction)
    assert telemetry.value(tn.SHARDING_EXPOSED_COMM, "dp") == \
        pytest.approx(bucketed_census.per_axis_exposed_s["dp"])


# ---------------------------------------------------------------------------
# ProgramReport / analyze integration (cheap dp=4 toy)
# ---------------------------------------------------------------------------

def _toy_step(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(8, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(8,)).astype("int32"))
    return net, step, x, y


@needs_mesh
def test_program_report_carries_overlap_brief():
    _, step, x, y = _toy_step()
    with tspace.trial({"zero.shard_min_size": 1,
                       "zero.bucket_bytes": 16384}):
        with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
            xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
            step(xs, ys)
            rep = step.analyze(xs, ys)
    assert rep.overlap is not None
    assert rep.overlap.total_comm_s > 0
    assert rep.overlap.zero_bucket_bytes == 16384
    d = rep.to_dict()
    assert d["overlap"]["n_collectives"] == rep.overlap.n_collectives
    assert "overlap" in rep.summary()


@needs_mesh
def test_env_baseline_gate_fires_through_analyze(monkeypatch,
                                                 tmp_path):
    """MXNET_OVERLAP_BASELINE=<path>:<leg> rides analyze(): a baseline
    demanding an impossible fraction produces the overlap-regression
    finding on the ProgramReport."""
    p = tmp_path / "demanding.json"
    p.write_text(json.dumps({"toy": {"exposed_comm_s": 0.0,
                                     "overlap_fraction": 0.9,
                                     "tol_pct": 1}}))
    monkeypatch.setenv("MXNET_OVERLAP_BASELINE", f"{p}:toy")
    _, step, x, y = _toy_step(seed=5)
    with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        step(xs, ys)
        rep = step.analyze(xs, ys)
    hits = [f for f in rep.findings if f.rule == "overlap-regression"]
    assert hits and any("[toy]" in f.message for f in hits)


# ---------------------------------------------------------------------------
# numerics: bucketed update is BIT-EXACT vs the monolithic baseline
# ---------------------------------------------------------------------------

def _parity_run(opt, kwargs, bucket_bytes, min_size=None, steps=3):
    mx.random.seed(3)
    net = nn.HybridSequential()
    # sizes straddle DP divisibility (weight 15, bias 5) like the
    # canonical zero-shard fixture
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(5, in_units=8, activation="relu"))
    net.add(nn.Dense(3, in_units=5))
    net.initialize()
    trainer = Trainer(net.collect_params(), opt, dict(kwargs))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(8, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(8,)).astype("int32"))
    overrides = {"zero.bucket_bytes": bucket_bytes}
    if min_size is not None:
        overrides["zero.shard_min_size"] = min_size
    losses = []
    with tspace.trial(overrides):
        with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
            xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
            for _ in range(steps):
                losses.append(step(xs, ys).asnumpy())
    assert step.zero_sharded
    params = {k: p.data().asnumpy()
              for k, p in net.collect_params().items()}
    return losses, params


@needs_mesh
@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_bucketed_bit_exact_vs_monolithic(opt, kwargs):
    """Bucketing is pure routing: every bucket size — below the
    smallest param, and above the total gradient bytes — trains
    bit-identically to the serial monolithic step."""
    base_l, base_p = _parity_run(opt, kwargs, 0)
    for bb in (16, 1 << 30):
        l, p = _parity_run(opt, kwargs, bb)
        for a, b in zip(base_l, l):
            onp.testing.assert_array_equal(a, b)
        for k in base_p:
            onp.testing.assert_array_equal(base_p[k], p[k], err_msg=k)


@needs_mesh
def test_bucketed_bit_exact_multi_unit_min_size_one():
    """shard_min_size=1 makes EVERY param its own shard unit: several
    buckets of several units each, still bit-exact."""
    base_l, base_p = _parity_run("adam", {"learning_rate": 1e-2}, 0,
                                 min_size=1)
    l, p = _parity_run("adam", {"learning_rate": 1e-2}, 64, min_size=1)
    for a, b in zip(base_l, l):
        onp.testing.assert_array_equal(a, b)
    for k in base_p:
        onp.testing.assert_array_equal(base_p[k], p[k], err_msg=k)


# ---------------------------------------------------------------------------
# transfer guard: the bucketed pipelined hot loop stays sync-free
# ---------------------------------------------------------------------------

@needs_mesh
def test_bucketed_pipelined_loop_zero_unblessed_syncs(monkeypatch):
    """MXNET_TRANSFER_GUARD=raise + a 12-step prefetched run with the
    bucketed zero step: the only host syncs are the blessed window
    retires — bucketing adds no hidden device round-trips."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(3, in_units=8))
    net.initialize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    rng = onp.random.RandomState(7)
    x = nd.array(rng.randn(8, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(8,)).astype("int32"))
    with tspace.trial({"zero.bucket_bytes": 16384,
                       "zero.shard_min_size": 1}):
        with make_mesh({"dp": DP}, jax.devices()[:DP]):
            tguard.reset_sync_counts()
            tguard.clear_events()
            losses = []
            for bx, by in loop.prefetch((x, y) for _ in range(12)):
                losses.append(loop.step(bx, by))
            loop.synchronize()
    assert loop.compiled_step.zero_sharded
    counts = tguard.sync_counts()
    assert counts.get("wait_to_read", 0) == 0
    assert counts.get("window_retire", 0) == 12
    assert tguard.events() == []
    assert onp.isfinite(losses[-1].asnumpy()).all()


# ---------------------------------------------------------------------------
# double-buffered pipeline permutes (parallel/pipeline.py)
# ---------------------------------------------------------------------------

def _stage(p, x):
    return jnp.tanh(x @ p)


def test_double_buffer_pipeline_bit_exact():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from mxnet_tpu.parallel.pipeline import run_pipeline
    pp, d, b, m = 4, 6, 16, 8
    rng = onp.random.RandomState(5)
    stages = jnp.asarray(rng.randn(pp, d, d).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, d).astype("float32"))
    mesh = Mesh(onp.array(jax.devices()[:pp]), ("pp",))
    classic = run_pipeline(_stage, stages, x, m, mesh,
                           double_buffer=False)
    db = run_pipeline(_stage, stages, x, m, mesh, double_buffer=True)
    # one extra slot of latency, identical math: bit-exact outputs
    onp.testing.assert_array_equal(onp.asarray(classic),
                                   onp.asarray(db))


def test_double_buffer_env_default(monkeypatch):
    from mxnet_tpu.parallel import pipeline as pmod
    monkeypatch.delenv("MXNET_PIPELINE_DOUBLE_BUFFER", raising=False)
    assert pmod._double_buffer_default() is False
    for v in ("1", "true", "YES", "on"):
        monkeypatch.setenv("MXNET_PIPELINE_DOUBLE_BUFFER", v)
        assert pmod._double_buffer_default() is True
    for v in ("0", "false", "off", ""):
        monkeypatch.setenv("MXNET_PIPELINE_DOUBLE_BUFFER", v)
        assert pmod._double_buffer_default() is False


# ---------------------------------------------------------------------------
# autotuner scoring: exposed comm is a first-class term
# ---------------------------------------------------------------------------

@needs_mesh
def test_analytical_backend_scores_exposed_comm():
    from mxnet_tpu.tuning.measure import AnalyticalStepBackend
    _, step, x, y = _toy_step(seed=9)
    with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        step(xs, ys)
        backend = AnalyticalStepBackend(step, (xs, ys))
        res = backend.measure({"zero.bucket_bytes": 16384,
                               "zero.shard_min_size": 1})
    assert res.feasible
    for k in ("exposed_comm_s", "overlap_fraction",
              "zero_bucket_bytes"):
        assert k in res.detail, res.detail
    assert res.detail["zero_bucket_bytes"] == 16384
    assert 0.0 <= res.detail["overlap_fraction"] <= 1.0
    # the exposed term is additive in the score
    assert res.score >= res.detail["exposed_comm_s"]
