"""mx.tuning — the self-tuning performance autopilot
(docs/PERF_NOTES.md "Autotuner").

Pins the autopilot's contracts:

- tunable registry semantics: override > env > default resolution at
  every consumer seam (engine window, ZeRO floor, VMEM budget,
  serving knobs), trial-context restore, validity filtering;
- the search: coordinate descent converges on a planted optimum within
  the trial budget; infeasible and FAULTING candidates (OOM-style
  errors) are scored infeasible without aborting; successive halving
  re-measures survivors on noisy backends; the budget is a hard cap;
- the cache: atomic JSON round-trip (a second construction replays the
  winner with ZERO trials), signature change invalidates, corrupt DB
  files degrade to a re-tune, never a crash;
- the ``off|cached|on`` gate semantics;
- numerics safety: tuned configs are bit-exact on losses vs defaults
  (window depth + kernel block knobs are speed, never math), and the
  timed backend's state snapshot/restore leaves the model untouched;
- the ACCEPTANCE loop: the analytical backend sweeps a real
  ``CompiledTrainStep`` space, persists a winner keyed by the compile
  signature, and a fresh construction under ``MXNET_AUTOTUNE=cached``
  replays it with zero trials and bit-exact losses.
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
from mxnet_tpu.tuning import (AutotuneCache, MeasureResult, Tunable,
                              cache, measure, search, space)

IN, HIDDEN, CLASSES, BS = 16, 32, 8, 8


@pytest.fixture(autouse=True)
def clean_tuning(monkeypatch):
    """Every test starts with no tuned overrides, a memory-only default
    cache, the env gate off, and zeroed telemetry."""
    monkeypatch.delenv("MXNET_AUTOTUNE", raising=False)
    monkeypatch.delenv("MXNET_AUTOTUNE_CACHE", raising=False)
    monkeypatch.delenv("MXNET_AUTOTUNE_BUDGET_TRIALS", raising=False)
    monkeypatch.delenv("MXNET_AUTOTUNE_BACKEND", raising=False)
    space.clear_overrides()
    telemetry.reset()
    yield
    space.clear_overrides()
    telemetry.reset()


def make_batch(seed=0):
    rs = onp.random.RandomState(seed)
    x = mx.nd.array(rs.randn(BS, IN).astype("float32"))
    y = mx.nd.array(rs.randint(0, CLASSES, size=(BS,)).astype("int32"))
    return x, y


def make_step(hidden=HIDDEN, autotune=None, lr=0.1):
    mx.random.seed(42)
    onp.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu", in_units=IN),
            nn.Dense(CLASSES, in_units=hidden))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, IN), "float32")))
    loss = SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": lr, "momentum": 0.9},
                      kvstore=None)
    step = trainer.compile_step(lambda a, b: loss(net(a), b),
                                autotune=autotune)
    return step, net, trainer


def make_loop(hidden=HIDDEN, lr=0.1):
    mx.random.seed(42)
    onp.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu", in_units=IN),
            nn.Dense(CLASSES, in_units=hidden))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, IN), "float32")))
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": lr, "momentum": 0.9},
                      kvstore=None)
    return TrainLoop(net, trainer, SoftmaxCrossEntropyLoss())


# ---------------------------------------------------------------------------
# space: registry + resolution
# ---------------------------------------------------------------------------

def test_registry_has_every_shipped_tunable():
    space.ensure_registered()
    names = {t.name for t in space.tunables()}
    assert {"engine.inflight_steps", "kernels.vmem_tile_budget",
            "kernels.rnn_block_t", "zero.shard_min_size",
            "serving.max_batch", "serving.batch_timeout_ms"} <= names
    for t in space.tunables():
        assert t.default in t.grid
        assert t.seam
        assert t.scope in ("train", "serving", "both")


def test_resolution_precedence(monkeypatch):
    space.ensure_registered()
    t = space.get("engine.inflight_steps")
    assert t.resolve() == 2                       # shipped default
    monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "5")
    assert t.resolve() == 5                       # env beats default
    space.set_override("engine.inflight_steps", 7)
    assert t.resolve() == 7                       # override beats env
    space.clear_overrides(["engine.inflight_steps"])
    assert t.resolve() == 5


def test_consumer_seams_resolve_overrides(monkeypatch):
    from mxnet_tpu import engine
    from mxnet_tpu.gluon import fused_step
    from mxnet_tpu.ops import kernels
    from mxnet_tpu.serving import batcher
    space.apply_config({"engine.inflight_steps": 6,
                        "zero.shard_min_size": 512,
                        "kernels.vmem_tile_budget": 2 * 1024 * 1024,
                        "serving.max_batch": 16,
                        "serving.batch_timeout_ms": 0.5})
    assert engine.inflight_steps() == 6
    assert fused_step._zero_min_size() == 512
    assert kernels.vmem_tile_budget() == 2 * 1024 * 1024
    assert batcher.max_batch_rows() == 16
    assert batcher.batch_timeout_s() == pytest.approx(0.5e-3)


def test_vmem_accessor_env_and_clamp(monkeypatch):
    from mxnet_tpu.ops import kernels
    assert kernels.vmem_tile_budget() == kernels.VMEM_TILE_BUDGET_BYTES
    monkeypatch.setenv("MXNET_VMEM_TILE_BUDGET", str(8 * 1024 * 1024))
    assert kernels.vmem_tile_budget() == 8 * 1024 * 1024
    # clamped to the physical VMEM above, to 64 KiB below
    space.set_override("kernels.vmem_tile_budget", 10**12)
    assert kernels.vmem_tile_budget() == kernels.VMEM_BYTES_PER_CORE
    space.set_override("kernels.vmem_tile_budget", 1)
    assert kernels.vmem_tile_budget() == 64 * 1024


def test_vmem_budget_feeds_all_four_kernel_sizers():
    """One accessor, four consumers: shrinking the budget shrinks the
    rnn timestep block, the attention head group, and the norm/opt
    row-block caps together."""
    import jax.numpy as jnp
    from mxnet_tpu.ops import kernels
    from mxnet_tpu.ops.attention import _head_group
    from mxnet_tpu.ops.kernels import norm as knorm
    from mxnet_tpu.ops.kernels import opt_update as kopt
    from mxnet_tpu.ops.kernels import rnn_scan as krnn
    big = (kernels.vmem_tile_budget(),
           krnn._block_t(64, 8, 4, 128, 4, interpret=False),
           _head_group(8, 128, 128), knorm._budget_rows(128),
           kopt._block_rows_cap())
    space.set_override("kernels.vmem_tile_budget", 64 * 1024)
    small = (kernels.vmem_tile_budget(),
             krnn._block_t(64, 8, 4, 128, 4, interpret=False),
             _head_group(8, 128, 128), knorm._budget_rows(128),
             kopt._block_rows_cap())
    assert small[0] < big[0]
    for b, s in zip(big[1:], small[1:]):
        assert s <= b
    assert small[3] < big[3] and small[4] < big[4]


def test_rnn_block_t_tunable_and_interpret_contract():
    """The kernels.rnn_block_t override governs the compiled-TPU block
    size but NOT the interpret parity tier, which stays at block 1 —
    that is what keeps the fp32 forward bit-identical to the scan
    reference (PR 10 contract): the tunable can never change the
    numbers the parity sweep pins."""
    from mxnet_tpu.ops.kernels import rnn_scan as krnn
    args = (64, 8, 4, 128, 4)           # seq, N, gates, Hp, itemsize
    auto = krnn._block_t(*args, interpret=False)
    space.set_override("kernels.rnn_block_t", 8)
    assert krnn._block_t(*args, interpret=False) == 8
    assert krnn._block_t(*args, interpret=True) == 1
    space.set_override("kernels.rnn_block_t", 0)   # 0 = auto
    assert krnn._block_t(*args, interpret=False) == auto


def test_trial_context_restores_overrides():
    space.set_override("engine.inflight_steps", 3)
    with space.trial({"engine.inflight_steps": 8,
                      "zero.shard_min_size": 512}):
        assert space.value("engine.inflight_steps") == 8
        assert space.value("zero.shard_min_size") == 512
    assert space.value("engine.inflight_steps") == 3
    assert space.get_override("zero.shard_min_size") == (False, None)


def test_search_space_views_and_signature():
    space.ensure_registered()
    train = tuning.SearchSpace("train")
    serving_sp = tuning.SearchSpace("serving")
    assert {t.name for t in serving_sp} == {"serving.max_batch",
                                            "serving.batch_timeout_ms",
                                            "decode.slot_ladder",
                                            "decode.kv_page_size",
                                            "decode.prefill_chunk",
                                            "decode.spec_k",
                                            "decode.prefix_share"}
    assert not any(t.name.startswith(("serving.", "decode."))
                   for t in train)
    assert train.valid(train.defaults())
    assert not train.valid({"kernels.vmem_tile_budget": 2**40})
    assert train.signature() != serving_sp.signature()
    assert train.signature() == space.space_signature("train")


# ---------------------------------------------------------------------------
# search: planted optimum, infeasibility, budget, halving
# ---------------------------------------------------------------------------

def planted_space():
    tx = Tunable("syn.x", default=3, grid=(1, 2, 3, 4, 5),
                 seam="synthetic")
    ty = Tunable("syn.y", default=5, grid=(1, 2, 3, 4, 5),
                 seam="synthetic")
    return (tx, ty)


class FakeBackend:
    name = "analytical"
    deterministic = True

    def __init__(self, fn):
        self.fn = fn
        self.calls = 0

    def measure(self, config, fidelity=1):
        self.calls += 1
        return MeasureResult(self.fn(config))


def _bowl(c):
    return 1e-3 + 1e-4 * ((c["syn.x"] - 4) ** 2
                          + (c["syn.y"] - 2) ** 2)


def test_search_converges_on_planted_optimum_within_budget():
    backend = FakeBackend(_bowl)
    budget = 16
    res = search.coordinate_search(planted_space(), backend, budget)
    assert res.best_config == {"syn.x": 4, "syn.y": 2}
    assert res.n_trials <= budget
    assert res.improved and res.delta_pct > 0
    assert res.tuned_overrides() == {"syn.x": 4, "syn.y": 2}
    assert res.default_score == pytest.approx(_bowl(
        {"syn.x": 3, "syn.y": 5}))


def test_search_budget_is_a_hard_cap():
    backend = FakeBackend(_bowl)
    res = search.coordinate_search(planted_space(), backend, budget=3)
    assert res.n_trials == 3 and res.exhausted
    # best-so-far is still returned, never an exception
    assert res.best_score <= res.default_score


def test_faulting_candidates_scored_infeasible_not_fatal():
    """An OOM-style failure inside a trial becomes an infeasible score
    via the PR 11 taxonomy; the search completes and the winner comes
    from the surviving candidates."""
    def fn(c):
        if c["syn.x"] == 4:
            raise MXNetError("RESOURCE_EXHAUSTED: out of memory "
                             "allocating 8G")
        return _bowl(c)

    backend = FakeBackend(fn)
    res = search.coordinate_search(planted_space(), backend, budget=32)
    assert res.best_config["syn.x"] != 4          # faulting value lost
    assert res.best_config["syn.y"] == 2
    bad = [t for t in res.trials if not t.result.feasible]
    assert bad and all("oom" in t.result.reason for t in bad)


def test_infeasible_default_recovers_to_feasible_candidate():
    def fn(c):
        if c["syn.x"] == 3:                       # the DEFAULT faults
            raise MXNetError("RESOURCE_EXHAUSTED: oom")
        return _bowl(c)

    res = search.coordinate_search(planted_space(), FakeBackend(fn),
                                   budget=32)
    assert res.best_config["syn.x"] == 4
    assert res.delta_pct is None                  # no default baseline


def test_validity_predicate_filters_before_measuring():
    t = Tunable("syn.v", default=1, grid=(1, 2, 3, 4),
                valid=lambda v, _c: v <= 2, seam="synthetic")
    backend = FakeBackend(lambda c: 1.0 / c["syn.v"])
    res = search.coordinate_search((t,), backend, budget=16)
    assert res.best_config == {"syn.v": 2}        # 3, 4 never measured
    assert all(tr.config["syn.v"] <= 2 for tr in res.trials)


def test_successive_halving_on_noisy_backend():
    """Noisy backends re-measure surviving candidates at doubled
    fidelity; deterministic ones measure each candidate exactly once."""
    class Noisy(FakeBackend):
        deterministic = False

    t = Tunable("syn.x", default=1, grid=(1, 2, 3, 4, 5, 6, 7, 8),
                seam="synthetic")
    backend = Noisy(lambda c: 1e-3 + 1e-4 * abs(c["syn.x"] - 6))
    res = search.coordinate_search((t,), backend, budget=64)
    assert res.best_config == {"syn.x": 6}
    assert max(tr.fidelity for tr in res.trials) >= 2   # rungs climbed
    det = FakeBackend(lambda c: 1e-3 + 1e-4 * abs(c["syn.x"] - 6))
    res2 = search.coordinate_search((t,), det, budget=64)
    assert all(tr.fidelity == 1 for tr in res2.trials)
    assert det.calls == len({tuple(sorted(tr.config.items()))
                             for tr in res2.trials})


# ---------------------------------------------------------------------------
# cache: round-trip, invalidation, corruption
# ---------------------------------------------------------------------------

def test_cache_atomic_roundtrip(tmp_path):
    db = AutotuneCache(str(tmp_path / "at.json"))
    db.put("k1", {"config": {"a.b": 1}, "trials": 5})
    fresh = AutotuneCache(str(tmp_path / "at.json"))
    assert fresh.get("k1")["config"] == {"a.b": 1}
    assert fresh.get("nope") is None
    doc = json.loads((tmp_path / "at.json").read_text())
    assert doc["schema"] == cache.CACHE_SCHEMA


def test_cache_corrupt_file_degrades_to_retune(tmp_path):
    p = tmp_path / "at.json"
    p.write_text("{ not json !!!")
    db = AutotuneCache(str(p))
    assert db.get("k1") is None                   # no raise
    db.put("k1", {"config": {}})                  # rewrites cleanly
    assert AutotuneCache(str(p)).get("k1") == {"config": {}}


def test_step_signature_stable_and_shape_sensitive():
    step1, _, _ = make_step()
    step2, _, _ = make_step()
    x, y = make_batch()
    assert cache.step_signature(step1, (x, y)) \
        == cache.step_signature(step2, (x, y))
    # a different model is a different program: the key must move
    step3, _, _ = make_step(hidden=HIDDEN * 2)
    assert cache.step_signature(step1, (x, y)) \
        != cache.step_signature(step3, (x, y))
    # and a different input bucket too
    x2 = mx.nd.array(onp.zeros((BS * 2, IN), "float32"))
    y2 = mx.nd.array(onp.zeros((BS * 2,), "int32"))
    assert cache.step_signature(step1, (x, y)) \
        != cache.step_signature(step1, (x2, y2))


def test_signature_change_invalidates_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    # cache-keying semantics only — a tiny search budget keeps the
    # three full searches cheap without touching what's asserted
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "8")
    x, y = make_batch()
    step, _, _ = make_step(autotune="on")
    step(x, y)
    assert step.autotune_result.source == "search"
    space.clear_overrides()
    # same program, fresh construction: HIT
    step2, _, _ = make_step(autotune="on")
    step2(x, y)
    assert step2.autotune_result.source == "cache"
    assert step2.autotune_result.trials == 0
    space.clear_overrides()
    # different program: MISS -> its own search
    step3, _, _ = make_step(hidden=HIDDEN * 2, autotune="on")
    step3(x, y)
    assert step3.autotune_result.source == "search"
    assert step3.autotune_result.key != step2.autotune_result.key


# ---------------------------------------------------------------------------
# gate semantics
# ---------------------------------------------------------------------------

def test_autotune_mode_parsing(monkeypatch):
    assert tuning.autotune_mode() == "off"
    for v, want in (("on", "on"), ("1", "on"), ("true", "on"),
                    ("cached", "cached"), ("CACHED", "cached"),
                    ("off", "off"), ("0", "off"), ("", "off"),
                    ("bogus", "off")):
        monkeypatch.setenv("MXNET_AUTOTUNE", v)
        assert tuning.autotune_mode() == want, v
    # the explicit kwarg wins over the env
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    assert tuning.autotune_mode("off") == "off"
    assert tuning.autotune_mode(True) == "on"
    assert tuning.autotune_mode(False) == "off"


def test_gate_off_does_nothing():
    x, y = make_batch()
    step, _, _ = make_step()                      # env gate off
    step(x, y)
    out = step.autotune_result
    assert out.mode == "off" and out.trials == 0
    assert space.overrides() == {}
    assert telemetry.value(telemetry.names.AUTOTUNE_CACHE_MISSES) == 0


def test_gate_cached_miss_runs_defaults_zero_trials(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    x, y = make_batch()
    step, _, _ = make_step(autotune="cached")
    step(x, y)
    out = step.autotune_result
    assert out.source == "default" and out.trials == 0
    assert out.config == {}
    assert space.overrides() == {}                # defaults untouched
    assert not (tmp_path / "at.json").exists()    # nothing persisted
    assert telemetry.value(telemetry.names.AUTOTUNE_CACHE_MISSES) == 1
    assert telemetry.value(telemetry.names.AUTOTUNE_TRIALS,
                           "analytical") == 0


def test_gate_on_searches_within_budget(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "5")
    x, y = make_batch()
    step, _, _ = make_step(autotune="on")
    step(x, y)
    out = step.autotune_result
    assert out.source == "search" and 1 <= out.trials <= 5
    assert out.backend == "analytical"            # CPU auto-selects
    assert (tmp_path / "at.json").exists()
    assert telemetry.value(telemetry.names.AUTOTUNE_TRIALS,
                           "analytical") == out.trials


def test_explicit_autotune_method_and_outcome_record(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    # outcome-record plumbing only — a tiny search budget suffices
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "8")
    x, y = make_batch()
    step, _, _ = make_step()
    out = step.autotune(x, y, mode="on")
    assert out is step.autotune_result
    assert out.source == "search"
    d = out.bench_dict()
    assert set(d) == {"autotune_config", "autotune_trials",
                      "autotune_delta_pct"}
    assert tuning.last_outcome() is out
    # the subsequent first step call does NOT re-tune
    before = telemetry.value(telemetry.names.AUTOTUNE_TRIALS,
                             "analytical")
    step(x, y)
    assert telemetry.value(telemetry.names.AUTOTUNE_TRIALS,
                           "analytical") == before


# ---------------------------------------------------------------------------
# numerics safety
# ---------------------------------------------------------------------------

def run_trajectory(config=None, steps=6):
    """Loss trajectory of the canonical seeded TrainLoop under a tuned
    config (None = shipped defaults)."""
    space.clear_overrides()
    if config:
        space.apply_config(config)
    try:
        loop = make_loop()
        x, y = make_batch()
        losses = [loop.step(x, y) for _ in range(steps)]
        loop.synchronize()
        return [float(l._data.mean()) for l in losses]
    finally:
        space.clear_overrides()


def test_tuned_configs_are_bit_exact_on_losses():
    """Tunables change SPEED, never numerics: the window-depth and
    kernel-block knobs at non-default values produce bit-identical
    loss trajectories (window parity pinned since PR 5; the rnn block
    tunable cannot leak into the CPU reference path by construction)."""
    base = run_trajectory(None)
    tuned = run_trajectory({"engine.inflight_steps": 4,
                            "kernels.rnn_block_t": 8,
                            "kernels.vmem_tile_budget": 1024 * 1024})
    assert tuned == base
    sync = run_trajectory({"engine.inflight_steps": 0})
    assert sync == base


def test_timed_backend_restores_train_state(tmp_path, monkeypatch):
    """Timed trials execute real steps; the orchestrator's
    capture/apply_train_state bracket must leave params, optimizer
    state and counters exactly where they started."""
    monkeypatch.setenv("MXNET_AUTOTUNE_BACKEND", "timed")
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "4")
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    x, y = make_batch()
    step, net, trainer = make_step()
    params = list(net.collect_params().values())
    before = [onp.asarray(p._data._data) for p in params]
    n_before = trainer._optimizer.num_update
    out = tuning.tune_step(step, (x, y), mode="on")
    assert out.source == "search" and out.backend == "timed"
    assert trainer._optimizer.num_update == n_before
    for p, b in zip(params, before):
        onp.testing.assert_array_equal(onp.asarray(p._data._data), b)
    # and the tuned step still trains bit-exactly vs an untouched one
    space.clear_overrides()
    ref_step, _, _ = make_step()
    l_ref = float(ref_step(x, y)._data.mean())
    l_tuned = float(step(x, y)._data.mean())
    assert l_tuned == l_ref


# ---------------------------------------------------------------------------
# serving scope
# ---------------------------------------------------------------------------

def make_predictor():
    mx.random.seed(11)
    onp.random.seed(11)
    net = nn.HybridSequential()
    net.add(nn.Dense(HIDDEN, activation="relu", in_units=IN),
            nn.Dense(CLASSES, in_units=HIDDEN))
    net.initialize()
    net(mx.nd.array(onp.zeros((1, IN), "float32")))
    from mxnet_tpu import serving
    return serving.CompiledPredictor(net, bucket_sizes=(1, 2, 4, 8))


def test_predictor_warmup_autotune_and_bucket_feasibility(tmp_path,
                                                          monkeypatch):
    """warmup(autotune='on') sweeps the serving knobs; max_batch
    candidates over the largest bucket are infeasible (bucket_for
    raises inside the trial) and the winner respects the ladder. The
    tuned overrides govern a batcher constructed afterwards."""
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    from mxnet_tpu.serving import batcher
    pred = make_predictor()
    x1 = mx.nd.array(onp.zeros((1, IN), "float32"))
    pred.warmup(x1, autotune="on")
    out = pred.autotune_result
    assert out is not None and out.source == "search"
    applied_max = space.value("serving.max_batch")
    assert applied_max <= 8                       # largest bucket
    assert batcher.max_batch_rows() == applied_max
    rec = tuning.default_cache().get(out.key)
    bad = [t for t in rec["trial_log"] if not t["feasible"]]
    assert bad                                    # 16/32/64 infeasible
    # replay: fresh predictor, cached gate, zero trials, same config
    space.clear_overrides()
    telemetry.reset()
    pred2 = make_predictor()
    pred2.warmup(x1, autotune="cached")
    assert pred2.autotune_result.source == "cache"
    assert pred2.autotune_result.trials == 0
    assert space.value("serving.max_batch") == applied_max


def test_train_and_serving_scopes_do_not_cross(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    # scope filtering only — any search size proves it
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "8")
    x, y = make_batch()
    step, _, _ = make_step(autotune="on")
    step(x, y)
    tuned = step.autotune_result.config
    assert not any(k.startswith("serving.") for k in tuned)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_autotune_metric_flow(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    # metric plumbing only (trials counter, active-config gauges,
    # hit/miss counters) — a tiny search budget keeps it cheap
    monkeypatch.setenv("MXNET_AUTOTUNE_BUDGET_TRIALS", "8")
    x, y = make_batch()
    step, _, _ = make_step(autotune="on")
    step(x, y)
    n = telemetry.value(telemetry.names.AUTOTUNE_TRIALS, "analytical")
    assert n == step.autotune_result.trials >= 1
    assert telemetry.value(telemetry.names.AUTOTUNE_CACHE_MISSES) == 1
    for name, v in step.autotune_result.config.items():
        g = telemetry.value(telemetry.names.AUTOTUNE_ACTIVE, name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            assert g == float(v)
        else:
            assert g is not None
    space.clear_overrides()
    step2, _, _ = make_step(autotune="cached")
    step2(x, y)
    assert telemetry.value(telemetry.names.AUTOTUNE_CACHE_HITS) == 1


# ---------------------------------------------------------------------------
# ACCEPTANCE: the deterministic closed loop, end to end
# ---------------------------------------------------------------------------

def test_closed_loop_end_to_end_cpu(tmp_path, monkeypatch):
    """The tier-1 acceptance loop on CPU: (1) the analytical backend
    sweeps a REAL CompiledTrainStep's tunable space and persists a
    winner keyed by the compile signature; (2) a fresh construction —
    new net, new trainer, new step, overrides cleared, exactly what a
    restarted process rebuilds (the signature hashes only process-
    independent facts; tests above pin cross-construction equality) —
    under MXNET_AUTOTUNE=cached replays it with ZERO trials; (3) the
    replayed config trains BIT-EXACTLY like the defaults."""
    db_path = tmp_path / "autotune.json"
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", str(db_path))
    x, y = make_batch()

    # ---- defaults trajectory (gate off), the numerics reference
    losses_default = run_trajectory(None)

    # ---- phase 1: tune (mode=on) — search runs, winner persists
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    loop = make_loop()
    loop.step(x, y)
    loop.synchronize()
    out1 = loop.compiled_step.autotune_result
    assert out1.source == "search" and out1.trials >= 1
    assert out1.backend == "analytical"
    assert db_path.exists()
    doc = json.loads(db_path.read_text())
    assert list(doc["entries"]) == [out1.key]
    persisted = doc["entries"][out1.key]["config"]
    assert persisted == out1.config
    # the analytical model prefers deeper pipelining: a genuinely
    # non-default winner proves the sweep moved something
    assert persisted, "search should tune at least one knob"

    # ---- phase 2: fresh construction, cached gate -> zero trials
    space.clear_overrides()
    telemetry.reset()
    monkeypatch.setenv("MXNET_AUTOTUNE", "cached")
    loop2 = make_loop()
    x2, y2 = make_batch()
    losses_replay = []
    for _ in range(6):
        losses_replay.append(loop2.step(x2, y2))
    loop2.synchronize()
    out2 = loop2.compiled_step.autotune_result
    assert out2.source == "cache" and out2.trials == 0
    assert out2.config == persisted
    assert space.overrides() == persisted         # config is LIVE
    assert telemetry.value(telemetry.names.AUTOTUNE_TRIALS,
                           "analytical") == 0
    assert telemetry.value(telemetry.names.AUTOTUNE_CACHE_HITS) == 1

    # ---- phase 3: bit-exact losses vs the defaults
    # (loop2's first step ran inside phase 2; its trajectory includes
    # it — compare the full 6-step trajectories)
    losses_replay = [float(l._data.mean()) for l in losses_replay]
    assert losses_replay == losses_default
